/* C ABI for embedding xflow-tpu training in native applications.
 *
 * The reference's FFI surface (/root/reference/src/c_api/c_api.h:31-41:
 * XFCreate constructs a worker, XFStartTrain runs it) is kept, extended
 * with config overrides and result access. Thread-safety: calls must
 * come from one thread (the embedded interpreter owns the GIL).
 *
 * Build the implementation with:
 *   gcc -shared -fPIC xflow_c_api.c $(python3-config --includes) \
 *       $(python3-config --ldflags --embed) -o libxflow_api.so
 */

#ifndef XFLOW_C_API_H_
#define XFLOW_C_API_H_

#ifdef __cplusplus
extern "C" {
#endif

/* Create a trainer for `train_prefix`/`test_prefix` shard sets
 * (reads <prefix>-%05d). Returns 0 on success, nonzero on failure. */
int XFCreate(void** out_handle, const char* train_prefix, const char* test_prefix);

/* Apply a dotted config override, e.g. ("model.name", "fm"). */
int XFSetConfig(void* handle, const char* dotted_key, const char* value);

/* Run training (and evaluation when a test prefix was given). */
int XFStartTrain(void* handle);

/* Test AUC from the last XFStartTrain (NaN if not evaluated). */
double XFGetAUC(void* handle);

/* Load the newest COMMITTED checkpoint under `checkpoint_dir` into an
 * online predictor for this handle (reshard-on-load; corrupt newer
 * steps walk back to the previous committed one). Config overrides
 * applied via XFSetConfig must match the checkpoint's model/hash
 * config. Returns 0 on success, nonzero on failure. */
int XFLoadCheckpoint(void* handle, const char* checkpoint_dir);

/* Predict pCTR for newline-separated libffm feature rows (an optional
 * leading label per row is ignored). Writes up to `capacity` values
 * into `out_pctr`; returns the number of predictions written, or -1
 * on error (no loaded checkpoint, malformed row). Predictions come
 * from the same forward the trainer's evaluate uses. */
int XFPredict(void* handle, const char* rows, double* out_pctr, int capacity);

/* Release the trainer. */
int XFDestroy(void* handle);

#ifdef __cplusplus
}
#endif

#endif /* XFLOW_C_API_H_ */
