"""Python side of the C ABI (see xflow_c_api.h).

The reference exposes `XFCreate`/`XFStartTrain` wrapping an `LRWorker`
behind `extern "C"` for FFI embedding (`/root/reference/src/c_api/
c_api.cc:10-20`, disabled in its build). Here the C shim embeds CPython
and drives this module; handles are integers into a registry.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict

_registry: Dict[int, dict] = {}
_ids = itertools.count(1)


def create(train_prefix: str, test_prefix: str) -> int:
    handle = next(_ids)
    _registry[handle] = {
        "overrides": {
            "data.train_path": train_prefix,
            "data.test_path": test_prefix,
        },
        "result": None,
        "auc": float("nan"),
    }
    return handle


def set_config(handle: int, key: str, value: str) -> None:
    _registry[handle]["overrides"][key] = value


def start_train(handle: int) -> int:
    from xflow_tpu.config import Config, override
    from xflow_tpu.train.trainer import Trainer

    entry = _registry[handle]
    cfg = override(Config(), **entry["overrides"])
    trainer = Trainer(cfg)
    res = trainer.fit()
    entry["result"] = res
    if cfg.data.test_path:
        auc, ll = trainer.evaluate()
        entry["auc"] = auc
        entry["logloss"] = ll
    return 0


def get_auc(handle: int) -> float:
    return float(_registry[handle]["auc"])


def destroy(handle: int) -> None:
    _registry.pop(handle, None)
