"""Python side of the C ABI (see xflow_c_api.h).

The reference exposes `XFCreate`/`XFStartTrain` wrapping an `LRWorker`
behind `extern "C"` for FFI embedding (`/root/reference/src/c_api/
c_api.cc:10-20`, disabled in its build). Here the C shim embeds CPython
and drives this module; handles are integers into a registry.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict

_registry: Dict[int, dict] = {}
_ids = itertools.count(1)


def create(train_prefix: str, test_prefix: str) -> int:
    handle = next(_ids)
    _registry[handle] = {
        "overrides": {
            "data.train_path": train_prefix,
            "data.test_path": test_prefix,
        },
        "result": None,
        "auc": float("nan"),
    }
    return handle


def set_config(handle: int, key: str, value: str) -> None:
    _registry[handle]["overrides"][key] = value


def start_train(handle: int) -> int:
    from xflow_tpu.config import Config, override
    from xflow_tpu.train.trainer import Trainer

    entry = _registry[handle]
    cfg = override(Config(), **entry["overrides"])
    trainer = Trainer(cfg)
    res = trainer.fit()
    entry["result"] = res
    if cfg.data.test_path:
        auc, ll = trainer.evaluate()
        entry["auc"] = auc
        entry["logloss"] = ll
    return 0


def get_auc(handle: int) -> float:
    return float(_registry[handle]["auc"])


def load_checkpoint(handle: int, ckpt_dir: str) -> int:
    """Back XFLoadCheckpoint: stand up a serve runner over the newest
    COMMITTED checkpoint in `ckpt_dir` (reshard-on-load; walk-back on
    corrupt steps — train/checkpoint.restore_any), using this handle's
    accumulated config overrides so the model/hash config matches what
    trained. The reference's c_api was exactly this embedding-serving
    surface, never finished (`/root/reference/src/c_api`, disabled in
    its build)."""
    from xflow_tpu.config import Config, override
    from xflow_tpu.serve.runner import ServeRunner

    entry = _registry[handle]
    overrides = dict(entry["overrides"])
    overrides["train.checkpoint_dir"] = ckpt_dir
    cfg = override(Config(), **overrides)
    runner = ServeRunner(cfg)
    runner.load()  # raises when nothing committed loads -> C returns -1
    entry["runner"] = runner
    return 0


def predict(handle: int, rows_text: str) -> list:
    """Back XFPredict: newline-separated libffm feature rows (optional
    leading label ignored) -> [pctr floats], through the SAME jitted
    forward `evaluate` uses (models/predict.py). Raises on malformed
    rows or a handle without a loaded checkpoint — the C shim surfaces
    that as -1, never a crash."""
    runner = _registry[handle].get("runner")
    if runner is None:
        raise RuntimeError("no checkpoint loaded; call XFLoadCheckpoint first")
    rows = [ln for ln in rows_text.splitlines() if ln.strip()]
    pctrs, _gen = runner.predict_rows(rows)
    return [float(p) for p in pctrs]


def get_serving_step(handle: int) -> int:
    """Checkpoint step the handle's runner serves (-1 = none loaded)."""
    runner = _registry[handle].get("runner")
    return int(runner.step) if runner is not None else -1


def destroy(handle: int) -> None:
    _registry.pop(handle, None)
