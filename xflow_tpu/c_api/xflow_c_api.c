/* C ABI implementation: embeds CPython and drives xflow_tpu.c_api.embed.
 * See xflow_c_api.h for the contract and build line. */

#include "xflow_c_api.h"

#include <Python.h>
#include <math.h>
#include <stdint.h>

static PyObject* g_embed = NULL;

static int ensure_interp(void) {
  if (g_embed != NULL) return 0;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
  }
  g_embed = PyImport_ImportModule("xflow_tpu.c_api.embed");
  if (g_embed == NULL) {
    PyErr_Print();
    return -1;
  }
  return 0;
}

/* Call embed.<fn>(*args); steals the `args` reference on every path
 * (including lookup failure and args==NULL from a failed Py_BuildValue). */
static PyObject* call(const char* fn, PyObject* args) {
  if (args == NULL) return NULL;
  PyObject* f = PyObject_GetAttrString(g_embed, fn);
  if (f == NULL) {
    Py_DECREF(args);
    return NULL;
  }
  PyObject* r = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_DECREF(args);
  return r;
}

int XFCreate(void** out_handle, const char* train_prefix, const char* test_prefix) {
  if (ensure_interp() != 0) return -1;
  PyObject* r = call("create", Py_BuildValue("(ss)", train_prefix, test_prefix));
  if (r == NULL) {
    PyErr_Print();
    return -1;
  }
  long h = PyLong_AsLong(r);
  Py_DECREF(r);
  if (h == -1 && PyErr_Occurred()) {
    PyErr_Print();
    return -1;
  }
  *out_handle = (void*)(intptr_t)h;
  return 0;
}

int XFSetConfig(void* handle, const char* dotted_key, const char* value) {
  if (ensure_interp() != 0) return -1;
  PyObject* r = call("set_config",
                     Py_BuildValue("(lss)", (long)(intptr_t)handle, dotted_key, value));
  if (r == NULL) {
    PyErr_Print();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

int XFStartTrain(void* handle) {
  if (ensure_interp() != 0) return -1;
  PyObject* r = call("start_train", Py_BuildValue("(l)", (long)(intptr_t)handle));
  if (r == NULL) {
    PyErr_Print();
    return -1;
  }
  long rc = PyLong_AsLong(r);
  Py_DECREF(r);
  if (rc == -1 && PyErr_Occurred()) {
    PyErr_Print();
    return -1;
  }
  return (int)rc;
}

double XFGetAUC(void* handle) {
  if (ensure_interp() != 0) return NAN;
  PyObject* r = call("get_auc", Py_BuildValue("(l)", (long)(intptr_t)handle));
  if (r == NULL) {
    PyErr_Print();
    return NAN;
  }
  double auc = PyFloat_AsDouble(r);
  Py_DECREF(r);
  if (auc == -1.0 && PyErr_Occurred()) {
    PyErr_Print();
    return NAN;
  }
  return auc;
}

int XFLoadCheckpoint(void* handle, const char* checkpoint_dir) {
  if (ensure_interp() != 0) return -1;
  PyObject* r = call("load_checkpoint",
                     Py_BuildValue("(ls)", (long)(intptr_t)handle, checkpoint_dir));
  if (r == NULL) {
    PyErr_Print();
    return -1;
  }
  long rc = PyLong_AsLong(r);
  Py_DECREF(r);
  if (rc == -1 && PyErr_Occurred()) {
    PyErr_Print();
    return -1;
  }
  return (int)rc;
}

int XFPredict(void* handle, const char* rows, double* out_pctr, int capacity) {
  if (ensure_interp() != 0) return -1;
  PyObject* r = call("predict",
                     Py_BuildValue("(ls)", (long)(intptr_t)handle, rows));
  if (r == NULL) {
    PyErr_Print();
    return -1;
  }
  PyObject* seq = PySequence_Fast(r, "predict() did not return a sequence");
  Py_DECREF(r);
  if (seq == NULL) {
    PyErr_Print();
    return -1;
  }
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  int wrote = 0;
  for (Py_ssize_t i = 0; i < n && wrote < capacity; ++i) {
    double v = PyFloat_AsDouble(PySequence_Fast_GET_ITEM(seq, i));
    if (v == -1.0 && PyErr_Occurred()) {
      Py_DECREF(seq);
      PyErr_Print();
      return -1;
    }
    out_pctr[wrote++] = v;
  }
  Py_DECREF(seq);
  return wrote;
}

int XFDestroy(void* handle) {
  if (ensure_interp() != 0) return -1;
  PyObject* r = call("destroy", Py_BuildValue("(l)", (long)(intptr_t)handle));
  if (r == NULL) {
    PyErr_Print();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}
