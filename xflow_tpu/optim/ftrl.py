"""FTRL-proximal (McMahan et al., "Ad Click Prediction: a View from the
Trenches" — the paper the reference README cites).

Math is exactly `/root/reference/src/optimizer/ftrl.h:58-74` (w table)
and `:124-141` (v table), per element:

    n' = n + g²
    z' = z + g − (√n' − √n)/α · w
    w' = 0                                  if |z'| ≤ λ1
       = −(z' − sign(z')·λ1) / ((β + √n')/α + λ2)   otherwise

applied to dense (w, n, z) arrays instead of lazily-constructed hash-map
entries. Hyperparameter defaults match `ftrl.h:17-20`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from xflow_tpu.optim.base import Optimizer, register_optimizer


def _init_state(tables):
    return {
        name: {"n": jnp.zeros_like(t), "z": jnp.zeros_like(t)} for name, t in tables.items()
    }


def _update_one(w, n, z, g, alpha, beta, lambda1, lambda2):
    n_new = n + g * g
    z_new = z + g - (jnp.sqrt(n_new) - jnp.sqrt(n)) / alpha * w
    shrink = jnp.sign(z_new) * lambda1
    denom = (beta + jnp.sqrt(n_new)) / alpha + lambda2
    w_new = jnp.where(jnp.abs(z_new) <= lambda1, 0.0, -(z_new - shrink) / denom)
    # Lazy-init parity (`ftrl.h:113-120`): the reference only creates an
    # entry when a key is first pushed, so a never-touched slot keeps its
    # random v-table init. A dense recompute of w from z would zero every
    # untouched slot (z=0 ⇒ w=0) on step 1, wiping the v init and stalling
    # FM/MVM second-order terms. Keep w unchanged where the slot has never
    # seen a gradient (g=0 this step AND n=0 from all prior steps).
    # Edge divergence vs the reference (documented in docs/PARITY.md C11):
    # a key whose first-ever push is exactly g=0 would have its w zeroed
    # by the reference; the dense form can't see the key list and keeps
    # the init.
    untouched = (g == 0.0) & (n == 0.0)
    w_new = jnp.where(untouched, w, w_new)
    return w_new, n_new, z_new


def _apply(tables, opt_state, grads, cfg):
    hp = cfg.optim.ftrl
    new_tables, new_state = {}, {}
    for name, w in tables.items():
        st, g = opt_state[name], grads[name]
        w_new, n_new, z_new = _update_one(
            w, st["n"], st["z"], g, hp.alpha, hp.beta, hp.lambda1, hp.lambda2
        )
        new_tables[name] = w_new
        new_state[name] = {"n": n_new, "z": z_new}
    return new_tables, new_state


OPTIMIZER = register_optimizer(Optimizer(name="ftrl", init_state=_init_state, apply=_apply))
