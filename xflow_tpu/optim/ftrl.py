"""FTRL-proximal (McMahan et al., "Ad Click Prediction: a View from the
Trenches" — the paper the reference README cites).

Math is exactly `/root/reference/src/optimizer/ftrl.h:58-74` (w table)
and `:124-141` (v table), per element:

    n' = n + g²
    z' = z + g − (√n' − √n)/α · w
    w' = 0                                  if |z'| ≤ λ1
       = −(z' − sign(z')·λ1) / ((β + √n')/α + λ2)   otherwise

applied to dense (w, n, z) arrays instead of lazily-constructed hash-map
entries. Hyperparameter defaults match `ftrl.h:17-20`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from xflow_tpu.optim.base import Optimizer, register_optimizer


def _init_state(tables):
    return {
        name: {"n": jnp.zeros_like(t), "z": jnp.zeros_like(t)} for name, t in tables.items()
    }


def _update_one(w, n, z, g, alpha, beta, lambda1, lambda2):
    n_new = n + g * g
    z_new = z + g - (jnp.sqrt(n_new) - jnp.sqrt(n)) / alpha * w
    shrink = jnp.sign(z_new) * lambda1
    denom = (beta + jnp.sqrt(n_new)) / alpha + lambda2
    w_new = jnp.where(jnp.abs(z_new) <= lambda1, 0.0, -(z_new - shrink) / denom)
    return w_new, n_new, z_new


def _apply(tables, opt_state, grads, cfg):
    hp = cfg.optim.ftrl
    new_tables, new_state = {}, {}
    for name, w in tables.items():
        st, g = opt_state[name], grads[name]
        w_new, n_new, z_new = _update_one(
            w, st["n"], st["z"], g, hp.alpha, hp.beta, hp.lambda1, hp.lambda2
        )
        new_tables[name] = w_new
        new_state[name] = {"n": n_new, "z": z_new}
    return new_tables, new_state


OPTIMIZER = register_optimizer(Optimizer(name="ftrl", init_state=_init_state, apply=_apply))
