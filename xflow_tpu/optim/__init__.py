from xflow_tpu.optim.base import Optimizer, get_optimizer
from xflow_tpu.optim import ftrl, sgd  # noqa: F401  (registration side effects)

__all__ = ["Optimizer", "get_optimizer"]
