"""Optimizer interface.

In the reference the optimizer runs *on the server* as a ps-lite
request handler mutating per-key entries in a hash map
(`/root/reference/src/model/server.h:23-29` installs the handles from
`src/optimizer/ftrl.h` / `sgd.h`); workers only push raw gradients.
Here the optimizer is a pure elementwise function over dense state
arrays, compiled into the train step. Because FTRL's closed-form w is a
deterministic function of (z, n) and a zero gradient leaves (z, n)
unchanged, applying the update to every slot is a no-op for untouched
slots — so no touched-mask is needed and XLA fuses the whole update
with the gradient scatter.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict

from xflow_tpu.config import Config


@dataclass(frozen=True)
class Optimizer:
    name: str
    # tables -> opt_state pytree (dict per table)
    init_state: Callable
    # (tables, opt_state, grads, cfg) -> (new_tables, new_opt_state)
    apply: Callable


_REGISTRY: Dict[str, Optimizer] = {}


def register_optimizer(opt: Optimizer) -> Optimizer:
    _REGISTRY[opt.name] = opt
    return opt


def get_optimizer(name: str) -> Optimizer:
    if name not in _REGISTRY:
        raise KeyError(f"unknown optimizer {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[name]
