"""Plain SGD.

Reference: `/root/reference/src/optimizer/sgd.h` — `w -= lr·g` with
lr = 0.001 (`sgd.h:16,51-52`), same handle structure for the w and v
tables. Stateless.
"""

from __future__ import annotations

from xflow_tpu.optim.base import Optimizer, register_optimizer


def _init_state(tables):
    return {name: {} for name in tables}


def _apply(tables, opt_state, grads, cfg):
    lr = cfg.optim.sgd.lr
    new_tables = {name: w - lr * grads[name] for name, w in tables.items()}
    return new_tables, opt_state


OPTIMIZER = register_optimizer(Optimizer(name="sgd", init_state=_init_state, apply=_apply))
