"""Request-path distributed tracing (docs/OBSERVABILITY.md "Request
tracing").

The serving fleet answers one request through many independent hops —
client -> router (retry/hedge legs, breaker consults) -> replica
coalescer (queue wait, brownout window) -> one shared device batch ->
response — and the aggregate counters (PR 7/8: p99 windows, failovers,
shed_requests) cannot say WHICH hop ate a slow request's budget. This
module is the Dapper-style span layer that can: every hop appends a
`kind="span"` JSONL record through the existing stamped appender
(replica/port/gen/world stamps free), keyed by one trace id that
travels the whole path in the `X-Trace-Id` header and is echoed back
to the client. tools/request_trace.py reassembles the per-replica +
router streams into per-request timelines and critical paths.

Three design points carry the module:

- **Deterministic head sampling.** `sampled(trace_id, rate)` hashes
  the trace id itself, so the router and every replica make the SAME
  keep/drop decision with zero coordination — a kept trace is kept at
  every hop it touched, never a torso. `serve.trace_sample_rate=0`
  disables tracing outright (the serve streams stay byte-identical to
  pre-tracing builds).

- **Tail-based capture.** Exactly the requests you page on — errors,
  sheds, retries, hedges, anything over `serve.trace_slow_ms` — are
  ALWAYS captured: spans buffer per trace in the process that made
  them and flush on the request's completion verdict (`finish(force=)`),
  so head sampling bounds the steady-state cost while the tail
  exemplars are guaranteed on disk. A hop that cannot know the verdict
  locally is told: the router stamps `X-Trace-Force: 1` onto retry and
  hedge legs so the replica side of a forced trace survives too.

- **Shared batch spans.** The coalescer answers N requests with ONE
  device batch; that batch is one `device_batch` span (batch_fill,
  flush reason, device time) added to every member trace and emitted
  exactly once when the first sampled member flushes — request spans
  link to it by span id (`batch=`), turning "my request was slow" into
  "my request rode a 3%-full window flush behind a 2.1 ms device
  batch".

Span record shape (the appender prefixes ts/rank/run_id/gen/world and,
in a fleet, replica/port):

    {"kind": "span", "trace": <16-hex>, "span": <16-hex>,
     "parent": <span id, absent on the root>, "name": "request" |
     "attempt" | "server" | "parse" | "queue" | "device" |
     "device_batch" | "reload" | "checkpoint_save" | ...,
     "t0": <wall seconds>, "dur_ms": <float>, ...attrs}

Durations are perf_counter-measured; `t0` converts to wall-clock
through one per-process offset so spans from different processes on
one host line up (the same correlation-only contract as the `ts`
stamp, xflow_tpu/jsonl.py).
"""

from __future__ import annotations

import hashlib
import threading
import time
import uuid
from collections import OrderedDict
from typing import Iterable, Optional

# the propagation headers (serve/server.py, serve/router.py,
# tools/serve_bench.py speak them; any HTTP proxy can forward them)
TRACE_HEADER = "X-Trace-Id"
PARENT_HEADER = "X-Parent-Span"
FORCE_HEADER = "X-Trace-Force"

# request-path span names (tools/request_trace.py and the
# metrics_report --check span gates key on these; operational spans —
# reload / checkpoint_save / checkpoint_restore — are everything else)
REQUEST_SPAN_NAMES = ("request", "attempt", "server", "parse", "queue", "device")
BATCH_SPAN_NAME = "device_batch"


def new_id() -> str:
    """A fresh 16-hex trace/span id."""
    return uuid.uuid4().hex[:16]


def clean_id(value: Optional[str]) -> str:
    """A header-supplied id, sanitized: stripped, length-capped, token
    characters only ('' = unusable). Ids land verbatim in JSONL and in
    echoed headers — an adversarial header must not inject either."""
    if not value:
        return ""
    value = value.strip()
    if not value or len(value) > 64:
        return ""
    if not all(c.isalnum() or c in "-_." for c in value):
        return ""
    return value


def sampled(trace_id: str, rate: float) -> bool:
    """The head-sampling decision for one trace id — a pure function of
    the id, so every hop (router, each replica) agrees without
    coordination. rate <= 0 never samples, >= 1 always does."""
    if rate <= 0:
        return False
    if rate >= 1:
        return True
    h = int(hashlib.sha1(trace_id.encode("utf-8", "replace")).hexdigest()[:8], 16)
    return h / float(1 << 32) < rate


class Tracer:
    """Per-process span buffer + sampling verdicts over one stamped
    JSONL appender. Thread-safe: HTTP handler threads, the device
    worker, and the router's hedge legs all add spans concurrently.

    Lifecycle per trace: `span()`/`end()` (or `add()`) buffer records
    under the trace id; `finish(trace, force=)` delivers the verdict —
    emit everything (head-sampled or forced) or drop everything. A span
    landing AFTER the verdict (a hedge leg losing the race) follows the
    recorded verdict, so a kept trace never loses its stragglers.
    Verdict memory and the pending buffer are both bounded: a trace
    whose finish never comes (a leaked id) is evicted oldest-first
    instead of growing the process."""

    def __init__(
        self,
        appender,
        sample_rate: float = 0.0,
        slow_ms: float = 250.0,
        max_pending: int = 2048,
        max_verdicts: int = 8192,
    ):
        self._app = appender
        self.sample_rate = float(sample_rate)
        self.slow_s = max(float(slow_ms), 0.0) / 1e3
        self._max_pending = max(int(max_pending), 1)
        self._max_verdicts = max(int(max_verdicts), 1)
        self._lock = threading.Lock()
        self._pending: "OrderedDict[str, list]" = OrderedDict()
        self._verdicts: "OrderedDict[str, bool]" = OrderedDict()
        # one per-process perf->wall offset: every span of a process
        # converts through the same anchor, so intra-process deltas
        # stay perf-counter-exact
        self._wall_off = time.time() - time.perf_counter()

    @property
    def enabled(self) -> bool:
        """Tracing is on iff the sample rate is positive — rate 0 is
        the byte-identical-output switch, tail capture included."""
        return self.sample_rate > 0

    def wall(self, t_perf: float) -> float:
        return t_perf + self._wall_off

    # -------------------------------------------------------------- spans
    def span(self, trace: str, name: str, parent: Optional[str] = None,
             t0: Optional[float] = None, **attrs) -> dict:
        """An OPEN span handle: its id exists now (children/headers can
        reference it) but nothing is buffered until `end()`. `t0` is a
        perf_counter instant (default: now)."""
        s = {
            "trace": trace,
            "span": new_id(),
            "name": name,
            "_t0": time.perf_counter() if t0 is None else float(t0),
        }
        if parent:
            s["parent"] = parent
        s.update(attrs)
        return s

    def end(self, span: dict, t1: Optional[float] = None, **attrs) -> dict:
        """Close an open span and buffer its record; returns the
        record (tests)."""
        t1 = time.perf_counter() if t1 is None else float(t1)
        t0 = span.pop("_t0")
        rec = {
            "kind": "span",
            **span,
            **attrs,
            "t0": round(self.wall(t0), 6),
            "dur_ms": round(max(t1 - t0, 0.0) * 1e3, 3),
        }
        self.add(rec["trace"], rec)
        return rec

    def add(self, trace: str, rec: dict) -> None:
        """Buffer one finished span record under its trace (or follow
        an already-recorded verdict — the late-span path)."""
        with self._lock:
            verdict = self._verdicts.get(trace)
            if verdict is None:
                self._pending.setdefault(trace, []).append(rec)
                while len(self._pending) > self._max_pending:
                    self._pending.popitem(last=False)  # evict oldest
                return
            emit = verdict
        if emit:
            self._emit(rec)

    def add_shared(self, rec: dict, traces: Iterable[str]) -> None:
        """Buffer ONE record (a device-batch span) under several
        traces; whichever member trace emits first carries it, the
        rest see it already done — the span appends exactly once."""
        rec["_shared"] = False  # not yet emitted
        for t in traces:
            self.add(t, rec)

    def _emit(self, rec: dict) -> None:
        # shared records emit once, whichever sampled member flushes
        # first (checked under the appender's own lock-free path is
        # fine: _shared flips under OUR lock in finish/add)
        if "_shared" in rec:
            with self._lock:
                if rec["_shared"]:
                    return
                rec["_shared"] = True
            rec = {k: v for k, v in rec.items() if k != "_shared"}
        self._app.append(rec)

    # ------------------------------------------------------------ verdicts
    def finish(self, trace: str, force: bool = False) -> bool:
        """Deliver the trace's verdict: emit its buffered spans when
        head-sampled or `force`d (tail capture), else drop them.
        Returns whether the trace was emitted."""
        emit = force or sampled(trace, self.sample_rate)
        with self._lock:
            spans = self._pending.pop(trace, [])
            self._verdicts[trace] = emit
            while len(self._verdicts) > self._max_verdicts:
                self._verdicts.popitem(last=False)
        if emit:
            for rec in spans:
                self._emit(rec)
        return emit

    def pending_traces(self) -> int:
        with self._lock:
            return len(self._pending)


def emit_op_span(appender, name: str, t0_wall: float, dur_s: float,
                 **attrs) -> dict:
    """One standalone OPERATIONAL span — checkpoint save/restore, a
    serve hot-reload swap — always emitted (these are rare, operator-
    initiated events, not per-request traffic; sampling them would
    punch holes in the exact timeline request_trace --timeline overlays
    against latency spikes). Each gets its own fresh trace id so the
    request-trace parenting gates never see it as a torso."""
    rec = {
        "kind": "span",
        "trace": new_id(),
        "span": new_id(),
        "name": name,
        "t0": round(t0_wall, 6),
        "dur_ms": round(max(dur_s, 0.0) * 1e3, 3),
        **attrs,
    }
    appender.append(rec)
    return rec


def emit_linked_span(appender, name: str, t0_wall: float, dur_s: float,
                     trace: str, parent: Optional[str] = None,
                     span: Optional[str] = None, **attrs) -> dict:
    """An operational span CARRYING a given trace id — the freshness
    loop's cross-boundary links (docs/SERVING.md "Freshness"): the
    trainer's `publish` span ships an INGEST trace id into the span
    stream, the serve runner's reload swap and first-served-prediction
    spans continue it on the other side of the train/serve boundary,
    and tools/freshness_report.py reassembles the one tree that spans
    ingested row -> served prediction. Like emit_op_span these are
    rare operator-cadence events, always emitted (never sampled);
    unlike it the trace (and optionally parent/span) ids are the
    CALLER's, because the whole point is that they match across
    processes."""
    rec = {
        "kind": "span",
        "trace": trace,
        "span": span or new_id(),
        "name": name,
        "t0": round(t0_wall, 6),
        "dur_ms": round(max(dur_s, 0.0) * 1e3, 3),
        **attrs,
    }
    if parent:
        rec["parent"] = parent
    appender.append(rec)
    return rec
