"""Supervised auto-restart: the recovery half of the fault-tolerance
contract (docs/ROBUSTNESS.md "Elastic recovery").

PR 1/PR 3 built detection — non-finite guards, self-healing restores,
the heartbeat/straggler watchdog — but a preempted or killed rank still
ended the run until a human relaunched it, which is exactly the gap
classic parameter-server systems close with supervised restarts (Li et
al., OSDI'14: recovery, not just detection, is the contract). This
module is the ONE supervision loop both launchers wrap their job in:

- `supervise(run_attempt, ...)` re-runs the whole job (all ranks torn
  down and relaunched together — SPMD peers of a dead rank are blocked
  in collectives and unrecoverable in place) with exponential backoff
  and jitter between attempts, up to ``--max-restarts`` times.
- Every relaunch forces ``train.resume=true`` (`resume_forward_args`),
  so the job restores the last COMMITTED checkpoint and — with the
  checkpoint's `data_state` — continues the input stream at the stored
  offset instead of replaying it (train/checkpoint.py).
- The attempt index is the **restart generation**, exported to every
  rank as ``XFLOW_RESTART_GEN`` and stamped as `gen` into every JSONL
  record (jsonl.JsonlAppender), so `metrics_report.py` segments the
  multi-generation streams instead of tripping on step counters that
  restart from 0.
- ``--min-uptime-s``: an attempt that dies FASTER than this is treated
  as a configuration error (a crash loop would burn every restart in
  seconds), not a transient fault — supervision stops and the exit
  code surfaces.

`backoff_delay` / `retry_call` are the shared transient-failure
primitives; `parallel/distributed.py` reuses them for rendezvous
retries (a restarted rank rejoining before its peers must not turn a
survivable blip into a failed job).
"""

from __future__ import annotations

import random
import sys
import time
from typing import Callable, Optional

BACKOFF_CAP_S = 60.0
# returned when only the watchdog's dead/missing verdict failed the
# attempt (a wedged rank never exits, so there is no child code to
# propagate): EX_TEMPFAIL — "temporary failure, retry" is exactly what
# the supervision loop should read
EX_TEMPFAIL = 75


class DeadHostTracker:
    """Dead-HOST bookkeeping for degraded-mode supervision
    (``--allow-shrink``, docs/ROBUSTNESS.md "Host lost").

    The failure taxonomy the shrink policy rests on: a rank that EXITS
    nonzero is a dead *process* on a live host — the same host can run
    the relaunch, so the job restarts same-shape. A watchdog
    dead/missing VERDICT (no heartbeat across the grace window — the
    host-unreachable signature, since a merely-crashed process would
    have exited) is a dead *host*: relaunching the same shape would
    just re-fail the rendezvous, so with ``--allow-shrink`` the next
    attempt runs with the SURVIVING set and a recomputed world size.
    Both launchers feed their watchdog's ``on_dead`` rows through
    `record` and size the relaunch with `survivors`/`shrunk_world`;
    launch-dist additionally `revive`s a host its pre-relaunch probe
    finds reachable again — the grow-back path (the elastic restore
    reshards the checkpoint either way).

    `record` takes an opaque label — a host string for launch-dist, a
    per-generation rank tag for launch-local (where a "host" is an
    emulated process slot and cannot rejoin). Off (`allow_shrink`
    False) every method is a no-op and the relaunch stays same-shape.
    """

    def __init__(self, allow_shrink: bool = False):
        self.allow_shrink = bool(allow_shrink)
        self.lost: set = set()

    def record(self, label) -> None:
        if self.allow_shrink:
            self.lost.add(label)

    def attempt_recorder(self, labels: Optional[list] = None, gen: int = 0):
        """The watchdog `on_dead` hook for ONE attempt — records ONE
        loss: once a host wedges, its SPMD peers block in the next
        collective and stop beating ~2 steps later, so the same
        watchdog scan flags them too; the culprit ordering (lowest
        step first) makes the FIRST verdict the host actually lost and
        the rest its victims. (Under a coarse heartbeat cadence the
        culprit and its victims can tie on the same beat step; a
        victim recorded by mistake costs one extra restart — its probe
        passes and it rejoins — while the true loss gets verdicted
        again next attempt, so the policy converges.)

        `labels` maps the verdict's rank to a durable label (the
        attempt's host list, launch-dist); None tags the loss
        ``(gen, rank)`` (launch-local's emulated slots, where
        renumbered ranks must not collide across attempts). Malformed
        or out-of-range ranks are ignored, never recorded."""
        fired: list = []

        def on_dead(row: dict) -> None:
            r = row.get("rank")
            if fired or not isinstance(r, int) or r < 0:
                return
            if labels is None:
                fired.append(row)
                self.record((gen, r))
            elif r < len(labels):
                fired.append(row)
                self.record(labels[r])

        return on_dead

    def revive(self, label) -> None:
        self.lost.discard(label)

    def shrunk_world(self, total: int, floor: int = 1) -> int:
        """World size for the next attempt: the original count minus
        the lost set, never below `floor` (a job cannot shrink to zero
        ranks — the last survivor keeps the run alive)."""
        if not self.allow_shrink:
            return int(total)
        return max(int(total) - len(self.lost), int(floor))

    def survivors(self, items: list) -> list:
        """`items` minus the lost labels, original order preserved
        (the first survivor becomes rank 0 / the coordinator)."""
        if not self.allow_shrink:
            return list(items)
        return [x for x in items if x not in self.lost]


def backoff_delay(
    attempt: int, base_s: float, cap_s: float = BACKOFF_CAP_S, rng=None
) -> float:
    """Exponential backoff with jitter: base·2^attempt capped at
    `cap_s`, then scaled uniformly into [0.5, 1.0]× — the decorrelation
    that keeps N restarted ranks (or N supervised jobs sharing a
    coordinator) from re-stampeding the rendezvous in lockstep."""
    d = min(float(base_s) * (2.0 ** max(int(attempt), 0)), float(cap_s))
    return d * (rng or random).uniform(0.5, 1.0)


def retry_call(
    fn: Callable,
    what: str,
    retries: int,
    base_s: float,
    cap_s: float = BACKOFF_CAP_S,
    cleanup: Optional[Callable] = None,
    sleep: Callable[[float], None] = time.sleep,
    out=None,
):
    """Call `fn()` with up to `retries` backoff-spaced retries.

    Every failure is logged with its reason and the chosen delay;
    `cleanup` (when given) runs between attempts to tear down partial
    state the failed call may have left (e.g. a half-initialized
    distributed runtime). The LAST failure propagates unchanged."""
    for attempt in range(max(int(retries), 0) + 1):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — transient-failure seam:
            # every failure mode retries; the last one propagates as-is
            if attempt >= retries:
                raise
            delay = backoff_delay(attempt, base_s, cap_s)
            print(
                f"{what}: attempt {attempt + 1}/{retries + 1} failed "
                f"({type(e).__name__}: {e}); retrying in {delay:.1f}s",
                file=out or sys.stderr,
            )
            if cleanup is not None:
                try:
                    cleanup()
                except Exception:
                    pass
            sleep(delay)


def terminate_procs(procs, kill_after_s: float = 5.0) -> None:
    """TERM every live process, then KILL stragglers after
    `kill_after_s` — the ONE escalation both launchers' teardowns end
    with (a rank blocked in a collective never reaches a
    signal-coordination point, so the KILL is mandatory; launch-dist
    additionally closes ssh stdin pipes first, its die-with-connection
    signal)."""
    for p in procs:
        if p.poll() is None:
            p.terminate()
    deadline = time.monotonic() + kill_after_s
    while time.monotonic() < deadline and any(p.poll() is None for p in procs):
        time.sleep(0.2)
    for p in procs:
        if p.poll() is None:
            p.kill()


def wait_fail_fast(
    procs,
    teardown: Callable,
    dead_verdict=None,
    label: str = "launch",
    grace_s: float = 0.0,
    poll_s: float = 0.2,
    out=None,
) -> int:
    """Poll rank processes until all exit; FAIL-FAST on the first bad
    sign. The ONE wait loop both launchers run (launch/local.py,
    launch/dist.py — only their teardown mechanics differ): on the
    first NONZERO rank exit, or a watchdog dead/missing verdict
    (`dead_verdict`, a threading.Event set by the RunWatchdog's on_dead
    policy — a wedged rank never exits on its own), wait `grace_s` for
    stragglers' own error output, then `teardown(procs)` — SPMD peers
    of a dead rank are blocked in collectives and unrecoverable in
    place. Returns the first bad rank's exit code (EX_TEMPFAIL for a
    verdict-only failure), or 0 when every rank exits clean."""
    first_bad = 0
    while True:
        codes = [p.poll() for p in procs]
        bad = [c for c in codes if c]  # nonzero AND not None
        if not first_bad and (
            bad or (dead_verdict is not None and dead_verdict.is_set())
        ):
            first_bad = bad[0] if bad else EX_TEMPFAIL
            reason = (
                f"a rank exited with code {first_bad}"
                if bad
                else "watchdog verdict: dead/missing rank"
            )
            grace_note = f" in {grace_s:.0f}s" if grace_s > 0 else ""
            print(
                f"{label}: {reason}; terminating the remaining ranks"
                f"{grace_note} (peers would otherwise block in collectives "
                "forever)",
                file=out or sys.stderr,
            )
            if grace_s > 0:
                deadline = time.monotonic() + grace_s
                while time.monotonic() < deadline and any(
                    p.poll() is None for p in procs
                ):
                    time.sleep(poll_s)
            teardown(procs)
        if all(c is not None for c in codes):
            return first_bad or next((c for c in codes if c), 0)
        time.sleep(poll_s)


def resume_forward_args(forward_args: list[str]) -> list[str]:
    """The relaunch's `xflow train` argv: the original args plus a
    FORCED train.resume=true appended last, so it wins over any
    user-passed `--set train.resume=false` (cli._build_config applies
    --set pairs in order) and the restarted job restores the last
    committed checkpoint + data_state instead of training from
    scratch."""
    return [*forward_args, "--set", "train.resume=true"]


def supervise(
    run_attempt: Callable[[int], int],
    max_restarts: int = 0,
    restart_backoff: float = 1.0,
    min_uptime_s: float = 0.0,
    label: str = "launch",
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    out=None,
) -> int:
    """Run `run_attempt(gen)` until it exits 0 or the restart budget is
    spent; returns the final attempt's exit code.

    `run_attempt` receives the restart generation (0 = first launch)
    and owns the actual job: spawning every rank with
    ``XFLOW_RESTART_GEN=<gen>``, tearing all ranks down on a failure
    (a nonzero exit or a watchdog dead-rank verdict), and returning the
    job's exit code. Generations > 0 must launch with
    `resume_forward_args`. max_restarts=0 is plain un-supervised
    behavior: one attempt, its code returned."""
    err = out or sys.stderr
    gen = 0
    while True:
        t0 = clock()
        rc = int(run_attempt(gen))
        uptime = clock() - t0
        if rc == 0:
            if gen:
                print(
                    f"{label}: job succeeded after {gen} restart(s)", file=err
                )
            return 0
        if gen >= max_restarts:
            if max_restarts > 0:
                print(
                    f"{label}: restart budget exhausted "
                    f"({max_restarts} restart(s)); giving up with rc={rc}",
                    file=err,
                )
            return rc
        if min_uptime_s > 0 and uptime < min_uptime_s:
            print(
                f"{label}: attempt {gen} died after {uptime:.1f}s "
                f"(< --min-uptime-s {min_uptime_s:g}) — this looks like a "
                "configuration error, not a transient fault; not restarting",
                file=err,
            )
            return rc
        delay = backoff_delay(gen, restart_backoff)
        print(
            f"{label}: attempt {gen} exited rc={rc} after {uptime:.1f}s; "
            f"restarting generation {gen + 1} with train.resume=true in "
            f"{delay:.1f}s ({max_restarts - gen} restart(s) left)",
            file=err,
        )
        sleep(delay)
        gen += 1
