"""Run-dir liveness watchdog: dead-rank and straggler detection.

Each training rank appends heartbeat records —
``{ts, rank, run_id, kind: "heartbeat", step}`` every
``train.heartbeat_every`` steps plus start/interrupted/final events —
to ``<run_dir>/heartbeat_rank<k>.jsonl`` (the path the launchers wire
per rank, launch/local.py ``rank_metrics_args``). This module is the
reader side, the Dapper-style cross-rank view the ROADMAP's
serve-heavy-traffic north-star needs: instead of N per-rank log files
someone greps after the fact, ONE watchdog in the launcher process
polls the shared run dir and flags, while the job is still running:

- **dead** ranks: no heartbeat for ``dead_after_s`` (a killed process,
  a wedged host). SPMD corollary, stated plainly: once one rank stops
  dispatching, its peers block in the next collective and go stale
  ~2 steps later (the one-step-behind metrics block bounds how far a
  host can run ahead), so on an all-stale cluster the LOWEST-step rank
  is the culprit and the rest are victims — `classify` orders by step
  so that reading is immediate.
- **stragglers**: ranks whose last-seen step trails the leader by more
  than ``straggler_factor``× (``max_step > factor * max(step, 1)``).

Detection is heartbeat-file-only on purpose — the watchdog needs no
channel into the ranks (works over any shared filesystem, exactly like
the reference's operators tailing per-worker logs, minus the tailing).

`metrics_report.py --health` reuses `classify` for the offline
post-mortem view (with "now" = the newest heartbeat seen, so a
finished run isn't all "dead").
"""

from __future__ import annotations

import glob
import os
import sys
import threading
import time
from typing import Optional

DEFAULT_STRAGGLER_FACTOR = 2.0
DEFAULT_DEAD_AFTER_S = 60.0
DEFAULT_POLL_S = 2.0


def fold_heartbeats(
    records,
    beats: Optional[dict] = None,
    run_id: Optional[str] = None,
    gen: Optional[int] = None,
) -> dict:
    """Fold heartbeat records into {rank: {"step", "ts", "event"}},
    keeping the newest record per rank (a step-less event keeps the
    rank's last known step). The ONE place this fold lives — the live
    watchdog (`read_heartbeats`) and the offline post-mortem
    (tools/metrics_report.py --health) both classify through it, so
    they cannot drift. `run_id` filters to one launch — a reused
    --run-dir appends a second run's beats to the same files, and
    without the filter the OLD run's ranks would read as permanently
    dead in the new run's live view. `gen` filters to one RESTART
    GENERATION the same way: a supervised relaunch keeps the run_id,
    and without the filter the previous attempt's stale (or already
    dead-verdicted) beats would re-fire the new watchdog's dead policy
    before the relaunched ranks write their first beat — a teardown
    loop that burns the whole restart budget. The offline view passes
    neither: it folds everything, newest beat per rank winning."""
    beats = {} if beats is None else beats
    for rec in records:
        rank = rec.get("rank")
        ts = rec.get("ts")
        if run_id is not None and rec.get("run_id") != run_id:
            continue
        # defensive like rank/ts below: one damaged gen value (a
        # string, a NaN) must skip one record (or fold as gen 0 in the
        # unfiltered view), not raise and blind every later scan
        g = rec.get("gen", 0)
        try:
            g = int(g) if isinstance(g, (int, float)) else None
        except (ValueError, OverflowError):  # NaN/inf floats
            g = None
        if gen is not None and g != gen:
            continue
        if not isinstance(rank, int) or not isinstance(ts, (int, float)):
            continue
        cur = beats.get(rank)
        if cur is None or ts >= cur["ts"]:
            step = rec.get("step")
            beats[rank] = {
                "step": int(step) if isinstance(step, (int, float)) else (cur["step"] if cur else 0),
                "ts": float(ts),
                "event": rec.get("event"),
                # the beat's restart generation rides along for the
                # offline view: metrics_report --health labels a rank
                # whose beats STOP at an old generation of a shrunk run
                # as retired@genK, not dead
                "gen": g if g is not None else 0,
            }
    return beats


def read_heartbeats(
    run_dir: str, run_id: Optional[str] = None, gen: Optional[int] = None
) -> dict:
    """{rank: {"step": int, "ts": float, "event": str|None}} — the
    newest heartbeat per rank across ``heartbeat_rank*.jsonl`` in
    `run_dir`, optionally restricted to one `run_id` and one restart
    generation (see `fold_heartbeats`). Truncation-tolerant (a rank
    killed mid-append must not blind the watchdog to its earlier
    beats)."""
    from xflow_tpu.jsonl import read_jsonl

    beats: dict = {}
    for path in sorted(glob.glob(os.path.join(run_dir, "heartbeat_rank*.jsonl"))):
        fold_heartbeats(read_jsonl(path, warn=False), beats, run_id=run_id, gen=gen)
    return beats


def classify(
    beats: dict,
    now: float,
    straggler_factor: float = DEFAULT_STRAGGLER_FACTOR,
    dead_after_s: float = DEFAULT_DEAD_AFTER_S,
    expected_ranks: Optional[int] = None,
) -> list[dict]:
    """One status row per rank, lowest step first (the culprit ordering).

    Statuses: ``ok``; ``straggler`` (step lag beyond the factor);
    ``dead`` (heartbeat older than `dead_after_s`, and not cleanly
    finished — a rank whose LAST record is the ``final``/``interrupted``
    event is done, not dead); ``starting`` (newest record is still the
    ``start`` event — the rank is inside first-step compilation, which
    on a real TPU takes minutes and must not read as dead/straggling;
    heads-up cadence note: pick ``dead_after_s`` comfortably above
    `heartbeat_every` steps' worth of wall time, or a healthy rank
    reads dead between beats); ``missing`` (an expected rank that never
    wrote a heartbeat at all). Dead wins over straggler."""
    finished = {
        r for r, b in beats.items() if b.get("event") in ("final", "interrupted")
    }
    starting = {r for r, b in beats.items() if b.get("event") == "start"}
    max_step = max((b["step"] for b in beats.values()), default=0)
    rows = []
    for rank in sorted(beats, key=lambda r: (beats[r]["step"], r)):
        b = beats[rank]
        age = max(0.0, now - b["ts"])
        lagging = max_step > straggler_factor * max(b["step"], 1)
        if rank in finished:
            status = "finished"
        elif rank in starting:
            status = "starting"
        elif age > dead_after_s:
            status = "dead"
        elif lagging:
            status = "straggler"
        else:
            status = "ok"
        rows.append(
            {
                "rank": rank,
                "step": b["step"],
                "max_step": max_step,
                "age_s": round(age, 3),
                "status": status,
            }
        )
    if expected_ranks is not None:
        for rank in range(expected_ranks):
            if rank not in beats:
                rows.append(
                    {
                        "rank": rank,
                        "step": 0,
                        "max_step": max_step,
                        # None, not inf: these rows serialize into
                        # watchdog.jsonl, which stays strict JSON
                        "age_s": None,
                        "status": "missing",
                    }
                )
    return rows


class RunWatchdog:
    """Launcher-side poller: warn on stderr (and append events to
    ``<run_dir>/watchdog.jsonl``) whenever a rank's status degrades to
    straggler/dead, and log the recovery when it comes back. Started by
    ``launch-local``/``launch-dist`` when ``--run-dir`` is set.

    Escalation is a PLUGGABLE policy, not built in: by default the
    watchdog only flags (teardown stays with the launcher, which
    already fail-fasts on a nonzero rank exit), but `on_dead` — called
    once per rank transition into ``dead``/``missing``, with the status
    row — lets a caller act on the verdict. The supervised launchers
    (launch/local.py, launch/dist.py under ``--max-restarts``) pass a
    policy that tears the whole job down and relaunches it with
    ``train.resume=true`` (launch/supervise.py): a WEDGED rank (alive
    but stuck — the case a nonzero exit never signals) is thereby
    recovered instead of merely reported. `gen` stamps the restart
    generation into watchdog.jsonl events (the launcher process owns
    the generation; its own env has no XFLOW_RESTART_GEN)."""

    def __init__(
        self,
        run_dir: str,
        num_ranks: int,
        straggler_factor: float = 0.0,
        dead_after_s: float = 0.0,
        poll_s: float = 0.0,
        run_id: str = "",
        out=None,
        on_dead=None,
        gen: int = 0,
    ):
        from xflow_tpu.jsonl import JsonlAppender

        self._run_dir = run_dir
        self._on_dead = on_dead
        self._n = num_ranks
        # <= 0 means "module default" — the launchers and their CLI
        # flags pass 0 straight through, so the sentinel resolution
        # lives in ONE place
        self._factor = float(straggler_factor) if straggler_factor > 0 else DEFAULT_STRAGGLER_FACTOR
        self._dead_after = float(dead_after_s) if dead_after_s > 0 else DEFAULT_DEAD_AFTER_S
        self._poll = max(float(poll_s), 0.05) if poll_s > 0 else DEFAULT_POLL_S
        self._out = out  # test seam; defaults to sys.stderr
        self._run_id = run_id
        self._gen = int(gen)
        self._events = JsonlAppender(
            os.path.join(run_dir, "watchdog.jsonl"),
            # rank -1 = the launcher itself; kind separates the stream;
            # gen AND world passed explicitly — the launcher process
            # owns the generation and its (possibly shrunk) rank count;
            # its own env has neither XFLOW_RESTART_GEN nor
            # XFLOW_NUM_PROCESSES
            stamp={
                "rank": -1,
                "run_id": run_id or "?",
                "kind": "watchdog",
                "gen": int(gen),
                "world": int(num_ranks),
            },
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started = time.time()
        # poll_once is both the _run-thread body AND a public test/
        # launcher seam — serialize scans so two concurrent polls can
        # never interleave _reported transitions into duplicate events
        # (xflowlint XF301, the PR 8 unlocked-writer bug class)
        self._poll_lock = threading.Lock()
        self._reported: dict = {}  # rank -> last reported status
        self.flagged: dict = {}  # rank -> worst status ever reported

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="xflow-run-watchdog"
        )
        self._thread.start()

    def poll_once(self, now: Optional[float] = None) -> list[dict]:
        """One scan (also the test seam): classify every rank and report
        transitions. The WHOLE scan — snapshot read included — holds
        the poll lock: if only the transition fold were locked, two
        concurrent polls could apply their snapshots in reversed order
        and report a stale backwards transition (a recovered rank
        re-flagged dead, escalating on_dead for a healthy rank)."""
        with self._poll_lock:
            # generation-filtered: a relaunched attempt must not
            # classify (and re-kill) on the PREVIOUS attempt's stale
            # beats
            beats = read_heartbeats(
                self._run_dir, run_id=self._run_id or None, gen=self._gen
            )
            t = time.time() if now is None else now
            # "missing" needs a startup grace: ranks open their
            # heartbeat streams hundreds of ms apart, and a poll
            # landing between the first and last start beat must not
            # flag the slower ranks. A rank is only "missing" once the
            # run has both produced beats AND outlived the dead
            # threshold since this watchdog started.
            expect = (
                self._n
                if beats and (t - self._started) > min(self._dead_after, 30.0)
                else None
            )
            rows = classify(
                beats,
                t,
                straggler_factor=self._factor,
                dead_after_s=self._dead_after,
                expected_ranks=expect,
            )
            for row in rows:
                status = row["status"]
                prev = self._reported.get(row["rank"], "ok")
                # event payload keys deliberately avoid "rank"/"step":
                # those would collide with the appender's launcher stamp
                # and the report tool's step-monotonicity gate
                payload = {
                    "flagged_rank": row["rank"],
                    "at_step": row["step"],
                    "max_step": row["max_step"],
                    "age_s": row["age_s"],
                }
                if status in ("straggler", "dead", "missing") and status != prev:
                    self.flagged[row["rank"]] = status
                    self._events.append({"event": status, **payload})
                    beat = (
                        f"last heartbeat {row['age_s']:.1f}s ago"
                        if isinstance(row["age_s"], float)
                        else "no heartbeat ever"
                    )
                    print(
                        f"launch watchdog: rank {row['rank']} is a {status.upper()}"
                        f" (step {row['step']} vs leader {row['max_step']}, {beat})",
                        file=self._out or sys.stderr,
                    )
                    if status in ("dead", "missing") and self._on_dead is not None:
                        # escalation policy: once per transition, AFTER
                        # the event is durably logged; a policy error
                        # must not kill the poller (the flagging half
                        # keeps working)
                        try:
                            self._on_dead(dict(row))
                        except Exception as e:
                            print(
                                f"launch watchdog: on_dead policy failed: {e}",
                                file=self._out or sys.stderr,
                            )
                elif status in ("ok", "finished") and prev in ("straggler", "dead", "missing"):
                    self._events.append({"event": "recovered", **payload})
                    print(
                        f"launch watchdog: rank {row['rank']} recovered "
                        f"(step {row['step']})",
                        file=self._out or sys.stderr,
                    )
                self._reported[row["rank"]] = status
        return rows

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            try:
                self.poll_once()
            except Exception as e:  # a torn read must not kill the poller
                print(f"launch watchdog: scan failed: {e}", file=sys.stderr)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        self._events.close()
