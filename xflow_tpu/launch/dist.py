"""Multi-machine launcher (SURVEY.md §2 C17).

The reference's multi-machine bring-up is four hand-run shell scripts
and a hosts file (`run_ps_dist.sh:9-16`: start_scheduler.sh on machine
1, start_server.sh there too, start_worker.sh on each worker machine,
`scripts/hosts` listing addresses). The SPMD analog needs no role
split: every machine runs ONE identical `xflow train` process; rank 0's
address is the rendezvous coordinator (`jax.distributed.initialize`
replaces the ZMQ scheduler), and rank k reads shard `<prefix>-%05d` % k
(`lr_worker.cc:210` convention).

`xflow launch-dist` drives N machines from one seat:

    xflow launch-dist --hosts hosts.txt -- \
        --train /data/train --test /data/test --model fm ...

- `hosts.txt`: one host per line (optionally ``user@host``), comments
  with ``#`` — the same shape as the reference's ``scripts/hosts``. The
  FIRST host is rank 0 / the coordinator.
- each rank is started over ssh (``--ssh-cmd`` to swap in a different
  remote runner) with the ``XFLOW_*`` env contract
  (parallel/distributed.py): ``XFLOW_COORDINATOR=<host0>:<port>``,
  ``XFLOW_NUM_PROCESSES=N``, ``XFLOW_PROCESS_ID=k``.
- ``--workdir`` may contain ``{rank}`` / ``{host}`` placeholders so
  ranks run in separate directories (per-rank pred/metric files stay
  separate even on a shared filesystem).
- ``--dry-run`` prints the exact per-host command lines instead of
  running them — for clusters driven by something other than plain ssh
  (e.g. ``gcloud compute tpus tpu-vm ssh --worker=k``), paste the
  printed env + command into that runner. See docs/DISTRIBUTED.md for
  the TPU-pod walkthrough (where `jax.distributed.initialize()`
  auto-detects and `XFLOW_AUTO_DIST=1` is all a pod slice needs).

Unlike `launch-local` (single-machine emulation, forces CPU children),
launch-dist does NOT touch JAX_PLATFORMS: each machine's ambient
accelerators are exactly what the rank should use. Extra env goes
through repeatable ``--env K=V``.
"""

from __future__ import annotations

import os
import shlex
import subprocess
import sys


def parse_hosts(path: str) -> list[str]:
    """Hosts file -> host list. One host per line (optionally user@host);
    blank lines and '#' comments ignored. First host = rank 0."""
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if line:
                hosts.append(line.split()[0])
    if not hosts:
        raise ValueError(f"hosts file {path!r} lists no hosts")
    return hosts


def rank_command(
    host: str,
    rank: int,
    hosts: list[str],
    forward_args: list[str],
    port: int,
    workdir: str = "",
    python: str = "",
    env_extra: dict | None = None,
    run_dir: str = "",
) -> str:
    """The exact shell line rank `rank` runs on `host` (also what
    --dry-run prints). `run_dir` (a REMOTE path, typically on a shared
    filesystem) points this rank's metrics JSONL at
    `<run_dir>/metrics_rank<rank>.jsonl` — collect the files afterwards
    and summarize with tools/metrics_report.py."""
    from xflow_tpu.launch.local import rank_metrics_args

    coordinator_host = hosts[0].rsplit("@", 1)[-1]  # strip user@ for the address
    env = {
        "XFLOW_COORDINATOR": f"{coordinator_host}:{port}",
        "XFLOW_NUM_PROCESSES": str(len(hosts)),
        "XFLOW_PROCESS_ID": str(rank),
        **(env_extra or {}),
    }
    forward_args = [*forward_args, *rank_metrics_args(run_dir, rank)]
    py = python or "python3"
    parts = []
    if workdir:
        wd = workdir.format(rank=rank, host=host.rsplit("@", 1)[-1])
        parts.append(f"mkdir -p {shlex.quote(wd)} && cd {shlex.quote(wd)}")
    parts.append(
        " ".join(
            [*(f"{k}={shlex.quote(v)}" for k, v in env.items()),
             py, "-m", "xflow_tpu", "train",
             *(shlex.quote(a) for a in forward_args)]
        )
    )
    inner = " && ".join(parts)
    # die-with-connection wrapper: ssh without a TTY does NOT signal the
    # remote command when the client dies, so a rank blocked in a
    # collective would outlive a fail-fast teardown and hold the
    # coordinator port. The launcher holds the ssh client's stdin open
    # (stdin=PIPE, never written); the watcher `read` below unblocks only
    # when that pipe closes — client exit, kill, or network drop — and
    # then TERMs (5 s later KILLs) the rank. Normal completion reaps the
    # watcher and preserves the rank's exit status.
    # `exec 3<&0` + `<&3`: background jobs get /dev/null stdin (POSIX),
    # so the watcher must be fed the session's real stdin explicitly.
    # `set -m` (where supported) makes the subshell a process-group
    # leader so `kill -- -$xfp` reaps the whole tree; the plain-pid kill
    # covers shells without job control, where it still reaches the rank
    # because bash/dash tail-exec the last command of the subshell
    # (verified on both) — python IS $xfp there.
    return (
        f"exec 3<&0; set -m 2>/dev/null; ( {inner} ) & xfp=$!; set +m 2>/dev/null; "
        "{ while read -r xfl; do :; done; "
        "kill -TERM -- -$xfp 2>/dev/null; kill -TERM $xfp 2>/dev/null; sleep 5; "
        "kill -KILL -- -$xfp 2>/dev/null; kill -KILL $xfp 2>/dev/null; } <&3 & "
        "xfw=$!; wait $xfp; xfs=$?; kill $xfw 2>/dev/null; exit $xfs"
    )


def probe_host(host: str, ssh_cmd: str = "ssh", timeout_s: float = 10.0) -> bool:
    """One cheap reachability probe (`<ssh_cmd> host true`) — the
    rejoin detector for degraded-mode supervision: before each shrunk
    relaunch the launcher probes the hosts it lost, and one that
    answers again rejoins the world at that relaunch (the next
    checkpoint boundary's restore reshards onto the grown mesh)."""
    try:
        r = subprocess.run(
            [*shlex.split(ssh_cmd), host, "true"],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            stdin=subprocess.DEVNULL,
            timeout=timeout_s,
        )
        return r.returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        return False


def launch_dist(
    hosts: list[str],
    forward_args: list[str],
    port: int = 29431,
    ssh_cmd: str = "ssh",
    workdir: str = "",
    python: str = "",
    env_extra: dict | None = None,
    dry_run: bool = False,
    run_dir: str = "",
    straggler_factor: float = 0.0,
    dead_after_s: float = 0.0,
    watchdog_poll_s: float = 0.0,
    max_restarts: int = 0,
    restart_backoff: float = 1.0,
    min_uptime_s: float = 0.0,
    allow_shrink: bool = False,
) -> int:
    """Start one rank per host over ssh, under the supervision loop.

    One attempt (`_launch_dist_once`) starts every rank and fail-fasts
    on the first nonzero exit or watchdog dead-rank verdict. With
    ``--max-restarts`` the supervision wrapper (launch/supervise.py)
    then relaunches the WHOLE job — same hosts, same run id and run
    dir, ``train.resume=true`` forced, the restart generation exported
    as XFLOW_RESTART_GEN to every rank — with exponential backoff +
    jitter between attempts. Transient ssh/connect failures (a host
    rebooting out of a preemption, a TIME_WAIT coordinator port) ride
    the same loop: the failed attempt tears down, the backoff absorbs
    the blip, the relaunch reconnects; the rendezvous itself also
    retries per rank (parallel/distributed.py). max_restarts=0 is one
    plain un-supervised attempt.

    ``--allow-shrink`` (degraded-mode supervision, docs/ROBUSTNESS.md
    "Host lost"): a watchdog dead/missing verdict — no heartbeat across
    the grace window, the host-UNREACHABLE signature, as opposed to a
    rank process that exits nonzero on a live host — marks that host
    lost, and the relaunch runs on the SURVIVING host set with a
    recomputed XFLOW_NUM_PROCESSES (the first survivor becomes rank
    0 / the coordinator). The elastic restore reshards the last
    committed checkpoint into the smaller world and the data pipeline
    re-assigns the lost host's shard, so the record set stays covered.
    Before each relaunch the lost hosts are probed (`probe_host`); one
    that answers again rejoins — the job grows back at that restart's
    checkpoint-restore boundary."""
    from xflow_tpu.launch.local import resolve_launch_run_id
    from xflow_tpu.launch.supervise import (
        DeadHostTracker,
        resume_forward_args,
        supervise,
    )

    if forward_args and forward_args[0] == "--":
        forward_args = forward_args[1:]
    # one run id across all ranks AND all restart generations, ALWAYS
    # (not just under --run-dir: ranks given a metrics_path via
    # forwarded --set args must join too)
    env_extra = dict(env_extra or {})
    env_extra.setdefault("XFLOW_RUN_ID", resolve_launch_run_id())
    # the launch's ORIGINAL host count: a shrunk relaunch with no
    # committed data_state yet still learns the full shard set from
    # this (see trainer._fit) instead of silently training a subset
    env_extra.setdefault("XFLOW_ORIG_WORLD", str(len(hosts)))
    if dry_run:
        return _launch_dist_once(
            hosts, forward_args, port=port, ssh_cmd=ssh_cmd, workdir=workdir,
            python=python, env_extra=env_extra, dry_run=True, run_dir=run_dir,
        )
    tracker = DeadHostTracker(allow_shrink)

    def attempt(gen: int) -> int:
        for lost in sorted(tracker.lost):
            if probe_host(lost, ssh_cmd=ssh_cmd):
                print(
                    f"launch-dist: lost host {lost} answers again; "
                    f"rejoining the world at generation {gen}",
                    file=sys.stderr,
                )
                tracker.revive(lost)
        alive = tracker.survivors(hosts) or hosts[:1]
        if len(alive) < len(hosts):
            print(
                f"launch-dist: relaunching generation {gen} DEGRADED on "
                f"{len(alive)}/{len(hosts)} host(s) (--allow-shrink; "
                f"lost: {', '.join(sorted(tracker.lost))}); rank 0 = "
                f"{alive[0]}",
                file=sys.stderr,
            )
        args = forward_args if gen == 0 else resume_forward_args(forward_args)
        env_gen = {**env_extra, "XFLOW_RESTART_GEN": str(gen)}
        return _launch_dist_once(
            alive, args, port=port, ssh_cmd=ssh_cmd, workdir=workdir,
            python=python, env_extra=env_gen, run_dir=run_dir,
            straggler_factor=straggler_factor, dead_after_s=dead_after_s,
            watchdog_poll_s=watchdog_poll_s, gen=gen,
            # one-lost-HOST-per-attempt policy (culprit ordering) lives
            # on the tracker; the verdict names a rank of THIS
            # attempt's world, mapped back to the host it ran on
            on_dead_row=tracker.attempt_recorder(labels=alive),
        )

    return supervise(
        attempt,
        max_restarts=max_restarts,
        restart_backoff=restart_backoff,
        min_uptime_s=min_uptime_s,
        label="launch-dist",
    )


def _launch_dist_once(
    hosts: list[str],
    forward_args: list[str],
    port: int = 29431,
    ssh_cmd: str = "ssh",
    workdir: str = "",
    python: str = "",
    env_extra: dict | None = None,
    dry_run: bool = False,
    run_dir: str = "",
    straggler_factor: float = 0.0,
    dead_after_s: float = 0.0,
    watchdog_poll_s: float = 0.0,
    gen: int = 0,
    on_dead_row=None,
) -> int:
    """One attempt: start one rank per host over ssh and wait for all.

    Output streams are inherited (prefix-free, like the reference's
    `start_worker.sh` background jobs). FAIL-FAST: SPMD ranks block in
    collectives when a peer dies, so the first rank to exit NONZERO —
    or a watchdog dead/missing verdict (a wedged host that never exits)
    — terminates the rest (after `grace_s` seconds for the stragglers'
    own error output) and its exit code is returned. Rank 0 (the first
    host) is started LAST so the coordinator's listener never races the
    workers' connect loop backwards — JAX ranks retry the rendezvous,
    so ordering is cosmetic, but starting workers first keeps slow-host
    stragglers off the critical path.
    """
    import threading

    env_extra = dict(env_extra or {})
    cmds = [
        rank_command(h, i, hosts, forward_args, port, workdir, python, env_extra,
                     run_dir=run_dir)
        for i, h in enumerate(hosts)
    ]
    if dry_run:
        for i, (h, c) in enumerate(zip(hosts, cmds)):
            print(f"# rank {i} on {h}:")
            print(f"{ssh_cmd} {h} {shlex.quote(c)}")
        return 0
    watchdog = None
    dead_verdict = threading.Event()
    if run_dir:
        # mirror launch_local: create the run dir from this seat so the
        # recommended shared-filesystem setup works without
        # pre-creation (on a non-shared path this just makes an unused
        # local dir the watchdog watches quietly — no beats, no flags)
        try:
            os.makedirs(run_dir, exist_ok=True)
        except OSError as e:
            print(
                f"launch-dist: cannot create run dir {run_dir!r} locally "
                f"({e}); live watchdog disabled — run "
                "`tools/metrics_report.py --health` on the collected "
                "files afterwards",
                file=sys.stderr,
            )
    if run_dir and os.path.isdir(run_dir):
        # the run dir is visible from this seat (shared filesystem —
        # the recommended setup): poll the ranks' heartbeat streams for
        # dead ranks/stragglers, same watchdog launch-local runs
        # (<= 0 knobs take the module defaults). A purely remote run
        # dir skips this; run `metrics_report.py --health` on the
        # collected files instead.
        from xflow_tpu.launch.watchdog import RunWatchdog

        def on_dead(row):
            # escalation policy (elastic recovery): the verdict only
            # SETS a flag here (and feeds the supervisor's dead-host
            # tracker under --allow-shrink); teardown happens on the
            # launcher thread's poll loop below, and the supervision
            # wrapper decides whether — and at what shape — the job
            # relaunches
            if on_dead_row is not None:
                on_dead_row(row)
            dead_verdict.set()

        watchdog = RunWatchdog(
            run_dir,
            num_ranks=len(hosts),
            straggler_factor=straggler_factor,
            dead_after_s=dead_after_s,
            poll_s=watchdog_poll_s,
            run_id=env_extra.get("XFLOW_RUN_ID", ""),
            on_dead=on_dead,
            gen=gen,
        )
        watchdog.start()
    procs = []
    grace_s = 10.0

    def teardown(procs):
        """Close stdin pipes first (the remote die-with-connection
        watcher fires on EOF — the graceful path even over dead ssh
        clients), then the shared TERM-then-KILL escalation: ssh
        ignoring TERM must not leave the launcher hanging."""
        from xflow_tpu.launch.supervise import terminate_procs

        for p in procs:
            if p.stdin:
                try:
                    p.stdin.close()
                except OSError:
                    pass
        terminate_procs(procs)

    try:
        for i in reversed(range(len(hosts))):
            # stdin=PIPE, held open and never written: its EOF is the
            # remote watcher's death signal (rank_command wrapper)
            procs.append(
                subprocess.Popen(
                    [*shlex.split(ssh_cmd), hosts[i], cmds[i]],
                    stdin=subprocess.PIPE,
                )
            )
        from xflow_tpu.launch.supervise import wait_fail_fast

        return wait_fail_fast(
            procs, teardown, dead_verdict=dead_verdict, label="launch-dist",
            grace_s=grace_s, poll_s=0.5,
        )
    except KeyboardInterrupt:
        teardown(procs)
        for p in procs:
            p.wait()
        raise
    finally:
        if watchdog is not None:
            watchdog.stop()
