"""Local multi-process cluster emulation.

The reference's `scripts/local.sh:16-35` forks one scheduler + S servers
+ W workers of the same binary on 127.0.0.1 with `DMLC_*` role env vars.
The SPMD analog forks N identical `xflow train` processes pointed at a
local coordinator; rank k reads shard `<prefix>-%05d` % k (same
convention as `lr_worker.cc:210`). Each process sees only its own
devices (CPU here), so this exercises the true multi-process path:
rendezvous, global mesh, cross-process collectives.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def launch_local(num_processes: int, forward_args: list[str], port: int = 0) -> int:
    if forward_args and forward_args[0] == "--":
        forward_args = forward_args[1:]
    port = port or _free_port()
    coordinator = f"127.0.0.1:{port}"
    procs = []
    for rank in range(num_processes):
        env = dict(os.environ)
        env.update(
            XFLOW_COORDINATOR=coordinator,
            XFLOW_NUM_PROCESSES=str(num_processes),
            XFLOW_PROCESS_ID=str(rank),
            # Children MUST default to CPU: inheriting an ambient
            # accelerator platform would land every child on the same
            # device (this image pins one TPU), the world would never
            # form, and each child would silently train shard 0 as its
            # own rank 0. Real multi-host accelerator launches opt in
            # via XFLOW_LAUNCH_PLATFORM; parallel/distributed.py's
            # process-count assert catches any remaining mismatch.
            JAX_PLATFORMS=env.get("XFLOW_LAUNCH_PLATFORM", "cpu"),
        )
        cmd = [sys.executable, "-m", "xflow_tpu", "train", *forward_args]
        procs.append(subprocess.Popen(cmd, env=env))
    rc = 0
    for p in procs:
        rc = p.wait() or rc
    return rc
