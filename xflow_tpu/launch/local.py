"""Local multi-process cluster emulation.

The reference's `scripts/local.sh:16-35` forks one scheduler + S servers
+ W workers of the same binary on 127.0.0.1 with `DMLC_*` role env vars.
The SPMD analog forks N identical `xflow train` processes pointed at a
local coordinator; rank k reads shard `<prefix>-%05d` % k (same
convention as `lr_worker.cc:210`). Each process sees only its own
devices (CPU here), so this exercises the true multi-process path:
rendezvous, global mesh, cross-process collectives.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def rank_metrics_args(run_dir: str, rank: int) -> list[str]:
    """Extra `xflow train` args pointing rank `rank`'s metrics AND
    heartbeat JSONL into the run dir — ONE file per rank per stream
    (two ranks appending to one file would interleave mid-line under
    concurrent flush). Shared by launch-local and launch-dist so the
    layout (`<run_dir>/metrics_rank<k>.jsonl` +
    `<run_dir>/heartbeat_rank<k>.jsonl`, what tools/metrics_report.py
    globs and the run watchdog polls) is defined once."""
    if not run_dir:
        return []
    path = os.path.join(run_dir, f"metrics_rank{rank}.jsonl")
    hb = os.path.join(run_dir, f"heartbeat_rank{rank}.jsonl")
    return [
        "--set", f"train.metrics_path={path}",
        "--set", f"train.heartbeat_path={hb}",
    ]


def resolve_launch_run_id() -> str:
    """The run id every rank of this launch stamps: honor an
    operator-exported XFLOW_RUN_ID, else mint one PER LAUNCH (two
    launches from one driver process must not share an id, so this is
    telemetry.new_run_id, not the process-cached resolve_run_id)."""
    from xflow_tpu.telemetry import new_run_id

    return new_run_id()


def launch_local(
    num_processes: int,
    forward_args: list[str],
    port: int = 0,
    run_dir: str = "",
    straggler_factor: float = 0.0,
    dead_after_s: float = 0.0,
    watchdog_poll_s: float = 0.0,
) -> int:
    if forward_args and forward_args[0] == "--":
        forward_args = forward_args[1:]
    port = port or _free_port()
    coordinator = f"127.0.0.1:{port}"
    # one run id across all ranks: their metrics/quarantine JSONL
    # streams join on it (telemetry.resolve_run_id reads the env)
    run_id = resolve_launch_run_id()
    watchdog = None
    if run_dir:
        os.makedirs(run_dir, exist_ok=True)
        # liveness watchdog over the ranks' heartbeat streams: flags
        # dead ranks and stragglers while the run is still going
        # (launch/watchdog.py; <= 0 knobs take the module defaults)
        from xflow_tpu.launch.watchdog import RunWatchdog

        watchdog = RunWatchdog(
            run_dir,
            num_ranks=num_processes,
            straggler_factor=straggler_factor,
            dead_after_s=dead_after_s,
            poll_s=watchdog_poll_s,
            run_id=run_id,
        )
        watchdog.start()
    procs = []
    for rank in range(num_processes):
        env = dict(os.environ)
        env.update(
            XFLOW_COORDINATOR=coordinator,
            XFLOW_NUM_PROCESSES=str(num_processes),
            XFLOW_PROCESS_ID=str(rank),
            XFLOW_RUN_ID=run_id,
            # Children MUST default to CPU: inheriting an ambient
            # accelerator platform would land every child on the same
            # device (this image pins one TPU), the world would never
            # form, and each child would silently train shard 0 as its
            # own rank 0. Real multi-host accelerator launches opt in
            # via XFLOW_LAUNCH_PLATFORM; parallel/distributed.py's
            # process-count assert catches any remaining mismatch.
            JAX_PLATFORMS=env.get("XFLOW_LAUNCH_PLATFORM", "cpu"),
        )
        cmd = [
            sys.executable, "-m", "xflow_tpu", "train",
            *forward_args, *rank_metrics_args(run_dir, rank),
        ]
        procs.append(subprocess.Popen(cmd, env=env))
    rc = 0
    try:
        for p in procs:
            rc = p.wait() or rc
    finally:
        if watchdog is not None:
            watchdog.stop()
    return rc
