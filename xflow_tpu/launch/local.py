"""Local multi-process cluster emulation.

The reference's `scripts/local.sh:16-35` forks one scheduler + S servers
+ W workers of the same binary on 127.0.0.1 with `DMLC_*` role env vars.
The SPMD analog forks N identical `xflow train` processes pointed at a
local coordinator; rank k reads shard `<prefix>-%05d` % k (same
convention as `lr_worker.cc:210`). Each process sees only its own
devices (CPU here), so this exercises the true multi-process path:
rendezvous, global mesh, cross-process collectives.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import threading


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def rank_metrics_args(run_dir: str, rank: int) -> list[str]:
    """Extra `xflow train` args pointing rank `rank`'s metrics AND
    heartbeat JSONL into the run dir — ONE file per rank per stream
    (two ranks appending to one file would interleave mid-line under
    concurrent flush). Shared by launch-local and launch-dist so the
    layout (`<run_dir>/metrics_rank<k>.jsonl` +
    `<run_dir>/heartbeat_rank<k>.jsonl`, what tools/metrics_report.py
    globs and the run watchdog polls) is defined once."""
    if not run_dir:
        return []
    path = os.path.join(run_dir, f"metrics_rank{rank}.jsonl")
    hb = os.path.join(run_dir, f"heartbeat_rank{rank}.jsonl")
    return [
        "--set", f"train.metrics_path={path}",
        "--set", f"train.heartbeat_path={hb}",
    ]


def resolve_launch_run_id() -> str:
    """The run id every rank of this launch stamps: honor an
    operator-exported XFLOW_RUN_ID, else mint one PER LAUNCH (two
    launches from one driver process must not share an id, so this is
    telemetry.new_run_id, not the process-cached resolve_run_id)."""
    from xflow_tpu.telemetry import new_run_id

    return new_run_id()


def _teardown(procs) -> None:
    """TERM-then-KILL every live rank (the shared escalation,
    launch/supervise.terminate_procs)."""
    from xflow_tpu.launch.supervise import terminate_procs

    terminate_procs(procs)


def _launch_local_once(
    num_processes: int,
    forward_args: list[str],
    port: int = 0,
    run_dir: str = "",
    straggler_factor: float = 0.0,
    dead_after_s: float = 0.0,
    watchdog_poll_s: float = 0.0,
    run_id: str = "",
    gen: int = 0,
    on_dead_row=None,
    orig_world: int = 0,
) -> int:
    """One attempt: fork the ranks, watch them, return the job's exit
    code. FAIL-FAST like launch-dist: SPMD peers of a dead rank block
    in collectives forever, so the first nonzero rank exit — or a
    watchdog dead/missing verdict (a WEDGED rank, which never exits on
    its own) — tears the whole job down; the supervision wrapper
    (`launch_local`) decides whether to relaunch."""
    port = port or _free_port()
    coordinator = f"127.0.0.1:{port}"
    watchdog = None
    dead_verdict = threading.Event()
    if run_dir:
        os.makedirs(run_dir, exist_ok=True)
        # liveness watchdog over the ranks' heartbeat streams: flags
        # dead ranks and stragglers while the run is still going
        # (launch/watchdog.py; <= 0 knobs take the module defaults).
        # The on_dead policy only SETS a flag (and hands the status row
        # to the supervisor's dead-host tracker, --allow-shrink) —
        # teardown happens on the launcher thread below, never on the
        # poller thread.
        from xflow_tpu.launch.watchdog import RunWatchdog

        def on_dead(row):
            if on_dead_row is not None:
                on_dead_row(row)
            dead_verdict.set()

        watchdog = RunWatchdog(
            run_dir,
            num_ranks=num_processes,
            straggler_factor=straggler_factor,
            dead_after_s=dead_after_s,
            poll_s=watchdog_poll_s,
            run_id=run_id,
            on_dead=on_dead,
            gen=gen,
        )
        watchdog.start()
    procs = []
    for rank in range(num_processes):
        env = dict(os.environ)
        env.update(
            XFLOW_COORDINATOR=coordinator,
            XFLOW_NUM_PROCESSES=str(num_processes),
            # the launch's ORIGINAL rank count: a shrunk relaunch that
            # has no committed data_state yet (death before the first
            # checkpoint) still learns the full shard set from this —
            # without it the survivors would silently train a subset
            XFLOW_ORIG_WORLD=str(orig_world or num_processes),
            XFLOW_PROCESS_ID=str(rank),
            XFLOW_RUN_ID=run_id,
            # restart generation: stamped into every JSONL record the
            # rank emits (jsonl.JsonlAppender) so metrics_report.py can
            # segment the multi-generation streams of a supervised run
            XFLOW_RESTART_GEN=str(gen),
            # Children MUST default to CPU: inheriting an ambient
            # accelerator platform would land every child on the same
            # device (this image pins one TPU), the world would never
            # form, and each child would silently train shard 0 as its
            # own rank 0. Real multi-host accelerator launches opt in
            # via XFLOW_LAUNCH_PLATFORM; parallel/distributed.py's
            # process-count assert catches any remaining mismatch.
            JAX_PLATFORMS=env.get("XFLOW_LAUNCH_PLATFORM", "cpu"),
        )
        cmd = [
            sys.executable, "-m", "xflow_tpu", "train",
            *forward_args, *rank_metrics_args(run_dir, rank),
        ]
        procs.append(subprocess.Popen(cmd, env=env))
    from xflow_tpu.launch.supervise import wait_fail_fast

    try:
        return wait_fail_fast(
            procs, _teardown, dead_verdict=dead_verdict, label="launch-local"
        )
    except KeyboardInterrupt:
        _teardown(procs)
        raise
    finally:
        if watchdog is not None:
            watchdog.stop()


def launch_local(
    num_processes: int,
    forward_args: list[str],
    port: int = 0,
    run_dir: str = "",
    straggler_factor: float = 0.0,
    dead_after_s: float = 0.0,
    watchdog_poll_s: float = 0.0,
    max_restarts: int = 0,
    restart_backoff: float = 1.0,
    min_uptime_s: float = 0.0,
    allow_shrink: bool = False,
) -> int:
    """Run the local cluster under the supervision loop
    (launch/supervise.py): on a nonzero rank exit or a watchdog
    dead-rank verdict the whole job is torn down and — while the
    ``--max-restarts`` budget lasts — relaunched with
    ``train.resume=true`` under the SAME run dir and run id, the
    restart generation stamped into every record. With
    ``--allow-shrink``, a watchdog dead/missing verdict (the emulated
    host-loss: a WEDGED rank, vs a dead process that merely exits)
    relaunches with a SHRUNK world — the surviving rank count, ranks
    renumbered 0..M-1 — and the elastic restore reshards the
    checkpoint and re-assigns the data shards so the full record set
    stays covered (docs/ROBUSTNESS.md "Host lost"). max_restarts=0 is
    one plain un-supervised attempt."""
    from xflow_tpu.launch.supervise import (
        DeadHostTracker,
        resume_forward_args,
        supervise,
    )

    if forward_args and forward_args[0] == "--":
        forward_args = forward_args[1:]
    # one run id across all ranks AND all restart generations: their
    # metrics/quarantine/heartbeat JSONL streams join on it, and the
    # `gen` stamp keeps the generations apart within it
    run_id = resolve_launch_run_id()
    tracker = DeadHostTracker(allow_shrink)

    def attempt(gen: int) -> int:
        n = tracker.shrunk_world(num_processes)
        if n < num_processes:
            print(
                f"launch-local: relaunching generation {gen} DEGRADED at "
                f"{n}/{num_processes} rank(s) (--allow-shrink; "
                f"{len(tracker.lost)} emulated host(s) lost)",
                file=sys.stderr,
            )
        args = forward_args if gen == 0 else resume_forward_args(forward_args)
        return _launch_local_once(
            n,
            args,
            port=port,
            run_dir=run_dir,
            straggler_factor=straggler_factor,
            dead_after_s=dead_after_s,
            watchdog_poll_s=watchdog_poll_s,
            run_id=run_id,
            gen=gen,
            # one-loss-per-attempt policy (culprit ordering) lives on
            # the tracker; a local "host" is an emulated process slot
            on_dead_row=tracker.attempt_recorder(gen=gen),
            orig_world=num_processes,
        )

    return supervise(
        attempt,
        max_restarts=max_restarts,
        restart_backoff=restart_backoff,
        min_uptime_s=min_uptime_s,
        label="launch-local",
    )
