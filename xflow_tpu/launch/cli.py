"""Command-line interface.

Replaces the reference's entry point and launchers:

- `xflow train` ≙ the `xflow_lr` binary's train path
  (`/root/reference/src/model/main.cc:27-45`: argv = train-prefix,
  test-prefix, model-index, epochs) plus all the knobs the reference
  hard-codes;
- `xflow launch-local` ≙ `scripts/local.sh` (single-machine cluster
  emulation) — see launch/local.py;
- `xflow gen-data` — deterministic synthetic libffm shards;
- `xflow export` — sparse nonzero-weight export from a checkpoint.

Model indices 0/1/2 (LR/FM/MVM) are accepted for reference-CLI parity;
names are preferred. Arbitrary config overrides: `--set a.b.c=value`.
"""

from __future__ import annotations

import argparse
import json
import sys

MODEL_INDEX = {"0": "lr", "1": "fm", "2": "mvm"}


def _add_common(ap: argparse.ArgumentParser) -> None:
    ap.add_argument("--set", action="append", default=[], metavar="KEY=VALUE",
                    help="dotted config override, e.g. --set optim.name=sgd")


def _add_supervise_flags(ap: argparse.ArgumentParser) -> None:
    """Supervised auto-restart knobs shared by launch-local/launch-dist
    (launch/supervise.py). --max-restarts 0 (default) keeps the plain
    single-attempt behavior."""
    ap.add_argument("--max-restarts", type=int, default=0,
                    help="relaunch the whole job (with train.resume=true) up "
                         "to this many times after a nonzero rank exit or a "
                         "watchdog dead-rank verdict (default 0 = no "
                         "supervision)")
    ap.add_argument("--restart-backoff", type=float, default=1.0,
                    help="base seconds between restarts; doubles per attempt "
                         "with jitter, capped at 60s (default 1.0)")
    ap.add_argument("--min-uptime-s", type=float, default=0.0,
                    help="an attempt dying faster than this is treated as a "
                         "config error and NOT restarted (default 0 = always "
                         "restart while the budget lasts)")
    ap.add_argument("--allow-shrink", action="store_true",
                    help="degraded-mode supervision: a watchdog dead-HOST "
                         "verdict (unreachable across the grace window, vs a "
                         "process that merely exits) relaunches on the "
                         "surviving host set with a recomputed world size; "
                         "the elastic restore reshards the checkpoint and "
                         "re-assigns the lost rank's data shards (default: "
                         "relaunch same-shape)")


def _add_watchdog_flags(ap: argparse.ArgumentParser) -> None:
    """Liveness-watchdog knobs shared by launch-local/launch-dist
    (active with --run-dir; launch/watchdog.py). 0 = module default."""
    ap.add_argument("--straggler-factor", type=float, default=0.0,
                    help="flag a rank whose heartbeat step trails the leader "
                         "by more than this factor (default 2.0)")
    ap.add_argument("--dead-after-s", type=float, default=0.0,
                    help="flag a rank with no heartbeat for this many "
                         "seconds as dead (default 60)")
    ap.add_argument("--watchdog-poll-s", type=float, default=0.0,
                    help="heartbeat poll interval in seconds (default 2)")


def _build_config(args) -> "Config":
    from xflow_tpu.config import Config, override

    cfg = Config()
    pairs = {}
    if getattr(args, "train", None):
        pairs["data.train_path"] = args.train
    if getattr(args, "test", None):
        pairs["data.test_path"] = args.test
    if getattr(args, "model", None):
        pairs["model.name"] = MODEL_INDEX.get(args.model, args.model)
    if getattr(args, "epochs", None) is not None:
        pairs["train.epochs"] = args.epochs
    if getattr(args, "batch_size", None) is not None:
        pairs["data.batch_size"] = args.batch_size
    if getattr(args, "optimizer", None):
        pairs["optim.name"] = args.optimizer
    if getattr(args, "log2_slots", None) is not None:
        pairs["data.log2_slots"] = args.log2_slots
    if getattr(args, "checkpoint_dir", None):
        pairs["train.checkpoint_dir"] = args.checkpoint_dir
    # serve-only flags (cmd_serve's parser uses serve_* dests so the
    # launchers' unrelated --port never collides here)
    for attr, key in (
        ("serve_port", "serve.port"),
        ("serve_host", "serve.host"),
        ("serve_unix_socket", "serve.unix_socket"),
        ("serve_window_ms", "serve.window_ms"),
        ("serve_max_batch", "serve.max_batch"),
        ("serve_poll_s", "serve.reload_poll_s"),
        ("serve_metrics_path", "serve.metrics_path"),
        # fleet/router flags (cmd_serve_fleet)
        ("serve_replicas", "serve.replicas"),
        ("serve_reload_stagger_s", "serve.reload_stagger_s"),
        ("serve_route_retries", "serve.route_retries"),
        ("serve_route_deadline_ms", "serve.route_deadline_ms"),
        ("serve_route_hedge_ms", "serve.route_hedge_ms"),
        ("serve_eject_failures", "serve.eject_failures"),
        ("serve_circuit_open_s", "serve.circuit_open_s"),
        ("serve_health_poll_s", "serve.health_poll_s"),
    ):
        v = getattr(args, attr, None)
        if v is not None:
            pairs[key] = v
    for item in args.set:
        k, _, v = item.partition("=")
        pairs[k] = v
    return override(cfg, **pairs)


def cmd_train(args) -> int:
    from xflow_tpu.parallel.distributed import maybe_initialize

    rank = maybe_initialize(args.coordinator, args.num_processes, args.process_id)
    cfg = _build_config(args)

    import jax

    from xflow_tpu.parallel.mesh import make_mesh
    from xflow_tpu.train.trainer import Trainer

    mesh = None
    if not args.no_mesh and len(jax.devices()) > 1:
        mesh = make_mesh(cfg)
    trainer = Trainer(cfg, mesh=mesh, process_index=rank)
    resumed = trainer.maybe_restore()
    if resumed:
        print(f"resumed from step {int(trainer.state.step)}", file=sys.stderr)
    res = trainer.fit()
    summary = {
        "rank": rank,
        "steps": res.steps,
        "epochs": res.epochs,
        "examples": res.examples,
        "seconds": round(res.seconds, 3),
        "examples_per_sec": round(res.examples_per_sec, 1),
        "last_loss": res.last_loss,
        "occupancy": res.occupancy,
        "bad_steps": res.bad_steps,
    }
    if res.interrupted:
        # preempted: checkpoint was saved at the last step boundary; skip
        # the eval pass and report, so the grace period isn't spent there
        summary["interrupted"] = res.interrupted
        if rank == 0:
            print(json.dumps(summary))
        return 0
    # reference: only rank 0 runs predict (lr_worker.cc:211-215); here the
    # eval contains collectives, so every process participates and rank 0
    # reports/dumps
    if cfg.data.test_path:
        import jax as _jax

        if rank == 0 or _jax.process_count() > 1:
            auc, ll = trainer.evaluate()
            if rank == 0:
                summary["auc"], summary["logloss"] = auc, ll
                print(f"logloss: {ll}\tauc = {auc}", file=sys.stderr)
    if rank == 0:
        print(json.dumps(summary))
    return 0


def cmd_serve(args) -> int:
    """`xflow serve`: online pCTR inference over a committed checkpoint
    (docs/SERVING.md) — microbatched HTTP/unix-socket serving with hot
    reload when a newer checkpoint commits. The model/data config must
    match the checkpoint's (same contract as `xflow export`); pass the
    training run's --set overrides."""
    cfg = _build_config(args)
    if not cfg.train.checkpoint_dir:
        print("serve: --checkpoint-dir is required", file=sys.stderr)
        return 2
    import jax

    from xflow_tpu.parallel.mesh import make_mesh
    from xflow_tpu.serve.server import serve_main

    mesh = None
    if not args.no_mesh and len(jax.devices()) > 1:
        mesh = make_mesh(cfg)
        if cfg.serve.max_batch % mesh.shape["data"] != 0:
            print(
                f"serve: serve.max_batch={cfg.serve.max_batch} must divide "
                f"by the mesh data axis ({mesh.shape['data']})",
                file=sys.stderr,
            )
            return 2
    try:
        return serve_main(cfg, mesh=mesh)
    except (FileNotFoundError, RuntimeError) as e:
        print(f"serve: cannot load a checkpoint: {e}", file=sys.stderr)
        return 1


def cmd_serve_fleet(args) -> int:
    """`xflow serve-fleet`: N supervised `xflow serve` replicas on
    distinct ports behind the health-checked failover router
    (serve/fleet.py, docs/SERVING.md "Fleet") — retries, circuit
    breaking, staggered hot reload, ordered drain. The serving analog
    of `launch-local --max-restarts`."""
    cfg = _build_config(args)
    if not cfg.train.checkpoint_dir:
        print("serve-fleet: --checkpoint-dir is required", file=sys.stderr)
        return 2

    from xflow_tpu.serve.fleet import fleet_main

    # the per-replica `xflow serve` argv: every serve-relevant flag the
    # operator passed, minus the fleet-owned ones (--port is per
    # replica, --metrics-path per replica under --run-dir)
    serve_args = ["--checkpoint-dir", args.checkpoint_dir]
    if args.serve_host:
        # the replicas must bind the same host the router dials
        serve_args += ["--host", args.serve_host]
    if args.model:
        serve_args += ["--model", args.model]
    if args.log2_slots is not None:
        serve_args += ["--log2-slots", str(args.log2_slots)]
    if args.serve_window_ms is not None:
        serve_args += ["--window-ms", str(args.serve_window_ms)]
    if args.serve_max_batch is not None:
        serve_args += ["--max-batch", str(args.serve_max_batch)]
    if args.serve_poll_s is not None:
        serve_args += ["--poll-s", str(args.serve_poll_s)]
    if args.no_mesh:
        serve_args += ["--no-mesh"]
    for item in args.set:
        serve_args += ["--set", item]
    return fleet_main(
        cfg, serve_args, run_dir=args.run_dir,
        max_restarts=args.max_restarts,
        restart_backoff=args.restart_backoff,
        min_uptime_s=args.min_uptime_s,
    )


def cmd_gen_data(args) -> int:
    from xflow_tpu.data.synth import generate_shards, generate_shards_bulk

    if args.bulk:
        if args.truth != "linear":
            print("--bulk supports only the linear truth (the vectorized "
                  "writer has no field-pair mode)", file=sys.stderr)
            return 2
        paths, _ = generate_shards_bulk(
            args.out_prefix, args.shards, args.rows,
            num_fields=args.fields, ids_per_field=args.ids_per_field,
            seed=args.seed, truth_seed=args.truth_seed,
            zipf_alpha=args.zipf_alpha,
        )
        print("\n".join(paths))
        return 0
    paths = generate_shards(
        args.out_prefix, args.shards, args.rows,
        num_fields=args.fields, ids_per_field=args.ids_per_field, seed=args.seed,
        truth_seed=args.truth_seed, zipf_alpha=args.zipf_alpha,
        truth=args.truth,
    )
    print("\n".join(paths))
    return 0


def cmd_export(args) -> int:
    import os

    import numpy as np

    from xflow_tpu.train.checkpoint import export_sparse_array, latest_step

    step = latest_step(args.checkpoint_dir)
    if step is None:
        print(f"no committed checkpoint in {args.checkpoint_dir}", file=sys.stderr)
        return 1
    data = np.load(os.path.join(args.checkpoint_dir, f"step_{step}", "state.npz"))
    key = f"tables/{args.table}"
    if key in data:
        arr = data[key]
    elif args.table in ("w", "v") and "tables/wv" in data:
        # fused FM layout: w is column 0, v the rest (models/fm.py)
        wv = data["tables/wv"]
        arr = wv[:, 0] if args.table == "w" else wv[:, 1:]
    else:
        have = sorted(k.split("/", 1)[1] for k in data.files if k.startswith("tables/"))
        print(f"no table {args.table!r} in checkpoint; have {have}", file=sys.stderr)
        return 1
    n = export_sparse_array(arr, args.out)
    print(json.dumps({"step": step, "table": args.table, "nonzero": n}))
    return 0


def cmd_collisions(args) -> int:
    from xflow_tpu.tools.collisions import measure

    print(json.dumps(measure(args.paths, args.log2_slots, args.salt)))
    return 0


def cmd_launch_local(args) -> int:
    from xflow_tpu.launch.local import launch_local

    return launch_local(
        args.num_processes, args.forward, port=args.port, run_dir=args.run_dir,
        straggler_factor=args.straggler_factor, dead_after_s=args.dead_after_s,
        watchdog_poll_s=args.watchdog_poll_s,
        max_restarts=args.max_restarts, restart_backoff=args.restart_backoff,
        min_uptime_s=args.min_uptime_s, allow_shrink=args.allow_shrink,
    )


def cmd_launch_multislice(args) -> int:
    from xflow_tpu.parallel.multislice import launch_multislice

    return launch_multislice(
        args.slices, args.forward, run_dir=args.run_dir,
        straggler_factor=args.straggler_factor, dead_after_s=args.dead_after_s,
        watchdog_poll_s=args.watchdog_poll_s,
        max_restarts=args.max_restarts, restart_backoff=args.restart_backoff,
        min_uptime_s=args.min_uptime_s,
    )


def cmd_launch_dist(args) -> int:
    from xflow_tpu.launch.dist import launch_dist, parse_hosts

    hosts = list(args.host or [])
    if args.hosts:
        hosts = parse_hosts(args.hosts) + hosts
    if len(hosts) < 2:
        print("launch-dist needs >= 2 hosts (--hosts FILE or repeated --host)",
              file=sys.stderr)
        return 2
    for kv in args.env or []:
        if "=" not in kv:
            print(f"--env expects K=V, got {kv!r}", file=sys.stderr)
            return 2
    env_extra = dict(kv.split("=", 1) for kv in (args.env or []))
    return launch_dist(
        hosts, args.forward, port=args.port, ssh_cmd=args.ssh_cmd,
        workdir=args.workdir, python=args.python, env_extra=env_extra,
        dry_run=args.dry_run, run_dir=args.run_dir,
        straggler_factor=args.straggler_factor, dead_after_s=args.dead_after_s,
        watchdog_poll_s=args.watchdog_poll_s,
        max_restarts=args.max_restarts, restart_backoff=args.restart_backoff,
        min_uptime_s=args.min_uptime_s, allow_shrink=args.allow_shrink,
    )


def _apply_platform_env() -> None:
    """Honor JAX_PLATFORMS / XFLOW_NUM_CPU_DEVICES even when an ambient
    site config pins another platform (this image pins a TPU plugin)."""
    import os

    plat = os.environ.get("JAX_PLATFORMS")
    ncpu = os.environ.get("XFLOW_NUM_CPU_DEVICES")
    if plat or ncpu:
        import jax

        if plat:
            jax.config.update("jax_platforms", plat)
        if ncpu:
            jax.config.update("jax_num_cpu_devices", int(ncpu))


def main(argv=None) -> int:
    _apply_platform_env()
    ap = argparse.ArgumentParser(prog="xflow", description="TPU-native sparse CTR training")
    sub = ap.add_subparsers(dest="cmd", required=True)

    tr = sub.add_parser("train", help="train a model (LR/FM/FFM/MVM)")
    tr.add_argument("--train", required=True, help="train shard prefix (reads <prefix>-%%05d)")
    tr.add_argument("--test", default="", help="test shard prefix")
    tr.add_argument("--model", default="lr",
                    help="lr|fm|mvm|ffm or reference index 0|1|2")
    tr.add_argument("--epochs", type=int, default=None)
    tr.add_argument("--batch-size", type=int, default=None)
    tr.add_argument("--optimizer", default=None, help="ftrl|sgd")
    tr.add_argument("--log2-slots", type=int, default=None)
    tr.add_argument("--checkpoint-dir", default=None)
    tr.add_argument("--no-mesh", action="store_true", help="force single-device")
    tr.add_argument("--coordinator", default=None, help="host:port of rank 0 (multi-host)")
    tr.add_argument("--num-processes", type=int, default=None)
    tr.add_argument("--process-id", type=int, default=None)
    _add_common(tr)
    tr.set_defaults(fn=cmd_train)

    sv = sub.add_parser(
        "serve",
        help="online pCTR inference over a committed checkpoint "
             "(microbatching + hot reload; docs/SERVING.md)",
    )
    sv.add_argument("--checkpoint-dir", required=True,
                    help="run dir holding COMMITTED checkpoints; the newest "
                         "loads at startup and newer commits hot-reload")
    sv.add_argument("--model", default=None,
                    help="model of the checkpoint (lr|fm|mvm|ffm); must match")
    sv.add_argument("--log2-slots", type=int, default=None)
    sv.add_argument("--port", dest="serve_port", type=int, default=None,
                    help="TCP port (default 8000; 0 = pick free, reported in "
                         "the ready line; -1 = unix socket only)")
    sv.add_argument("--host", dest="serve_host", default=None)
    sv.add_argument("--unix-socket", dest="serve_unix_socket", default=None,
                    help="also (or only) serve HTTP over this AF_UNIX path")
    sv.add_argument("--window-ms", dest="serve_window_ms", type=float,
                    default=None,
                    help="microbatch coalescing window (default 2.0)")
    sv.add_argument("--max-batch", dest="serve_max_batch", type=int,
                    default=None,
                    help="rows per device batch = compiled batch shape "
                         "(default 256)")
    sv.add_argument("--poll-s", dest="serve_poll_s", type=float, default=None,
                    help="hot-reload checkpoint poll interval (default 2.0)")
    sv.add_argument("--metrics-path", dest="serve_metrics_path", default=None,
                    help="kind=serve telemetry JSONL (QPS/latency windows + "
                         "reload events; tools/metrics_report.py reads it)")
    sv.add_argument("--no-mesh", action="store_true", help="force single-device")
    _add_common(sv)
    sv.set_defaults(fn=cmd_serve)

    sf = sub.add_parser(
        "serve-fleet",
        help="N supervised serve replicas behind a health-checked "
             "failover router (retries, circuit breaking, staggered hot "
             "reload; docs/SERVING.md)",
    )
    sf.add_argument("--checkpoint-dir", required=True,
                    help="run dir holding COMMITTED checkpoints (every "
                         "replica loads + hot-reloads from it)")
    sf.add_argument("--model", default=None,
                    help="model of the checkpoint (lr|fm|mvm|ffm); must match")
    sf.add_argument("--log2-slots", type=int, default=None)
    sf.add_argument("--replicas", dest="serve_replicas", type=int, default=None,
                    help="replica count (default 2); each is one "
                         "supervised `xflow serve` on its own port")
    sf.add_argument("--port", dest="serve_port", type=int, default=None,
                    help="ROUTER port, the client-facing one (default "
                         "8000; 0 = pick free, reported in the ready "
                         "line); replicas always pick their own")
    sf.add_argument("--host", dest="serve_host", default=None)
    sf.add_argument("--window-ms", dest="serve_window_ms", type=float,
                    default=None,
                    help="per-replica microbatch coalescing window")
    sf.add_argument("--max-batch", dest="serve_max_batch", type=int,
                    default=None, help="per-replica rows per device batch")
    sf.add_argument("--poll-s", dest="serve_poll_s", type=float, default=None,
                    help="per-replica hot-reload poll interval")
    sf.add_argument("--reload-stagger-s", dest="serve_reload_stagger_s",
                    type=float, default=None,
                    help="replica k delays a noticed reload by k * this "
                         "(default 1.0) — never every replica swapping "
                         "at once")
    sf.add_argument("--retries", dest="serve_route_retries", type=int,
                    default=None,
                    help="router retries on another replica after a "
                         "connect failure / 503 (default 2)")
    sf.add_argument("--deadline-ms", dest="serve_route_deadline_ms",
                    type=float, default=None,
                    help="per-request routing budget (default 2000)")
    sf.add_argument("--hedge-ms", dest="serve_route_hedge_ms", type=float,
                    default=None,
                    help="tail-latency hedge delay (default 0 = off)")
    sf.add_argument("--eject-failures", dest="serve_eject_failures", type=int,
                    default=None,
                    help="consecutive failures ejecting a replica into "
                         "circuit OPEN (default 3)")
    sf.add_argument("--circuit-open-s", dest="serve_circuit_open_s",
                    type=float, default=None,
                    help="OPEN hold before the half-open probe (default 2)")
    sf.add_argument("--health-poll-s", dest="serve_health_poll_s", type=float,
                    default=None,
                    help="replica /healthz poll cadence (default 0.5)")
    sf.add_argument("--run-dir", default="",
                    help="collect fleet telemetry here: "
                         "<run-dir>/serve_replica<k>.jsonl + "
                         "serve_router.jsonl + replica<k>.log, one shared "
                         "run_id; summarize with tools/metrics_report.py")
    sf.add_argument("--max-restarts", type=int, default=0,
                    help="per-replica supervised restarts after a crash "
                         "(default 0 = a dead replica stays dead)")
    sf.add_argument("--restart-backoff", type=float, default=1.0,
                    help="base seconds between one replica's restarts "
                         "(exponential + jitter, capped 60s)")
    sf.add_argument("--min-uptime-s", type=float, default=0.0,
                    help="a replica dying faster than this stops its "
                         "supervision (crash loop = config error)")
    sf.add_argument("--no-mesh", action="store_true",
                    help="force single-device replicas")
    _add_common(sf)
    sf.set_defaults(fn=cmd_serve_fleet)

    gd = sub.add_parser("gen-data", help="generate synthetic libffm shards")
    gd.add_argument("out_prefix")
    gd.add_argument("--shards", type=int, default=3)
    gd.add_argument("--rows", type=int, default=1000)
    gd.add_argument("--fields", type=int, default=18)
    gd.add_argument("--ids-per-field", type=int, default=500)
    gd.add_argument("--seed", type=int, default=0)
    gd.add_argument("--truth-seed", type=int, default=None,
                    help="seed for the planted ground truth (default: --seed); use the "
                         "same value for train/test splits generated with different --seed")
    gd.add_argument("--zipf-alpha", type=float, default=0.0,
                    help="power-law feature skew (0 = uniform; ~1.1 ≈ CTR-like)")
    gd.add_argument("--truth", default="linear",
                    help="planted concept: linear | ffm (field-pair "
                         "interactions with non-separable signs — the "
                         "field-aware-model learnability gate)")
    gd.add_argument("--bulk", action="store_true",
                    help="chunked vectorized writer for realistic-scale datasets "
                         "(~30x faster; different RNG stream than the default)")
    gd.set_defaults(fn=cmd_gen_data)

    ex = sub.add_parser("export", help="export nonzero weights from a checkpoint")
    ex.add_argument("checkpoint_dir")
    ex.add_argument("--table", default="w")
    ex.add_argument("--out", required=True)
    ex.set_defaults(fn=cmd_export)

    co = sub.add_parser("collisions", help="measure feature-hash collision rate on libffm files")
    co.add_argument("paths", nargs="+")
    co.add_argument("--log2-slots", type=int, default=22)
    co.add_argument("--salt", type=int, default=0)
    co.set_defaults(fn=cmd_collisions)

    ll = sub.add_parser("launch-local", help="fork a local multi-process cluster (scripts/local.sh analog)")
    ll.add_argument("--num-processes", type=int, default=2)
    ll.add_argument("--port", type=int, default=0, help="coordinator port (0 = pick free)")
    ll.add_argument("--run-dir", default="",
                    help="collect per-rank telemetry here: each rank writes "
                         "<run-dir>/metrics_rank<k>.jsonl (overrides any "
                         "train.metrics_path in the forwarded args) and all "
                         "ranks share one run_id; summarize with "
                         "tools/metrics_report.py")
    _add_watchdog_flags(ll)
    _add_supervise_flags(ll)
    ll.add_argument("forward", nargs=argparse.REMAINDER,
                    help="-- followed by `xflow train` args to run in every process")
    ll.set_defaults(fn=cmd_launch_local)

    lm = sub.add_parser(
        "launch-multislice",
        help="emulate N slices with bounded-staleness table sync "
             "across them (sync.mode/staleness_k; each slice is an "
             "independent supervised `xflow train`; "
             "docs/DISTRIBUTED.md 'Multi-slice bounded staleness')",
    )
    lm.add_argument("--slices", type=int, default=2,
                    help="slice count (default 2); each slice is its own "
                         "single-process training world exchanging table "
                         "deltas via <run-dir>/sync")
    lm.add_argument("--run-dir", required=True,
                    help="REQUIRED shared run dir: the sync tier lives in "
                         "<run-dir>/sync (deltas, snapshots, "
                         "membership.json) and slice j writes "
                         "<run-dir>/metrics_rank<j>.jsonl + "
                         "heartbeat_rank<j>.jsonl; summarize with "
                         "tools/metrics_report.py")
    _add_watchdog_flags(lm)
    _add_supervise_flags(lm)
    lm.add_argument("forward", nargs=argparse.REMAINDER,
                    help="-- followed by `xflow train` args for every "
                         "slice; the literal {slice} substitutes to the "
                         "slice index (per-slice --train prefix / "
                         "--checkpoint-dir)")
    lm.set_defaults(fn=cmd_launch_multislice)

    ld = sub.add_parser(
        "launch-dist",
        help="start one rank per machine over ssh (run_ps_dist.sh analog; "
             "see docs/DISTRIBUTED.md)",
    )
    ld.add_argument("--hosts", help="hosts file: one host per line, first = rank 0 "
                                    "(scripts/hosts shape)")
    ld.add_argument("--host", action="append",
                    help="repeatable inline host (appended after --hosts entries)")
    ld.add_argument("--port", type=int, default=29431, help="coordinator port on host 0")
    ld.add_argument("--ssh-cmd", default="ssh",
                    help="remote runner prefix (default ssh; e.g. 'ssh -i key')")
    ld.add_argument("--workdir", default="",
                    help="remote working dir; {rank}/{host} placeholders supported")
    ld.add_argument("--python", default="", help="remote python (default python3)")
    ld.add_argument("--env", action="append", metavar="K=V",
                    help="extra env for every rank (repeatable)")
    ld.add_argument("--run-dir", default="",
                    help="REMOTE dir (shared filesystem recommended) for "
                         "per-rank telemetry: each rank writes "
                         "<run-dir>/metrics_rank<k>.jsonl and all ranks share "
                         "one run_id (XFLOW_RUN_ID); summarize with "
                         "tools/metrics_report.py")
    ld.add_argument("--dry-run", action="store_true",
                    help="print the per-host command lines instead of running")
    _add_watchdog_flags(ld)
    _add_supervise_flags(ld)
    ld.add_argument("forward", nargs=argparse.REMAINDER,
                    help="-- followed by `xflow train` args to run on every host")
    ld.set_defaults(fn=cmd_launch_dist)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
