"""Configuration tree for xflow-tpu.

The reference scatters its configuration across three primitive layers
(SURVEY.md §5 "Config / flag system"): positional argv
(`/root/reference/src/model/main.cc:16-45`), `DMLC_*` env vars for
topology, and hard-coded constants (FTRL hyperparams
`/root/reference/src/optimizer/ftrl.h:17-20`, SGD lr `sgd.h:16`, latent
dim `ftrl.h:16`, IO block size `lr_worker.h:68`). Here everything lives
in one dataclass tree with CLI/env overrides (see launch/cli.py).

Defaults reproduce the reference's hard-coded values so that a default
run is hyperparameter-equivalent to the reference's default run.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass(frozen=True)
class FTRLConfig:
    """FTRL-proximal hyperparameters.

    Defaults match `/root/reference/src/optimizer/ftrl.h:17-20`.
    """

    alpha: float = 5e-2
    beta: float = 1.0
    lambda1: float = 5e-5
    lambda2: float = 10.0


@dataclass(frozen=True)
class SGDConfig:
    """SGD hyperparameters. Default lr matches `/root/reference/src/optimizer/sgd.h:16`."""

    lr: float = 1e-3


@dataclass(frozen=True)
class OptimConfig:
    """Optimizer selection.

    The reference selects the optimizer by editing
    `/root/reference/src/model/server.h:24-29`; here it is config.
    `v_init_scale` / `v_init_sgd` reproduce the lazy v-table inits
    (`ftrl.h:117` ~N(0,1)*1e-2; `sgd.h:69` constant 1e-3).
    """

    name: str = "ftrl"  # "ftrl" | "sgd"
    ftrl: FTRLConfig = field(default_factory=FTRLConfig)
    sgd: SGDConfig = field(default_factory=SGDConfig)
    v_init_scale: float = 1e-2
    v_init_sgd: float = 1e-3
    # fused scatter+FTRL (ops/sorted_table.scatter_ftrl_sorted): the
    # single-device sorted FM step applies the optimizer INSIDE the
    # windowed scatter's block write (in-place state aliasing), so the
    # [S/8, 8K] table gradient never materializes in HBM. Measured
    # throughput-NEUTRAL vs the two-pass form (XLA already fuses that
    # chain; docs/PERF.md lever 5b) — the win is one table-sized
    # transient off peak HBM (738 MB at 2^24 FM). "auto" (default)
    # fuses the eligible FM config (ftrl + fused FM + flat sorted plan,
    # single device); "on" additionally covers the MVM product path —
    # measured ~3% slower there, so its memory win is an explicit
    # opt-in — and asserts eligibility loudly; "off" keeps the
    # two-pass form. Identical math either way (equality-tested; the
    # update runs on each window's COMPLETE gradient block; on-device
    # scatter_ftrl_* parity checks).
    fused_scatter: str = "auto"


@dataclass(frozen=True)
class ModelConfig:
    """Model selection and dims.

    `v_dim` default matches the reference latent dim
    (`/root/reference/src/optimizer/ftrl.h:16`, `fm_worker.h:92`).
    `num_fields` bounds the libffm field-group ids (bundled data uses 18,
    fields 0..17). `fm_standard` selects the textbook FM second-order
    term (per-latent-dim, with the 1/2 factor); the reference's FM
    couples latent dims through a shared accumulator
    (`/root/reference/src/model/fm/fm_worker.cc:178-196` sums v over all
    k into one scalar per row) — an accident SURVEY.md §7 says to fix,
    not replicate. Default is the standard form.
    """

    name: str = "lr"  # "lr" | "fm" | "mvm" | "ffm"
    v_dim: int = 10
    num_fields: int = 18
    # MVM exclusive-fields product path (models/mvm.py): when every
    # masked (row, field) has at most one occurrence — the natural
    # libffm shape — the field product collapses to a product over the
    # row's occurrences, computed through the same cache-resident
    # [B, ~24] row-sum kernel FM uses instead of the [B·nf, k+1]
    # segment aggregate (the measured MVM wall, docs/PERF.md 3a).
    # "auto": check each batch on the host and route duplicate-field
    # batches to the segment path. Single-process routes locally; the
    # multi-process fullshard engine coordinates the per-batch choice
    # through a rank-symmetric flag allgather
    # (trainer._resolve_fullshard_overflow) so every rank picks the
    # same mode; other multi-process engines raise on duplicates (no
    # coordination point — models/mvm.py resolve_mvm_product). "on":
    # require exclusive fields (raise on duplicates). "off": always
    # the general segment path.
    mvm_exclusive: str = "auto"
    # MVM factor form: False = plain view-sum product Π_f s (the
    # reference's live forward, mvm_worker.cc:202); True = Π_f (1 + s),
    # the bias-augmented form its OWN hand gradient assumes
    # (mvm_worker.cc:153-157 divides by 1 + v_sum; the `1+` forward is
    # commented out at :201). The plus-one form is what makes MVM
    # learnable from small inits: factors sit near 1 instead of near 0,
    # so the product — and every gradient, itself a product of the
    # row's OTHER factors — does not vanish multiplicatively with the
    # field count. Works on both the product and segment paths.
    mvm_plus_one: bool = False
    fm_standard: bool = True
    fm_half: bool = True
    # fused [S, 1+k] w+v table (one gather+scatter pass instead of two;
    # same math — docs/PERF.md lever 1). False = reference's two-table
    # layout (`fm_worker.cc:227-242`)
    fm_fused: bool = True


@dataclass(frozen=True)
class DataConfig:
    """Input pipeline configuration.

    `log2_slots` replaces the reference's unbounded 64-bit key space
    (hash of the feature-id string, `load_data_from_disk.cc:151`, stored
    sparsely in server hash maps) with a dense `2**log2_slots` table;
    collisions are accepted, as in the reference, and measurable via
    tools/collisions. `max_nnz` is the padded per-row feature capacity
    (bundled data has ~18). `block_bytes` mirrors the reference reader's
    block-buffered fread (`lr_worker.h:68` block_size=2 MiB).
    """

    train_path: str = ""
    test_path: str = ""
    batch_size: int = 1024
    max_nnz: int = 32
    log2_slots: int = 22
    hash_salt: int = 0
    block_bytes: int = 2 << 20
    drop_remainder: bool = False  # reference drops remainder rows (lr_worker.cc:190); we pad instead
    use_native_parser: bool = True  # C++ parser if built; falls back to Python
    # parser worker threads (reference: hardware_concurrency() pool,
    # thread_pool.h:70-86). 0 = auto (one per core, capped 16); 1 = the
    # sequential parser. Output is byte-identical either way (blocks are
    # reassembled in file order).
    parser_threads: int = 0
    # sorted-window table layout (ops/sorted_table.py): "auto" enables it
    # for single-device fused-FM and MVM training (where the windowed MXU
    # gather/scatter replaces latency-bound random HBM access); "on"/"off"
    # force it. Identical math either way (equality-tested).
    sorted_layout: str = "auto"
    # bf16 fast mode for the sorted-window Pallas kernels: table values
    # are read (and gradient rows written) through a single bf16 MXU
    # pass (8 mantissa bits) instead of the f32-accurate 3-term
    # decomposition — the standard bf16-training trade, +24% FM
    # throughput. Default off: table reads are then bit-exact and
    # gradients differ from the row-major path only in f32 summation
    # order (≤1 ulp per accumulated pair, as between any two reduction
    # schedules).
    sorted_bf16: bool = False
    # sub-batches per step for the sorted layout: the forward maps over
    # row-contiguous sub-batches so per-row aggregates stay cache-resident
    # (matters for MVM's [B·nf, k]); the optimizer still updates once per
    # batch, so the math is NS-invariant. 0 = auto (1 for FM; for MVM the
    # smallest power of two keeping B/NS·nf·(k+1)·4B under 16 MiB — the
    # measured sweet spot on v5e, docs/PERF.md).
    sorted_sub_batches: int = 0
    # which sorted engine runs on a device mesh:
    # - "fullshard" (default): table + optimizer state sharded over the
    #   WHOLE mesh, P(('data','table')) — each device owns S/(D*T) slots,
    #   occurrences travel to their slot owners by one all_to_all, row
    #   aggregates return by one psum_scatter + psum, and the table
    #   gradient never leaves its device (parallel/sorted_fullshard.py).
    #   The 1B-feature regime (12 GB+ FTRL state) requires this layout.
    # - "replicated": table sharded on the 'table' axis only, replicated
    #   across 'data' (D× table memory; parallel/sorted_sharded.py) —
    #   fewer collectives, viable when the table fits per-device HBM.
    sorted_mesh: str = "fullshard"
    # host-side batch dedup for the ROW-MAJOR paths (reference analog:
    # per-minibatch unique-key Pull, lr_worker.cc:150-165): ship
    # (unique_slots, inverse) so the table gather moves U rows instead
    # of B*F (ops/sorted_table.dedup_slots). DEFAULT OFF, from
    # measurement: with packed tables the single-chip two-level gather
    # LOSES at every tested skew (hot-head U=168k: 303k vs 503k ex/s
    # direct — the [B, F] re-index gather costs as much as the direct
    # gather it replaces; docs/PERF.md lever 4). Turn "auto" on for
    # multi-chip GSPMD eval/fallback paths, where the win is CROSS-CHIP
    # gather volume over ICI (U rows instead of B*F through the
    # collectives), not local HBM traffic. "auto" applies to
    # single-process row-major batches only (multi-process cannot dedup
    # per batch: the unique count is data-dependent and the overflow
    # fallback would bake different collective programs on different
    # ranks); capacity = dedup_cap_frac * batch_size * max_nnz, the
    # first batch decides for the run.
    dedup: str = "off"
    dedup_cap_frac: float = 0.5
    # packed table storage (ops/sorted_table.py pack_table): vector
    # tables live as [S/8, 8K] instead of [S, K]. TPU HBM buffers are
    # (8, 128)-tiled, so a logical [S, 11] f32 table is STORED [S, 128]
    # — 11.6x its logical bytes (at 2^24 slots the FM FTRL state alone
    # is 3 x 8 GB and cannot fit one chip) and every elementwise
    # optimizer pass runs at 11/128 lane efficiency. Packed: 1.45x
    # padding and 88/128-lane FTRL. "auto" (default) packs whenever
    # num_slots % 8 == 0; "off" keeps logical [S, K] storage. Layout is
    # detected FROM THE SHAPE everywhere (pack_of), so hand-built
    # logical tables and old checkpoints keep working.
    packed_tables: str = "auto"
    # per-(source shard, owner block) occurrence buffer capacity, as a
    # multiple of the uniform-hash expectation Np/(D*T). Salted hashing
    # spreads slots near-uniformly, but a single hot feature's
    # occurrences all land in ONE owner block (the ps-lite analog has the
    # same imbalance: one server owns the hot key) — raise this for
    # heavily skewed data; overflow fails loudly at plan time.
    fullshard_slack: float = 2.0
    # packed shard cache (data/shardcache.py, docs/DATA.md): pre-hashed
    # binary sidecars (`<shard>.xfc`, built once by
    # `criteo_convert cache`) replace the per-epoch read/parse/hash/
    # batch/pad producer stages with np.memmap zero-copy slices —
    # batch assembly becomes an offset computation. "auto" (default)
    # uses a shard's cache whenever one exists, is fresh for this
    # config's hash parameters, and passes its crc32 digests (a stale
    # cache warns and falls back; a CORRUPT one is quarantined with a
    # logged text-path fallback — never a crash); "on" requires caches
    # to exist (missing/stale raise loudly; corruption still only
    # degrades); "off" never looks. Batches are bitwise-identical to
    # the text path's either way (pinned by tests/test_shardcache.py).
    cache: str = "auto"
    # where the .xfc files live: "" = sibling of each text shard; a
    # directory = `<cache_dir>/<shard basename>.xfc` (fast local disk
    # for caches of shards on slow shared storage)
    cache_dir: str = ""
    # bad-record budget (docs/ROBUSTNESS.md): a "bad" row is a labeled
    # line whose features ALL failed to parse (zero masked occurrences).
    # Both parsers keep such rows (a labeled line is an example), so an
    # entire epoch of garbage would train in silently — the reference
    # does exactly that (`load_data_from_disk.cc:150-153` skips
    # malformed tokens with no signal). Detection is batch-level
    # (row_mask on, feature mask all-zero), so the Python and native
    # parsing paths count identically. -1 = count + warn only; >= 0 =
    # raise BadRecordError once a file pass exceeds the budget.
    max_bad_rows: int = -1
    # "" = off; else bad rows are appended to this JSONL file
    # (source path, batch/row index, label) for offline triage
    quarantine_path: str = ""
    # ---- streaming source (docs/DATA.md "Streaming source") ----------
    # "off" (default): the exact batch pipeline above — every existing
    # stream stays byte-identical (no ingest records, no tail thread).
    # "tail": follow-the-tail mode — watch the train_path shard set for
    # new/growing libffm files, cut each poll's newly COMPLETED lines
    # into an immutable spool segment, convert it on arrival into a
    # packed .xfc cache (shardcache.write_shard_cache) so streamed data
    # rides the same device-rate path batch training does, and stamp
    # each segment with an ingest trace id (kind="ingest" record) the
    # freshness tooling follows across the train/serve boundary.
    stream: str = "off"
    # directory poll cadence while tailing (seconds)
    stream_poll_s: float = 0.25
    # end-of-stream idle timeout: no new complete rows for this long
    # ends the tail stream and the run (0 = follow forever). CI drills
    # set it so a tail run is bounded.
    stream_idle_s: float = 0.0
    # where spool segments and their .xfc caches land ("" = an
    # .xfstream dir next to the watched shards)
    stream_dir: str = ""


@dataclass(frozen=True)
class MeshConfig:
    """Device mesh: ('data', 'table').

    `data` is the analog of the reference's N worker processes
    (file-sharded async data parallelism), `table` the analog of its N
    key-range-sharded server processes (SURVEY.md §2 C13). -1 means
    "infer from available devices".
    """

    data: int = -1
    table: int = 1


@dataclass(frozen=True)
class TrainConfig:
    epochs: int = 60  # reference default (lr_worker.h:63)
    seed: int = 0
    eval_every: int = 0  # 0 = eval only at end, like the reference
    log_every: int = 100
    checkpoint_dir: str = ""
    checkpoint_every: int = 0  # steps; 0 = only at end if dir set
    checkpoint_format: str = "npz"  # "npz" (host-gathered) | "orbax" (sharded OCDBT)
    resume: bool = True
    pred_dump: bool = True  # write pred_<rank>_<block>.txt like lr_worker.cc:74-78
    # >0: streaming bucketed eval (local histograms + one collective; no
    # host ever holds the global pctr vector — the Criteo-1TB-scale path).
    # 0: exact rank-sum AUC with a host sort (reference parity,
    # base.h:84-110). -1 (default) = auto: exact when single-process,
    # 65536 buckets when multi-process — the exact path allgathers a
    # stacked [B, 3] array per eval batch, which dead-ends before
    # pod-scale eval (AUC error is bounded by bucket width, ~1/buckets).
    eval_buckets: int = -1
    metrics_path: str = ""  # JSONL per-step metrics stream ("" = stdout summary only)
    # size cap for the metrics JSONL (bytes; 0 = unbounded): past it
    # the file rolls to ONE <path>.1 sibling (jsonl.JsonlAppender), so
    # streaming/online trainers that never stop don't grow the stream
    # with uptime; read_jsonl folds the roll back in file order
    metrics_max_bytes: int = 0
    # checkpoint-lifecycle spans (docs/OBSERVABILITY.md "Request
    # tracing"): every checkpoint save/restore emits one kind="span"
    # record (start/end + bytes) into the metrics stream, so
    # tools/request_trace.py --timeline can overlay checkpoint and
    # hot-reload swaps against request-latency spikes. Off = the
    # pre-tracing record stream, byte-identical.
    ckpt_spans: bool = True
    profile_dir: str = ""  # jax.profiler trace output ("" = disabled)
    # programmatic trace window (telemetry.TraceWindow): with profile_dir
    # set and trace_start_step >= 1, the xprof trace starts just before
    # that step's dispatch — after compilation settles, so the window
    # shows the steady state instead of compile noise — and stops once
    # trace_num_steps steps have dispatched. 0 = legacy whole-run trace.
    trace_start_step: int = 0
    trace_num_steps: int = 20
    # preemption: on SIGTERM/SIGINT save a checkpoint at the next
    # coordination point and return early. Single-process coordinates
    # every step; multi-process runs agree on "stop at step N" through a
    # tiny flag allgather every `signal_sync_every` steps (a signal on
    # ANY rank stops ALL ranks at the same step, so the collective save
    # is rank-symmetric — round-2 weak #6). The reference loses all
    # weights on any termination (SURVEY.md §5 A3: server state is
    # in-memory only).
    ckpt_on_signal: bool = True
    # multi-process signal-coordination cadence, in steps (0 disables
    # the periodic allgather; preemption then degrades to the
    # checkpoint_every cadence). One [1]-int32 host allgather per
    # `signal_sync_every` steps is the entire cost.
    signal_sync_every: int = 100
    # non-finite guard (docs/ROBUSTNESS.md): every train step also
    # returns an `update_ok` flag — one jnp.isfinite reduction over the
    # loss and the updated table/optimizer leaves, computed INSIDE the
    # SPMD program so multi-process ranks agree for free (the flag is
    # replicated; no new host collectives). "skip" (default): a bad
    # step's state update is discarded on device (jnp.where on the
    # flag — no recompute), counted, and training continues; "halt":
    # abort on the first bad step, after committing a checkpoint;
    # "off": no check (a NaN batch silently poisons the tables — the
    # reference behavior).
    nonfinite_guard: str = "skip"
    # under "skip", this many CONSECUTIVE discarded steps abort anyway
    # (after a committed checkpoint): a stream of bad steps means the
    # data or the state is systematically poisoned, and skipping
    # forever would burn an epoch of compute learning nothing.
    # 0 = never abort.
    nonfinite_max_consecutive: int = 10
    # digest verification on restore (docs/ROBUSTNESS.md "Silent shard
    # corruption"): "auto" checks every stored array read against the
    # per-array digests meta.json recorded at save (checkpoint v3) —
    # a mismatch is a logged CheckpointDigestError and restore_any
    # walks back to the previous committed step; arrays without
    # digests (pre-v3 checkpoints, pod-scale multi-process orbax
    # saves) restore unverified. "off" skips the check entirely.
    checkpoint_verify: str = "auto"
    # checkpoint retention: keep the N newest COMMITTED checkpoints
    # and sweep stale uncommitted step dirs after each save (a crashed
    # save leaves a partial dir; readers already ignore it, this
    # reclaims the space). 0 = keep everything.
    keep_checkpoints: int = 0
    # asynchronous checkpointing (docs/ROBUSTNESS.md "Async tiered
    # checkpointing"): at checkpoint cadence the fit loop only SNAPSHOTS
    # — copy_to_host_async() on every table/optimizer leaf plus the
    # synchronously-captured data_state — and hands the snapshot to a
    # single background writer thread that serializes, digests, stages
    # the sidecars, and writes the COMMITTED marker last (the same
    # atomicity/walk-back contract as a synchronous save; a crash
    # mid-async-write is just the uncommitted-dir walk-back). At most
    # one save is in flight: a cadence hit while one is pending is a
    # logged, counted SKIP, never a queue; the halt/signal/end-of-fit
    # saves drain the writer so the run's last state is always durable.
    # Every async save emits one kind="ckpt" record per tier. Requires
    # a single process (the host-gather collectives cannot run on a
    # background thread; multi-process logs once and falls back to
    # synchronous saves). Default off = today's synchronous save path,
    # byte-identical (pinned by test).
    ckpt_async: bool = False
    # tier-2 checkpoint replica dir ("" = off): every committed step is
    # MIRRORED here — copy, digest re-verify of the replica's own bytes,
    # then the replica's own COMMITTED marker — so a lost/poisoned
    # primary volume costs no committed state. restore walks the UNION
    # of both tiers newest-step-first (primary preferred per step), and
    # under ckpt_async an ENOSPC/IO failure on the primary DEGRADES the
    # writer to replica-only saves instead of killing training
    # (docs/ROBUSTNESS.md failure matrix). The serve watcher reads the
    # same union, so a digest-poisoned primary hot-reloads from the
    # replica with zero dropped requests.
    ckpt_replica_dir: str = ""
    # replica-tier retention: keep_checkpoints semantics applied to
    # ckpt_replica_dir (0 = keep everything). Independent of the
    # primary's knob so the cheap tier can keep a deeper history.
    keep_replica_checkpoints: int = 0
    # in-run checkpoint publication cadence, in steps (0 = off): every
    # publish_every-th step commits a checkpoint through the atomic
    # staging contract WITH a publication.json sidecar stamped with the
    # newest ingest trace id whose data contributed to that step, and
    # emits one kind="publish" record plus one `publish` span carrying
    # that trace id — the train-side half of the freshness loop
    # (docs/SERVING.md "Freshness"). Requires checkpoint_dir.
    publish_every: int = 0
    # time-decayed sliding-window eval (streaming BucketAUC): each
    # eval_every pass multiplies the persistent bucket histograms by
    # this factor before folding the new pass in, so the logged
    # eval_auc tracks the recent window instead of restarting from
    # zero each pass. 0.0 (default) = per-pass-fresh histograms, the
    # exact pre-knob behavior.
    eval_window_decay: float = 0.0
    # model-health signals (docs/OBSERVABILITY.md "Health metrics"):
    # "norms" adds global grad-norm / update-norm / param-norm scalars to
    # every step's metrics output (fused into the jitted step — one
    # isfinite-style reduction per table, read back through the same
    # one-step-behind block the StepTimer uses, so no sync bubble) plus a
    # host-side loss EMA and live table-occupancy / collision-estimate
    # gauges; "full" additionally emits per-table norms. "off" (default)
    # leaves the step program untouched — zero overhead.
    health_metrics: str = "off"
    # loss-EMA decay for the health monitor (ema = d*ema + (1-d)*loss,
    # seeded by the first finite loss; McMahan et al. 2013 monitor
    # exactly this kind of smoothed online loss in production CTR)
    health_ema_decay: float = 0.99
    # liveness heartbeat JSONL ("" = off): one {step} record every
    # heartbeat_every steps plus start/final events, stamped
    # ts/rank/run_id/kind=heartbeat — the launcher watchdog and
    # metrics_report --health read these to flag dead ranks/stragglers
    heartbeat_path: str = ""
    heartbeat_every: int = 25
    # no-progress hang watchdog (0 = off): if no train step completes
    # for this many seconds, dump ALL thread stacks to stderr once per
    # stall (faulthandler), then re-arm when progress resumes. SIGUSR1
    # stack dumps are always installed during fit() (main thread only).
    hang_timeout_s: float = 0.0
    # input-pipeline stage profiler (docs/OBSERVABILITY.md
    # "Input-pipeline attribution"): attribute wall time per pipeline
    # stage — read/parse/hash/batch/pad/plan on the prefetch thread,
    # queue-wait/transfer/dispatch/device on the fit loop, plus the
    # prefetch queue's depth and producer-blocked gauges — into
    # kind="pipeline" window records in the metrics JSONL, read by
    # tools/pipeline_attrib.py (per-stage % table, bottleneck verdict,
    # host-gap bench record). Default off: the instrumented seams take
    # their exact pre-profiler code paths and the JSONL streams are
    # byte-identical to a build without the profiler (pinned by test).
    pipeline_metrics: bool = False
    # compile accounting (docs/OBSERVABILITY.md "Compile accounting"):
    # every step/predict compilation routes through a shared
    # telemetry.CompileRecorder — explicit .lower().compile() with the
    # compile timed and XLA's cost/memory analysis captured into
    # kind="compile" records in the metrics JSONL, plus the
    # {HLO op -> named_scope} map tools/trace_attrib.py joins traces
    # against, and the recompile counter metrics_report --check gates
    # on ("each program compiles exactly once per run"). The compile
    # itself costs the same either way (jit would have built the same
    # executable lazily); off restores the implicit-jit path.
    compile_metrics: bool = True


@dataclass(frozen=True)
class ServeConfig:
    """Online-serving knobs (`xflow serve`, docs/SERVING.md).

    The model/data/train sections still apply at serve time: the model
    config must match the checkpoint (same contract as `xflow export`),
    `data.max_nnz`/`log2_slots`/`hash_salt` define the request hash
    path (a served feature must land in the slot it trained into), and
    `train.checkpoint_dir`/`checkpoint_format`/`checkpoint_verify`
    locate and gate what gets loaded.
    """

    host: str = "127.0.0.1"
    # TCP port (0 = pick a free one, reported in the ready line;
    # -1 = no TCP listener — unix_socket only)
    port: int = 8000
    # AF_UNIX socket path ("" = off): same HTTP protocol, for colocated
    # clients (the C API's native embedder) without the TCP stack
    unix_socket: str = ""
    # microbatching (serve/coalescer.py): requests queued inside this
    # window coalesce into ONE padded device batch — the window is the
    # idle-server latency floor and the busy-server throughput lever
    window_ms: float = 2.0
    # rows per device batch = the compiled batch shape (fixed, so the
    # predict program compiles once); also the per-request row cap
    max_batch: int = 256
    # backlog cap in rows; beyond it submits shed load with 503
    max_queue_rows: int = 8192
    # hot reload: poll the checkpoint dir for a newer COMMITTED step
    # this often (serve/runner.CheckpointWatcher); 0 < poll always on
    reload_poll_s: float = 2.0
    # kind="serve" telemetry JSONL ("" = off): QPS / batch-fill /
    # latency windows + reload events (docs/OBSERVABILITY.md)
    metrics_path: str = ""
    metrics_every_s: float = 5.0
    # size cap for the serve telemetry/span JSONL (bytes; 0 = unbounded):
    # past it the file rolls to a single <path>.1 sibling, so a
    # long-running fleet's streams are bounded at ~2x this
    # (jsonl.JsonlAppender; read_jsonl folds the roll transparently)
    metrics_max_bytes: int = 0
    # ---- request tracing (xflow_tpu/tracing.py, docs/OBSERVABILITY.md
    # "Request tracing") --------------------------------------------------
    # head-sampling rate for per-request span capture: each trace id
    # keeps/drops deterministically from its own hash, so the router
    # and every replica agree with no coordination. 0 (default) = off —
    # the serve JSONL output is byte-identical to a pre-tracing build.
    trace_sample_rate: float = 0.0
    # tail capture: any request slower than this (router budget or
    # replica-observed) — and any that errors, sheds, retries, or
    # hedges — is captured regardless of the sampling rate
    trace_slow_ms: float = 250.0
    # a request unanswered this long gets 503 (the device wedged)
    request_timeout_s: float = 30.0
    # ---- fleet (serve/fleet.py, `xflow serve-fleet`) -----------------
    # replica count for `serve-fleet` (each replica is one supervised
    # `xflow serve` process on its own port; docs/SERVING.md "Fleet")
    replicas: int = 2
    # per-replica hot-reload stagger: replica k delays acting on a newer
    # committed step by k * this many seconds, so the fleet never pauses
    # every replica for a checkpoint swap at once (0 = no stagger)
    reload_stagger_s: float = 1.0
    # ---- router (serve/router.py) ------------------------------------
    # replica health-check cadence (GET /healthz per replica); the same
    # loop runs circuit-breaker recovery (the half-open probe)
    health_poll_s: float = 0.5
    # consecutive failures (failed forwards or health checks) that eject
    # a replica into circuit-breaker OPEN state
    eject_failures: int = 3
    # how long an OPEN circuit waits before its half-open probe
    circuit_open_s: float = 2.0
    # per-request routing budget: retries/hedges must fit inside it;
    # exhausted = 503 deadline_exceeded back to the client
    route_deadline_ms: float = 2000.0
    # transparent retries on a DIFFERENT replica after a connect
    # failure / 503 (the "retry later" the coalescer's shed asks for)
    route_retries: int = 2
    # tail-latency hedging: a request outstanding this long fires a
    # duplicate at another healthy replica, first answer wins (0 = off)
    route_hedge_ms: float = 0.0
    # ---- brownout admission control (serve/coalescer.py) -------------
    # backlog above high_frac * max_queue_rows sustained for after_s
    # enters brownout: the coalescing window shrinks by window_factor
    # (drain faster) and low-priority requests (X-Request-Priority: low)
    # shed with 503 BEFORE the hard max_queue_rows cliff; backlog below
    # low_frac * max_queue_rows sustained for after_s exits it.
    brownout_high_frac: float = 0.5
    brownout_low_frac: float = 0.25
    brownout_after_s: float = 0.25
    brownout_window_factor: float = 0.25
    # ---- SLO autotuning (serve/autotune.py, docs/SERVING.md
    # "Autotuning") ----------------------------------------------------
    # closed-loop controller: each flushed telemetry window's queue-wait
    # vs device p99 decomposition steers window_ms (and the ladder rung)
    # toward slo_p99_ms. Off (default) leaves every knob exactly where
    # the config put it — the serve stream is byte-identical to a
    # pre-autotune build (pinned by test, like trace_sample_rate=0).
    autotune: bool = False
    # the total-latency p99 target the controller steers toward (ms)
    slo_p99_ms: float = 25.0
    # hysteresis band: no decision while total_p99 is within
    # slo * (1 ± band_frac) — the controller converges instead of
    # chasing window-to-window noise
    autotune_band_frac: float = 0.15
    # initial multiplicative step per decision; every direction
    # reversal halves the knob's step (damping), so an overshoot
    # cannot oscillate at constant amplitude
    autotune_step_frac: float = 0.5
    # window_ms floor: asked to shrink below it, the controller pins
    # there and emits ONE floor_pinned warning (unattainable SLO must
    # not flap the knob every window)
    autotune_min_window_ms: float = 0.25
    # precompiled batch-shape ladder ("16,64,256"; "" = max_batch
    # only): every rung AOT-compiles at startup and each batch flushes
    # at the smallest rung that fits, so small batches stop paying
    # full-max_batch padding. max_batch always joins as the top rung.
    ladder: str = ""


@dataclass(frozen=True)
class SyncConfig:
    """Cross-slice bounded-staleness table sync — the DCN tier of the
    two-tier topology (parallel/multislice.py, docs/DISTRIBUTED.md
    "Multi-slice bounded staleness"). Each slice trains synchronously
    inside its own mesh; between K-step blocks a host-level SliceSyncer
    exchanges additive table deltas with the other slices through a
    shared directory, with parameter-server failure semantics
    (timeout + retry/backoff on every wait, proceed-on-stale policy,
    dead slices dropped from the sync group)."""

    # off = no sync tier at all (byte-identical trainer behavior);
    # sync = wait for every live peer's current round (K is forced 0 —
    # lockstep across slices, today's fully-sync semantics);
    # bounded = wait only until every live peer is within staleness_k
    # rounds; async = never wait, apply whatever deltas have landed
    mode: str = "off"
    # the staleness bound K, in sync ROUNDS a live peer may trail
    # before the on_stale policy triggers (bounded mode only)
    staleness_k: int = 0
    # steps between sync rounds (the K-step scan block boundary)
    every_steps: int = 50
    # the shared sync directory (deltas + snapshots + membership);
    # launch-multislice wires it to <run_dir>/sync for every slice
    dir: str = ""
    # per-wait budget before a retry; every exchange is bounded — a
    # vanished peer costs timeout_s * (retries + 1), never a hang
    timeout_s: float = 30.0
    # staleness-wait retries, backoff_s * 2^attempt (jittered, the
    # supervise.backoff_delay curve) between them
    retries: int = 3
    backoff_s: float = 0.5
    # what a missed staleness bound does after the retry budget:
    # wait = keep training only after the bounded wait (counted);
    # proceed = check once and continue on stale state (counted)
    on_stale: str = "wait"
    # publish a full-state catch-up snapshot every this many rounds
    # (0 = never); a rejoining slice adopts the freshest one
    snapshot_every: int = 10


@dataclass(frozen=True)
class Config:
    model: ModelConfig = field(default_factory=ModelConfig)
    optim: OptimConfig = field(default_factory=OptimConfig)
    data: DataConfig = field(default_factory=DataConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)
    sync: SyncConfig = field(default_factory=SyncConfig)

    @property
    def num_slots(self) -> int:
        return 1 << self.data.log2_slots


def _replace_nested(obj: Any, path: list[str], value: Any) -> Any:
    if len(path) == 1:
        fld = {f.name: f for f in dataclasses.fields(obj)}[path[0]]
        typ = fld.type
        cur = getattr(obj, path[0])
        if isinstance(cur, bool):
            if isinstance(value, str):
                value = value.lower() in ("1", "true", "yes", "on")
        elif isinstance(cur, int):
            value = int(value)
        elif isinstance(cur, float):
            value = float(value)
        return dataclasses.replace(obj, **{path[0]: value})
    child = getattr(obj, path[0])
    return dataclasses.replace(obj, **{path[0]: _replace_nested(child, path[1:], value)})


def override(cfg: Config, **dotted: Any) -> Config:
    """Apply dotted-path overrides: override(cfg, **{"optim.name": "sgd"})."""
    for key, value in dotted.items():
        cfg = _replace_nested(cfg, key.split("."), value)
    return cfg


def from_overrides(pairs: dict[str, Any], base: Optional[Config] = None) -> Config:
    return override(base or Config(), **pairs)
