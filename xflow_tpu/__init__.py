"""xflow-tpu: a TPU-native sparse CTR training framework.

A ground-up JAX/XLA rebuild of the capabilities of pandadady/xflow
(reference surveyed in SURVEY.md): distributed training of sparse
logistic regression, factorization machines, and multi-view machines
over hashed libffm features, with server-side-equivalent FTRL-proximal
and SGD optimizers.

Where the reference runs an asynchronous parameter server (ps-lite over
ZeroMQ; scheduler/server/worker roles, sparse KV Push/Pull), this
framework is synchronous SPMD over a `jax.sharding.Mesh`:

- the parameter "tables" (reference: `std::unordered_map<ps::Key, Entry>`
  on server processes, `/root/reference/src/optimizer/ftrl.h:84`) are
  dense ``[2**K]``-slot arrays sharded on the feature-hash axis;
- Pull becomes a sharded gather (``table[slots]``), Push becomes the
  scatter-add that `jax.grad` produces through that gather;
- the optimizer update (reference: server request handler,
  `/root/reference/src/optimizer/ftrl.h:38-85`) is a pure elementwise
  XLA update over the dense state arrays, fused into the train step.
"""

from xflow_tpu.version import __version__

__all__ = ["__version__"]
