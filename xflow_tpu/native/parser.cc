// Native libffm parser: the framework's C++ data plane.
//
// The reference's hot input path is a block-buffered fread parser with
// partial-line carry feeding ragged C++ vectors
// (/root/reference/src/io/load_data_from_disk.cc:103-210). This is the
// TPU-native equivalent, designed fresh for the padded-COO batch schema:
// it parses straight into caller-provided fixed-shape buffers (the numpy
// arrays that become device HBM uploads), so there is no intermediate
// ragged representation at all.
//
// Semantics kept in lockstep with data/libffm.py (the Python reference
// path) and hashing.py:
//   - label token parsed as double, label = 1 iff > 1e-7
//   - feature token "fgid:fid:value": fgid parsed as number, fid hashed
//     as a *string* with salted FNV-1a 64, value ignored
//   - slot = mix64(hash) & (2^log2_slots - 1), mix64 = xor-shift,
//     multiply by 0xD6E8FEB86659FD93, xor-shift (hashing.py slot_of)
//   - rows longer than max_nnz are truncated (truncation counted)
//
// C ABI (consumed by data/native.py via ctypes):
//   xf_hash64(bytes, len, salt) -> uint64
//   xf_parser_open(path, block_bytes) -> handle (NULL on failure)
//   xf_parser_next_batch(handle, batch_size, max_nnz, log2_slots, salt,
//                        slots*, fields*, mask*, labels*, row_mask*)
//       -> rows filled (0 = EOF, -1 = error)
//   xf_parser_truncated(handle) -> truncated-feature count so far
//   xf_parser_close(handle)

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint64_t kFnvOffset = 0xCBF29CE484222325ULL;
constexpr uint64_t kFnvPrime = 0x100000001B3ULL;
constexpr uint64_t kMixMul = 0xD6E8FEB86659FD93ULL;

inline uint64_t fnv1a64(const char* data, size_t len, uint64_t salt) {
  uint64_t h = kFnvOffset ^ salt;
  for (size_t i = 0; i < len; ++i) {
    h ^= static_cast<unsigned char>(data[i]);
    h *= kFnvPrime;
  }
  return h;
}

inline uint64_t mix64(uint64_t x) {
  x ^= x >> 32;
  x *= kMixMul;
  x ^= x >> 32;
  return x;
}

// Field id as int32 with explicit nan→0 and saturation: a raw
// static_cast from an out-of-range double is UB, and the Python path
// (data/libffm.py _fgid_i32) implements these exact semantics.
inline int32_t fgid_i32(double d) {
  if (d != d) return 0;
  if (d >= 2147483647.0) return 2147483647;
  if (d <= -2147483648.0) return INT32_MIN;
  return static_cast<int32_t>(d);
}

// Parse one CR-stripped line into padded row buffers (srow/frow/mrow are
// max_nnz-stride spans, assumed zeroed). Returns true iff the line is a
// row (non-empty with a label separator). Shared by the single-threaded
// and multi-threaded parsers so their outputs are byte-identical.
inline bool parse_row(const char* line, size_t len, long max_nnz,
                      int log2_slots, uint64_t salt, int32_t* srow,
                      int32_t* frow, float* mrow, float* label,
                      long* truncated) {
  // strip surrounding ASCII whitespace exactly like the Python path's
  // line.strip(): a label-only line with trailing spaces is NOT a row
  auto is_ws = [](char c) {
    return c == ' ' || c == '\t' || c == '\r' || c == '\v' || c == '\f';
  };
  while (len > 0 && is_ws(line[len - 1])) --len;
  while (len > 0 && is_ws(line[0])) {
    ++line;
    --len;
  }
  if (len == 0) return false;
  const char* cur = line;
  const char* lend = line + len;
  // label/features separator: the FIRST TAB if the line has one, else the
  // first space — mirroring parse_line's split("\t", 1) -> split(" ", 1)
  const char* tab =
      static_cast<const char*>(memchr(cur, '\t', static_cast<size_t>(len)));
  if (tab == nullptr)
    tab = static_cast<const char*>(memchr(cur, ' ', static_cast<size_t>(len)));
  if (tab == nullptr) return false;  // malformed: no features
  *label = (strtod(cur, nullptr) > 1e-7) ? 1.0f : 0.0f;
  cur = tab + 1;
  long nnz = 0;
  // tokens split on any whitespace, matching the Python path's .split()
  auto is_sep = is_ws;
  while (cur < lend) {
    while (cur < lend && is_sep(*cur)) ++cur;
    if (cur >= lend) break;
    const char* tok_end = cur;
    while (tok_end < lend && !is_sep(*tok_end)) ++tok_end;
    // token = fgid:fid[:value...]; value never parsed (reference
    // behavior: load_data_from_disk.cc:150-153 breaks after fid)
    const char* c1 = static_cast<const char*>(
        memchr(cur, ':', static_cast<size_t>(tok_end - cur)));
    if (c1 != nullptr) {
      const char* c2 = static_cast<const char*>(
          memchr(c1 + 1, ':', static_cast<size_t>(tok_end - c1 - 1)));
      const char* fid_end = (c2 != nullptr) ? c2 : tok_end;
      if (nnz < max_nnz) {
        frow[nnz] = fgid_i32(strtod(cur, nullptr));
        uint64_t key =
            fnv1a64(c1 + 1, static_cast<size_t>(fid_end - c1 - 1), salt);
        srow[nnz] = static_cast<int32_t>(mix64(key) &
                                         ((1ULL << log2_slots) - 1ULL));
        mrow[nnz] = 1.0f;
        ++nnz;
      } else {
        ++*truncated;
      }
    }
    cur = tok_end;
  }
  // rows with zero valid features are kept (mask all-zero), matching the
  // Python path: a labeled line is an example even if its features are
  // unparseable
  return true;
}

struct Parser {
  FILE* fp = nullptr;
  std::vector<char> buf;
  size_t pos = 0;    // next unparsed byte
  size_t end = 0;    // valid bytes in buf
  bool eof = false;
  bool error = false;  // fread failed (ferror), distinct from EOF
  long truncated = 0;

  // Returns [line, line+len) for the next complete line (without the
  // trailing newline) or nullptr at EOF. The pointer is valid until the
  // next call.
  const char* next_line(size_t* len) {
    for (;;) {
      // scan for newline in the unparsed region
      char* nl = static_cast<char*>(memchr(buf.data() + pos, '\n', end - pos));
      if (nl != nullptr) {
        const char* line = buf.data() + pos;
        *len = static_cast<size_t>(nl - line);
        pos = static_cast<size_t>(nl - buf.data()) + 1;
        return line;
      }
      if (eof) {
        if (pos < end) {  // final line without trailing newline
          const char* line = buf.data() + pos;
          *len = end - pos;
          pos = end;
          return line;
        }
        return nullptr;
      }
      // carry the partial line to the front and refill
      size_t carry = end - pos;
      if (carry > 0 && pos > 0) memmove(buf.data(), buf.data() + pos, carry);
      pos = 0;
      end = carry;
      if (end == buf.size()) {
        // a single line longer than the buffer: grow
        buf.resize(buf.size() * 2);
      }
      size_t got = fread(buf.data() + end, 1, buf.size() - end, fp);
      end += got;
      if (got == 0) {
        eof = true;
        if (ferror(fp)) {
          // I/O fault, not end-of-data: discard the buffered partial tail
          // immediately so no data from a failed read ever reaches a batch
          error = true;
          return nullptr;
        }
      }
    }
  }
};

}  // namespace

extern "C" {

uint64_t xf_hash64(const char* data, long len, uint64_t salt) {
  return fnv1a64(data, static_cast<size_t>(len), salt);
}

uint64_t xf_slot(uint64_t key, int log2_slots) {
  return mix64(key) & ((1ULL << log2_slots) - 1ULL);
}

void* xf_parser_open(const char* path, long block_bytes) {
  FILE* fp = fopen(path, "rb");
  if (fp == nullptr) return nullptr;
  Parser* p = new Parser();
  p->fp = fp;
  p->buf.resize(block_bytes > 4096 ? static_cast<size_t>(block_bytes) : 4096);
  return p;
}

long xf_parser_truncated(void* handle) {
  return static_cast<Parser*>(handle)->truncated;
}

// Fills one padded batch. Buffers must be shaped:
//   slots, fields: int32 [batch_size, max_nnz]
//   mask:          float [batch_size, max_nnz]
//   labels, row_mask: float [batch_size]
// and are assumed zero-initialized by the caller.
long xf_parser_next_batch(void* handle, long batch_size, long max_nnz,
                          int log2_slots, uint64_t salt, int32_t* slots,
                          int32_t* fields, float* mask, float* labels,
                          float* row_mask) {
  Parser* p = static_cast<Parser*>(handle);
  long row = 0;
  size_t len = 0;
  while (row < batch_size) {
    const char* line = p->next_line(&len);
    if (line == nullptr) {
      if (p->error) return -1;
      break;
    }
    if (parse_row(line, len, max_nnz, log2_slots, salt, slots + row * max_nnz,
                  fields + row * max_nnz, mask + row * max_nnz, labels + row,
                  &p->truncated)) {
      row_mask[row] = 1.0f;
      ++row;
    }
  }
  return row;
}

void xf_parser_close(void* handle) {
  Parser* p = static_cast<Parser*>(handle);
  if (p->fp != nullptr) fclose(p->fp);
  delete p;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Multi-threaded parser pool.
//
// The reference fans parsing + compute over hardware_concurrency() worker
// threads (/root/reference/src/base/thread_pool.h:70-86, lr_worker.cc:190-199)
// with no ordering guarantees (hogwild). Here the host data plane is the
// bottleneck feeder for a synchronous SPMD device step, so the design is:
// N workers each parse disjoint ~block_bytes file blocks (newline-aligned)
// into padded row buffers, and a sequencer drains blocks IN FILE ORDER —
// output is byte-identical to the single-threaded parser, keeping training
// deterministic, while hashing/strtod (the actual cost) runs in parallel.
// A bounded window (2x threads) of in-flight blocks caps memory.
// ---------------------------------------------------------------------------

namespace {

struct ParsedBlock {
  long rows = 0;
  std::vector<float> labels;
  std::vector<int32_t> slots, fields;
  std::vector<float> mask;
  long truncated = 0;
  bool error = false;
};

struct MtParser {
  std::string path;
  long block_bytes = 0, max_nnz = 0;
  int log2_slots = 0;
  uint64_t salt = 0;
  long n_blocks = 0;
  long window = 0;  // max blocks a worker may run ahead of the consumer

  std::atomic<long> next_block{0};
  std::mutex mu;
  std::condition_variable cv_ready, cv_space;
  std::map<long, ParsedBlock> ready;
  long consume_idx = 0;  // next block index the consumer needs
  bool shutdown = false;
  std::vector<std::thread> threads;

  // consumer-side cursor
  ParsedBlock cur;
  long cur_row = 0;
  bool failed = false;
  long truncated_total = 0;

  ~MtParser() {
    {
      std::lock_guard<std::mutex> lk(mu);
      shutdown = true;
    }
    cv_space.notify_all();
    for (auto& t : threads) t.join();
  }

  ParsedBlock parse_block(long b) {
    ParsedBlock out;
    FILE* fp = fopen(path.c_str(), "rb");
    if (fp == nullptr) {
      out.error = true;
      return out;
    }
    // Read from one byte before the block so we can tell whether the
    // block boundary falls exactly on a line start (previous byte '\n').
    long base = b * block_bytes - (b > 0 ? 1 : 0);
    if (fseek(fp, base, SEEK_SET) != 0) {
      out.error = true;
      fclose(fp);
      return out;
    }
    std::vector<char> data;
    size_t want = static_cast<size_t>(block_bytes + (b > 0 ? 1 : 0));
    data.resize(want + 4096);
    size_t size = fread(data.data(), 1, data.size(), fp);
    bool eof = size < data.size();
    if (eof && ferror(fp)) {
      out.error = true;
      fclose(fp);
      return out;
    }
    // limit: lines whose first byte lies within this block
    size_t limit = want < size ? want : size;
    size_t pos = 0;
    if (b > 0) {
      if (size == 0) {
        fclose(fp);
        return out;  // past EOF
      }
      if (data[0] != '\n') {
        // mid-line start: the line belongs to the previous block; skip it
        const char* nl =
            static_cast<const char*>(memchr(data.data(), '\n', size));
        if (nl == nullptr) {
          fclose(fp);
          return out;  // a single line spans the whole block
        }
        pos = static_cast<size_t>(nl - data.data()) + 1;
      } else {
        pos = 1;
      }
    }
    while (pos < limit) {
      // ensure the line starting at pos is fully buffered
      const char* nl = static_cast<const char*>(
          memchr(data.data() + pos, '\n', size - pos));
      while (nl == nullptr && !eof) {
        size_t old = size;
        data.resize(data.size() + (64 << 10));
        size_t got = fread(data.data() + old, 1, data.size() - old, fp);
        size += got;
        eof = size < data.size();
        if (eof && ferror(fp)) {
          out.error = true;
          fclose(fp);
          return out;
        }
        nl = static_cast<const char*>(
            memchr(data.data() + old, '\n', size - old));
      }
      size_t line_end = nl ? static_cast<size_t>(nl - data.data()) : size;
      long r = out.rows;
      out.labels.resize(r + 1, 0.0f);
      out.slots.resize((r + 1) * max_nnz, 0);
      out.fields.resize((r + 1) * max_nnz, 0);
      out.mask.resize((r + 1) * max_nnz, 0.0f);
      if (parse_row(data.data() + pos, line_end - pos, max_nnz, log2_slots,
                    salt, out.slots.data() + r * max_nnz,
                    out.fields.data() + r * max_nnz,
                    out.mask.data() + r * max_nnz, out.labels.data() + r,
                    &out.truncated)) {
        out.rows = r + 1;
      }
      if (nl == nullptr) break;  // final unterminated line
      pos = line_end + 1;
    }
    // shrink over-allocated last row if the final line was not a row
    out.labels.resize(out.rows);
    out.slots.resize(out.rows * max_nnz);
    out.fields.resize(out.rows * max_nnz);
    out.mask.resize(out.rows * max_nnz);
    fclose(fp);
    return out;
  }

  void worker() {
    for (;;) {
      long b = next_block.fetch_add(1);
      if (b >= n_blocks) return;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_space.wait(lk, [&] { return shutdown || b < consume_idx + window; });
        if (shutdown) return;
      }
      ParsedBlock blk = parse_block(b);
      {
        std::lock_guard<std::mutex> lk(mu);
        ready.emplace(b, std::move(blk));
      }
      cv_ready.notify_all();
    }
  }
};

}  // namespace

extern "C" {

void* xf_mt_open(const char* path, long block_bytes, int threads, long max_nnz,
                 int log2_slots, uint64_t salt) {
  FILE* fp = fopen(path, "rb");
  if (fp == nullptr) return nullptr;
  fseek(fp, 0, SEEK_END);
  long fsize = ftell(fp);
  fclose(fp);
  if (fsize < 0) return nullptr;
  MtParser* p = new MtParser();
  p->path = path;
  p->block_bytes = block_bytes > 4096 ? block_bytes : 4096;
  p->max_nnz = max_nnz;
  p->log2_slots = log2_slots;
  p->salt = salt;
  p->n_blocks = (fsize + p->block_bytes - 1) / p->block_bytes;
  if (threads <= 0) {
    unsigned hw = std::thread::hardware_concurrency();
    threads = hw > 0 ? static_cast<int>(hw) : 4;
  }
  if (threads > 16) threads = 16;
  if (static_cast<long>(threads) > p->n_blocks && p->n_blocks > 0)
    threads = static_cast<int>(p->n_blocks);
  if (threads < 1) threads = 1;
  p->window = 2L * threads;
  for (int i = 0; i < threads; ++i)
    p->threads.emplace_back(&MtParser::worker, p);
  return p;
}

long xf_mt_truncated(void* handle) {
  return static_cast<MtParser*>(handle)->truncated_total;
}

// Same output contract as xf_parser_next_batch (buffers zero-initialized
// by the caller); parse parameters were fixed at xf_mt_open.
long xf_mt_next_batch(void* handle, long batch_size, int32_t* slots,
                      int32_t* fields, float* mask, float* labels,
                      float* row_mask) {
  MtParser* p = static_cast<MtParser*>(handle);
  if (p->failed) return -1;
  long row = 0;
  long nnz = p->max_nnz;
  while (row < batch_size) {
    if (p->cur_row >= p->cur.rows) {
      // current block exhausted: pull the next one, in file order
      std::unique_lock<std::mutex> lk(p->mu);
      if (p->consume_idx >= p->n_blocks) break;  // all input consumed
      long want = p->consume_idx;
      p->cv_ready.wait(lk, [&] { return p->ready.count(want) != 0; });
      p->cur = std::move(p->ready[want]);
      p->ready.erase(want);
      p->consume_idx = want + 1;
      p->truncated_total += p->cur.truncated;
      p->cur_row = 0;
      lk.unlock();
      p->cv_space.notify_all();
      if (p->cur.error) {
        p->failed = true;
        return -1;
      }
      continue;
    }
    long take = batch_size - row;
    long avail = p->cur.rows - p->cur_row;
    if (take > avail) take = avail;
    memcpy(labels + row, p->cur.labels.data() + p->cur_row,
           take * sizeof(float));
    memcpy(slots + row * nnz, p->cur.slots.data() + p->cur_row * nnz,
           take * nnz * sizeof(int32_t));
    memcpy(fields + row * nnz, p->cur.fields.data() + p->cur_row * nnz,
           take * nnz * sizeof(int32_t));
    memcpy(mask + row * nnz, p->cur.mask.data() + p->cur_row * nnz,
           take * nnz * sizeof(float));
    for (long i = 0; i < take; ++i) row_mask[row + i] = 1.0f;
    row += take;
    p->cur_row += take;
  }
  return row;
}

void xf_mt_close(void* handle) { delete static_cast<MtParser*>(handle); }

// Count the rows xf_parser_next_batch would produce for this file — the
// EXACT same line predicate (CR-stripped non-empty line containing a
// label separator), no hashing or token parsing. Used to precompute
// per-epoch batch counts so multi-process training needs ONE collective
// per epoch instead of one per step. Returns -1 on open/read failure.
long xf_count_rows(const char* path, long block_bytes) {
  void* handle = xf_parser_open(path, block_bytes);
  if (handle == nullptr) return -1;
  Parser* p = static_cast<Parser*>(handle);
  long rows = 0;
  size_t len = 0;
  auto is_ws = [](char c) {
    return c == ' ' || c == '\t' || c == '\r' || c == '\v' || c == '\f';
  };
  for (;;) {
    const char* line = p->next_line(&len);
    if (line == nullptr) break;
    // same strip as parse_row: a row iff the STRIPPED line still contains
    // a label separator (tab or space)
    while (len > 0 && is_ws(line[len - 1])) --len;
    while (len > 0 && is_ws(line[0])) {
      ++line;
      --len;
    }
    if (len == 0) continue;
    if (memchr(line, '\t', len) != nullptr || memchr(line, ' ', len) != nullptr) {
      ++rows;
    }
  }
  bool err = p->error;
  xf_parser_close(handle);
  return err ? -1 : rows;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Sorted-window plan builder (ops/sorted_table.py host side).
//
// Stable LSD radix sort of a batch's feature occurrences by table slot,
// emitting the padded arrays the Pallas sorted-window kernels consume.
// np.argsort(kind="stable") on 2M occurrences costs ~150 ms in the
// Python planner — enough to wall the host data plane at the step times
// the sorted engine reaches; this builder is O(n) per 11-bit digit
// (2 passes at log2_slots <= 22).
//
// Output contract matches plan_sorted_batch exactly (parity-tested):
//   - out arrays have np_len entries; pads carry slot num_slots-1,
//     row/field 0, mask 0
//   - out_win_off[w] = first sorted position with slot >= w*window,
//     w in [0, num_slots/window]; pads are owned by the last window
//   - stability: equal slots keep original (row-major) occurrence order

namespace {

// PAIR-ENCODED LSD radix (docs/PERF.md host-plane lever): each element
// is one uint64 (slot << 32 | original index), sorted by the slot
// digits only. The index-array variant did an indirect slots[cur[i]]
// load per element per pass — a cache-hostile random read through the
// permutation; here every pass streams the key array sequentially.
// Stability: LSD passes are stable and the index rides in the low
// bits, so equal slots keep their original order — bit-identical
// output to the numpy argsort(kind='stable') planner (parity-tested).
// Returns the sorted key pointer (into keys or scratch), or nullptr on
// invalid input — validation lives here so both emitters share it.
uint64_t* plan_sort_core(const int32_t* slots, long n, long nnz_per_row,
                         long num_slots, long window, long np_len,
                         std::vector<uint64_t>& keys,
                         std::vector<uint64_t>& scratch) {
  if (n < 0 || np_len < n || nnz_per_row <= 0 || num_slots <= 0 ||
      window <= 0 || num_slots % window != 0) {
    return nullptr;
  }
  // validate slot range up front: the radix sort masks each 11-bit digit,
  // so an out-of-range slot would otherwise be silently aliased into a
  // wrong window (and its gradient scattered to a wrong table row) —
  // loud failure matches this function's convention (advisor r2)
  for (long i = 0; i < n; ++i) {
    if (slots[i] < 0 || slots[i] >= num_slots) return nullptr;
  }
  if (n == 0) {
    // nullptr is this function's ERROR sentinel, and vector::data() on
    // an empty vector may legally return nullptr — hand back a valid
    // pointer the (empty) emission loop never dereferences, so a
    // zero-row batch produces an all-pad plan like the numpy path
    keys.resize(1);
    return keys.data();
  }
  constexpr int kDigitBits = 11;
  constexpr int kRadix = 1 << kDigitBits;
  keys.resize(n);
  scratch.resize(n);
  for (long i = 0; i < n; ++i) {
    keys[i] = (static_cast<uint64_t>(static_cast<uint32_t>(slots[i])) << 32) |
              static_cast<uint32_t>(i);
  }
  int bits = 0;
  while ((1L << bits) < num_slots) ++bits;
  uint64_t* cur = keys.data();
  uint64_t* nxt = scratch.data();
  long hist[kRadix + 1];
  for (int shift = 32; shift < 32 + bits; shift += kDigitBits) {
    memset(hist, 0, sizeof(hist));
    for (long i = 0; i < n; ++i) {
      ++hist[(cur[i] >> shift) & (kRadix - 1)];
    }
    long sum = 0;
    for (int d = 0; d < kRadix; ++d) {
      long c = hist[d];
      hist[d] = sum;
      sum += c;
    }
    for (long i = 0; i < n; ++i) {
      uint64_t k = cur[i];
      nxt[hist[(k >> shift) & (kRadix - 1)]++] = k;
    }
    uint64_t* t = cur;
    cur = nxt;
    nxt = t;
  }
  return cur;
}

void plan_win_off(const int32_t* out_slots, long np_len, long num_slots,
                  long window, int32_t* out_win_off) {
  // win_off by linear scan over the sorted (padded) slots
  long n_win = num_slots / window;
  long pos = 0;
  out_win_off[0] = 0;
  for (long w = 1; w <= n_win; ++w) {
    long bound = w * window;
    while (pos < np_len && out_slots[pos] < bound) ++pos;
    out_win_off[w] = static_cast<int32_t>(pos);
  }
}

}  // namespace

extern "C" {

long xf_plan_sorted(const int32_t* slots, const float* mask, const int32_t* fields,
                    long n, long nnz_per_row, long num_slots, long window,
                    long np_len, int32_t* out_slots, int32_t* out_row,
                    float* out_mask, int32_t* out_fields, int32_t* out_win_off) {
  std::vector<uint64_t> keys, scratch;
  uint64_t* cur =
      plan_sort_core(slots, n, nnz_per_row, num_slots, window, np_len, keys, scratch);
  if (cur == nullptr) return -1;
  for (long i = 0; i < n; ++i) {
    uint64_t k = cur[i];
    int32_t src = static_cast<int32_t>(k & 0xffffffffu);
    out_slots[i] = static_cast<int32_t>(k >> 32);
    out_row[i] = static_cast<int32_t>(src / nnz_per_row);
    out_mask[i] = mask[src];
    if (out_fields != nullptr) out_fields[i] = fields[src];
  }
  for (long i = n; i < np_len; ++i) {
    out_slots[i] = static_cast<int32_t>(num_slots - 1);
    out_row[i] = 0;
    out_mask[i] = 0.0f;
    if (out_fields != nullptr) out_fields[i] = 0;
  }
  plan_win_off(out_slots, np_len, num_slots, window, out_win_off);
  return 0;
}

// Wire-format emitter (ops/sorted_table.compact_plan_wire's dtypes
// produced DIRECTLY): uint16 row ids, uint8 0/1 mask, uint8 fields —
// the numpy intermediate plus three astype passes per batch disappear
// from the host budget. The caller guarantees the bounds from CONFIG
// (rows <= 2^16, fields < 2^8 — never from data, the multi-process
// rank-symmetry rule); a violated bound or a non-0/1 mask returns -2
// (distinct from -1 = malformed plan input) so the Python wrapper can
// name the actual contract broken.
long xf_plan_sorted_wire(const int32_t* slots, const float* mask,
                         const int32_t* fields, long n, long nnz_per_row,
                         long num_slots, long window, long np_len,
                         int32_t* out_slots, uint16_t* out_row,
                         uint8_t* out_mask, uint8_t* out_fields,
                         int32_t* out_win_off) {
  std::vector<uint64_t> keys, scratch;
  uint64_t* cur =
      plan_sort_core(slots, n, nnz_per_row, num_slots, window, np_len, keys, scratch);
  if (cur == nullptr) return -1;
  for (long i = 0; i < n; ++i) {
    uint64_t k = cur[i];
    int32_t src = static_cast<int32_t>(k & 0xffffffffu);
    long row = src / nnz_per_row;
    float m = mask[src];
    if (row >= (1L << 16) || (m != 0.0f && m != 1.0f)) return -2;
    out_slots[i] = static_cast<int32_t>(k >> 32);
    out_row[i] = static_cast<uint16_t>(row);
    out_mask[i] = static_cast<uint8_t>(m != 0.0f);
    if (out_fields != nullptr) {
      int32_t f = fields[src];
      if (f < 0 || f >= (1 << 8)) return -2;
      out_fields[i] = static_cast<uint8_t>(f);
    }
  }
  for (long i = n; i < np_len; ++i) {
    out_slots[i] = static_cast<int32_t>(num_slots - 1);
    out_row[i] = 0;
    out_mask[i] = 0;
    if (out_fields != nullptr) out_fields[i] = 0;
  }
  plan_win_off(out_slots, np_len, num_slots, window, out_win_off);
  return 0;
}

}  // extern "C"
