// Native libffm parser: the framework's C++ data plane.
//
// The reference's hot input path is a block-buffered fread parser with
// partial-line carry feeding ragged C++ vectors
// (/root/reference/src/io/load_data_from_disk.cc:103-210). This is the
// TPU-native equivalent, designed fresh for the padded-COO batch schema:
// it parses straight into caller-provided fixed-shape buffers (the numpy
// arrays that become device HBM uploads), so there is no intermediate
// ragged representation at all.
//
// Semantics kept in lockstep with data/libffm.py (the Python reference
// path) and hashing.py:
//   - label token parsed as double, label = 1 iff > 1e-7
//   - feature token "fgid:fid:value": fgid parsed as number, fid hashed
//     as a *string* with salted FNV-1a 64, value ignored
//   - slot = mix64(hash) & (2^log2_slots - 1), mix64 = xor-shift,
//     multiply by 0xD6E8FEB86659FD93, xor-shift (hashing.py slot_of)
//   - rows longer than max_nnz are truncated (truncation counted)
//
// C ABI (consumed by data/native.py via ctypes):
//   xf_hash64(bytes, len, salt) -> uint64
//   xf_parser_open(path, block_bytes) -> handle (NULL on failure)
//   xf_parser_next_batch(handle, batch_size, max_nnz, log2_slots, salt,
//                        slots*, fields*, mask*, labels*, row_mask*)
//       -> rows filled (0 = EOF, -1 = error)
//   xf_parser_truncated(handle) -> truncated-feature count so far
//   xf_parser_close(handle)

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr uint64_t kFnvOffset = 0xCBF29CE484222325ULL;
constexpr uint64_t kFnvPrime = 0x100000001B3ULL;
constexpr uint64_t kMixMul = 0xD6E8FEB86659FD93ULL;

inline uint64_t fnv1a64(const char* data, size_t len, uint64_t salt) {
  uint64_t h = kFnvOffset ^ salt;
  for (size_t i = 0; i < len; ++i) {
    h ^= static_cast<unsigned char>(data[i]);
    h *= kFnvPrime;
  }
  return h;
}

inline uint64_t mix64(uint64_t x) {
  x ^= x >> 32;
  x *= kMixMul;
  x ^= x >> 32;
  return x;
}

// Field id as int32 with explicit nan→0 and saturation: a raw
// static_cast from an out-of-range double is UB, and the Python path
// (data/libffm.py _fgid_i32) implements these exact semantics.
inline int32_t fgid_i32(double d) {
  if (d != d) return 0;
  if (d >= 2147483647.0) return 2147483647;
  if (d <= -2147483648.0) return INT32_MIN;
  return static_cast<int32_t>(d);
}

struct Parser {
  FILE* fp = nullptr;
  std::vector<char> buf;
  size_t pos = 0;    // next unparsed byte
  size_t end = 0;    // valid bytes in buf
  bool eof = false;
  bool error = false;  // fread failed (ferror), distinct from EOF
  long truncated = 0;

  // Returns [line, line+len) for the next complete line (without the
  // trailing newline) or nullptr at EOF. The pointer is valid until the
  // next call.
  const char* next_line(size_t* len) {
    for (;;) {
      // scan for newline in the unparsed region
      char* nl = static_cast<char*>(memchr(buf.data() + pos, '\n', end - pos));
      if (nl != nullptr) {
        const char* line = buf.data() + pos;
        *len = static_cast<size_t>(nl - line);
        pos = static_cast<size_t>(nl - buf.data()) + 1;
        return line;
      }
      if (eof) {
        if (pos < end) {  // final line without trailing newline
          const char* line = buf.data() + pos;
          *len = end - pos;
          pos = end;
          return line;
        }
        return nullptr;
      }
      // carry the partial line to the front and refill
      size_t carry = end - pos;
      if (carry > 0 && pos > 0) memmove(buf.data(), buf.data() + pos, carry);
      pos = 0;
      end = carry;
      if (end == buf.size()) {
        // a single line longer than the buffer: grow
        buf.resize(buf.size() * 2);
      }
      size_t got = fread(buf.data() + end, 1, buf.size() - end, fp);
      end += got;
      if (got == 0) {
        eof = true;
        if (ferror(fp)) {
          // I/O fault, not end-of-data: discard the buffered partial tail
          // immediately so no data from a failed read ever reaches a batch
          error = true;
          return nullptr;
        }
      }
    }
  }
};

}  // namespace

extern "C" {

uint64_t xf_hash64(const char* data, long len, uint64_t salt) {
  return fnv1a64(data, static_cast<size_t>(len), salt);
}

uint64_t xf_slot(uint64_t key, int log2_slots) {
  return mix64(key) & ((1ULL << log2_slots) - 1ULL);
}

void* xf_parser_open(const char* path, long block_bytes) {
  FILE* fp = fopen(path, "rb");
  if (fp == nullptr) return nullptr;
  Parser* p = new Parser();
  p->fp = fp;
  p->buf.resize(block_bytes > 4096 ? static_cast<size_t>(block_bytes) : 4096);
  return p;
}

long xf_parser_truncated(void* handle) {
  return static_cast<Parser*>(handle)->truncated;
}

// Fills one padded batch. Buffers must be shaped:
//   slots, fields: int32 [batch_size, max_nnz]
//   mask:          float [batch_size, max_nnz]
//   labels, row_mask: float [batch_size]
// and are assumed zero-initialized by the caller.
long xf_parser_next_batch(void* handle, long batch_size, long max_nnz,
                          int log2_slots, uint64_t salt, int32_t* slots,
                          int32_t* fields, float* mask, float* labels,
                          float* row_mask) {
  Parser* p = static_cast<Parser*>(handle);
  long row = 0;
  size_t len = 0;
  while (row < batch_size) {
    const char* line = p->next_line(&len);
    if (line == nullptr) {
      if (p->error) return -1;
      break;
    }
    while (len > 0 && (line[len - 1] == '\r')) --len;  // CRLF input
    if (len == 0) continue;
    const char* cur = line;
    const char* lend = line + len;
    // label token ends at tab (or space)
    const char* tab = cur;
    while (tab < lend && *tab != '\t' && *tab != ' ') ++tab;
    if (tab == lend) continue;  // malformed: no features
    labels[row] = (strtod(cur, nullptr) > 1e-7) ? 1.0f : 0.0f;
    row_mask[row] = 1.0f;
    cur = tab + 1;
    long nnz = 0;
    int32_t* srow = slots + row * max_nnz;
    int32_t* frow = fields + row * max_nnz;
    float* mrow = mask + row * max_nnz;
    // tokens split on any whitespace, matching the Python path's .split()
    auto is_sep = [](char c) { return c == ' ' || c == '\t' || c == '\r'; };
    while (cur < lend) {
      while (cur < lend && is_sep(*cur)) ++cur;
      if (cur >= lend) break;
      const char* tok_end = cur;
      while (tok_end < lend && !is_sep(*tok_end)) ++tok_end;
      // token = fgid:fid[:value...]; value never parsed (reference
      // behavior: load_data_from_disk.cc:150-153 breaks after fid)
      const char* c1 = static_cast<const char*>(
          memchr(cur, ':', static_cast<size_t>(tok_end - cur)));
      if (c1 != nullptr) {
        const char* c2 = static_cast<const char*>(
            memchr(c1 + 1, ':', static_cast<size_t>(tok_end - c1 - 1)));
        const char* fid_end = (c2 != nullptr) ? c2 : tok_end;
        if (nnz < max_nnz) {
          frow[nnz] = fgid_i32(strtod(cur, nullptr));
          uint64_t key =
              fnv1a64(c1 + 1, static_cast<size_t>(fid_end - c1 - 1), salt);
          srow[nnz] = static_cast<int32_t>(mix64(key) &
                                           ((1ULL << log2_slots) - 1ULL));
          mrow[nnz] = 1.0f;
          ++nnz;
        } else {
          ++p->truncated;
        }
      }
      cur = tok_end;
    }
    // rows with zero valid features are kept (mask all-zero), matching the
    // Python path: a labeled line is an example even if its features are
    // unparseable
    ++row;
  }
  return row;
}

void xf_parser_close(void* handle) {
  Parser* p = static_cast<Parser*>(handle);
  if (p->fp != nullptr) fclose(p->fp);
  delete p;
}

// Count the rows xf_parser_next_batch would produce for this file — the
// EXACT same line predicate (CR-stripped non-empty line containing a
// label separator), no hashing or token parsing. Used to precompute
// per-epoch batch counts so multi-process training needs ONE collective
// per epoch instead of one per step. Returns -1 on open/read failure.
long xf_count_rows(const char* path, long block_bytes) {
  void* handle = xf_parser_open(path, block_bytes);
  if (handle == nullptr) return -1;
  Parser* p = static_cast<Parser*>(handle);
  long rows = 0;
  size_t len = 0;
  for (;;) {
    const char* line = p->next_line(&len);
    if (line == nullptr) break;
    while (len > 0 && (line[len - 1] == '\r')) --len;
    if (len == 0) continue;
    if (memchr(line, '\t', len) != nullptr || memchr(line, ' ', len) != nullptr) {
      // separator must come before the end: matches the batch parser's
      // "label token ends before lend" check because memchr can only
      // find it at index < len
      ++rows;
    }
  }
  bool err = p->error;
  xf_parser_close(handle);
  return err ? -1 : rows;
}

}  // extern "C"
