"""Training state: parameter tables + optimizer state + step counter.

This is the TPU-resident analog of the reference's *server* state —
the per-key FTRL entries in `std::unordered_map<ps::Key, Entry>`
(`/root/reference/src/optimizer/ftrl.h:84,151`) — as a pytree of dense
sharded arrays. Unlike the reference (which never serializes it,
SURVEY.md §5 "Checkpoint / resume: absent"), this state is a plain
pytree and checkpoints via train/checkpoint.py.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp

from xflow_tpu.config import Config
from xflow_tpu.models.base import Model, init_tables
from xflow_tpu.optim.base import Optimizer


class TrainState(NamedTuple):
    tables: Dict[str, jax.Array]
    opt_state: Dict[str, Any]
    step: jax.Array  # int32 scalar


def init_state(model: Model, optimizer: Optimizer, cfg: Config, seed: int | None = None) -> TrainState:
    key = jax.random.PRNGKey(cfg.train.seed if seed is None else seed)
    tables = init_tables(model, cfg, key)
    return TrainState(
        tables=tables,
        opt_state=optimizer.init_state(tables),
        step=jnp.zeros((), dtype=jnp.int32),
    )
