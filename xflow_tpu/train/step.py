"""The jitted train/eval steps.

One reference worker-thread iteration (`lr_worker.cc:145-177`: gather
unique keys → Pull → forward → residual → per-key mean gradient → Push;
server applies FTRL per key) becomes ONE pure function:

    grads = ∇ mean-BCE(tables; batch)      # gather fwd, scatter-add bwd
    tables, opt_state = optimizer(tables, opt_state, grads)

`jax.grad` through the table gather produces exactly the reference's
Push payload (summed residuals per key / batch rows); the optimizer is
the reference's server-side handler as an elementwise array op. Under
jit XLA fuses forward, backward, and update; under a sharded mesh GSPMD
inserts the gather/scatter collectives that replace ps-lite RPC
(SURVEY.md §2 C13).

Masked padded rows contribute zero gradient; the loss mean divides by
the number of *real* rows (reference divides by its sub-batch line
count, `lr_worker.cc:116-118`).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp

from xflow_tpu.config import Config
from xflow_tpu.metrics import binary_logloss_from_logits, reference_pctr
from xflow_tpu.models.base import Model
from xflow_tpu.optim.base import Optimizer
from xflow_tpu.train.state import TrainState


def batch_to_arrays(batch) -> dict:
    """SparseBatch (host numpy) → the dict of arrays the step consumes."""
    return {
        "slots": batch.slots,
        "fields": batch.fields,
        "mask": batch.mask,
        "labels": batch.labels,
        "row_mask": batch.row_mask,
    }


def loss_fn(tables, batch, model: Model, cfg: Config):
    logits = model.forward(tables, batch, cfg)
    per_row = binary_logloss_from_logits(logits, batch["labels"])
    denom = jnp.maximum(batch["row_mask"].sum(), 1.0)
    return (per_row * batch["row_mask"]).sum() / denom


def make_train_step(model: Model, optimizer: Optimizer, cfg: Config, jit: bool = True) -> Callable:
    """Returns train_step(state, batch_arrays) -> (state, metrics)."""

    def train_step(state: TrainState, batch: dict):
        loss, grads = jax.value_and_grad(loss_fn)(state.tables, batch, model, cfg)
        new_tables, new_opt = optimizer.apply(state.tables, state.opt_state, grads, cfg)
        metrics = {"loss": loss, "rows": batch["row_mask"].sum()}
        return TrainState(new_tables, new_opt, state.step + 1), metrics

    if jit:
        # donate the state: tables and optimizer state update in place in HBM
        train_step = jax.jit(train_step, donate_argnums=(0,))
    return train_step


def make_eval_step(model: Model, cfg: Config, jit: bool = True) -> Callable:
    """Returns eval_step(tables, batch_arrays) -> pctr [B] (reference-clamped σ)."""

    def eval_step(tables, batch: dict):
        return reference_pctr(model.forward(tables, batch, cfg))

    return jax.jit(eval_step) if jit else eval_step
