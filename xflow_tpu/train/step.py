"""The jitted train/eval steps.

One reference worker-thread iteration (`lr_worker.cc:145-177`: gather
unique keys → Pull → forward → residual → per-key mean gradient → Push;
server applies FTRL per key) becomes ONE pure function:

    grads = ∇ mean-BCE(tables; batch)      # gather fwd, scatter-add bwd
    tables, opt_state = optimizer(tables, opt_state, grads)

`jax.grad` through the table gather produces exactly the reference's
Push payload (summed residuals per key / batch rows); the optimizer is
the reference's server-side handler as an elementwise array op. Under
jit XLA fuses forward, backward, and update; under a sharded mesh GSPMD
inserts the gather/scatter collectives that replace ps-lite RPC
(SURVEY.md §2 C13).

Masked padded rows contribute zero gradient; the loss mean divides by
the number of *real* rows (reference divides by its sub-batch line
count, `lr_worker.cc:116-118`).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp

from xflow_tpu.config import Config
from xflow_tpu.metrics import binary_logloss_from_logits
from xflow_tpu.models.base import Model
from xflow_tpu.optim.base import Optimizer
from xflow_tpu.train.state import TrainState


def batch_to_arrays(batch) -> dict:
    """SparseBatch (host numpy) → the dict of arrays the step consumes."""
    return {
        "slots": batch.slots,
        "fields": batch.fields,
        "mask": batch.mask,
        "labels": batch.labels,
        "row_mask": batch.row_mask,
    }


def masked_mean_logloss(logits, labels, row_mask):
    """Mean BCE over REAL rows (the reference divides by its sub-batch
    line count, `lr_worker.cc:116-118`) — the one loss reduction, shared
    by the autodiff and fused step forms so they cannot drift."""
    per_row = binary_logloss_from_logits(logits, labels)
    return (per_row * row_mask).sum() / jnp.maximum(row_mask.sum(), 1.0)


def loss_fn(tables, batch, model: Model, cfg: Config):
    # named scopes label the xprof trace (docs/OBSERVABILITY.md): the
    # forward holds the table gather; autodiff transposes it into the
    # scatter, which lands under the enclosing "grad" scope
    with jax.named_scope("gather"):
        logits = model.forward(tables, batch, cfg)
    with jax.named_scope("loss"):
        return masked_mean_logloss(logits, batch["labels"], batch["row_mask"])


def nonfinite_guard_on(cfg: Config) -> bool:
    """Validate train.nonfinite_guard and return whether the guard runs."""
    g = cfg.train.nonfinite_guard
    if g not in ("off", "skip", "halt"):
        raise ValueError(
            f"train.nonfinite_guard={g!r}: expected off|skip|halt"
        )
    return g != "off"


def guard_nonfinite(cfg: Config, state: TrainState, new_state: TrainState, metrics: dict):
    """Fold the non-finite update guard into one step's result.

    `update_ok` = the loss AND every updated table/optimizer leaf are
    finite, as ONE isfinite reduction per leaf fused into the step (the
    optimizer sweep already touches every element, so the extra HBM
    traffic is ~zero on the two-pass paths). On a bad step the whole
    update is discarded by `jnp.where` on the flag — no recompute, the
    previous state rides through. The step counter still advances, so
    checkpoint names stay monotonic.

    Shared by all four step builders (single-device, GSPMD, fullshard,
    replicated sorted) so their guard semantics cannot drift. The flag
    is computed inside the SPMD program from replicated values, so every
    multi-process rank sees the same bit with no host collective — the
    trainer's skip/halt bookkeeping stays rank-symmetric for free.
    """
    if not nonfinite_guard_on(cfg):
        return new_state, metrics
    ok = jnp.isfinite(metrics["loss"])
    for leaf in jax.tree.leaves((new_state.tables, new_state.opt_state)):
        ok = ok & jnp.isfinite(leaf).all()
    keep = lambda new, old: jnp.where(ok, new, old)
    guarded = TrainState(
        tables=jax.tree.map(keep, new_state.tables, state.tables),
        opt_state=jax.tree.map(keep, new_state.opt_state, state.opt_state),
        step=new_state.step,
    )
    return guarded, dict(metrics, update_ok=ok)


def health_mode(cfg: Config) -> str:
    """Validate train.health_metrics and return the mode."""
    m = cfg.train.health_metrics
    if m not in ("off", "norms", "full"):
        raise ValueError(f"train.health_metrics={m!r}: expected off|norms|full")
    return m


def health_metric_keys(cfg: Config) -> tuple:
    """The health-scalar keys every step's metrics dict carries under
    this config: global grad/update/param norms ("norms"), plus
    per-table norms ("full"). Derived from the model's table specs so
    the four step builders and the sharded out_shardings pytrees agree
    by construction."""
    mode = health_mode(cfg)
    if mode == "off":
        return ()
    keys = ["grad_norm", "update_norm", "param_norm"]
    if mode == "full":
        from xflow_tpu.models import get_model

        for t in sorted(get_model(cfg.model.name).table_specs(cfg)):
            keys += [f"grad_norm.{t}", f"update_norm.{t}", f"param_norm.{t}"]
    return tuple(keys)


def health_norms(cfg: Config, old_tables, new_tables, grads=None, grad_sq=None) -> dict:
    """Health scalars for one step, fused into the jitted program.

    Per table: squared grad norm (from `grads` arrays, or engine-supplied
    `grad_sq` scalars where the table gradient never materializes — the
    fused scatter+FTRL path passes the occurrence-space cotangent's
    norm), squared update norm ||new − old||², squared param norm
    ||new||². Emitted as sqrt'd scalars keyed by `health_metric_keys`.
    Reductions are plain sums, so under GSPMD/shard_map-produced sharded
    leaves they lower to shard-local reductions + one psum and every
    rank sees identical replicated values — no host collective, same
    cost model as the non-finite guard's isfinite sweep. Norms are taken
    on the PROPOSED update, before the guard's discard select: a
    discarded step's exploding grad norm is exactly the diagnostic the
    health stream exists to show."""
    mode = health_mode(cfg)
    if mode == "off":
        return {}
    names = sorted(new_tables)
    sqsum = lambda x: (x.astype(jnp.float32) ** 2).sum()
    sq = {}
    for name in names:
        if grad_sq is not None and name in grad_sq:
            sq[name] = jnp.asarray(grad_sq[name], jnp.float32)
        elif grads is not None and name in grads:
            sq[name] = sqsum(grads[name])
        else:
            sq[name] = jnp.float32(0.0)
    upd = {n: sqsum(new_tables[n] - old_tables[n]) for n in names}
    par = {n: sqsum(new_tables[n]) for n in names}
    total = lambda d: jnp.sqrt(sum(d.values()))
    out = {
        "grad_norm": total(sq),
        "update_norm": total(upd),
        "param_norm": total(par),
    }
    if mode == "full":
        for n in names:
            out[f"grad_norm.{n}"] = jnp.sqrt(sq[n])
            out[f"update_norm.{n}"] = jnp.sqrt(upd[n])
            out[f"param_norm.{n}"] = jnp.sqrt(par[n])
    return out


def metrics_keys(cfg: Config) -> tuple:
    """The step-metrics dict keys under this config — the sharded step
    builders derive their out_shardings pytrees from this so neither the
    guard's extra flag nor the health scalars ever desync a jit
    contract."""
    base = ("loss", "rows") + health_metric_keys(cfg)
    return base + (("update_ok",) if nonfinite_guard_on(cfg) else ())


def _fused_scatter_eligible(cfg: Config, allow_fused: bool) -> bool:
    """Fused scatter+FTRL (cfg.optim.fused_scatter, ops/sorted_table
    .scatter_ftrl_sorted) applies to the single-device sorted fused-FM
    step with FTRL — the one-table case where the step's whole table
    gradient comes from a single windowed scatter. `allow_fused` is the
    caller's single-device assertion: the sharded builders pass False
    (an in-place window kernel over a sharded table is not this op's
    contract), and `on` there is a config error, not a silent downgrade.
    """
    if cfg.optim.fused_scatter == "off":
        return False
    if cfg.optim.fused_scatter not in ("auto", "on"):
        raise ValueError(
            f"optim.fused_scatter={cfg.optim.fused_scatter!r}: expected auto|on|off"
        )
    fm_ok = cfg.model.name == "fm" and cfg.model.fm_fused
    mvm_ok = cfg.model.name == "mvm"
    ffm_ok = cfg.model.name == "ffm"
    base_ok = allow_fused and cfg.optim.name == "ftrl"
    if cfg.optim.fused_scatter == "on":
        if not (base_ok and (fm_ok or mvm_ok or ffm_ok)):
            raise ValueError(
                "optim.fused_scatter=on requires the single-device step "
                "with optim.name=ftrl and model.name=fm (fm_fused=true), "
                f"mvm, or ffm; got optim={cfg.optim.name} "
                f"model={cfg.model.name} fm_fused={cfg.model.fm_fused} "
                f"single_device={allow_fused}"
            )
        return True
    # auto: FM (measured throughput-NEUTRAL; kept for the memory win)
    # and FFM's aligned hybrid (the [S/8, 584]-wide dense gradient +
    # optimizer sweep it removes is real throughput there — docs/PERF.md
    # round 5). The MVM product path measured ~3% slower fused (41.3 vs
    # 40.0 ms at the bench shape), so its memory win stays an explicit
    # opt-in ("on").
    return base_ok and (fm_ok or ffm_ok)


def _fused_sorted_step(state: TrainState, batch: dict, cfg: Config):
    """Sorted train step with the optimizer applied inside the scatter's
    window write: gather → row-side vjp → ONE scatter_ftrl_sorted pass.
    Covers fused FM (table "wv") and the MVM product path (table "v").
    Bit-equal to value_and_grad + ftrl.apply (same kernels, same
    elementwise math on each window's complete gradient block); the
    difference is that the [S, K] gradient never exists in HBM and the
    dense optimizer sweep is gone."""
    from xflow_tpu.ops.sorted_table import pack_of, scatter_ftrl_sorted, table_gather_sorted

    mvm = cfg.model.name == "mvm"
    ffm = cfg.model.name == "ffm"
    tname = "v" if mvm else "wv"
    if ffm:
        K = 1 + cfg.model.num_fields * cfg.model.v_dim
    else:
        K = cfg.model.v_dim if mvm else 1 + cfg.model.v_dim
    table = state.tables[tname]
    pack = pack_of(table, K)
    with jax.named_scope("gather"):
        occ_t = table_gather_sorted(
            table, batch["sorted_slots"], batch["win_off"], cfg.data.sorted_bf16, pack
        )

    def row_loss(occ):
        # the row side and the loss reduction are the SAME functions the
        # two-pass form uses (fm._row_side_sorted / mvm._product_row_side
        # via sorted_gather_map; masked_mean_logloss via loss_fn) — only
        # the gather/scatter seam is split here so the table cotangent
        # feeds the fused kernel
        rows = batch["labels"].shape[0]
        if ffm:
            from xflow_tpu.models.ffm import ffm_aligned_logits

            logits = ffm_aligned_logits(occ, batch, cfg)
        elif mvm:
            from xflow_tpu.models.mvm import _product_row_side

            plus = 1.0 if cfg.model.mvm_plus_one else 0.0
            logits = _product_row_side(
                occ, batch["sorted_row"], batch["sorted_mask"], rows,
                cfg.model.v_dim, plus,
            )
        else:
            from xflow_tpu.models.fm import _row_side_sorted

            logits = _row_side_sorted(
                occ, batch["sorted_row"], batch["sorted_mask"], rows, cfg
            )
        return masked_mean_logloss(logits, batch["labels"], batch["row_mask"])

    with jax.named_scope("loss"):
        loss, vjp = jax.vjp(row_loss, occ_t)
    with jax.named_scope("grad"):
        (d_occ,) = vjp(jnp.ones_like(loss))
    st = state.opt_state[tname]
    # the fused kernel IS scatter + optimizer in one window write
    with jax.named_scope("scatter_optimizer"):
        w_new, n_new, z_new = scatter_ftrl_sorted(
            d_occ, batch["sorted_slots"], batch["win_off"], table, st["n"], st["z"],
            K, cfg.optim.ftrl, cfg.data.sorted_bf16, pack,
        )
    new_state = TrainState(
        {tname: w_new}, {tname: {"n": n_new, "z": z_new}}, state.step + 1
    )
    metrics = {"loss": loss, "rows": batch["row_mask"].sum()}
    # the table gradient never materializes on this path (that is the
    # point of the fusion) — the occurrence-space cotangent's norm
    # stands in for the grad norm (equal when the batch's occurrences
    # hit distinct slots; a divergence signal either way). update/param
    # norms keep the pre-step table live, same price the guard pays.
    metrics.update(
        health_norms(
            cfg, state.tables, new_state.tables,
            grad_sq={tname: (d_occ.astype(jnp.float32) ** 2).sum()},
        )
    )
    return new_state, metrics


def make_train_step(model: Model, optimizer: Optimizer, cfg: Config, jit: bool = True,
                    allow_fused: bool = True, recorder=None) -> Callable:
    """Returns train_step(state, batch_arrays) -> (state, metrics).

    `allow_fused=False` (the sharded builders) disables the fused
    scatter+FTRL path regardless of config — the fusion's contract is
    the single-device step (`_fused_scatter_eligible`).

    `recorder` (telemetry.CompileRecorder) routes the jit through the
    compile-accounting seam: explicit timed .lower().compile() with
    cost/memory analysis into a kind="compile" record, program name
    "train_step"."""
    fuse = _fused_scatter_eligible(cfg, allow_fused)

    def train_step(state: TrainState, batch: dict):
        # fused path: only for FLAT sorted plans without per-occurrence
        # fields (MVM's segment path keeps two-pass) — except FFM's
        # aligned hybrid, whose plan carries fields for the placement's
        # reverse map plus ffm_invperm. Batch structure is static under
        # jit, so this resolves at trace time
        fusable = (
            "sorted_slots" in batch
            and batch["sorted_slots"].ndim == 1
            and (
                "ffm_invperm" in batch
                if cfg.model.name == "ffm"
                else "sorted_fields" not in batch
            )
        )
        if fuse and fusable:
            new_state, metrics = _fused_sorted_step(state, batch, cfg)
            # guard note: selecting against the pre-step table forces XLA
            # to keep it live across the fused scatter, giving back the
            # table-sized transient the fusion removed — the price of
            # discardable updates (docs/ROBUSTNESS.md); set
            # train.nonfinite_guard=off to reclaim it
            return guard_nonfinite(cfg, state, new_state, metrics)
        if fuse and cfg.optim.fused_scatter == "on":
            raise ValueError(
                "optim.fused_scatter=on but this batch has no flat "
                "fields-free sorted plan (sorted_layout off/row-major "
                "fallback, stacked sub-batch plans, MVM's segment "
                "path, or a non-aligned FFM batch) — the fused path "
                "cannot run; use auto to allow the two-pass form on "
                "such batches"
            )
        # "grad" wraps forward+backward: the backward's table scatter
        # (the gather's transpose) shows up here in an xprof trace
        with jax.named_scope("grad"):
            loss, grads = jax.value_and_grad(loss_fn)(state.tables, batch, model, cfg)
        with jax.named_scope("optimizer"):
            new_tables, new_opt = optimizer.apply(
                state.tables, state.opt_state, grads, cfg
            )
        metrics = {"loss": loss, "rows": batch["row_mask"].sum()}
        metrics.update(health_norms(cfg, state.tables, new_tables, grads=grads))
        return guard_nonfinite(
            cfg, state, TrainState(new_tables, new_opt, state.step + 1), metrics
        )

    if jit:
        # donate the state: tables and optimizer state update in place in HBM
        train_step = jax.jit(train_step, donate_argnums=(0,))
        if recorder is not None:
            return recorder.wrap("train_step", train_step)
    return train_step


def make_eval_step(model: Model, cfg: Config, jit: bool = True, recorder=None) -> Callable:
    """Returns eval_step(tables, batch_arrays) -> pctr [B].

    Delegates to the ONE shared pctr forward (models/predict.py
    make_predict_fn) — the same function the serve runner compiles, so
    offline eval and online serving cannot drift."""
    from xflow_tpu.models.predict import make_predict_fn

    return make_predict_fn(model, cfg, jit=jit, recorder=recorder)
