"""Checkpoint / resume.

The reference has NO checkpointing: trained weights live only in
server-process memory and vanish at `ps::Finalize` (SURVEY.md §5
"Checkpoint / resume: absent"). This module closes that gap:

- `save`/`restore`: whole-TrainState checkpoints. Single-host saves an
  .npz per step; multi-host (or when orbax is preferred) uses Orbax's
  sharded async-capable format so 1B-feature FTRL state never gathers
  onto one host (SURVEY.md §7 hard part d).
- `export_sparse`: serving export of the *nonzero* weights only — the
  sparse model FTRL's L1 produces is the artifact a CTR serving stack
  consumes (the reference's closest analog is its prediction dump).
"""

from __future__ import annotations

import json
import os
import re
import shutil
import sys
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Optional

import jax
import numpy as np

from xflow_tpu.train.state import TrainState

_STEP_RE = re.compile(r"^step_(\d+)$")

# checkpoint metadata version (meta.json "version"):
#   (absent) — pre-elastic-recovery checkpoints: model state only
#   2 — adds the host-side data_state.json (exact data-pipeline resume)
#   3 — topology-elastic + integrity-verified (docs/DISTRIBUTED.md
#       "Canonical checkpoint layout"): meta carries the LOGICAL layout
#       ({array: shape}), the writer's world_size, and per-array
#       digests ("crc32:%08x" over the stored bytes) so a silently
#       bit-flipped shard fails the restore LOUDLY and restore_any
#       walks back to the previous committed step; data_state gains
#       per-SHARD batch offsets so a run checkpointed at N ranks
#       resumes at M ranks with exact record-set coverage.
# Readers NEVER require the new pieces: a version-less checkpoint (or a
# v2 one whose data_state was lost/truncated) restores the model and
# resumes with a fresh stream, logging the downgrade (read_data_state);
# a v2 data_state folds into the topology-independent v3 view
# (normalize_data_state).
CHECKPOINT_VERSION = 3
# data_state.json "version": 1 = per-rank counters (PR 4); 2 = the
# topology-independent form (global examples, per-shard offsets)
DATA_STATE_VERSION = 2
DATA_STATE_FILE = "data_state.json"


class CheckpointDigestError(RuntimeError):
    """A stored array's bytes no longer match the digest recorded in
    meta.json at save time — silent media/transfer corruption (the zip
    layer catches raw npz flips, but a rewritten container or an OCDBT
    data file has no such net). Raised from the restore paths so
    restore_any turns the corruption into a logged walk-back to the
    previous committed step, never a restore of corrupted state."""


def data_state_path(ckpt_dir: str, step: int, fmt: str = "npz") -> str:
    """Where a step's data_state JSON lives: inside the npz step dir
    (pruned with it), or as an `orbax_step_N.data_state.json` sibling
    for orbax (orbax owns its dir's contents; the sibling is written
    after the orbax save finalizes, so its presence implies a committed
    checkpoint — and its absence is just the fresh-stream downgrade)."""
    if fmt == "orbax":
        return os.path.join(ckpt_dir, f"orbax_step_{step}.data_state.json")
    return os.path.join(ckpt_dir, f"step_{step}", DATA_STATE_FILE)


def read_data_state(ckpt_dir: str, step: int, fmt: str = "npz") -> Optional[dict]:
    """The data-pipeline position saved alongside checkpoint `step`, or
    None with a logged downgrade when it is missing (a pre-v2
    checkpoint) or unreadable (truncated/corrupt JSON) — exact stream
    resume is an upgrade, never a gate: the model state still restores
    and the run resumes with a fresh stream (docs/ROBUSTNESS.md)."""
    path = data_state_path(ckpt_dir, step, fmt)
    if not os.path.exists(path):
        print(
            f"# checkpoint: step {step} has no data_state (pre-v2 "
            "checkpoint?); resuming with a fresh data stream",
            file=sys.stderr,
        )
        return None
    try:
        with open(path) as f:
            ds = json.load(f)
        if not isinstance(ds, dict):
            raise ValueError(f"expected a JSON object, got {type(ds).__name__}")
    except Exception as e:  # noqa: BLE001 — any unreadable data_state
        # (truncation, bit rot, bad hand edit) downgrades, never kills
        # the resume the model checkpoint itself supports
        print(
            f"# checkpoint: step {step} data_state unreadable "
            f"({type(e).__name__}: {e}); resuming with a fresh data stream",
            file=sys.stderr,
        )
        return None
    return ds


def publication_path(ckpt_dir: str, step: int, fmt: str = "npz") -> str:
    """Where a step's publication sidecar lives (train.publish_every,
    docs/SERVING.md "Freshness"): inside the npz step dir — written
    BEFORE the COMMITTED marker, so a committed publication is never
    torn and prunes with its step — or as an
    `orbax_step_N.publication.json` sibling (same contract as the
    data_state sibling: presence implies a committed checkpoint)."""
    if fmt == "orbax":
        return os.path.join(ckpt_dir, f"orbax_step_{step}.publication.json")
    return os.path.join(ckpt_dir, f"step_{step}", "publication.json")


def read_publication(ckpt_dir: str, step: int, fmt: str = "npz") -> Optional[dict]:
    """The publication context saved alongside checkpoint `step`
    ({step, seq, trace, span, ingest_ts, consumed_ts, published_ts}),
    or None. Absence is the NORMAL case — only publish-cadence saves
    carry one — so missing is silent; an unreadable sidecar downgrades
    with a logged warning, never gates the reload that found it (the
    serve runner still swaps, it just cannot link the trace)."""
    path = publication_path(ckpt_dir, step, fmt)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            pub = json.load(f)
        if not isinstance(pub, dict):
            raise ValueError(f"expected a JSON object, got {type(pub).__name__}")
    except Exception as e:  # noqa: BLE001 — any unreadable publication
        print(
            f"# checkpoint: step {step} publication unreadable "
            f"({type(e).__name__}: {e}); serving without a trace link",
            file=sys.stderr,
        )
        return None
    return pub


def normalize_data_state(ds: dict) -> dict:
    """Fold any stored data_state version into the canonical
    topology-independent v2 shape the elastic resume consumes:

    - ``examples``: GLOBAL total across ranks (v1 multi-process records
      keyed examples per rank; they fold by summation — a logged
      downgrade of precision never a failure),
    - ``shard_batches``: {shard index -> batches consumed within the
      epoch}. v1 records carry only the global coordinated offset, but
      v1 runs consumed their shards in LOCKSTEP (one shard per rank,
      coordinated steps), so every shard's consumed prefix IS that
      offset — the fold is exact, not approximate.
    - ``num_shards`` / ``world_size``: the shard set in play and the
      writer's rank count (v1: both = len(examples_per_rank), or 1).

    Raises TypeError/ValueError on malformed input — callers downgrade
    to a fresh stream (trainer._consume_resume_position)."""
    out = {
        "version": DATA_STATE_VERSION,
        "epoch": max(int(ds.get("epoch", 0)), 0),
        "batches": max(int(ds.get("batches", 0)), 0),
        "completed": bool(ds.get("completed", False)),
        "examples": max(int(ds.get("examples", 0)), 0),
        "quarantined_rows": max(int(ds.get("quarantined_rows", 0)), 0),
    }
    sb = ds.get("shard_batches")
    if isinstance(sb, dict):
        out["shard_batches"] = {
            int(k): max(int(v), 0) for k, v in sb.items()
        }
        out["num_shards"] = max(
            int(ds.get("num_shards", 0)),
            max(out["shard_batches"], default=-1) + 1,
            1,
        )
        out["world_size"] = max(int(ds.get("world_size", 1)), 1)
        return out
    # v1 (meta v2 era): per-rank-keyed record — fold into the global view
    per_rank = ds.get("examples_per_rank")
    n = len(per_rank) if isinstance(per_rank, list) and per_rank else 1
    out["world_size"] = n
    out["num_shards"] = n
    out["shard_batches"] = {i: out["batches"] for i in range(n)}
    if isinstance(per_rank, list) and per_rank:
        out["examples"] = sum(max(int(x), 0) for x in per_rank)
    if out["epoch"] or out["batches"]:
        print(
            f"# checkpoint: v1 data_state (per-rank keyed, {n} rank(s)) "
            "folded into the topology-independent form: global examples "
            f"{out['examples']}, per-shard offset {out['batches']}",
            file=sys.stderr,
        )
    return out


# ------------------------------------------------------------- integrity
def array_digest(arr: np.ndarray) -> str:
    """Digest of an array's raw bytes, written into meta.json at save
    and verified on restore. crc32 (stdlib, streams at GB/s — noise
    against the host gather the npz save already does) is enough to
    catch every single-bit and most multi-byte flips; the format tag
    leaves room for a stronger hash later without a version bump."""
    arr = np.ascontiguousarray(arr)
    return "crc32:%08x" % (zlib.crc32(arr.tobytes()) & 0xFFFFFFFF)


def verify_digest(label: str, arr: np.ndarray, digests: Optional[dict], source: str) -> None:
    """Raise CheckpointDigestError when `arr` no longer matches the
    digest meta.json recorded for `label`; arrays the meta never
    digested (pre-v3 checkpoints, multi-process orbax saves) pass."""
    if not digests:
        return
    want = digests.get(label)
    if not want:
        return
    got = array_digest(np.asarray(arr))
    if got != want:
        raise CheckpointDigestError(
            f"checkpoint {source!r}: array {label!r} digest mismatch "
            f"(stored {want}, read {got}) — silent shard corruption; "
            "walking back to the previous committed step"
        )


def read_meta(ckpt_dir: str, step: int, fmt: str = "npz") -> Optional[dict]:
    """meta.json of checkpoint `step` (the orbax format keeps it as an
    `orbax_step_N.meta.json` sibling, like its data_state), or None —
    with a logged note — when missing/unreadable: a pre-v3 checkpoint
    simply restores without digest verification, never fails on it."""
    if fmt == "orbax":
        path = os.path.join(ckpt_dir, f"orbax_step_{step}.meta.json")
    else:
        path = os.path.join(ckpt_dir, f"step_{step}", "meta.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            meta = json.load(f)
        if not isinstance(meta, dict):
            raise ValueError(f"expected a JSON object, got {type(meta).__name__}")
    except Exception as e:  # noqa: BLE001 — unreadable meta downgrades
        # to an unverified restore (the state itself may be fine); a
        # CORRUPT state still fails through the digest-less load path
        print(
            f"# checkpoint: step {step} meta unreadable "
            f"({type(e).__name__}: {e}); restoring without digest "
            "verification",
            file=sys.stderr,
        )
        return None
    return meta


def _to_host(arr) -> np.ndarray:
    """Fetch a (possibly cross-process-sharded) array to every host."""
    if isinstance(arr, jax.Array) and not arr.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(arr, tiled=True))
    return np.asarray(arr)


def _unpack_host(arr: np.ndarray, K: Optional[int]) -> np.ndarray:
    """Host-side packed [S/p, p*K] -> logical [S, K] (a free reshape).
    npz checkpoints ALWAYS store the logical layout, so export tools,
    the C API, and runs with a different data.packed_tables setting all
    read the same format; restore() re-packs to the target shape."""
    if K and arr.ndim == 2 and arr.shape[1] != K and arr.shape[1] % K == 0:
        return arr.reshape(-1, K)
    return arr


def _flatten(state: TrainState, logical_widths: Optional[dict] = None) -> dict:
    widths = logical_widths or {}
    flat = {}
    for name, t in state.tables.items():
        flat[f"tables/{name}"] = _unpack_host(_to_host(t), widths.get(name))
    for name, st in state.opt_state.items():
        for k, v in st.items():
            flat[f"opt/{name}/{k}"] = _unpack_host(_to_host(v), widths.get(name))
    flat["step"] = _to_host(state.step)
    return flat


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY fd: make a rename/replace that already landed
    in `path` durable against power/kernel loss. rename alone is not —
    default ext4/xfs can journal the name change before (or after) a
    crash boundary, so a commit-by-rename (orbax's finalize, our
    COMMITTED markers) needs the parent directory synced too."""
    dfd = os.open(path or ".", os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def _write_atomic(path: str, writer, fault=None) -> None:
    """Write a file through a temp name + fsync + os.replace + dir fsync,
    so a crash mid-write can never leave a half-written file under the
    final name (a truncated `state.npz` in a COMMITTED dir would defeat
    the commit-marker protocol — the marker only witnesses ordering, not
    write atomicity). The fsyncs extend the guarantee to power/kernel
    loss: without them, default ext4/xfs can journal the rename before
    the data blocks land, committing a zero-filled file.

    `fault` (testing/faults.ckpt_write_fault) is the disk-fault seam:
    called with the temp path after `writer` lands it, BEFORE the
    replace — an injected ENOSPC/slow-write fires exactly where a real
    one would, and the finally sweeps the temp so the final name never
    appears."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        writer(tmp)
        if fault is not None:
            fault(tmp)
        fd = os.open(tmp, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, path)
        fsync_dir(os.path.dirname(path))
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def save(
    ckpt_dir: str,
    state: TrainState,
    logical_widths: Optional[dict] = None,
    data_state: Optional[dict] = None,
    publication: Optional[dict] = None,
) -> str:
    """Write a checkpoint; returns its path.

    `data_state` (optional) is the host-side data-pipeline position —
    epoch index, batch offset, per-rank consumed-examples counters,
    quarantine count (trainer._data_state_record) — written atomically
    as data_state.json BEFORE the COMMITTED marker, so a committed
    checkpoint either carries a complete data_state or (pre-v2 /
    data_state=None) none at all, never a torn one.

    `publication` (optional) is the freshness trace context of a
    publish-cadence save (train.publish_every): the newest contributing
    ingest trace id + its wall anchors, written as publication.json
    under the SAME pre-COMMITTED contract so the serve runner either
    reads a complete publication or none.

    Host-gathered npz format: in multi-process mode every rank gathers
    (the allgather is collective) but only process 0 writes. Fine up to
    tables that fit one host's RAM; the Criteo-1TB-scale sharded format
    is Orbax-based (see OrbaxCheckpointer below when available).
    `logical_widths` ({table: K}) unpacks packed storage so the file is
    layout-independent (_unpack_host).

    Crash-safety: a pre-existing UNCOMMITTED step dir (a prior save that
    died mid-write) is removed first so one dir never mixes two
    generations of files; each file lands via temp name + os.replace;
    the COMMITTED marker is written last.
    """
    step = int(state.step)
    flat = _flatten(state, logical_widths)  # collective: all ranks participate
    if jax.process_index() == 0:
        path = write_flat(
            ckpt_dir, flat, step, data_state=data_state, publication=publication
        )
    else:
        path = os.path.join(ckpt_dir, f"step_{step}")
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"ckpt_save_{step}")
    return path


def write_flat(
    ckpt_dir: str,
    flat: dict,
    step: int,
    data_state: Optional[dict] = None,
    publication: Optional[dict] = None,
    tier: str = "primary",
) -> str:
    """The WRITE phase of an npz save: host arrays in, committed step
    dir out. No collectives and no device access, so it runs on the
    caller thread (`save`) or the async writer thread
    (AsyncCheckpointWriter) identically — the atomicity contract
    (uncommitted-dir cleanup, per-file temp+replace+fsync, COMMITTED
    marker last) lives here once. `tier` names the destination for the
    env-gated disk-fault injectors (testing/faults.ckpt_write_fault,
    resolved once per call — zero cost unset)."""
    from xflow_tpu.testing.faults import ckpt_write_fault

    fault = ckpt_write_fault(tier)
    path = os.path.join(ckpt_dir, f"step_{step}")
    if os.path.isdir(path) and not os.path.exists(
        os.path.join(path, "COMMITTED")
    ):
        shutil.rmtree(path)
    os.makedirs(path, exist_ok=True)

    def write_npz(p):
        # a file OBJECT, not a path: np.savez appends ".npz" to bare
        # paths, which would break the temp-name + os.replace dance
        with open(p, "wb") as f:
            np.savez(f, **flat)

    _write_atomic(os.path.join(path, "state.npz"), write_npz, fault=fault)
    # v3 metadata: the canonical LOGICAL layout (npz always stores
    # [S, K], _unpack_host), the writer's world size (informational
    # — restore reshards into whatever mesh is live), and per-array
    # digests over exactly the bytes a reader gets back, so a
    # silent flip fails the restore instead of training garbage
    meta = {
        "step": step,
        "tables": sorted(
            k.split("/", 1)[1] for k in flat if k.startswith("tables/")
        ),
        "format": "npz",
        "version": CHECKPOINT_VERSION,
        "world_size": jax.process_count(),
        "layout": {k: list(np.asarray(v).shape) for k, v in flat.items()},
        "digests": {k: array_digest(v) for k, v in flat.items()},
    }

    def write_json(p):
        with open(p, "w") as f:
            json.dump(meta, f)

    _write_atomic(os.path.join(path, "meta.json"), write_json, fault=fault)
    if data_state is not None:

        def write_ds(p):
            with open(p, "w") as f:
                json.dump(data_state, f)

        _write_atomic(os.path.join(path, DATA_STATE_FILE), write_ds, fault=fault)
    if publication is not None:

        def write_pub(p):
            with open(p, "w") as f:
                json.dump(publication, f)

        _write_atomic(
            os.path.join(path, "publication.json"), write_pub, fault=fault
        )

    def write_marker(p):
        with open(p, "w") as f:
            f.write("ok\n")

    # commit marker last: readers treat directories without it as partial
    _write_atomic(os.path.join(path, "COMMITTED"), write_marker, fault=fault)
    return path


def committed_steps(ckpt_dir: str) -> list[int]:
    """All COMMITTED npz checkpoint steps, newest first."""
    if not os.path.isdir(ckpt_dir):
        return []
    steps = []
    for name in os.listdir(ckpt_dir):
        m = _STEP_RE.match(name)
        if m and os.path.exists(os.path.join(ckpt_dir, name, "COMMITTED")):
            steps.append(int(m.group(1)))
    return sorted(steps, reverse=True)


def latest_step(ckpt_dir: str) -> Optional[int]:
    steps = committed_steps(ckpt_dir)
    return steps[0] if steps else None


def prune_checkpoints(ckpt_dir: str, keep: int, fmt: str = "npz") -> list[str]:
    """Retention sweep after a successful save (train.keep_checkpoints).

    Removes (a) committed checkpoints beyond the `keep` newest (keep <= 0
    keeps everything) and (b) stale crashed-save debris regardless of
    `keep`: uncommitted npz step dirs, and orbax's own temp dirs
    (`*.orbax-checkpoint-tmp-*`) — the save that just committed proves no
    writer is using them. Only process 0 mutates the filesystem (the same
    rank that writes npz checkpoints). Returns the removed paths."""
    removed = []
    if jax.process_index() != 0 or not os.path.isdir(ckpt_dir):
        return removed
    if fmt == "orbax":
        steps = orbax_steps(ckpt_dir)
        doomed = []
        for s in steps[keep:] if keep > 0 else []:
            # a pruned orbax step takes its sibling data_state AND meta
            # files with it — an orphaned sibling would pair with the
            # WRONG stream position / digests if that step number ever
            # recurs
            doomed.extend(
                [
                    f"orbax_step_{s}",
                    os.path.basename(data_state_path(ckpt_dir, s, "orbax")),
                    f"orbax_step_{s}.meta.json",
                    os.path.basename(publication_path(ckpt_dir, s, "orbax")),
                ]
            )
        # stale-debris sweep, orbax flavor: a save killed mid-write leaves
        # orbax's own temp dir (`orbax_step_N.orbax-checkpoint-tmp-...`),
        # which never matches orbax_steps and would leak forever
        for name in os.listdir(ckpt_dir):
            if name.startswith("orbax_step_") and ".orbax-checkpoint-tmp" in name:
                doomed.append(name)
    else:
        steps = committed_steps(ckpt_dir)
        live = set(steps[:keep] if keep > 0 else steps)
        doomed = []
        for name in os.listdir(ckpt_dir):
            m = _STEP_RE.match(name)
            if m and int(m.group(1)) not in live:
                doomed.append(name)
    for name in doomed:
        p = os.path.join(ckpt_dir, name)
        if os.path.isdir(p):
            shutil.rmtree(p, ignore_errors=True)
        elif os.path.exists(p):
            try:
                os.remove(p)  # plain files: the orbax data_state siblings
            except OSError:
                pass
        else:
            continue
        removed.append(p)
    return removed


def tier_steps(ckpt_dir: str, fmt: str = "npz") -> list[int]:
    """Committed steps of ONE tier dir, newest first (format-dispatched)."""
    return orbax_steps(ckpt_dir) if fmt == "orbax" else committed_steps(ckpt_dir)


def restore_any(
    ckpt_dir: str,
    like: TrainState,
    fmt: str = "npz",
    verify: str = "auto",
    replica_dir: Optional[str] = None,
):
    """Self-healing restore: walk back from the newest committed step.

    Returns (state, step) — the tiered walk with the source dir dropped
    (restore_tiered keeps it for callers that read sidecars)."""
    state, step, _src = restore_tiered(
        ckpt_dir, like, fmt=fmt, verify=verify, replica_dir=replica_dir
    )
    return state, step


def restore_tiered(
    ckpt_dir: str,
    like: TrainState,
    fmt: str = "npz",
    verify: str = "auto",
    replica_dir: Optional[str] = None,
):
    """Self-healing, replica-aware restore: walk the UNION of committed
    steps across the primary and (optional) tier-2 replica dir, newest
    step first, primary tier first within a step.

    Returns (state, step, source_dir) — source_dir is where the step
    actually loaded from, so callers read the matching sidecars
    (data_state, publication) from the SAME tier. A candidate that
    fails to load — truncated npz, bit-flipped orbax shard, a digest
    mismatch against the meta written at save (CheckpointDigestError —
    the SILENT-corruption case no container-level check catches),
    unreadable metadata — is logged with the reason and SKIPPED, and
    the next candidate (the step's other tier, then the previous
    committed step) is tried, instead of one corrupt file killing a
    resumable run (or, worse, restoring garbage). Raises
    FileNotFoundError when no checkpoint exists in any tier,
    RuntimeError (listing every failure) when none of the existing ones
    loads. `verify` is the digest policy (train.checkpoint_verify):
    "auto" verifies whenever digests exist and the arrays are
    host-visible; "off" skips."""
    dirs = [ckpt_dir]
    if replica_dir and replica_dir != ckpt_dir:
        dirs.append(replica_dir)
    by_dir = {d: set(tier_steps(d, fmt)) for d in dirs}
    steps = sorted(set().union(*by_dir.values()), reverse=True)
    if not steps:
        raise FileNotFoundError(
            f"no {'orbax' if fmt == 'orbax' else 'committed'} checkpoint "
            f"under {' or '.join(repr(d) for d in dirs)}"
        )
    errors = []
    for step in steps:
        for d in dirs:
            if step not in by_dir[d]:
                continue
            try:
                if fmt == "orbax":
                    state = restore_orbax(d, like, step=step, verify=verify)
                else:
                    state = restore(d, like, step=step, verify=verify)
            except Exception as e:  # noqa: BLE001 — every failure mode of
                # a corrupt file (BadZipFile, zlib.error, OSError, orbax/
                # tensorstore errors, shape mismatches) must take the
                # walk-back path; each is logged with its reason below
                tier = "replica" if len(dirs) > 1 and d == dirs[-1] else "primary"
                print(
                    f"# checkpoint: step {step} ({tier} tier) failed to "
                    f"load ({type(e).__name__}: {e}); trying the next "
                    "candidate",
                    file=sys.stderr,
                )
                errors.append((d, step, e))
                continue
            if errors:
                print(
                    f"# checkpoint: restored step {step} from {d!r} after "
                    f"skipping {len(errors)} unreadable candidate(s): "
                    + ", ".join(f"step {s} in {dd!r}" for dd, s, _ in errors),
                    file=sys.stderr,
                )
            return state, step, d
    raise RuntimeError(
        f"no loadable checkpoint under {' or '.join(repr(d) for d in dirs)}"
        f" — all {len(errors)} candidates failed: "
        + "; ".join(
            f"step {s} ({d}): {type(e).__name__}: {e}" for d, s, e in errors
        )
    )


def _fused_alias(lookup, tbl: str, like: TrainState):
    """Derive table (or per-table opt-state) array `tbl` from the OTHER
    FM layout when the checkpoint was written with a different
    `model.fm_fused` setting: stored fused ``wv [S, 1+k]`` splits into
    ``w = wv[:, 0]`` / ``v = wv[:, 1:]``; stored two-table merges by
    concatenation. FTRL's n/z split/merge identically (the update is
    elementwise per column). `lookup(name)` returns the stored array
    for the SAME group/sub-key (tables, opt n, opt z, ...) or None.
    Shapes are size-derived and normalized to the LOGICAL layout; the
    caller's reshape migration re-packs as needed. Returns None when
    the bridge doesn't apply."""
    if tbl in ("w", "v"):
        wv = lookup("wv")
        # gate on the template really being the two-table FM layout —
        # restoring a fused checkpoint into LR (w only) or MVM (v only)
        # must stay a loud error, not a silent cross-model restore
        if wv is None or "w" not in like.tables or "v" not in like.tables:
            return None
        S = like.tables["w"].size
        k_like = like.tables["v"].size // S
        wv = np.asarray(wv)
        if wv.size != S * (1 + k_like):
            return None  # dims differ: not a pure layout change
        wv = wv.reshape(S, 1 + k_like)
        return np.ascontiguousarray(wv[:, 0] if tbl == "w" else wv[:, 1:])
    if tbl == "wv":
        w, v = lookup("w"), lookup("v")
        if w is None or v is None:
            return None
        w = np.asarray(w).reshape(-1, 1)
        S = w.shape[0]
        if (
            np.asarray(v).size % S != 0
            or like.tables["wv"].size != S + np.asarray(v).size
        ):
            return None  # dims differ: not a pure layout change
        v = np.asarray(v).reshape(S, -1)
        return np.concatenate([w, v], axis=1)
    return None


def _put_migrated(label: str, arr, template, stored_tables, source: str):
    """Place one stored array into a template leaf, migrating layout.

    The single migration rule shared by the npz and orbax restore paths:
    a size-equal shape difference is a packed [S/p, p*K] <-> logical
    [S, K] layout change (a pure reshape); anything else is a real
    structure mismatch. `arr is None` means the checkpoint lacks the
    array entirely — most often a pre-fused FM checkpoint (two-table
    layout) read by a fused-default run, so the error says how to bridge.
    """
    if arr is None:
        raise RuntimeError(
            f"checkpoint {source!r} has no array {label!r} (stored tables: "
            f"{list(stored_tables)}), and no layout bridge applies — the "
            "fused<->two-table FM bridge (_fused_alias) and the "
            "packed<->logical reshape both handle their cases "
            "automatically, so this checkpoint belongs to a different "
            "model/config."
        )
    arr = np.asarray(arr)
    if arr.shape != template.shape:
        from xflow_tpu.ops.sorted_table import PACK

        def pack_related(a, b):
            # a = logical [S, K], b = packed [S/PACK, PACK*K]?
            return (
                len(a) == len(b) == 2
                and a[0] == b[0] * PACK
                and b[1] == a[1] * PACK
            )

        # only a pack toggle is a pure reshape; equal-size coincidences
        # (e.g. v_dim 8 -> 4 with log2_slots + 1) would interleave
        # unrelated rows and silently corrupt the restored state
        if not (
            pack_related(arr.shape, template.shape)
            or pack_related(template.shape, arr.shape)
        ):
            raise RuntimeError(
                f"checkpoint {source!r}: {label} stored shape {arr.shape} is "
                f"incompatible with expected {template.shape} — not a packed "
                f"[S/{PACK}, {PACK}*K] <-> logical [S, K] layout change "
                "(did model dims or log2_slots change?)."
            )
        arr = arr.reshape(template.shape)
    sharding = getattr(template, "sharding", None)
    if sharding is not None:
        return jax.device_put(arr, sharding)
    import jax.numpy as jnp

    return jnp.asarray(arr)


def restore(
    ckpt_dir: str,
    like: TrainState,
    step: Optional[int] = None,
    verify: str = "auto",
) -> TrainState:
    """Restore into the sharding/structure of `like` (device_put per
    leaf). Topology-agnostic by construction: the npz stores the full
    LOGICAL arrays, so a checkpoint written at N ranks restores into
    any M-rank mesh — each leaf is placed onto `like`'s live sharding,
    whatever engine (single-device, GSPMD, sorted replicated,
    fullshard) built it. With `verify` != "off", every stored array
    read is checked against the digest meta.json recorded at save; a
    mismatch raises CheckpointDigestError (restore_any walks back)."""
    step = latest_step(ckpt_dir) if step is None else step
    if step is None:
        raise FileNotFoundError(f"no committed checkpoint under {ckpt_dir}")
    path = os.path.join(ckpt_dir, f"step_{step}")
    data = np.load(os.path.join(path, "state.npz"))
    stored_tables = sorted(k.split("/", 1)[1] for k in data.files if k.startswith("tables/"))
    meta = read_meta(ckpt_dir, step) if verify != "off" else None
    digests = meta.get("digests") if isinstance(meta, dict) else None
    verified: set = set()

    def stored(name: str):
        """Read one stored array, digest-verified exactly once (the
        fused-alias bridge reads arrays under OTHER names; routing every
        read through here keeps the verification complete)."""
        if name not in data:
            return None
        arr = data[name]
        if name not in verified:
            verified.add(name)
            verify_digest(name, arr, digests, path)
        return arr

    def put(name: str, template):
        arr = stored(name)
        if arr is None:
            # fm_fused layout bridge: the key path keeps its group/sub
            # ("tables/w" <- "tables/wv"; "opt/w/n" <- "opt/wv/n")
            group, rest = name.split("/", 1)
            parts = rest.split("/")
            sub = "/" + parts[1] if len(parts) > 1 else ""
            arr = _fused_alias(
                lambda t: stored(f"{group}/{t}{sub}"),
                parts[0],
                like,
            )
        return _put_migrated(name, arr, template, stored_tables, path)

    tables = {n: put(f"tables/{n}", t) for n, t in like.tables.items()}
    opt_state = {
        n: {k: put(f"opt/{n}/{k}", v) for k, v in st.items()}
        for n, st in like.opt_state.items()
    }
    import jax.numpy as jnp

    return TrainState(tables=tables, opt_state=opt_state, step=jnp.asarray(stored("step")))


# --------------------------------------------------------------- orbax format
#
# The npz path above gathers the whole state to one host — fine for dev
# scale, impossible for the north-star config (1B-feature FTRL state,
# SURVEY.md §7 hard part d). The Orbax path saves each process's shards
# directly (OCDBT), so no host ever materializes the full table, and
# restore places shards straight onto the target sharding.

def _flatten_native(tree: dict) -> dict:
    """{label: leaf} over an orbax state tree in its NATIVE (device)
    layout — the ONE place the `tables/<n>` / `opt_state/<n>/<k>` key
    naming lives: the digest writer (save_orbax) and verifier
    (_verify_orbax_digests) must agree byte-for-byte or every digest
    lookup silently misses and verification becomes a no-op."""
    flat = {}
    for n, t in tree.get("tables", {}).items():
        flat[f"tables/{n}"] = t
    for n, st in tree.get("opt_state", {}).items():
        for k, v in st.items():
            flat[f"opt_state/{n}/{k}"] = v
    return flat


def save_orbax(
    ckpt_dir: str,
    state: TrainState,
    data_state: Optional[dict] = None,
    publication: Optional[dict] = None,
) -> str:
    import orbax.checkpoint as ocp

    step = int(state.step)
    path = os.path.abspath(os.path.join(ckpt_dir, f"orbax_step_{step}"))
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, state._asdict(), force=True)
    if jax.process_index() == 0:
        # orbax commits by renaming its tmp dir under the final name —
        # make that rename durable (fsync_dir): without the parent-dir
        # sync a host crash can reorder the commit past already-synced
        # data, resurfacing the tmp name (the npz path gets the same
        # guarantee from _write_atomic's own dir fsync)
        fsync_dir(os.path.abspath(ckpt_dir))
        # v3 meta sibling (same commit protocol as the data_state
        # sibling: written AFTER orbax's rename-commit, its absence is
        # just an unverified restore). Digests cover the NATIVE stored
        # layout and are computed only when every leaf is addressable
        # on this host (single-process): OCDBT data reads are NOT
        # checksum-verified (testing/faults.py, measured), so this is
        # the only net under a bit-flipped shard — but gathering a
        # 1B-feature state to hash it would defeat the shard-parallel
        # save, so pod-scale multi-process saves record layout only.
        flat = _flatten_native(state._asdict())
        meta = {
            "step": step,
            "tables": sorted(state.tables),
            "format": "orbax",
            "version": CHECKPOINT_VERSION,
            "world_size": jax.process_count(),
            "layout": {k: list(v.shape) for k, v in flat.items()},
        }
        if jax.process_count() == 1:
            meta["digests"] = {
                k: array_digest(np.asarray(v)) for k, v in flat.items()
            }

        def write_meta(p):
            with open(p, "w") as f:
                json.dump(meta, f)

        _write_atomic(
            os.path.join(ckpt_dir, f"orbax_step_{step}.meta.json"), write_meta
        )
    if data_state is not None and jax.process_index() == 0:
        # sibling file, written AFTER orbax finalizes its rename-commit:
        # its presence implies a committed checkpoint, its absence (an
        # old checkpoint, a crash in this window) is the fresh-stream
        # downgrade read_data_state already handles
        def write_ds(p):
            with open(p, "w") as f:
                json.dump(data_state, f)

        _write_atomic(data_state_path(ckpt_dir, step, fmt="orbax"), write_ds)
    if publication is not None and jax.process_index() == 0:
        # same sibling contract as data_state: written after the
        # rename-commit, absence is just "not a publication"

        def write_pub(p):
            with open(p, "w") as f:
                json.dump(publication, f)

        _write_atomic(publication_path(ckpt_dir, step, fmt="orbax"), write_pub)
    return path


def orbax_steps(ckpt_dir: str) -> list[int]:
    """All orbax checkpoint steps, newest first (orbax finalizes a save
    by renaming its tmp dir, so presence under the final name means the
    write completed — the OCDBT analog of the npz COMMITTED marker)."""
    if not os.path.isdir(ckpt_dir):
        return []
    steps = []
    for name in os.listdir(ckpt_dir):
        m = re.match(r"^orbax_step_(\d+)$", name)
        if m:
            steps.append(int(m.group(1)))
    return sorted(steps, reverse=True)


def latest_orbax_step(ckpt_dir: str) -> Optional[int]:
    steps = orbax_steps(ckpt_dir)
    return steps[0] if steps else None


def _orbax_stored_shapes(path: str) -> Optional[dict]:
    """Stored array shapes from checkpoint metadata as {'a/b': shape},
    without reading any array data. None when metadata is unavailable
    (older orbax layouts) — callers then skip migration detection."""
    import orbax.checkpoint as ocp

    flat = {}

    def walk(prefix, node):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(f"{prefix}/{k}" if prefix else str(k), v)
        else:
            flat[prefix] = tuple(node.shape)

    try:
        with ocp.PyTreeCheckpointer() as ckptr:
            md = ckptr.metadata(path)
        # orbax API drift: older releases (e.g. 0.7.x) return the metadata
        # tree itself (a dict of ArrayMetadata); newer ones wrap it as
        # CheckpointMetadata.item_metadata.tree
        tree = md if isinstance(md, dict) else md.item_metadata.tree
        if tree is None:
            return None
        walk("", tree)
    except json.JSONDecodeError as e:
        # a corrupt/truncated metadata file is I/O trouble, not an
        # older metadata-less layout — it must reach the noisy arm
        # below, and it subclasses ValueError, so catch it FIRST
        print(
            f"# checkpoint: metadata read failed (JSONDecodeError: {e}); "
            "skipping layout-migration detection",
            file=sys.stderr,
        )
        return None
    except (FileNotFoundError, KeyError, AttributeError, ValueError):
        # genuinely metadata-less layouts (older orbax) — migration
        # detection is impossible, callers take the fast path
        return None
    except Exception as e:  # I/O trouble is NOT "no metadata": say so
        # before falling back, or the fast path's eventual shape error
        # blames the checkpoint layout instead of the real problem
        print(
            f"# checkpoint: metadata read failed ({type(e).__name__}: {e}); "
            "skipping layout-migration detection",
            file=sys.stderr,
        )
        return None
    return flat


def _verify_orbax_digests(tree: dict, digests: Optional[dict], source: str) -> None:
    """Check a restored orbax pytree against the meta sibling's digests
    (single-process saves record them; see save_orbax). Skipped with a
    logged note when a leaf is not fully addressable — a pod-scale
    restore cannot re-gather the state just to hash it."""
    if not digests:
        return
    for label, leaf in _flatten_native(tree).items():
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            print(
                "# checkpoint: digest verification skipped (state not "
                "fully addressable on this host)",
                file=sys.stderr,
            )
            return
        verify_digest(label, np.asarray(leaf), digests, source)


def restore_orbax(
    ckpt_dir: str,
    like: TrainState,
    step: Optional[int] = None,
    verify: str = "auto",
) -> TrainState:
    """Restore with `like`'s shardings (shards load directly per process).

    Layout migration: orbax stores the NATIVE (possibly packed [S/p, p*K])
    device layout. Stored shapes are compared against `like`'s via the
    checkpoint *metadata* (no array reads); only when they genuinely
    differ — a `data.packed_tables` toggle, or a pre-packed checkpoint —
    does restore take the migration path: a host-side restore +
    size-equal reshape (the packed<->logical move is a pure reshape,
    same rule as the npz path, `_put_migrated`). The migration path
    materializes full arrays on each host — fine for a one-time
    migration; re-save after restoring to get back on the shard-parallel
    path. Matching shapes take the fast shard-parallel restore, and any
    error there (corrupt shard, I/O) propagates as-is.
    """
    import orbax.checkpoint as ocp

    step = latest_orbax_step(ckpt_dir) if step is None else step
    if step is None:
        raise FileNotFoundError(f"no orbax checkpoint under {ckpt_dir}")
    path = os.path.abspath(os.path.join(ckpt_dir, f"orbax_step_{step}"))
    meta = read_meta(ckpt_dir, step, fmt="orbax") if verify != "off" else None
    digests = meta.get("digests") if isinstance(meta, dict) else None

    like_tree = like._asdict()
    expected = {}
    for n, t in like.tables.items():
        expected[f"tables/{n}"] = tuple(t.shape)
    for n, st in like.opt_state.items():
        for k, v in st.items():
            expected[f"opt_state/{n}/{k}"] = tuple(v.shape)
    stored_shapes = _orbax_stored_shapes(path)
    migrate = stored_shapes is not None and any(
        stored_shapes.get(k) != shp for k, shp in expected.items()
    )

    if not migrate:
        def as_abstract(x):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None))

        abstract = jax.tree.map(as_abstract, like_tree)
        try:
            with ocp.StandardCheckpointer() as ckptr:
                restored = ckptr.restore(path, abstract)
        except Exception as e:
            if stored_shapes is None and "wv" in like.tables:
                # metadata was unreadable, so migration detection (and the
                # automatic fused<->two-table bridge it would route to)
                # could not run: say how to bridge manually instead of
                # surfacing orbax's raw tree-mismatch
                raise RuntimeError(
                    f"orbax restore of {path!r} failed ({e}), and this "
                    "checkpoint's metadata is unreadable so the automatic "
                    "layout bridge could not engage. If it is an FM "
                    "checkpoint written with the two-table layout, set "
                    "model.fm_fused=false to restore it."
                ) from e
            raise
        # fast path = stored shapes equal like's, so the restored leaves
        # are byte-comparable against the digests taken at save (OCDBT
        # itself never checksums its data reads — this is the only net)
        _verify_orbax_digests(restored, digests, path)
        return TrainState(**restored)

    # stored layout differs: host-side migration restore
    import jax.numpy as jnp

    with ocp.StandardCheckpointer() as ckptr:
        stored = ckptr.restore(path)  # host numpy, stored shapes
    # migration restores the NATIVE stored layout host-side — exactly
    # the bytes the digests were taken over; verify BEFORE migrating
    _verify_orbax_digests(stored, digests, path)
    stored_tables = sorted(stored.get("tables", {}))

    def put(label: str, arr, lookup, tbl, template):
        if arr is None:
            # fm_fused layout bridge (same rule as the npz path); stored
            # arrays may be packed — _fused_alias's size-derived reshape
            # is the free unpack
            arr = _fused_alias(lookup, tbl, like)
        return _put_migrated(label, arr, template, stored_tables, path)

    tables = {
        n: put(
            f"tables/{n}",
            stored.get("tables", {}).get(n),
            lambda t: stored.get("tables", {}).get(t),
            n,
            t,
        )
        for n, t in like.tables.items()
    }
    opt_state = {
        n: {
            k: put(
                f"opt_state/{n}/{k}",
                stored.get("opt_state", {}).get(n, {}).get(k),
                lambda t, k=k: stored.get("opt_state", {}).get(t, {}).get(k),
                n,
                v,
            )
            for k, v in st.items()
        }
        for n, st in like.opt_state.items()
    }
    return TrainState(
        tables=tables, opt_state=opt_state, step=jnp.asarray(stored["step"])
    )


# ------------------------------------------------- async tiered writer
#
# train.ckpt_async (docs/ROBUSTNESS.md "Async tiered checkpointing"):
# the fit loop snapshots and returns; one background thread owns every
# byte that leaves for disk — serialize, digest, sidecars, COMMITTED
# marker last (write_flat: the exact synchronous contract), then the
# tier-2 replica mirror (train.ckpt_replica_dir) and retention on both
# tiers. The reference's defining robustness property is that workers
# never block on state movement (ps-lite's async push/pull); this is
# that property applied to durability.


class SaveSnapshot:
    """Device-state capture for one async save (train.ckpt_async).

    Construction runs on the FIT-LOOP thread and MUST finish the host
    gather before returning: every train-step engine donates the input
    state (donate_argnums=(0,)), so the device buffers behind these
    leaves are deleted the moment the fit loop dispatches the next
    step — a reference pinned for the writer thread would read dead
    arrays. copy_to_host_async() is issued on every leaf first so the
    blocking device_get is the D2H transfer TAIL, not a fresh serial
    copy; the expensive half of a save (serialize + digest + fsync +
    rename) stays on the writer thread. Single-process only: _flatten's
    multihost allgather is a collective no side thread may run (the
    trainer gates ckpt_async on process_count == 1)."""

    def __init__(self, state: TrainState, logical_widths: Optional[dict] = None):
        self.widths = logical_widths or {}
        self.step = int(state.step)
        self.nbytes = 0
        for leaf in jax.tree.leaves((state.tables, state.opt_state)):
            if isinstance(leaf, jax.Array):
                leaf.copy_to_host_async()
            self.nbytes += int(getattr(leaf, "nbytes", 0))
        # host copies, gathered BEFORE the fit loop can donate the
        # device buffers away (TrainState is a pytree: device_get maps
        # every jax.Array leaf to numpy, structure unchanged)
        self.state = jax.device_get(state)

    def materialize(self) -> dict:
        """{label: host array} in the canonical logical npz layout."""
        return _flatten(self.state, self.widths)


@dataclass
class SaveJob:
    """One submitted async save: the snapshot plus everything the writer
    thread needs to reproduce save()/save_orbax() byte-for-byte.
    Captured at SUBMIT time on the fit-loop thread — data_state holds
    host-side counters that keep moving, so the writer must persist the
    cadence step's view, never a later one."""

    snapshot: SaveSnapshot
    ckpt_dir: str
    fmt: str = "npz"
    replica_dir: str = ""
    keep: int = 0
    keep_replica: int = 0
    data_state: Optional[dict] = None
    publication: Optional[dict] = None
    queued_ts: float = 0.0


def _copier(src: str):
    """_write_atomic writer callback that lands a copy of `src`."""

    def write(p):
        shutil.copyfile(src, p)

    return write


def _copytree_verified(src: str, dst: str, fault=None) -> str:
    """Recursive file copy with a per-file read-BACK crc check: the
    mirror must verify the bytes the copy actually landed on replica
    media, not trust the kernel's success return (a flip through bad
    RAM/NIC/controller is exactly the fault the tier exists to absorb).
    `fault` is the replica-targeted disk-fault seam."""
    os.makedirs(dst, exist_ok=True)
    for root, _dirs, files in os.walk(src):
        rel = os.path.relpath(root, src)
        out_root = dst if rel == "." else os.path.join(dst, rel)
        os.makedirs(out_root, exist_ok=True)
        for name in files:
            sp, dp = os.path.join(root, name), os.path.join(out_root, name)
            with open(sp, "rb") as f:
                blob = f.read()
            with open(dp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            if fault is not None:
                fault(dp)
            with open(dp, "rb") as f:
                back = f.read()
            if zlib.crc32(back) != zlib.crc32(blob):
                raise CheckpointDigestError(
                    f"replica mirror of {sp!r}: read-back crc mismatch — "
                    "the copy landed corrupted"
                )
    return dst


def mirror_step(
    primary_dir: str, replica_dir: str, step: int, fmt: str = "npz"
) -> str:
    """Mirror committed checkpoint `step` into the tier-2 replica dir
    (train.ckpt_replica_dir); returns the replica path. Idempotent: an
    already-committed replica step is left untouched.

    npz: every file of the primary step dir copies through the same
    temp+replace+fsync dance the save used, the replica's OWN state.npz
    bytes re-verify against the mirrored meta's digests (a torn or
    flipped copy fails HERE, loudly, instead of at a future restore),
    and the replica's COMMITTED marker lands last — so the replica obeys
    the exact reader contract the primary does. orbax: the step dir
    copies file-by-file with a read-back crc check into a tmp name the
    stale-debris sweep already knows, commits by rename + dir fsync,
    then the sidecar siblings follow (their presence implies the commit,
    same as the primary's contract).

    Disk faults aim here via tier="replica"
    (testing/faults.ckpt_write_fault)."""
    from xflow_tpu.testing.faults import ckpt_write_fault

    fault = ckpt_write_fault("replica")
    os.makedirs(replica_dir, exist_ok=True)
    if fmt == "orbax":
        src = os.path.join(primary_dir, f"orbax_step_{step}")
        dst = os.path.join(replica_dir, f"orbax_step_{step}")
        if not os.path.isdir(dst):
            tmp = os.path.join(
                replica_dir,
                f"orbax_step_{step}.orbax-checkpoint-tmp-mirror{os.getpid()}",
            )
            try:
                _copytree_verified(src, tmp, fault=fault)
                os.rename(tmp, dst)
            finally:
                if os.path.isdir(tmp):
                    shutil.rmtree(tmp, ignore_errors=True)
            fsync_dir(replica_dir)
        for name in (
            f"orbax_step_{step}.meta.json",
            os.path.basename(data_state_path(primary_dir, step, "orbax")),
            os.path.basename(publication_path(primary_dir, step, "orbax")),
        ):
            sp = os.path.join(primary_dir, name)
            if os.path.exists(sp) and not os.path.exists(
                os.path.join(replica_dir, name)
            ):
                _write_atomic(
                    os.path.join(replica_dir, name), _copier(sp), fault=fault
                )
        return dst
    src = os.path.join(primary_dir, f"step_{step}")
    dst = os.path.join(replica_dir, f"step_{step}")
    if os.path.exists(os.path.join(dst, "COMMITTED")):
        return dst
    if os.path.isdir(dst):
        shutil.rmtree(dst)  # uncommitted debris from a crashed mirror
    os.makedirs(dst, exist_ok=True)
    for name in ("state.npz", "meta.json", DATA_STATE_FILE, "publication.json"):
        sp = os.path.join(src, name)
        if os.path.exists(sp):
            _write_atomic(os.path.join(dst, name), _copier(sp), fault=fault)
    # digest re-verify the REPLICA's own bytes before committing it: the
    # digests were taken over the arrays at save time, so this closes
    # the whole primary-write -> copy -> replica-media loop
    meta = read_meta(replica_dir, step)
    digests = meta.get("digests") if isinstance(meta, dict) else None
    if digests:
        with np.load(os.path.join(dst, "state.npz")) as data:
            for name in data.files:
                verify_digest(name, data[name], digests, dst)

    def write_marker(p):
        with open(p, "w") as f:
            f.write("ok\n")

    _write_atomic(os.path.join(dst, "COMMITTED"), write_marker, fault=fault)
    return dst


class AsyncCheckpointWriter:
    """The single background checkpoint writer (train.ckpt_async).

    At most ONE save in flight: submit() while a save is pending is a
    logged, counted skip — never a queue (a queue under a slow disk
    would pile up host copies of the whole state without bound). The
    thread runs write_flat/save_orbax verbatim, so a crash mid-async-
    write leaves exactly today's uncommitted debris and the walk-back
    restore covers it. drain() blocks until idle — the halt/signal/
    end-of-fit saves use it so the run's last state is durable before
    fit returns; close() drains and stops the thread.

    Failure policy: an OSError on the PRIMARY tier (disk full, dead
    volume) latches DEGRADED — this and every later save lands
    replica-only (a full save, not a mirror) and training never stops;
    a non-IO primary failure falls back to the replica for that save
    without latching. Replica failures are logged and counted only.
    Every outcome emits one kind="ckpt" record per tier into `sink` (a
    thread-safe jsonl.JsonlAppender; metrics_report --check gates the
    schema and the one-in-flight invariant), plus — with ckpt_spans —
    one checkpoint_save span per committed write so saves still overlay
    request-latency timelines."""

    def __init__(self, sink=None, ckpt_spans: bool = False):
        self._sink = sink
        self._ckpt_spans = ckpt_spans
        self._lock = threading.Lock()
        self._job: Optional[SaveJob] = None
        self._idle = threading.Event()
        self._idle.set()
        self._wake = threading.Event()
        self._stop = False
        self.skips = 0
        self.saves = 0  # committed tier-writes (primary + replica)
        self.failures = 0
        self.degraded = False
        self.last_step: dict = {}  # tier -> last committed step
        self.last_error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run, name="ckpt-writer", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------- control
    def submit(self, job: SaveJob) -> bool:
        """Hand one save to the writer; False = a save is already in
        flight (the skip contract: the cadence hit is simply lost and
        the next boundary tries again)."""
        with self._lock:
            if self._stop:
                return False
            if self._job is not None or not self._idle.is_set():
                self.skips += 1
                now = time.time()
                print(
                    f"# checkpoint: async save of step {job.snapshot.step}"
                    f" skipped — previous save still in flight "
                    f"({self.skips} skip(s) so far)",
                    file=sys.stderr,
                )
                self._record(
                    job, "primary", "skipped",
                    queued_ts=job.queued_ts, start=now, end=now,
                )
                return False
            self._job = job
            self._idle.clear()
            self._wake.set()
        return True

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until no save is in flight. True = idle."""
        return self._idle.wait(timeout)

    def close(self, timeout: Optional[float] = None) -> None:
        """Drain and stop the thread (idempotent)."""
        self.drain(timeout)
        with self._lock:
            self._stop = True
            self._wake.set()
        self._thread.join(timeout)

    # -------------------------------------------------------------- thread
    def _run(self):
        while True:
            self._wake.wait()
            with self._lock:
                if self._stop:
                    return
                job, self._job = self._job, None
                self._wake.clear()
            if job is None:
                continue
            try:
                self._save(job)
            except BaseException as e:  # noqa: BLE001 — the writer
                # thread never dies: an unforeseen failure is a counted
                # failure, training (and the next cadence) continues
                self.failures += 1
                self.last_error = e
                print(
                    f"# checkpoint: async save of step {job.snapshot.step}"
                    f" failed ({type(e).__name__}: {e})",
                    file=sys.stderr,
                )
            finally:
                self._idle.set()

    def _save(self, job: SaveJob) -> None:
        step = job.snapshot.step
        t0 = time.perf_counter()
        t0_wall = time.time()
        flat = None
        primary_ok = False
        if not self.degraded:
            try:
                if job.fmt == "orbax":
                    save_orbax(
                        job.ckpt_dir, job.snapshot.state,
                        data_state=job.data_state,
                        publication=job.publication,
                    )
                else:
                    flat = job.snapshot.materialize()
                    write_flat(
                        job.ckpt_dir, flat, step,
                        data_state=job.data_state,
                        publication=job.publication,
                        tier="primary",
                    )
                primary_ok = True
            except OSError as e:
                with self._lock:
                    # the fit thread reads `degraded` (health surfacing)
                    self.degraded = True
                self.failures += 1
                self.last_error = e
                print(
                    f"# checkpoint: primary tier write failed at step "
                    f"{step} ({type(e).__name__}: {e}); degrading to "
                    "replica-only saves"
                    + ("" if job.replica_dir else
                       " — NO replica dir is configured: checkpointing "
                       "is now best-effort only"),
                    file=sys.stderr,
                )
                self._record(job, "primary", "failed",
                             queued_ts=job.queued_ts, start=t0_wall,
                             end=time.time())
            except Exception as e:  # noqa: BLE001 — a non-IO primary
                # failure (serialization bug, digest machinery) still
                # tries the replica for THIS save, without latching
                self.failures += 1
                self.last_error = e
                print(
                    f"# checkpoint: primary save of step {step} failed "
                    f"({type(e).__name__}: {e}); trying the replica tier",
                    file=sys.stderr,
                )
                self._record(job, "primary", "failed",
                             queued_ts=job.queued_ts, start=t0_wall,
                             end=time.time())
        if primary_ok:
            self.saves += 1
            self.last_step["primary"] = step
            self._record(job, "primary", "committed",
                         queued_ts=job.queued_ts, start=t0_wall,
                         end=time.time())
            self._span(job, t0_wall, time.perf_counter() - t0, step)
            prune_checkpoints(job.ckpt_dir, job.keep, fmt=job.fmt)
            if job.replica_dir:
                m0, m0_wall = time.perf_counter(), time.time()
                try:
                    mirror_step(job.ckpt_dir, job.replica_dir, step,
                                fmt=job.fmt)
                    prune_checkpoints(job.replica_dir, job.keep_replica,
                                      fmt=job.fmt)
                    self.saves += 1
                    self.last_step["replica"] = step
                    self._record(job, "replica", "committed",
                                 queued_ts=job.queued_ts, start=m0_wall,
                                 end=time.time())
                    self._span(job, m0_wall, time.perf_counter() - m0, step)
                except Exception as e:  # noqa: BLE001 — a replica-tier
                    # failure never harms the primary commit
                    self.failures += 1
                    self.last_error = e
                    print(
                        f"# checkpoint: replica mirror of step {step} "
                        f"failed ({type(e).__name__}: {e}); the primary "
                        "commit stands",
                        file=sys.stderr,
                    )
                    self._record(job, "replica", "failed",
                                 queued_ts=job.queued_ts, start=m0_wall,
                                 end=time.time())
        elif job.replica_dir:
            # degraded (or the primary just failed): the FULL save —
            # not a mirror, there is no primary copy — into the replica
            w0, w0_wall = time.perf_counter(), time.time()
            try:
                if job.fmt == "orbax":
                    save_orbax(job.replica_dir, job.snapshot.state,
                               data_state=job.data_state,
                               publication=job.publication)
                else:
                    if flat is None:
                        flat = job.snapshot.materialize()
                    write_flat(job.replica_dir, flat, step,
                               data_state=job.data_state,
                               publication=job.publication,
                               tier="replica")
                prune_checkpoints(job.replica_dir, job.keep_replica,
                                  fmt=job.fmt)
                self.saves += 1
                self.last_step["replica"] = step
                self._record(job, "replica", "committed",
                             queued_ts=job.queued_ts, start=w0_wall,
                             end=time.time())
                self._span(job, w0_wall, time.perf_counter() - w0, step)
            except Exception as e:  # noqa: BLE001 — both tiers failed:
                # counted, logged, training still lives
                self.failures += 1
                self.last_error = e
                print(
                    f"# checkpoint: replica-tier save of step {step} "
                    f"failed too ({type(e).__name__}: {e}); step not "
                    "checkpointed",
                    file=sys.stderr,
                )
                self._record(job, "replica", "failed",
                             queued_ts=job.queued_ts, start=w0_wall,
                             end=time.time())

    # ------------------------------------------------------------ telemetry
    def _record(self, job, tier, event, queued_ts, start, end):
        sink = self._sink
        if sink is None or not getattr(sink, "enabled", False):
            return
        # keys stay in lockstep with docs/OBSERVABILITY.md "Checkpoint
        # records" (XF501-parsed) and metrics_report.CKPT_KEYS; the
        # replica's queue_ms includes the primary write it mirrors
        sink.log({
            "kind": "ckpt",
            "step": int(job.snapshot.step),
            "tier": tier,
            "event": event,
            "queued_ts": round(float(queued_ts), 6),
            "committed_ts": round(float(end), 6),
            "queue_ms": round(max(start - queued_ts, 0.0) * 1000.0, 3),
            "write_ms": round(max(end - start, 0.0) * 1000.0, 3),
            "bytes": int(job.snapshot.nbytes),
            "skips": int(self.skips),
            "degraded": bool(self.degraded),
        })

    def _span(self, job, t0_wall, dur_s, step):
        sink = self._sink
        if (not self._ckpt_spans or sink is None
                or not getattr(sink, "enabled", False)):
            return
        from xflow_tpu.tracing import emit_op_span

        emit_op_span(sink, "checkpoint_save", t0_wall, dur_s,
                     step=int(step), bytes=int(job.snapshot.nbytes))


def export_sparse_array(w: np.ndarray, out_path: str) -> int:
    """Dump nonzero rows of a weight array as `slot\\tweight...` text."""
    w = np.asarray(w)
    if w.ndim == 1:
        nz = np.nonzero(w)[0]
    else:
        nz = np.nonzero(np.abs(w).sum(axis=tuple(range(1, w.ndim))))[0]
    with open(out_path, "w") as f:
        for i in nz:
            vals = (
                "%.8g" % w[i]
                if w.ndim == 1
                else "\t".join("%.8g" % x for x in np.ravel(w[i]))
            )
            f.write(f"{int(i)}\t{vals}\n")
    return int(nz.size)


def export_sparse(
    state: TrainState,
    out_path: str,
    table: str = "w",
    logical_widths: Optional[dict] = None,
) -> int:
    """Dump nonzero weights of a table as `slot\\tweight` text; returns count.

    Understands the fused FM layout (models/fm.py): requesting "w" or "v"
    from a state holding only "wv" slices the corresponding columns.

    `logical_widths` ({table: K}, from `model.table_specs`) unpacks the
    live packed [S/p, p*K] storage to logical [S, K] first, so slot ids
    and column slices are correct. It is REQUIRED when the state holds
    packed tables (the default since data.packed_tables landed) — without
    it a packed 2-D table cannot be told apart from a genuinely wide
    logical one, so we refuse rather than silently emit packed-row ids.
    Prefer Trainer.export_sparse, which passes the widths for you.
    """
    widths = logical_widths or {}

    def host(name: str) -> np.ndarray:
        arr = _to_host(state.tables[name])
        K = widths.get(name)
        if K:
            return _unpack_host(arr, K)
        if arr.ndim == 2:
            raise ValueError(
                f"export_sparse: no logical width for 2-D table {name!r} "
                f"(got logical_widths={sorted(widths)}) — cannot tell packed "
                "from logical storage. Pass the model's table_specs widths "
                "(Trainer.export_sparse does this)."
            )
        return arr

    if table not in state.tables and table in ("w", "v") and "wv" in state.tables:
        wv = host("wv")
        arr = wv[:, 0] if table == "w" else wv[:, 1:]
        return export_sparse_array(arr, out_path)
    return export_sparse_array(host(table), out_path)
