"""Checkpoint / resume.

The reference has NO checkpointing: trained weights live only in
server-process memory and vanish at `ps::Finalize` (SURVEY.md §5
"Checkpoint / resume: absent"). This module closes that gap:

- `save`/`restore`: whole-TrainState checkpoints. Single-host saves an
  .npz per step; multi-host (or when orbax is preferred) uses Orbax's
  sharded async-capable format so 1B-feature FTRL state never gathers
  onto one host (SURVEY.md §7 hard part d).
- `export_sparse`: serving export of the *nonzero* weights only — the
  sparse model FTRL's L1 produces is the artifact a CTR serving stack
  consumes (the reference's closest analog is its prediction dump).
"""

from __future__ import annotations

import json
import os
import re
from typing import Optional

import jax
import numpy as np

from xflow_tpu.train.state import TrainState

_STEP_RE = re.compile(r"^step_(\d+)$")


def _to_host(arr) -> np.ndarray:
    """Fetch a (possibly cross-process-sharded) array to every host."""
    if isinstance(arr, jax.Array) and not arr.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(arr, tiled=True))
    return np.asarray(arr)


def _unpack_host(arr: np.ndarray, K: Optional[int]) -> np.ndarray:
    """Host-side packed [S/p, p*K] -> logical [S, K] (a free reshape).
    npz checkpoints ALWAYS store the logical layout, so export tools,
    the C API, and runs with a different data.packed_tables setting all
    read the same format; restore() re-packs to the target shape."""
    if K and arr.ndim == 2 and arr.shape[1] != K and arr.shape[1] % K == 0:
        return arr.reshape(-1, K)
    return arr


def _flatten(state: TrainState, logical_widths: Optional[dict] = None) -> dict:
    widths = logical_widths or {}
    flat = {}
    for name, t in state.tables.items():
        flat[f"tables/{name}"] = _unpack_host(_to_host(t), widths.get(name))
    for name, st in state.opt_state.items():
        for k, v in st.items():
            flat[f"opt/{name}/{k}"] = _unpack_host(_to_host(v), widths.get(name))
    flat["step"] = _to_host(state.step)
    return flat


def save(ckpt_dir: str, state: TrainState, logical_widths: Optional[dict] = None) -> str:
    """Write a checkpoint; returns its path.

    Host-gathered npz format: in multi-process mode every rank gathers
    (the allgather is collective) but only process 0 writes. Fine up to
    tables that fit one host's RAM; the Criteo-1TB-scale sharded format
    is Orbax-based (see OrbaxCheckpointer below when available).
    `logical_widths` ({table: K}) unpacks packed storage so the file is
    layout-independent (_unpack_host).
    """
    step = int(state.step)
    path = os.path.join(ckpt_dir, f"step_{step}")
    flat = _flatten(state, logical_widths)  # collective: all ranks participate
    if jax.process_index() == 0:
        os.makedirs(path, exist_ok=True)
        np.savez(os.path.join(path, "state.npz"), **flat)
        meta = {
            "step": step,
            "tables": sorted(state.tables),
            "format": "npz",
        }
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(meta, f)
        # commit marker last: readers treat directories without it as partial
        with open(os.path.join(path, "COMMITTED"), "w") as f:
            f.write("ok\n")
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"ckpt_save_{step}")
    return path


def latest_step(ckpt_dir: str) -> Optional[int]:
    if not os.path.isdir(ckpt_dir):
        return None
    steps = []
    for name in os.listdir(ckpt_dir):
        m = _STEP_RE.match(name)
        if m and os.path.exists(os.path.join(ckpt_dir, name, "COMMITTED")):
            steps.append(int(m.group(1)))
    return max(steps) if steps else None


def restore(ckpt_dir: str, like: TrainState, step: Optional[int] = None) -> TrainState:
    """Restore into the sharding/structure of `like` (device_put per leaf)."""
    step = latest_step(ckpt_dir) if step is None else step
    if step is None:
        raise FileNotFoundError(f"no committed checkpoint under {ckpt_dir}")
    path = os.path.join(ckpt_dir, f"step_{step}")
    data = np.load(os.path.join(path, "state.npz"))

    def put(name: str, template):
        if name not in data:
            raise KeyError(
                f"checkpoint {path!r} has no array {name!r} (has "
                f"{sorted(data.files)}). If this is an FM checkpoint written "
                "with the two-table layout, set model.fm_fused=false to "
                "restore it (or re-train; the fused [S,1+k] layout is the "
                "current default)."
            )
        arr = data[name]
        if arr.shape != template.shape and arr.size == template.size:
            # layout migration: logical [S, K] stored <-> packed
            # [S/p, p*K] expected (or the reverse) is a pure reshape
            arr = arr.reshape(template.shape)
        sharding = getattr(template, "sharding", None)
        return jax.device_put(arr, sharding) if sharding is not None else arr

    tables = {n: put(f"tables/{n}", t) for n, t in like.tables.items()}
    opt_state = {
        n: {k: put(f"opt/{n}/{k}", v) for k, v in st.items()}
        for n, st in like.opt_state.items()
    }
    import jax.numpy as jnp

    return TrainState(tables=tables, opt_state=opt_state, step=jnp.asarray(data["step"]))


# --------------------------------------------------------------- orbax format
#
# The npz path above gathers the whole state to one host — fine for dev
# scale, impossible for the north-star config (1B-feature FTRL state,
# SURVEY.md §7 hard part d). The Orbax path saves each process's shards
# directly (OCDBT), so no host ever materializes the full table, and
# restore places shards straight onto the target sharding.

def save_orbax(ckpt_dir: str, state: TrainState) -> str:
    import orbax.checkpoint as ocp

    step = int(state.step)
    path = os.path.abspath(os.path.join(ckpt_dir, f"orbax_step_{step}"))
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, state._asdict(), force=True)
    return path


def latest_orbax_step(ckpt_dir: str) -> Optional[int]:
    if not os.path.isdir(ckpt_dir):
        return None
    steps = []
    for name in os.listdir(ckpt_dir):
        m = re.match(r"^orbax_step_(\d+)$", name)
        if m:
            steps.append(int(m.group(1)))
    return max(steps) if steps else None


def restore_orbax(ckpt_dir: str, like: TrainState, step: Optional[int] = None) -> TrainState:
    """Restore with `like`'s shardings (shards load directly per process)."""
    import orbax.checkpoint as ocp

    step = latest_orbax_step(ckpt_dir) if step is None else step
    if step is None:
        raise FileNotFoundError(f"no orbax checkpoint under {ckpt_dir}")
    path = os.path.abspath(os.path.join(ckpt_dir, f"orbax_step_{step}"))

    def as_abstract(x):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None))

    abstract = jax.tree.map(as_abstract, like._asdict())
    try:
        with ocp.StandardCheckpointer() as ckptr:
            restored = ckptr.restore(path, abstract)
    except Exception as e:
        if "wv" in like.tables:
            # likely a pre-fused FM checkpoint (two-table layout): surface a
            # migration hint instead of orbax's raw tree-mismatch error
            raise RuntimeError(
                f"orbax restore of {path!r} failed ({e}). If this is an FM "
                "checkpoint written with the two-table layout, set "
                "model.fm_fused=false to restore it — the fused [S,1+k] "
                "layout is the current default."
            ) from e
        raise
    return TrainState(**restored)


def export_sparse_array(w: np.ndarray, out_path: str) -> int:
    """Dump nonzero rows of a weight array as `slot\\tweight...` text."""
    w = np.asarray(w)
    if w.ndim == 1:
        nz = np.nonzero(w)[0]
    else:
        nz = np.nonzero(np.abs(w).sum(axis=tuple(range(1, w.ndim))))[0]
    with open(out_path, "w") as f:
        for i in nz:
            vals = (
                "%.8g" % w[i]
                if w.ndim == 1
                else "\t".join("%.8g" % x for x in np.ravel(w[i]))
            )
            f.write(f"{int(i)}\t{vals}\n")
    return int(nz.size)


def export_sparse(state: TrainState, out_path: str, table: str = "w") -> int:
    """Dump nonzero weights of a table as `slot\\tweight` text; returns count.

    Understands the fused FM layout (models/fm.py): requesting "w" or "v"
    from a state holding only "wv" slices the corresponding columns."""
    if table not in state.tables and table in ("w", "v") and "wv" in state.tables:
        wv = _to_host(state.tables["wv"])
        arr = wv[:, 0] if table == "w" else wv[:, 1:]
        return export_sparse_array(arr, out_path)
    return export_sparse_array(_to_host(state.tables[table]), out_path)
