"""Training orchestration.

The reference's worker loop (`LRWorker::batch_training`,
`/root/reference/src/model/lr/lr_worker.cc:179-205`: epochs → IO blocks
→ thread fan-out → Pull/compute/Push) and its rank-0 predict pass
(`lr_worker.cc:207-217`) become: epochs → prefetched padded batches →
one jitted SPMD step; then an eval pass that dumps
``pred_<rank>_<block>.txt`` rows (``pctr\\t1-label\\tlabel``,
`lr_worker.cc:67`) and prints logloss/AUC like `base.h:101-108`.
"""

from __future__ import annotations

import os
import sys
import time
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from xflow_tpu.config import Config
from xflow_tpu.jsonl import JsonlAppender
from xflow_tpu.data.pipeline import (
    assign_shards,
    batch_iterator,
    count_batches,
    prefetch,
)
from xflow_tpu.metrics import auc_logloss
from xflow_tpu.models import get_model
from xflow_tpu.telemetry import (
    HangWatchdog,
    HealthMonitor,
    PipelineProfiler,
    StepTimer,
    TraceWindow,
    default_registry,
    hbm_window_fields,
    install_stack_dump_handler,
    resolve_restart_gen,
    resolve_run_id,
)
from xflow_tpu.optim import get_optimizer
from xflow_tpu.train.state import TrainState, init_state
from xflow_tpu.train.step import (
    batch_to_arrays,
    make_eval_step,
    make_train_step,
    nonfinite_guard_on,
)


class NonFiniteHalt(RuntimeError):
    """Raised by fit() when the non-finite guard aborts the run
    (train.nonfinite_guard=halt, or nonfinite_max_consecutive discarded
    steps in a row under skip). A checkpoint of the last GOOD state was
    committed before raising whenever train.checkpoint_dir is set."""


@dataclass
class TrainResult:
    steps: int = 0
    epochs: int = 0
    examples: int = 0
    seconds: float = 0.0
    last_loss: float = float("nan")
    auc: float = float("nan")
    logloss: float = float("nan")
    occupancy: dict = field(default_factory=dict)
    interrupted: int = 0  # signal number when preempted mid-run (A3)
    bad_steps: int = 0  # non-finite updates discarded by the guard

    @property
    def examples_per_sec(self) -> float:
        return self.examples / self.seconds if self.seconds > 0 else 0.0


def resolve_eval_buckets(value: int, multiproc: bool) -> int:
    """train.eval_buckets -1 = auto: exact single-process; bucketed
    (65536) multi-process, so the default pod-scale config has ZERO
    per-batch host collectives (the exact path allgathers a stacked
    [B, 3] array per eval batch — round-2 weak #5). Depends only on
    config + process count, identical on every process — a per-rank
    choice would mismatch the collective sequences and deadlock."""
    return value if value >= 0 else (65536 if multiproc else 0)


class MetricsLogger(JsonlAppender):
    """Structured per-step metrics: JSONL to a file, or quiet.

    Lifecycle (lazy open with parent-dir creation, flush-per-record,
    reopen-safe close) comes from the shared appender (xflow_tpu/jsonl.py)
    — fit() closes the logger in its finally, and a later record (a
    second fit() on the same Trainer) transparently reopens in append
    mode."""

    log = JsonlAppender.append


class Trainer:
    def __init__(self, cfg: Config, mesh=None, process_index: int = 0):
        self.cfg = cfg
        self.model = get_model(cfg.model.name)
        self.optimizer = get_optimizer(cfg.optim.name)
        self.mesh = mesh
        self.rank = process_index
        # provenance stamp: every metrics record carries ts/rank/run_id
        # (jsonl.JsonlAppender) so per-rank streams from one run join.
        # Built BEFORE the engines: the compile recorder below is the
        # seam every step/predict jit routes through, and its
        # kind="compile" records land in the same stamped stream.
        self.run_id = resolve_run_id()
        # multi-slice identity: slice j stamps rank j (XFLOW_PROCESS_ID,
        # exported by launch-multislice) even though each slice is
        # process 0 of its own single-process world — the shared
        # watchdog and metrics_report key per-slice streams on the rank
        # stamp. Everyone else keeps the process index, byte-identical.
        self._stamp_rank = self.rank
        if os.environ.get("XFLOW_SLICE") is not None:
            from xflow_tpu.telemetry import resolve_rank

            self._stamp_rank = resolve_rank()
        self.metrics = MetricsLogger(
            cfg.train.metrics_path,
            stamp={"rank": self._stamp_rank, "run_id": self.run_id},
            max_bytes=cfg.train.metrics_max_bytes,
        )
        # lazily-started background checkpoint writer (train.ckpt_async)
        self._ckpt_writer = None
        # compile accounting (train.compile_metrics, docs/OBSERVABILITY.md
        # "Compile accounting"): explicit timed .lower().compile() per
        # program with XLA cost/memory analysis; recompiles counted
        from xflow_tpu.telemetry import CompileRecorder

        self.compile_recorder = (
            CompileRecorder(sink=self.metrics)
            if cfg.train.compile_metrics
            else None
        )
        _rec = self.compile_recorder
        # sorted-window table layout (ops/sorted_table.py):
        # - single device: fused-FM and MVM (Pallas kernels / XLA fallback)
        # - mesh: fused-FM and MVM via one of two engines selected by
        #   data.sorted_mesh — "fullshard" (default; table + state sharded
        #   over the WHOLE mesh, parallel/sorted_fullshard.py) or
        #   "replicated" (table on the 'table' axis only, D× memory,
        #   parallel/sorted_sharded.py). Multi-process works when the data
        #   axis divides across processes (2-process subprocess-tested for
        #   both engines). Configs neither engine can run keep the GSPMD
        #   row-major path.
        from xflow_tpu.ops.sorted_table import WINDOW, resolve_sub_batches

        sl = cfg.data.sorted_layout
        # mesh sorted engine: None (GSPMD row-major) | "fullshard"
        # (parallel/sorted_fullshard.py — table + state sharded over the
        # WHOLE mesh, no replication; the 1B-feature-regime fast path) |
        # "replicated" (parallel/sorted_sharded.py — 'table'-axis-only
        # sharding, D× table memory, fewer collectives)
        self._mesh_engine = None
        if mesh is not None:
            engine = cfg.data.sorted_mesh
            if engine not in ("fullshard", "replicated"):
                raise ValueError(
                    f"data.sorted_mesh={engine!r}: expected 'fullshard' or "
                    "'replicated'"
                )
            from xflow_tpu.parallel.sorted_fullshard import validate_sorted_fullshard
            from xflow_tpu.parallel.sorted_sharded import validate_sorted_sharded

            if sl == "on":
                # forced: reject unrunnable configs with the specific reason
                if engine == "fullshard":
                    validate_sorted_fullshard(cfg, mesh)
                else:
                    validate_sorted_sharded(cfg, mesh)
                self._mesh_engine = engine
            elif sl == "auto" and engine == "fullshard":
                # auto enables the fully-sharded engine whenever the config
                # can run it (it IS the fast path for FM/MVM, with the same
                # no-replication memory story as GSPMD); the replicated
                # engine stays opt-in only — its D× table memory must be an
                # explicit choice
                try:
                    validate_sorted_fullshard(cfg, mesh)
                    self._mesh_engine = "fullshard"
                except ValueError:
                    self._mesh_engine = None
            self._sorted = self._mesh_engine is not None
        else:
            supported = (
                cfg.model.name == "fm" and cfg.model.fm_fused
            ) or cfg.model.name in ("mvm", "ffm")
            # FFM under auto runs the ALIGNED HYBRID sorted engine since
            # round 5 (models/ffm.py: windowed gather + host placement
            # permutation + fused scatter+FTRL — 512k ex/s at B=64k vs
            # the round-4 row-major path's 193k at 16k, docs/PERF.md).
            # Batches with duplicate (row, field) occurrences fall back
            # per batch to the layout-fixed row-major einsum path
            # (_batch_arrays); the old per-(row, field) segment engine
            # remains the fullshard MESH row side only.
            self._sorted = sl == "on" or (
                sl == "auto" and supported and cfg.num_slots % WINDOW == 0
            )
            if sl == "on":
                # 'on' forces the layout, so reject configurations where it
                # cannot work instead of failing deep inside sharding/XLA
                # (or silently paying the host sort for an unused layout)
                if not supported:
                    raise ValueError(
                        "sorted_layout=on requires model.name=fm with "
                        "model.fm_fused=true, model.name=mvm, or "
                        f"model.name=ffm; got model={cfg.model.name} "
                        f"fm_fused={cfg.model.fm_fused}"
                    )
                if cfg.num_slots % WINDOW != 0:
                    raise ValueError(
                        f"sorted_layout=on needs num_slots divisible by {WINDOW}; "
                        f"got 2^{cfg.data.log2_slots}"
                    )
        self._sorted_sharded = self._sorted and mesh is not None
        if self._sorted_sharded:
            # one plan per LOCAL data shard; other processes build theirs
            self._sorted_sub = mesh.shape["data"] // jax.process_count()
        else:
            # FFM's aligned hybrid has no per-(row, field) segment
            # state to keep cache-resident, and its placement permutation
            # is defined over the whole batch — always one flat plan
            self._sorted_sub = (
                1
                if cfg.model.name == "ffm"
                else resolve_sub_batches(cfg) if self._sorted else 1
            )
        if mesh is not None:
            if cfg.optim.fused_scatter == "on":
                # fail at STARTUP, not data-dependently: the mesh engines
                # run the two-pass form (the in-place window kernel's
                # contract is the single-device step), and the fullshard
                # overflow fallback builds its GSPMD step lazily — under
                # "on" that build would raise mid-run on the first skewed
                # batch of a long job
                raise ValueError(
                    "optim.fused_scatter=on requires the single-device "
                    "step; mesh engines run the two-pass form — use auto "
                    "(fuses where eligible) or off"
                )
            from xflow_tpu.parallel.train_step import make_sharded_train_step, make_sharded_eval_step, shard_state

            if self._mesh_engine == "fullshard":
                from xflow_tpu.parallel.sorted_fullshard import (
                    make_fullshard_train_step,
                )

                # shard_state's default layout IS the fullshard layout:
                # every table/opt leaf P(('data','table')) on the slot axis
                self.state = shard_state(
                    init_state(self.model, self.optimizer, cfg), mesh
                )
                fullshard_step = make_fullshard_train_step(
                    self.optimizer, cfg, mesh, recorder=_rec
                )
                # per-batch dispatch: a batch too skewed for the buffer
                # capacity arrives as row-major arrays (single-process
                # overflow fallback in _batch_arrays) and runs the GSPMD
                # step — the state sharding is identical, so the two
                # steps interleave freely
                gspmd = {}

                def _dispatch(state, batch):
                    if "fs_slots" in batch:
                        return fullshard_step(state, batch)
                    if "step" not in gspmd:
                        gspmd["step"] = make_sharded_train_step(
                            self.model, self.optimizer, cfg, mesh,
                            recorder=_rec,
                        )
                    return gspmd["step"](state, batch)

                self.train_step = _dispatch
            elif self._mesh_engine == "replicated":
                # multi-process `mvm_exclusive=auto` here behaves like
                # `on`: clean one-feature-per-field data takes the
                # product path; a duplicate-field batch raises
                # (resolve_mvm_product — only the fullshard engine has
                # the per-batch flag allgather that makes data-dependent
                # routing rank-symmetric)
                from xflow_tpu.parallel.sorted_sharded import (
                    make_sorted_sharded_train_step,
                    shard_sorted_state,
                )

                self.state = shard_sorted_state(
                    init_state(self.model, self.optimizer, cfg), mesh
                )
                self.train_step = make_sorted_sharded_train_step(
                    self.optimizer, cfg, mesh, recorder=_rec
                )
            else:
                self.state = shard_state(
                    init_state(self.model, self.optimizer, cfg), mesh
                )
                self.train_step = make_sharded_train_step(
                    self.model, self.optimizer, cfg, mesh, recorder=_rec
                )
            # eval: the fullshard engine consumes the SAME host plan as
            # training (round-3 weak #5: the row-major [B, F] arrays are
            # dead ~24 MB/batch transfers there); overflow-fallback
            # batches arrive row-major and run the GSPMD eval step
            # (make_sharded_eval_step adopts the tables' LIVE sharding
            # as its in_sharding — jit never reshards explicit
            # in_shardings). The replicated engine keeps row-major eval.
            gspmd_eval = make_sharded_eval_step(self.model, cfg, mesh, recorder=_rec)
            if self._mesh_engine == "fullshard":
                from xflow_tpu.parallel.sorted_fullshard import (
                    make_fullshard_eval_step,
                )

                fullshard_eval = make_fullshard_eval_step(cfg, mesh, recorder=_rec)

                def _eval_dispatch(tables, arrays):
                    if "fs_slots" in arrays:
                        return fullshard_eval(tables, arrays)
                    return gspmd_eval(tables, arrays)

                self.eval_step = _eval_dispatch
            else:
                self.eval_step = gspmd_eval
            self._shard_batch = lambda b: _shard_batch_arrays(b, mesh)
        else:
            self.state = init_state(self.model, self.optimizer, cfg)
            self.train_step = make_train_step(
                self.model, self.optimizer, cfg, recorder=_rec
            )
            self.eval_step = make_eval_step(self.model, cfg, recorder=_rec)
            # ONE async device_put for the whole dict: per-array jnp.asarray
            # is a synchronous round trip each, which dominates on
            # high-latency links (tunneled devices: ~9 arrays × RTT/step)
            self._shard_batch = jax.device_put
        # host dedup for row-major batches (ops/sorted_table.dedup_slots):
        # single-process only — the unique count is data-dependent and a
        # per-rank overflow fallback would desync collective programs
        if cfg.data.dedup not in ("auto", "off"):
            raise ValueError(f"data.dedup={cfg.data.dedup!r}: expected auto|off")
        # packed shard cache (data/shardcache.py, docs/DATA.md):
        # validated at CONSTRUCTION like the guard/dedup modes (identical
        # config on every rank → rank-symmetric), not on the first shard
        # open deep inside the prefetch thread
        if cfg.data.cache not in ("auto", "on", "off"):
            raise ValueError(
                f"data.cache={cfg.data.cache!r}: expected auto|on|off"
            )
        self._dedup_cap = (
            int(cfg.data.batch_size * cfg.data.max_nnz * cfg.data.dedup_cap_frac)
            if cfg.data.dedup == "auto" and jax.process_count() == 1
            else 0
        )
        self._dedup_on = None  # undecided until the first row-major batch
        # model-health monitor (train.health_metrics, docs/OBSERVABILITY.md
        # "Health metrics"): consumes the step builders' fused norm
        # scalars one step behind, owns the loss EMA and the
        # occupancy/collision gauges. Validated at CONSTRUCTION like the
        # guard mode (identical config on every rank → rank-symmetric).
        from xflow_tpu.train.step import health_mode

        self._health = HealthMonitor(
            mode=health_mode(cfg),
            ema_decay=cfg.train.health_ema_decay,
            num_slots=cfg.num_slots,
        )
        # input-pipeline stage profiler (train.pipeline_metrics,
        # docs/OBSERVABILITY.md "Input-pipeline attribution"): threaded
        # through the TRAINING stream only (fit passes profiled=True to
        # _coordinated_batches; eval streams stay unprofiled so a
        # mid-run holdout pass never muddies the training attribution).
        # None when off — every instrumented seam then takes its exact
        # pre-profiler path, keeping off-runs byte-identical.
        self.pipeline_prof = (
            PipelineProfiler() if cfg.train.pipeline_metrics else None
        )
        # liveness heartbeat (train.heartbeat_path): tiny {step} records
        # the launcher watchdog and metrics_report --health read to flag
        # dead ranks and stragglers; kind="heartbeat" keeps the stream
        # distinct from metrics when both land in one run dir
        self.heartbeat = JsonlAppender(
            cfg.train.heartbeat_path,
            stamp={
                "rank": self._stamp_rank,
                "run_id": self.run_id,
                "kind": "heartbeat",
            },
        )
        # cross-slice bounded-staleness sync tier (sync.mode, parallel/
        # multislice.py, docs/DISTRIBUTED.md "Multi-slice bounded
        # staleness"): the fit loop publishes/gathers additive table
        # deltas every sync.every_steps steps, OUTSIDE the jit programs.
        # None when off — the default path stays byte-identical.
        self._syncer = None
        if cfg.sync.mode != "off":
            from xflow_tpu.parallel.multislice import SliceSyncer
            from xflow_tpu.telemetry import resolve_num_slices, resolve_slice

            self._syncer = SliceSyncer(
                cfg.sync,
                slice_id=resolve_slice() or 0,
                num_slices=resolve_num_slices(),
            )
        # data-stream position for exact resume (elastic recovery,
        # docs/ROBUSTNESS.md): (epoch, batches consumed within it) plus
        # the TOPOLOGY-INDEPENDENT truth — per-SHARD consumed-batch
        # counts (_shard_pos) and the shard set in play (_num_shards) —
        # maintained by the fit loop and snapshotted into every
        # checkpoint's data_state, so a run checkpointed at N ranks
        # resumes at M ranks with exact record-set coverage.
        # _examples_seen counts THIS process's rows this generation;
        # _examples_base carries the restored GLOBAL total forward.
        # _resume_data_state holds what maybe_restore read back,
        # consumed by the next fit().
        self._epoch_pos = (0, 0)
        self._shard_pos: dict = {}
        self._num_shards = 0
        self._examples_seen = 0
        self._examples_base = 0
        self._resume_data_state: Optional[dict] = None
        # time-decayed eval window (train.eval_window_decay): the
        # (BucketAUC, ll_sum, n_rows) accumulator the streaming eval
        # passes decay-and-fold into; None until the first decayed pass
        self._eval_window: Optional[tuple] = None
        # validate the guard mode at CONSTRUCTION (identical config on
        # every rank → rank-symmetric), not on the first bad batch
        self._guarded = nonfinite_guard_on(cfg)
        self._fullshard_overflow_warned = False
        # MVM and FFM key their views/blocks on the field id: a field >=
        # num_fields would be silently dropped by the one-hot, so reject
        # it loudly
        self._validate_fields = cfg.model.name in ("mvm", "ffm")

    def _check_batch(self, batch) -> None:
        if self._validate_fields:
            max_field = int(np.max(batch.fields)) if batch.fields.size else 0
            if max_field >= self.cfg.model.num_fields:
                raise ValueError(
                    f"libffm field id {max_field} >= model.num_fields="
                    f"{self.cfg.model.num_fields}; raise model.num_fields"
                )

    def _mvm_wants_fields(self, batch) -> tuple[bool, Optional[bool]]:
        """(plan with per-occurrence fields?, duplicate flag to coordinate).

        fields=False = the exclusive-fields product path (models/mvm.py):
        the host verified no row repeats a field, so the step needs
        neither the fields array nor the [B·nf] segment space. Routing is
        per-batch under `auto`: single-process decides locally; the
        multi-process fullshard engine plans WITH fields unconditionally
        and returns the local duplicate flag, which
        `_resolve_fullshard_overflow` allgathers so every rank picks the
        SAME mode for the batch (a local raise — round-3 ADVICE — would
        leave peer ranks blocked in their collectives). `on` keeps its
        contract: duplicates raise (resolve_mvm_product)."""
        from xflow_tpu.models.mvm import has_field_duplicates, resolve_mvm_product

        excl = self.cfg.model.mvm_exclusive
        multiproc = jax.process_count() > 1
        if excl == "auto" and multiproc and self._mesh_engine == "fullshard":
            return True, bool(has_field_duplicates(batch.fields, batch.mask))
        dup = excl != "off" and has_field_duplicates(batch.fields, batch.mask)
        return not resolve_mvm_product(excl, dup, jax.process_count()), None

    def _resolve_ffm_aligned(self, batch) -> bool:
        """Route one FFM batch: aligned hybrid (True) or the row-major
        general path (False). Mirrors MVM's product routing contracts:
        single-process routes per batch; multi-process (non-fullshard)
        cannot — the two paths' collective programs differ across ranks
        — so duplicate fields raise there; forced `sorted_layout=on`
        raises too (the user asserted the sorted engine, and FFM's
        sorted engine is the aligned hybrid)."""
        from xflow_tpu.models.ffm import resolve_ffm_aligned

        aligned = resolve_ffm_aligned(batch.fields, batch.mask)
        if aligned:
            return True
        forced = self.cfg.data.sorted_layout == "on"
        if forced or jax.process_count() > 1:
            raise ValueError(
                "FFM aligned hybrid: a row carries two masked occurrences "
                "of the same field. "
                + (
                    "sorted_layout=on requires aligned batches; use auto "
                    "for the per-batch row-major fallback"
                    if forced
                    else "this multi-process configuration cannot fall "
                    "back per batch (the paths' programs differ across "
                    "ranks); set data.sorted_layout=off"
                )
            )
        return False

    def _batch_arrays(self, batch, with_plan: bool = True) -> dict:
        """SparseBatch -> step input arrays (+ sorted-layout plan).

        On the sorted paths the step consumes ONLY the plan +
        labels/row_mask (+ sorted_fields for MVM's segment path), so the
        row-major [B, F] arrays are dropped — they would be dead ~24 MB
        host→device transfers per 64k-row batch. Eval batches build
        plans too (single-device sorted and fullshard-mesh eval both
        consume them); only the replicated mesh engine's eval passes
        `with_plan=False` and keeps row-major.
        """
        arrays = batch_to_arrays(batch)
        if self._sorted and with_plan and self._mesh_engine == "fullshard":
            from xflow_tpu.parallel.sorted_fullshard import (
                FullshardOverflowError,
                plan_fullshard_batch,
            )

            mvm = self.cfg.model.name == "mvm"
            if mvm:
                want_fields, dup_flag = self._mvm_wants_fields(batch)
            else:
                # FFM always consumes per-occurrence fields (its segment
                # space is row·nf + field); FM never does
                want_fields, dup_flag = self.cfg.model.name == "ffm", None
            try:
                from xflow_tpu.ops.sorted_table import compact_plan_wire

                out = {"labels": arrays["labels"], "row_mask": arrays["row_mask"]}
                out.update(
                    plan_fullshard_batch(
                        np.asarray(batch.slots),
                        np.asarray(batch.mask),
                        self.cfg,
                        self.mesh,
                        fields=np.asarray(batch.fields) if want_fields else None,
                    )
                )
                d_ax = self.mesh.shape["data"]
                out = compact_plan_wire(
                    out,
                    rows_bound=self.cfg.data.batch_size
                    // (d_ax // jax.process_count()),
                    fields_bound=self.cfg.model.num_fields if want_fields else 0,
                )
                if dup_flag is not None:
                    # multi-process auto routing: the fit loop's per-batch
                    # allgather decides product vs segment for ALL ranks
                    out["_mvm_dup"] = dup_flag
                return out
            except FullshardOverflowError:
                if not self._fullshard_overflow_warned:
                    self._fullshard_overflow_warned = True
                    print(
                        "fullshard: batch too skewed for "
                        f"data.fullshard_slack={self.cfg.data.fullshard_slack}; "
                        "falling back to the GSPMD row-major step for such "
                        "batches (raise the slack to keep the fast path)",
                        file=sys.stderr,
                    )
                    self.metrics.log({"fullshard_overflow_fallback": True})
                # row-major: the GSPMD step handles it — THROUGH dedup if
                # enabled (overflow batches are the most skewed = exactly
                # where the cross-chip dedup win lives). Multi-process: the
                # marker makes _resolve_fullshard_overflow (fit loop, main
                # thread) pull EVERY rank onto the row-major step for this
                # batch — a per-rank fallback would desync the ranks'
                # collective programs and deadlock.
                arrays = self._maybe_dedup(arrays, batch)
                if jax.process_count() > 1:
                    arrays["_fs_overflow"] = True
                return arrays
        if self._sorted and with_plan:
            from xflow_tpu.ops.sorted_table import plan_sorted_stacked

            if self.cfg.model.name == "ffm" and not self._resolve_ffm_aligned(batch):
                # duplicate (row, field) occurrence: the aligned hybrid
                # cannot place this batch — run the row-major general
                # einsum path for it (single-process per-batch routing,
                # same pattern as MVM's product fallback)
                return self._maybe_dedup(arrays, batch)
            arrays = {"labels": arrays["labels"], "row_mask": arrays["row_mask"]}
            want_fields = self.cfg.model.name == "ffm" or (
                self.cfg.model.name == "mvm" and self._mvm_wants_fields(batch)[0]
            )
            rows_bound = self.cfg.data.batch_size // max(self._sorted_sub, 1)
            plan = plan_sorted_stacked(
                np.asarray(batch.slots),
                np.asarray(batch.mask),
                self.cfg.num_slots,
                fields=np.asarray(batch.fields) if want_fields else None,
                num_sub=self._sorted_sub,
                # the sharded engine wants a leading [D] axis even at D=1
                always_stack=self._sorted_sharded,
                # CONFIG-derived (rank-symmetric) wire decision, the same
                # rule compact_plan_wire applies — the C planner then
                # emits uint16/uint8 directly and the compaction below
                # passes the arrays through untouched
                wire=rows_bound <= (1 << 16)
                and (not want_fields or self.cfg.model.num_fields <= (1 << 8)),
            )
            arrays.update(
                sorted_slots=plan.sorted_slots,
                sorted_row=plan.sorted_row,
                sorted_mask=plan.sorted_mask,
                win_off=plan.win_off,
            )
            if want_fields:
                arrays["sorted_fields"] = plan.sorted_fields
            if self.cfg.model.name == "ffm":
                from xflow_tpu.models.ffm import ffm_invperm

                arrays["ffm_invperm"] = ffm_invperm(
                    plan.sorted_row, plan.sorted_fields, plan.sorted_mask,
                    int(arrays["labels"].shape[0]), self.cfg.model.num_fields,
                )
            from xflow_tpu.ops.sorted_table import compact_plan_wire

            arrays = compact_plan_wire(
                arrays,
                rows_bound=self.cfg.data.batch_size // max(self._sorted_sub, 1),
                fields_bound=self.cfg.model.num_fields if want_fields else 0,
            )
        else:
            arrays = self._maybe_dedup(arrays, batch)
        return arrays

    def _resolve_fullshard_overflow(self, batch, arrays: dict) -> dict:
        """Rank-symmetric per-batch engine agreement (round-3 weak #1 +
        ADVICE: MVM auto-routing desync).

        Multi-process fullshard only: every rank contributes a [2]-int32
        flag vector — (occurrence buffers overflowed, MVM batch has
        duplicate fields) — to ONE host allgather per batch, and all
        ranks act on the elementwise max:

        - any overflow → ALL ranks run this batch on the GSPMD row-major
          step (identical state sharding, so the two jitted programs
          interleave — the same dispatch the single-process fallback
          uses). Ranks whose plan succeeded rebuild row-major arrays
          from the still-held SparseBatch (a host reshape, no re-parse).
          The reference never dies on a hot key — its PS just serves it
          slowly (`/root/reference/src/optimizer/ftrl.h:54-79`).
        - MVM under `mvm_exclusive=auto`: plans carry fields
          unconditionally (_mvm_wants_fields); if NO rank saw duplicate
          fields, every rank drops `fs_fields` here — before the
          device transfer — and the batch runs the fast product mode;
          any duplicate anywhere keeps the segment mode everywhere.

        Cost: one [2]-int32 host allgather per train batch, ~100-200 µs
        on CPU rendezvous — noise against the ≥40 ms device step at
        bench shapes (docs/DISTRIBUTED.md "Hot keys"). Runs on the MAIN
        thread (the prefetch thread builds plans; collectives from two
        threads could interleave across ranks).
        """
        if self._mesh_engine != "fullshard" or jax.process_count() == 1:
            return arrays
        from jax.experimental import multihost_utils

        mine_over = bool(arrays.pop("_fs_overflow", False))
        mine_dup = arrays.pop("_mvm_dup", None)
        flags = np.array([mine_over, bool(mine_dup)], np.int32)
        got = (
            np.asarray(multihost_utils.process_allgather(flags))
            .reshape(-1, 2)
            .max(axis=0)
        )
        if got[0]:
            if not mine_over:
                # a peer overflowed: drop my fullshard plan, rebuild
                # row-major. No dedup here — multi-process forces
                # _dedup_cap off (per-batch capacity routing would give
                # ranks different jitted programs, the exact desync this
                # method prevents)
                arrays = batch_to_arrays(batch)
        elif mine_dup is not None and not got[1]:
            arrays.pop("fs_fields", None)  # all-clear: product mode
        return arrays

    def _maybe_dedup(self, arrays: dict, batch) -> dict:
        """Attach the deduped gather arrays to a row-major batch when the
        batch fits the capacity (data.dedup). The first batch DECIDES
        for the run: if its unique count overflows (near-uniform data —
        dedup unprofitable there anyway), stop paying the host np.unique
        sort on every subsequent batch. On success the dead [B, F] slots
        array is dropped from the transfer (batch_rows reads only
        unique_slots/inverse)."""
        if not self._dedup_cap or self._dedup_on is False:
            return arrays
        from xflow_tpu.ops.sorted_table import dedup_slots

        got = dedup_slots(np.asarray(batch.slots), self._dedup_cap)
        if got is not None:
            arrays = dict(arrays)
            arrays["unique_slots"], arrays["inverse"] = got
            arrays.pop("slots", None)
            self._dedup_on = True
        elif self._dedup_on is None:
            self._dedup_on = False
        return arrays

    # -------------------------------------------------------- multi-process IO
    def _empty_batch(self):
        from xflow_tpu.data.schema import SparseBatch

        B, F = self.cfg.data.batch_size, self.cfg.data.max_nnz
        return SparseBatch(
            slots=np.zeros((B, F), np.int32),
            fields=np.zeros((B, F), np.int32),
            mask=np.zeros((B, F), np.float32),
            labels=np.zeros((B,), np.float32),
            row_mask=np.zeros((B,), np.float32),
        )

    def _epoch_batch_count(
        self, shards: list, skips: dict
    ) -> tuple[int, int]:
        """(global_steps, local_batches) for one pass over this rank's
        assigned `shards` ([(shard index, path)]), with each shard's
        stored `skips` offset fast-forwarded (data_state resume; the
        skip map comes from the checkpoint so it is identical on every
        rank, and each rank subtracts only its OWN shards' offsets —
        rank-symmetric by construction).

        SPMD steps are collective: if process A has 10 batches and process
        B has 9 (ragged shards — the reference tolerates this because its
        async workers never synchronize), B would deadlock A. Instead of
        a per-step host allgather (which dominates at µs-scale step times,
        round-1 weak #5), each process counts its local batches with the
        parser-matched row counter, and ONE allgather per epoch pass
        fixes the global step count = max over processes. Re-counted every
        pass (not cached) so shards that appear, grow, or shrink between
        epochs are picked up. A missing shard counts as 0 batches
        (reference: rank k simply finds no `<prefix>-%05d` file and its
        workers idle).
        """
        local = 0
        for idx, path in shards:
            try:
                n = count_batches(path, self.cfg.data)
            except FileNotFoundError:
                n = 0
            local += max(n - max(int(skips.get(idx, 0)), 0), 0)
        if jax.process_count() == 1:
            return local, local
        from jax.experimental import multihost_utils

        counts = np.asarray(multihost_utils.process_allgather(np.int32(local)))
        return int(counts.max()), local

    def _with_arrays(
        self,
        batch,
        with_plan: bool = True,
        track_health: bool = True,
        profiler=None,
    ):
        """(batch, step-input arrays) — validation + sorted-plan building
        happen HERE so that, wrapped in `prefetch`, the host-side sort
        overlaps device compute instead of serializing with dispatch.
        Training batches also feed the health monitor's touched-slot
        bitmap here (same overlap argument; eval passes skip it).
        `profiler` attributes the whole conversion — validation, sorted
        plan, dedup, array build — as the "plan" stage."""
        self._check_batch(batch)
        if track_health:
            self._health.observe_batch(batch.slots, batch.mask)
        if profiler is None:
            return batch, self._batch_arrays(batch, with_plan=with_plan)
        with profiler.stage("plan"):
            arrays = self._batch_arrays(batch, with_plan=with_plan)
        return batch, arrays

    def _coordinated_batches(
        self,
        path: "str | list",
        with_plan: bool = True,
        enforce_bad_rows: bool = True,
        quarantine: bool = True,
        track_health: bool = True,
        skip: int = 0,
        skips: Optional[dict] = None,
        profiled: bool = False,
    ):
        """Yield exactly the globally-agreed number of (batch, arrays)
        pairs for this rank's shard stream, padding with fully-masked
        empty batches once local input is exhausted.

        `path` is a single file (legacy single-shard contract, shard
        index = this rank) or a [(shard index, path)] assignment
        (`data/pipeline.assign_shards` — an elastic world where one
        rank may own several shards of the original record set); shards
        are streamed sequentially. One counting allgather per epoch
        pass — re-counted every pass so shards that appear, grow, or
        shrink between epochs are picked up (`_epoch_batch_count`); the
        batch stream itself adds no host collectives (the fullshard
        overflow flag, when that engine is on, is the fit loop's, not
        this iterator's). `with_plan` false skips sorted-plan building
        (mesh eval runs row-major); `enforce_bad_rows`/`quarantine`
        thread through to the bad-record monitor (eval passes count but
        never raise; only the first training pass quarantines).
        `skips` ({shard index -> batches}, or the legacy scalar `skip`)
        fast-forwards each shard past its stored offset (checkpointed
        data_state resume, data/pipeline.skip_batches) — the skipped
        prefix is neither planned, monitored, nor counted toward this
        pass's coordinated step total. Every REAL pair's arrays carry a
        `_shard` marker (popped by the consuming loop before the device
        transfer) so the fit loop can maintain the per-shard position
        the next checkpoint's data_state pins; padding pairs carry
        none. `profiled` threads the pipeline profiler through the
        parser/prefetch/plan seams (fit's training stream only)."""
        shards = [(self.rank, path)] if isinstance(path, str) else list(path)
        skips = dict(skips) if skips else {idx: skip for idx, _ in shards}
        prof = self.pipeline_prof if profiled else None

        prepare = lambda b: self._with_arrays(
            b, with_plan=with_plan, track_health=track_health, profiler=prof
        )

        def feed():
            # a REAL generator (map objects have no close): prefetch's
            # abandonment path close()s it, which cascades into
            # batch_iterator's finally — native parser handles and the
            # quarantine file release promptly, not at some later GC
            for idx, p in shards:
                if not os.path.exists(p):
                    continue  # ragged/elastic worlds: a missing shard idles
                for b in batch_iterator(
                    p, self.cfg.data,
                    enforce_bad_rows=enforce_bad_rows, quarantine=quarantine,
                    skip=max(int(skips.get(idx, 0)), 0),
                    profiler=prof,
                ):
                    bb, arrays = prepare(b)
                    arrays["_shard"] = idx
                    yield bb, arrays

        if jax.process_count() == 1:
            if not any(os.path.exists(p) for _, p in shards):
                # legacy loudness: a single process with NO input at all
                # is a user error, not an idle elastic rank
                raise FileNotFoundError(shards[0][1] if shards else "<no shards>")
            yield from prefetch(feed(), profiler=prof)
            return
        global_steps, local = self._epoch_batch_count(shards, skips)
        # open the real iterator whenever any shard exists (even if
        # counted 0) so the drift check below can catch a counter that
        # under-reads
        have_any = any(os.path.exists(p) for _, p in shards)
        it = iter(prefetch(feed(), profiler=prof)) if have_any else iter(())
        produced = 0
        for _ in range(global_steps):
            pair = next(it, None)
            if pair is None:
                # padding batches are built on the CONSUMER thread, so
                # their plan time must NOT be attributed (it would land
                # in the producer group while simultaneously counting
                # as the consumer's data-wait — double attribution)
                pair = self._with_arrays(
                    self._empty_batch(),
                    with_plan=with_plan, track_health=track_health,
                )
            else:
                produced += 1
            yield pair
        # loud drift check: if the counter mispredicted, data would be
        # silently dropped (under-count) or phantom empty steps run
        # (over-count) — either means the counter/parser predicates split
        if next(it, None) is not None or produced != local:
            names = ", ".join(repr(p) for _, p in shards)
            raise RuntimeError(
                f"batch count drift on {names}: counted {local}, parser "
                f"produced {produced}{'+' if produced == local else ''} — "
                "a file changed while this pass was reading it, or the "
                "row-counter and parser predicates disagree (bug)"
            )

    # ------------------------------------------------------------------ train
    def _install_signal_checkpoint(self):
        """Preemption hook (train.ckpt_on_signal): SIGTERM/SIGINT set a
        flag; the fit loop saves a checkpoint at the next COORDINATION
        point and returns early. Single-process coordinates every step;
        multi-process ranks agree through the `signal_sync_every` flag
        allgather (`_coordinated_signal`) so everyone stops — and saves,
        collectively — at the same step. Main-thread only; the second
        signal falls through to the previous handler, so a double Ctrl-C
        still kills a stuck run. Reference comparison (SURVEY.md §5 A3):
        any termination loses all server-side weights."""
        import signal
        import threading

        cfg = self.cfg
        multiproc_ok = jax.process_count() == 1 or cfg.train.signal_sync_every > 0
        if not (
            cfg.train.ckpt_on_signal and cfg.train.checkpoint_dir and multiproc_ok
        ):
            # config-off is RANK-SYMMETRIC (identical config everywhere),
            # so returning None — which skips the coordination allgathers
            # entirely — is safe
            return None, lambda: None
        if threading.current_thread() is not threading.main_thread():
            # cannot install handlers here, but MUST keep participating
            # in the flag allgathers: thread placement can differ across
            # ranks, and a rank that skipped them would desync the rest
            return {}, lambda: None
        flag = {}
        prev = {}

        def handler(signum, frame):
            flag["sig"] = signum
            # restore immediately: a second signal acts normally
            for s, h in prev.items():
                signal.signal(s, h)

        for s in (signal.SIGTERM, signal.SIGINT):
            prev[s] = signal.signal(s, handler)

        def restore():
            if "sig" not in flag:
                for s, h in prev.items():
                    signal.signal(s, h)

        return flag, restore

    def _step_cost(self) -> Optional[dict]:
        """{"flops", "bytes"} per train-step execution from the newest
        compiled train program's cost analysis — the roofline numerators
        the StepTimer's window gauges consume. None until a train
        program compiled (or with compile accounting off)."""
        rec = self.compile_recorder
        return rec.latest_cost("train_step") if rec is not None else None

    def fit(self, train_path: Optional[str] = None) -> TrainResult:
        try:
            return self._fit(train_path)
        finally:
            # drain + stop the async checkpoint writer BEFORE the
            # metrics sink closes: its final kind="ckpt" records must
            # land, and fit() returning implies the last submitted save
            # is durable (or its failure logged)
            if self._ckpt_writer is not None:
                self._ckpt_writer.close()
                self._ckpt_writer = None
            # release the metrics/heartbeat handles even on abnormal
            # exit; a later log() on this Trainer transparently reopens
            # in append mode
            self.metrics.close()
            self.heartbeat.close()
            if self.pipeline_prof is not None:
                # drop the pipeline.* gauges from the (process-global)
                # registry so a later profiler-off fit in this process
                # snapshots no pipeline metrics (per-run zero-overhead
                # contract); the next profiled fit's start() re-arms
                self.pipeline_prof.close()

    def _fit(self, train_path: Optional[str] = None) -> TrainResult:
        cfg = self.cfg
        if cfg.data.stream not in ("off", "tail"):
            raise ValueError(
                f"data.stream={cfg.data.stream!r}: expected 'off' or 'tail'"
            )
        if cfg.data.stream == "tail":
            # follow-the-tail streaming fit (docs/DATA.md "Streaming
            # ingest"): its own loop — the epoch-coordinated path counts
            # batches per pass up front, which is meaningless over a
            # growing input. stream=off never reaches this branch, so
            # every existing stream stays byte-identical (the PR 9
            # zero-overhead discipline; pinned by tests/test_freshness).
            return self._fit_tail(train_path)
        res = TrainResult()
        # perf_counter for every DURATION (monotonic — wall clock jumps
        # under NTP slew); the records' `ts` field (JsonlAppender) is the
        # wall-clock correlation handle
        start = time.perf_counter()
        trace = TraceWindow(
            cfg.train.profile_dir,
            cfg.train.trace_start_step,
            cfg.train.trace_num_steps,
        )
        trace.maybe_start_run()
        steptimer = StepTimer()
        registry = default_registry()
        health = self._health
        # input-pipeline attribution (train.pipeline_metrics): re-anchor
        # the profiler clock at fit start so Trainer construction (state
        # init) never reads as pipeline wall; None when off — the
        # profiled branches below are then never taken and the record
        # stream is byte-identical to a pre-profiler build
        prof = self.pipeline_prof
        if prof is not None:
            prof.start()
        # operator stack dumps: `kill -USR1 <pid>` prints every thread's
        # stack (main-thread-only; restored in the finally), and the
        # optional no-progress watchdog dumps them automatically when no
        # step completes for train.hang_timeout_s
        dump_restore = install_stack_dump_handler()
        hang = HangWatchdog(cfg.train.hang_timeout_s)
        # straggler/stall/kill drill injectors (testing/faults.py):
        # env-gated, resolved ONCE here — zero per-step cost in real runs
        from xflow_tpu.testing.faults import fit_delays_from_env, kill_step_from_env

        step_delay_s, stall_step, stall_s = fit_delays_from_env(self.rank)
        kill_step = kill_step_from_env(self.rank)
        hb_every = cfg.train.heartbeat_every
        if cfg.train.eval_every and not cfg.data.test_path:
            # the eval_every gate below requires a holdout; say so once
            # instead of silently never producing eval_auc records
            print(
                "xflow: warning: train.eval_every is set but "
                "data.test_path is empty — no streaming eval will run",
                file=sys.stderr,
            )
        self.heartbeat.append({"event": "start", "step": 0})
        last_metrics = None
        sig_flag, sig_restore = self._install_signal_checkpoint()
        multiproc = jax.process_count() > 1
        sync_every = cfg.train.signal_sync_every
        guard_halt = cfg.train.nonfinite_guard == "halt"
        max_consec = cfg.train.nonfinite_max_consecutive
        bad_run = 0  # consecutive discarded steps
        halted = False
        pending_ok = None  # (metrics, step index) awaiting the flag check
        pending_rec = None  # a log-cadence step's payload, written one behind

        def emit_pending_record() -> None:
            """Write the staged metrics-JSONL record for the last
            log-cadence step. Called right after the NEXT step's
            dispatch (or the end-of-data flush) has block_until_ready'd
            the staged step's metrics, so every float() here is a
            ready-buffer host copy — never a device sync. Reading the
            loss at staging time instead stalled the device once per
            train.log_every steps (the XF110 sync-bubble class; same
            one-step-behind discipline as telemetry.StepTimer)."""
            nonlocal pending_rec
            if pending_rec is None:
                return
            pm, at_step, at_epoch, at_examples, at_elapsed, counters = \
                pending_rec
            pending_rec = None
            loss = float(pm["loss"])
            # under the guard a bad step's NaN loss belongs to a
            # DISCARDED update: last_loss tracks the last loss that
            # actually trained in, and the JSONL record stays
            # strict-JSON (None, not a bare NaN literal)
            finite = loss == loss and abs(loss) != float("inf")
            if finite or not self._guarded:
                res.last_loss = loss
            # step/examples/elapsed_s/counters were all captured at the
            # staging step (host-only reads — no sync), so every
            # rate a consumer derives from them (pipeline_attrib's
            # e2e_examples_per_sec, host_gap_ratio) stays internally
            # consistent; only the device-value reads wait for the
            # one-behind block
            rec = {
                "step": at_step,
                "epoch": at_epoch,
                "loss": loss if finite else None,
                "examples": at_examples,
                "elapsed_s": at_elapsed,
            }
            # window stats: rows/s, steps/s, p50/p99 step time,
            # data-wait/dispatch/device decomposition (telemetry.
            # StepTimer) — emitted one step behind, the window now
            # covers exactly the cadence's finished steps — plus the
            # measured roofline gauges when the compile recorder knows
            # the step's cost
            rec.update(steptimer.window_record(cost=self._step_cost()))
            # live HBM gauges (guarded: CPU allocators report nothing
            # and the fields simply stay out)
            rec.update(hbm_window_fields(registry))
            # health window: norms, loss EMA, occupancy / collision
            # gauges (one behind, like the timer)
            rec.update(health.window_record())
            if counters:
                rec["counters"] = counters
            self.metrics.log(rec)
            if prof is not None:
                # the pipeline window rides the same log cadence as its
                # OWN kind="pipeline" record (schema: docs/
                # OBSERVABILITY.md "Input-pipeline attribution")
                prec = prof.window_record()
                if prec:
                    self.metrics.log(
                        {"kind": "pipeline", "step": at_step, **prec}
                    )

        def check_pending() -> bool:
            """Consume the PREVIOUS step's update_ok flag. Called right
            AFTER the next step's async dispatch, so the host read
            overlaps that step's device execution instead of inserting a
            sync bubble before it (the flag is replicated, so the read
            is collective-free and every rank computes the same
            skip/halt decision). Returns True when the guard demands an
            abort."""
            nonlocal pending_ok, bad_run
            if pending_ok is None:
                return False
            m, at_step = pending_ok
            pending_ok = None
            if "update_ok" not in m or bool(m["update_ok"]):
                bad_run = 0
                return False
            res.bad_steps += 1
            bad_run += 1
            self.metrics.log(
                {
                    "step": at_step,
                    "nonfinite_skipped": True,
                    "bad_steps": res.bad_steps,
                }
            )
            print(
                f"nonfinite update at step {at_step} discarded "
                f"(total {res.bad_steps}, {bad_run} consecutive)",
                file=sys.stderr,
            )
            return guard_halt or (0 < max_consec <= bad_run)

        def run_sync_round() -> None:
            """One cross-slice sync boundary (parallel/multislice.py):
            same bracketing discipline as the checkpoint cadence — flush
            the staged record first (the exchange is a durability
            window: a peer may SIGKILL us believing our delta landed),
            beat around the possibly bounded-wait-long exchange so a
            watchful launcher never reads it as death, tick the hang
            watchdog after. The kind="sync" record + span land in the
            same stamped stream as everything else."""
            emit_pending_record()
            self.heartbeat.append({"step": res.steps, "event": "sync"})
            t0_wall, t0 = time.time(), time.perf_counter()
            self.state, sync_rec = self._syncer.sync(self.state)
            if self.metrics.enabled:
                # the GLOBAL step (restored base + this generation's
                # progress) — checkpoint spans stamp the same counter,
                # so a rejoined slice's stream stays step-monotone
                gstep = int(self.state.step)
                self.metrics.log({"step": gstep, **sync_rec})
                from xflow_tpu.tracing import emit_op_span

                emit_op_span(
                    self.metrics, "slice_sync", t0_wall,
                    time.perf_counter() - t0,
                    step=gstep,
                    round=sync_rec["round"],
                    bytes=sync_rec["bytes_out"] + sync_rec["bytes_in"],
                )
            self.heartbeat.append({"step": res.steps})
            hang.tick()  # a bounded staleness wait is progress, not a hang

        def pending_signal() -> int:
            return int(sig_flag["sig"]) if sig_flag and "sig" in sig_flag else 0

        def coordinated_signal() -> int:
            """The stop decision every rank computes IDENTICALLY: local
            flag single-process; the max over all ranks' flags multi-
            process (one [1]-int32 host allgather), called at the same
            step on every rank — so a signal on ANY rank stops ALL ranks
            at the same step and the collective save stays symmetric."""
            if sig_flag is None:
                return 0
            if not multiproc:
                return pending_signal()
            from jax.experimental import multihost_utils

            got = int(
                np.asarray(
                    multihost_utils.process_allgather(np.int32(pending_signal()))
                ).max()
            )
            if got and not pending_signal():
                sig_flag["sig"] = got  # adopt the peer's signal for reporting
            return got

        # exact data resume (elastic recovery, docs/ROBUSTNESS.md): a
        # restored checkpoint's data_state pins the stream position the
        # run stopped at — PER SHARD, so the position survives a
        # topology change; this fit continues there instead of replaying
        # already-trained records from row 0
        start_epoch, resume_skips = self._consume_resume_position()
        world = jax.process_count()
        # the shard set in play: a fresh run covers exactly one shard
        # per rank (the legacy contract, unchanged); an elastic resume
        # covers the ORIGINAL record set round-robin over the CURRENT
        # world (assign_shards), so a run checkpointed at N ranks keeps
        # training every shard at M ranks. TWO carriers of the original
        # set size: the checkpoint data_state (num_shards, consumed in
        # _consume_resume_position) AND the supervisor's XFLOW_ORIG_WORLD
        # env (the launch's original rank count) — the env covers the
        # shrink-before-first-checkpoint window and completed-checkpoint
        # continuation, where there is no (usable) data_state to carry it
        try:
            orig_world = int(os.environ.get("XFLOW_ORIG_WORLD", 0) or 0)
        except ValueError:
            orig_world = 0
        self._num_shards = max(self._num_shards, world, orig_world)
        if train_path:
            epoch_shards = [(self.rank, train_path)]
        else:
            epoch_shards = assign_shards(
                cfg.data.train_path, self.rank, world, self._num_shards
            )
        # a RESUMED shard (nonzero stored offset — the previous world
        # was mid-way through it) whose file this host cannot see is
        # DATA LOSS, not the benign ragged-shard idle: per-host shard
        # files do not follow a lost host's reassignment — say so
        # loudly (elastic shrink wants a shared filesystem)
        for idx, p in epoch_shards:
            if resume_skips.get(idx, 0) > 0 and not os.path.exists(p):
                print(
                    f"xflow: warning: resumed shard {idx} ({p!r}) is "
                    "missing from this host — its remaining records "
                    "will NOT be trained (per-host shard files are not "
                    "visible to the surviving ranks; keep shards on a "
                    "shared filesystem for elastic shrink)",
                    file=sys.stderr,
                )
        self._epoch_pos = (start_epoch, max(resume_skips.values(), default=0))
        # cross-slice sync tier attach (sync.mode != off): a RELAUNCHED
        # slice (gen > 0) first catches up from the freshest published
        # table snapshot — its own checkpoint restore above already
        # pinned step/data position (the zero-lost-examples half of the
        # rejoin), the snapshot brings the peers' table contributions
        # its dead generation missed. attach() then fixes the delta
        # base, so the first sync publishes exactly this fit's progress.
        if self._syncer is not None:
            if resolve_restart_gen() > 0:
                t0_wall, t0 = time.time(), time.perf_counter()
                self.state, adopted = self._syncer.adopt_latest_snapshot(
                    self.state
                )
                if adopted is not None:
                    print(
                        f"multislice: slice {self._syncer.slice_id} caught "
                        f"up from snapshot round {adopted[0]} "
                        f"(published by slice {adopted[1]})",
                        file=sys.stderr,
                    )
                    self._ckpt_span(
                        "sync_catchup", t0_wall, t0, int(self.state.step)
                    )
            self._syncer.attach(self.state)
        stop_sig = 0
        try:
            for epoch in range(start_epoch, cfg.train.epochs):
                # the resume offsets apply to the FIRST (partially
                # consumed) epoch only; later epochs read from row 0
                skips = resume_skips if epoch == start_epoch else {}
                self._shard_pos = {
                    idx: max(int(skips.get(idx, 0)), 0) for idx, _ in epoch_shards
                }
                steps_in_epoch = max(self._shard_pos.values(), default=0)
                # profiled consumer tiling: the end-of-iteration mark the
                # next step's dispatch attribution continues from (None =
                # no gap to claim: epoch start, or a checkpoint/eval just
                # spent wall that is NOT per-step host work)
                prof_mark = None
                # quarantine on the FIRST pass only: later epochs see the
                # same bad rows again (still counted/enforced), and one
                # record per bad row beats epochs× duplicates
                for batch, arrays in steptimer.batches(
                    self._coordinated_batches(
                        epoch_shards, quarantine=epoch == 0, skips=skips,
                        profiled=True,
                    )
                ):
                    # which shard fed this step (None = a padding batch):
                    # popped BEFORE overflow resolution / device transfer
                    shard_idx = arrays.pop("_shard", None)
                    trace.before_step(res.steps + 1)
                    if step_delay_s:  # drill injector (testing/faults.py)
                        time.sleep(step_delay_s)
                    arrays = self._resolve_fullshard_overflow(batch, arrays)
                    if prof is None:
                        arrays = self._shard_batch(arrays)
                        self.state, m = self.train_step(self.state, arrays)
                        # finish the PREVIOUS step's timing: the block on
                        # its metrics overlaps this step's device
                        # execution, so neither the timer, the health
                        # read, nor the guard below adds a bubble
                        steptimer.dispatched(m, batch.num_rows)
                    else:
                        # the consumer-side stage split — the SAME calls
                        # as above with their boundaries stamped (no
                        # extra sync), TILING the fit loop under the
                        # StepTimer's own definitions: queue_wait = the
                        # batch's full data-wait (time inside next()),
                        # dispatch = every other host-side slice of the
                        # step (fetch end -> dispatch return minus the
                        # transfer refinement, plus the previous
                        # iteration's tail bookkeeping: health reads,
                        # guard checks, log writes — claimed via
                        # prof_mark), device = the one-behind metrics
                        # block. Tiling is what makes the attribution
                        # coverage hit its >= 95% bar.
                        t0 = time.perf_counter()
                        arrays = self._shard_batch(arrays)
                        t1 = time.perf_counter()
                        self.state, m = self.train_step(self.state, arrays)
                        t2 = time.perf_counter()
                        steptimer.dispatched(m, batch.num_rows)
                        t3 = time.perf_counter()
                        wait_end = steptimer.last_wait_end or t0
                        fetch_start = wait_end - steptimer.last_wait
                        gap = (
                            max(fetch_start - prof_mark, 0.0)
                            if prof_mark is not None
                            else 0.0
                        )
                        prof.add_many({
                            "queue_wait": steptimer.last_wait,
                            "transfer": t1 - t0,
                            "dispatch": (t2 - t1)
                            + max(t0 - wait_end, 0.0) + gap,
                            "device": t3 - t2,
                        })
                        prof_mark = t3
                    # the previous step's metrics are ready now — the
                    # health scalars (norms, loss for the EMA) read free
                    health.collect()
                    health.staged(m)
                    # ... and so is the previous log-cadence step's
                    # staged record: its reads hide under THIS step's
                    # device time (one-behind discipline, XF110)
                    emit_pending_record()
                    hang.tick()
                    last_metrics = m
                    res.steps += 1
                    res.examples += batch.num_rows
                    steps_in_epoch += 1
                    self._examples_seen += batch.num_rows
                    # the position the NEXT checkpoint's data_state pins:
                    # the global coordinated offset AND this shard's own
                    # consumed count (the topology-independent truth)
                    self._epoch_pos = (epoch, steps_in_epoch)
                    if shard_idx is not None:
                        self._shard_pos[shard_idx] = (
                            self._shard_pos.get(shard_idx, 0) + 1
                        )
                    if hb_every and res.steps % hb_every == 0:
                        self.heartbeat.append({"step": res.steps})
                    if stall_s and res.steps == stall_step:
                        # one-shot stall (straggler drill): this rank
                        # stops progressing while peers run ahead
                        time.sleep(stall_s)
                        stall_s = 0.0
                    # consume the PREVIOUS step's flag now that this
                    # step is dispatched — its device time hides the
                    # host read, so the guard adds no pipeline bubble
                    if check_pending():
                        halted = True
                        break
                    if self._guarded:
                        pending_ok = (m, res.steps)
                    if cfg.train.log_every and res.steps % cfg.train.log_every == 0:
                        # stage, don't read: float(m["loss"]) here would
                        # block on the step JUST dispatched — the exact
                        # sync bubble XF110 exists to catch. The record
                        # is written next iteration (or at the end-of-
                        # data flush), when the one-behind block has
                        # already made its reads free. elapsed_s and the
                        # counter snapshot are host-only and captured
                        # NOW so they pair with this step's examples.
                        pending_rec = (
                            m, res.steps, epoch, res.examples,
                            round(time.perf_counter() - start, 3),
                            registry.snapshot(),
                        )
                    if (
                        cfg.train.checkpoint_dir
                        and cfg.train.checkpoint_every
                        and res.steps % cfg.train.checkpoint_every == 0
                    ):
                        # a record staged THIS step must be durable
                        # before the kill window a checkpoint boundary
                        # opens (the elastic drills SIGKILL right after
                        # the save — SIGKILL bypasses every salvage
                        # net); the save below is itself a full state
                        # sync, so these reads hide under it
                        emit_pending_record()
                        # bracket the (possibly minutes-long collective)
                        # save with beats: no train step completes inside
                        # it, and under a supervised launch a false dead
                        # verdict is a TEARDOWN, not just a warning —
                        # operators still must keep dead_after_s above
                        # the save duration itself
                        self.heartbeat.append(
                            {"step": res.steps, "event": "checkpoint"}
                        )
                        self.save_checkpoint()
                        self.heartbeat.append({"step": res.steps})
                        hang.tick()  # a slow collective save is progress
                        # a (possibly minutes-long) save is NOT per-step
                        # host work: drop the tiling mark so the next
                        # step's dispatch never claims it
                        prof_mark = None
                    if (
                        self._syncer is not None
                        and cfg.sync.every_steps
                        and res.steps % cfg.sync.every_steps == 0
                    ):
                        # the K-step scan-block boundary: exchange table
                        # deltas with the other slices (AFTER the
                        # checkpoint cadence, so a sync-round kill drill
                        # leaves a boundary-committed checkpoint behind)
                        run_sync_round()
                        # a bounded wait is not per-step host work either
                        prof_mark = None
                    if kill_step and res.steps == kill_step:
                        # elastic-recovery drill (testing/faults.py):
                        # SIGKILL AFTER the checkpoint cadence above, so
                        # a kill on a boundary leaves that step committed
                        from xflow_tpu.testing.faults import hard_kill

                        print(
                            f"xflow: fault injector: hard-killing rank "
                            f"{self.rank} at step {res.steps} "
                            "(XFLOW_FAULT_KILL_STEP)",
                            file=sys.stderr, flush=True,
                        )
                        hard_kill()
                    if not multiproc or (sync_every and res.steps % sync_every == 0):
                        stop_sig = coordinated_signal()
                        if stop_sig:
                            break
                if halted:
                    break
                if not stop_sig:
                    # epoch consumed in full: the stream position rolls
                    # over (an interrupted epoch keeps its mid-epoch pos)
                    self._epoch_pos = (epoch + 1, 0)
                    self._shard_pos = {}
                res.epochs = epoch + (0 if stop_sig else 1)
                if not stop_sig:
                    if (epoch + 1) % 30 == 0:
                        print(f"epoch : {epoch}", file=sys.stderr)
                    if (
                        cfg.train.eval_every
                        and cfg.data.test_path
                        and (epoch + 1) % cfg.train.eval_every == 0
                    ):
                        # mid-training holdout pass: STREAMING by default
                        # (BucketAUC histograms, no global score sort —
                        # the giant-eval-set path) so quality lands in
                        # the metrics JSONL while the run is still going
                        # an eval pass makes no train-step progress:
                        # bracket it with ticks so a long (healthy)
                        # holdout doesn't read as a hang — at most one
                        # dump can fire, and only if the eval ITSELF
                        # exceeds the timeout. Same bracketing for the
                        # heartbeat stream: a quiet holdout pass must
                        # not age into a dead verdict (which a
                        # supervised launcher acts on, not just logs)
                        hang.tick()
                        self.heartbeat.append({"step": res.steps, "event": "eval"})
                        auc, ll = self.evaluate(dump=False, streaming=True)
                        self.heartbeat.append({"step": res.steps})
                        hang.tick()
                        # strict JSON: a one-class shard's NaN AUC logs
                        # as null, same convention as the guarded loss
                        self.metrics.log(
                            {
                                "step": res.steps,
                                "epoch": epoch,
                                "eval_auc": auc if auc == auc else None,
                                "eval_logloss": ll if ll == ll else None,
                            }
                        )
                        # gauges only for finite values: a one-class eval
                        # shard yields NaN AUC, and a NaN in the registry
                        # snapshot would leak into the (strict-JSON)
                        # counters dict
                        if auc == auc:
                            registry.gauge("health.eval_auc").set(auc)
                        if ll == ll:
                            registry.gauge("health.eval_logloss").set(ll)
                    # re-check AFTER the epoch eval too (an end-of-epoch
                    # coordination point): a signal landing there, or
                    # between sync cadences, must not be lost
                    stop_sig = coordinated_signal()
                if stop_sig:
                    res.interrupted = stop_sig
                    self.metrics.log({"interrupted": res.interrupted, "step": res.steps})
                    self.heartbeat.append({"event": "interrupted", "step": res.steps})
                    # flush-and-close BOTH sinks here, before the (slow,
                    # collective) checkpoint save: if the grace period
                    # expires mid-save and the process is KILLed, the
                    # metrics/heartbeat tails are already on disk. Later
                    # appends transparently reopen (JsonlAppender).
                    self.metrics.close()
                    self.heartbeat.close()
                    print(
                        f"signal {res.interrupted}: checkpointing at step "
                        f"{res.steps} and exiting",
                        file=sys.stderr,
                    )
                    break
            # the last step's flag is still pending after the data ends
            if not halted and check_pending():
                halted = True
            if halted:
                # a record staged on the halting step is the run's most
                # diagnostic line — write it before aborting (the abort
                # path can afford its one sync; the eager pre-XF110
                # code always wrote it)
                emit_pending_record()
                self.metrics.log(
                    {
                        "nonfinite_halt": True,
                        "step": res.steps,
                        "bad_steps": res.bad_steps,
                    }
                )
                if cfg.train.checkpoint_dir:
                    # the bad updates were discarded on device, so the
                    # live state IS the last good state — commit it
                    # before aborting, like the preemption path
                    self.save_checkpoint(wait=True)
                raise NonFiniteHalt(
                    f"non-finite guard aborted at step {res.steps}: "
                    f"{res.bad_steps} bad step(s), {bad_run} consecutive "
                    f"(train.nonfinite_guard={cfg.train.nonfinite_guard}, "
                    f"train.nonfinite_max_consecutive={max_consec})"
                    + (
                        f"; last good state committed under "
                        f"{cfg.train.checkpoint_dir!r}"
                        if cfg.train.checkpoint_dir
                        else ""
                    )
                )
            if last_metrics is not None:
                loss = float(last_metrics["loss"])
                # a discarded final step keeps the last GOOD loss (the
                # state never took the bad update)
                if (loss == loss and abs(loss) != float("inf")) or not self._guarded:
                    res.last_loss = loss
        except BaseException:
            # ANY crash between staging and the next emit (quarantine
            # exhaustion, a checkpoint IOError, SIGINT) must not lose
            # the staged log record — before the XF110 staging it was
            # already on disk, and it is the line that explains the
            # crash. Never let a failing emit mask the real exception —
            # not even a second Ctrl+C while the salvage read blocks on
            # a wedged device (hence BaseException here too).
            try:
                emit_pending_record()
            except BaseException:
                pass
            raise
        finally:
            sig_restore()
            dump_restore()
            hang.close()
            trace.close()
        # the final step's timing is still in flight (one behind); this
        # block is the single end-of-data sync the timer adds — the
        # health monitor's tail collect rides the same block
        if prof is None:
            steptimer.flush()
            health.flush()
            # a record staged on the run's final step has no successor
            # dispatch to hide behind; the flush above just paid its
            # one end-of-data sync, so these reads are free too
            emit_pending_record()
        else:
            t0 = time.perf_counter()
            steptimer.flush()
            health.flush()
            # the last step's metrics block belongs to its device stage
            prof.add("device", time.perf_counter() - t0)
            emit_pending_record()  # consumes the tail pipeline window too
            prec = prof.window_record()
            if prec:
                # the tail pipeline window, BEFORE the occupancy sweep
                # below — post-loop host work is not pipeline wall
                self.metrics.log(
                    {"kind": "pipeline", "step": res.steps, **prec}
                )
        res.seconds = time.perf_counter() - start
        # final sync boundary: publish the tail block's delta and fold
        # in whatever peers have landed, so the state this fit returns
        # (and evaluates / checkpoints below) carries every slice's
        # contribution. Skipped on preemption/halt — the grace window
        # must not fund a bounded staleness wait; the rejoin snapshot
        # path covers catch-up instead.
        if self._syncer is not None and res.steps and not stop_sig and not halted:
            run_sync_round()
        # table occupancy: fraction of slots ever touched by a gradient —
        # the sparse-model health metric (SURVEY.md §5 "table-occupancy").
        # FTRL's n accumulator (n>0 ⇔ slot was pushed) is the reliable
        # signal; untouched slots keep their build-time init, so a
        # nonzero count would read ~1.0 for randomly-initialized v tables.
        specs = self.model.table_specs(cfg)

        def slot_any(mask2d, name):
            """Per-SLOT any over the row width — packed storage
            ([S/pack, pack*K], ops/sorted_table.pack_table) groups pack
            slots per stored row, and an any over the full stored row
            would count 8-slot groups, not slots."""
            K = specs[name][0]
            sp, width = mask2d.shape
            return mask2d.reshape(sp, width // K, K).any(axis=-1)

        for name, t in self.state.tables.items():
            st = self.state.opt_state.get(name)
            if isinstance(st, dict) and "n" in st:
                touched = (
                    slot_any(st["n"] > 0, name) if st["n"].ndim > 1 else st["n"] > 0
                )
            else:
                # stateless optimizer (SGD): a touched slot has moved off
                # its build-time init (0 for scalar tables, v_init_sgd for
                # vector tables — models/base.py init_tables)
                init = cfg.optim.v_init_sgd if t.ndim > 1 else 0.0
                touched = slot_any(t != init, name) if t.ndim > 1 else t != init
            res.occupancy[name] = float(jnp.mean(touched))
        final_rec = {
            "final": True,
            "steps": res.steps,
            "examples": res.examples,
            "elapsed_s": round(res.seconds, 3),
            "occupancy": res.occupancy,
        }
        # tail window (steps since the last log tick) + run-total counters
        final_rec.update(steptimer.window_record(cost=self._step_cost()))
        final_rec.update(hbm_window_fields(registry))
        final_rec.update(health.window_record())
        counters = registry.snapshot()
        if counters:
            final_rec["counters"] = counters
        self.metrics.log(final_rec)
        self.heartbeat.append({"event": "final", "step": res.steps})
        if cfg.train.checkpoint_dir:
            # the run's terminal state must be durable when fit returns
            self.save_checkpoint(wait=True)
        return res

    # ---------------------------------------------------------- streaming fit
    def _fit_tail(self, train_path: Optional[str] = None) -> TrainResult:
        """Follow-the-tail streaming fit (`data.stream=tail`, docs/DATA.md
        "Streaming ingest"): train on sealed ingest segments as a
        TailFollower spools them off the growing input, and publish
        committed checkpoints every `train.publish_every` steps — each
        publication stamped with the NEWEST ingest trace whose rows a
        completed step consumed, so the serve tier (and
        tools/freshness_report.py) can measure data freshness end to
        end.

        Deliberately leaner than the epoch loop: single-process only
        (the counting allgather the coordinated path leans on has no
        meaning over an unbounded stream), no epochs (the stream IS one
        open-ended pass), no profiler tiling or fault injectors. What
        it keeps: the one-behind metrics staging (XF110), the
        non-finite guard, heartbeat/hang bracketing around saves, and
        signal-checkpoint handling — the operational contracts every
        fit honors."""
        cfg = self.cfg
        if jax.process_count() > 1:
            raise ValueError(
                "data.stream=tail is single-process only: the tail "
                "follower has no cross-rank batch coordination (shard "
                "the stream upstream instead)"
            )
        from xflow_tpu.data.pipeline import TailFollower

        res = TrainResult()
        start = time.perf_counter()
        steptimer = StepTimer()
        registry = default_registry()
        health = self._health
        dump_restore = install_stack_dump_handler()
        hang = HangWatchdog(cfg.train.hang_timeout_s)
        sig_flag, sig_restore = self._install_signal_checkpoint()
        hb_every = cfg.train.heartbeat_every
        guard_halt = cfg.train.nonfinite_guard == "halt"
        max_consec = cfg.train.nonfinite_max_consecutive
        bad_run = 0
        halted = False
        pending_ok = None
        pending_rec = None
        self.heartbeat.append({"event": "start", "step": 0})
        follower = TailFollower(
            train_path or cfg.data.train_path, cfg.data,
            appender=self.metrics if self.metrics.enabled else None,
        )

        def emit_pending_record() -> None:
            # the same one-step-behind staging as _fit (XF110): reads
            # happen after the NEXT dispatch made them free
            nonlocal pending_rec
            if pending_rec is None:
                return
            pm, at_step, at_examples, at_elapsed, counters = pending_rec
            pending_rec = None
            loss = float(pm["loss"])
            finite = loss == loss and abs(loss) != float("inf")
            if finite or not self._guarded:
                res.last_loss = loss
            rec = {
                "step": at_step,
                "epoch": 0,
                "loss": loss if finite else None,
                "examples": at_examples,
                "elapsed_s": at_elapsed,
            }
            rec.update(steptimer.window_record(cost=self._step_cost()))
            rec.update(hbm_window_fields(registry))
            rec.update(health.window_record())
            if counters:
                rec["counters"] = counters
            self.metrics.log(rec)

        def check_pending() -> bool:
            nonlocal pending_ok, bad_run
            if pending_ok is None:
                return False
            m, at_step = pending_ok
            pending_ok = None
            if "update_ok" not in m or bool(m["update_ok"]):
                bad_run = 0
                return False
            res.bad_steps += 1
            bad_run += 1
            self.metrics.log(
                {
                    "step": at_step,
                    "nonfinite_skipped": True,
                    "bad_steps": res.bad_steps,
                }
            )
            print(
                f"nonfinite update at step {at_step} discarded "
                f"(total {res.bad_steps}, {bad_run} consecutive)",
                file=sys.stderr,
            )
            return guard_halt or (0 < max_consec <= bad_run)

        # freshness bookkeeping: the newest (trace, ingest_ts,
        # consumed_ts) triple whose segment a completed step trained on
        # — what the next publication stamps
        newest: Optional[tuple] = None
        pub_seq = 0
        publish_every = cfg.train.publish_every
        last_metrics = None
        stop_sig = 0
        try:
            for seg in follower.segments():
                seg_consumed = False
                for batch, arrays in steptimer.batches(
                    self._coordinated_batches([(0, seg.path)], quarantine=True)
                ):
                    arrays.pop("_shard", None)
                    arrays = self._resolve_fullshard_overflow(batch, arrays)
                    arrays = self._shard_batch(arrays)
                    self.state, m = self.train_step(self.state, arrays)
                    steptimer.dispatched(m, batch.num_rows)
                    health.collect()
                    health.staged(m)
                    emit_pending_record()
                    hang.tick()
                    last_metrics = m
                    res.steps += 1
                    res.examples += batch.num_rows
                    self._examples_seen += batch.num_rows
                    self._epoch_pos = (0, res.steps)
                    if not seg_consumed:
                        # the first step over a segment marks its rows
                        # as consumed; the wall clock here is the
                        # ingest-to-train edge of the freshness Δ
                        seg_consumed = True
                        newest = (seg.trace, seg.ingest_ts, time.time())
                    if hb_every and res.steps % hb_every == 0:
                        self.heartbeat.append({"step": res.steps})
                    if check_pending():
                        halted = True
                        break
                    if self._guarded:
                        pending_ok = (m, res.steps)
                    if cfg.train.log_every and res.steps % cfg.train.log_every == 0:
                        pending_rec = (
                            m, res.steps, res.examples,
                            round(time.perf_counter() - start, 3),
                            registry.snapshot(),
                        )
                    if (
                        cfg.train.checkpoint_dir
                        and publish_every
                        and res.steps % publish_every == 0
                        and newest is not None
                    ):
                        emit_pending_record()
                        self.heartbeat.append(
                            {"step": res.steps, "event": "checkpoint"}
                        )
                        # the seq number is consumed only when the
                        # publication landed (an async skip retries at
                        # the next cadence with the SAME next seq)
                        if self._publish_checkpoint(newest, pub_seq + 1):
                            pub_seq += 1
                        self.heartbeat.append({"step": res.steps})
                        hang.tick()  # a slow publish is progress
                        if (
                            cfg.train.eval_every
                            and cfg.data.test_path
                            and pub_seq % cfg.train.eval_every == 0
                        ):
                            # in stream mode eval_every counts
                            # PUBLICATIONS (there are no epochs); with
                            # train.eval_window_decay the repeated
                            # passes form the time-decayed window
                            hang.tick()
                            self.heartbeat.append(
                                {"step": res.steps, "event": "eval"}
                            )
                            auc, ll = self.evaluate(dump=False, streaming=True)
                            self.heartbeat.append({"step": res.steps})
                            hang.tick()
                            self.metrics.log(
                                {
                                    "step": res.steps,
                                    "epoch": 0,
                                    "eval_auc": auc if auc == auc else None,
                                    "eval_logloss": ll if ll == ll else None,
                                }
                            )
                    elif (
                        cfg.train.checkpoint_dir
                        and not publish_every
                        and cfg.train.checkpoint_every
                        and res.steps % cfg.train.checkpoint_every == 0
                    ):
                        # publish_every=0: plain checkpoint cadence,
                        # no publication sidecar — freshness stays off
                        emit_pending_record()
                        self.heartbeat.append(
                            {"step": res.steps, "event": "checkpoint"}
                        )
                        self.save_checkpoint()
                        self.heartbeat.append({"step": res.steps})
                        hang.tick()
                    stop_sig = (
                        int(sig_flag["sig"])
                        if sig_flag and "sig" in sig_flag
                        else 0
                    )
                    if stop_sig:
                        break
                if halted or stop_sig:
                    break
            if not halted and check_pending():
                halted = True
            if halted:
                emit_pending_record()
                self.metrics.log(
                    {
                        "nonfinite_halt": True,
                        "step": res.steps,
                        "bad_steps": res.bad_steps,
                    }
                )
                if cfg.train.checkpoint_dir:
                    self.save_checkpoint(wait=True)
                raise NonFiniteHalt(
                    f"non-finite guard aborted at step {res.steps}: "
                    f"{res.bad_steps} bad step(s), {bad_run} consecutive"
                )
            if stop_sig:
                res.interrupted = stop_sig
                self.metrics.log(
                    {"interrupted": res.interrupted, "step": res.steps}
                )
                self.heartbeat.append(
                    {"event": "interrupted", "step": res.steps}
                )
        except BaseException:
            try:
                emit_pending_record()
            except BaseException:
                pass
            raise
        finally:
            sig_restore()
            dump_restore()
            hang.close()
            follower.close()
        steptimer.flush()
        health.flush()
        emit_pending_record()
        if last_metrics is not None:
            loss = float(last_metrics["loss"])
            if (loss == loss and abs(loss) != float("inf")) or not self._guarded:
                res.last_loss = loss
        res.seconds = time.perf_counter() - start
        res.epochs = 1 if res.steps else 0
        final_rec = {
            "final": True,
            "steps": res.steps,
            "examples": res.examples,
            "elapsed_s": round(res.seconds, 3),
            "occupancy": res.occupancy,
        }
        final_rec.update(steptimer.window_record(cost=self._step_cost()))
        final_rec.update(hbm_window_fields(registry))
        final_rec.update(health.window_record())
        counters = registry.snapshot()
        if counters:
            final_rec["counters"] = counters
        self.metrics.log(final_rec)
        self.heartbeat.append({"event": "final", "step": res.steps})
        if cfg.train.checkpoint_dir and res.steps:
            # the tail commit publishes too when a publication cadence
            # is on: the stream's last rows must become servable even
            # when the idle timeout lands mid-cadence
            if publish_every and newest is not None:
                # wait=True drains any in-flight save first, so the
                # final publication is never skipped
                if self._publish_checkpoint(newest, pub_seq + 1, wait=True):
                    pub_seq += 1
            else:
                self.save_checkpoint(wait=True)
        return res

    def _publish_checkpoint(self, newest: tuple, seq: int,
                            wait: bool = False) -> bool:
        """One in-run checkpoint PUBLICATION (docs/SERVING.md
        "Freshness"): a normal committed save plus the publication.json
        sidecar binding this step to the newest ingest trace whose rows
        it trained on, a `kind="publish"` record, and a `publish` span
        CARRYING that ingest trace id (tracing.emit_linked_span) — the
        link freshness_report follows across the train/serve boundary.
        The sidecar lands before the COMMITTED marker (checkpoint.save),
        so a watcher never sees a committed step whose publication is
        still in flight. Under train.ckpt_async the save may be SKIPPED
        (previous save still in flight) — then no publication happened:
        no record, no span, the seq number is not consumed, and the
        caller retries at the next cadence. Returns whether the
        publication was accepted."""
        from xflow_tpu.tracing import emit_linked_span, new_id

        trace, ingest_ts, consumed_ts = newest
        t0_wall, t0 = time.time(), time.perf_counter()
        step = int(self.state.step)
        pub = {
            "step": step,
            "seq": int(seq),
            "trace": trace,
            "span": new_id(),
            "ingest_ts": round(float(ingest_ts), 6),
            "consumed_ts": round(float(consumed_ts), 6),
            "published_ts": round(t0_wall, 6),
        }
        if not self.save_checkpoint(publication=pub, wait=wait):
            return False
        if self.metrics.enabled:
            self.metrics.log(
                {
                    "kind": "publish",
                    "step": step,
                    "seq": int(seq),
                    "trace": trace,
                    "ingest_ts": pub["ingest_ts"],
                    "published_ts": pub["published_ts"],
                }
            )
            # record + span symmetry (the run_sync_round idiom): the
            # span's end is the publication's commit instant — the
            # publish edge of the freshness Δ decomposition
            emit_linked_span(
                self.metrics, "publish", t0_wall,
                time.perf_counter() - t0,
                trace=trace, span=pub["span"], step=step, seq=int(seq),
            )
        return True

    # ------------------------------------------------------------------- eval
    def _local_pctrs(self, p_dev) -> np.ndarray:
        """This process's rows of the (possibly cross-process) pctr array."""
        if isinstance(p_dev, jax.Array) and not p_dev.is_fully_addressable:
            shards = sorted(p_dev.addressable_shards, key=lambda s: s.index[0].start or 0)
            return np.concatenate([np.asarray(s.data) for s in shards])
        return np.asarray(p_dev)

    def evaluate(
        self,
        test_path: Optional[str] = None,
        dump: Optional[bool] = None,
        block: int = 0,
        streaming: bool = False,
    ) -> tuple[float, float]:
        """Predict pass. Returns (auc, logloss); optionally dumps pred file.

        Two paths (round-1 verdict item 7):

        - exact (default): collect every (pctr, label); multi-process
          gathers ONE stacked [B, 3] array per batch (the round-1 code
          issued three separate allgathers) and rank-sorts on the host.
          Reference parity: `base.h:84-110`.
        - bucketed (``train.eval_buckets > 0``): histogram positives /
          negatives by score bucket locally (`metrics.BucketAUC`), ONE
          collective at the end — no host ever materializes the global
          pctr vector, so Criteo-1TB-scale eval streams. AUC error is
          bounded by bucket width (±~1/buckets).

        The exact-vs-bucketed choice depends only on config (identical on
        every process), never on rank — a per-rank choice would mismatch
        the collective sequences across processes and deadlock. With
        buckets on, each rank dumps its OWN rows to ``pred_<rank>_*.txt``
        (the reference's per-worker files, `lr_worker.cc:74-78`).

        `streaming=True` (the trainer's mid-training `eval_every` pass)
        upgrades the auto default to the bucketed path even
        single-process — a holdout pass DURING training should stream
        rather than sort a growing global score vector — while an
        explicit `train.eval_buckets` setting still wins (it's config,
        hence rank-symmetric either way).
        """
        cfg = self.cfg
        world = jax.process_count()
        if test_path:
            shards: "str | list" = test_path
        else:
            # the same elastic assignment as training: after a shrink
            # the surviving ranks cover the full test record set too
            shards = assign_shards(
                cfg.data.test_path, self.rank, world,
                max(self._num_shards, world),
            )
        dump = cfg.train.pred_dump if dump is None else dump
        multiproc = world > 1
        buckets = resolve_eval_buckets(cfg.train.eval_buckets, multiproc)
        if streaming and buckets == 0 and cfg.train.eval_buckets < 0:
            buckets = 65536
        if buckets:
            return self._evaluate_bucketed(shards, buckets, dump, block)
        dump = dump and (not multiproc or self.rank == 0)
        fout = open(f"pred_{self.rank}_{block}.txt", "w") if dump else None
        pctrs, labels = [], []
        for batch, arrays in self._coordinated_batches(
            shards, with_plan=self._mesh_engine != "replicated",
            enforce_bad_rows=False, quarantine=False, track_health=False,
        ):
            arrays.pop("_shard", None)
            arrays = self._resolve_fullshard_overflow(batch, arrays)
            arrays = self._shard_batch(arrays)
            p_dev = self.eval_step(self.state.tables, arrays)
            if multiproc:
                # ONE allgather of the stacked local rows per batch
                from jax.experimental import multihost_utils

                local = np.stack(
                    [
                        self._local_pctrs(p_dev),
                        np.asarray(batch.labels, np.float32),
                        np.asarray(batch.row_mask, np.float32),
                    ],
                    axis=1,
                )
                gathered = np.asarray(
                    multihost_utils.process_allgather(local, tiled=True)
                )
                p, y_all, rm = gathered[:, 0], gathered[:, 1], gathered[:, 2] > 0
            else:
                p = np.asarray(p_dev)
                rm = np.asarray(batch.row_mask) > 0
                y_all = np.asarray(batch.labels)
            p, y = p[rm], y_all[rm]
            pctrs.append(p)
            labels.append(y)
            if fout:
                for pi, yi in zip(p, y):
                    # reference row format: pctr \t 1-label \t label (lr_worker.cc:67)
                    fout.write(f"{pi:.6f}\t{int(1 - yi)}\t{int(yi)}\n")
        if fout:
            fout.close()
        if not pctrs:
            return float("nan"), float("nan")
        auc, ll = auc_logloss(np.concatenate(pctrs), np.concatenate(labels))
        return auc, ll

    def _evaluate_bucketed(
        self, shards, num_buckets: int, dump: bool = False, block: int = 0
    ) -> tuple[float, float]:
        """Streaming eval: local bucket histograms, one collective at the end.

        With `dump`, each rank writes its own local rows (reference
        per-worker pred files) — no cross-rank gather is needed for it.
        """
        from xflow_tpu.metrics import BucketAUC

        st = BucketAUC.init(num_buckets)
        ll_sum, n_rows = 0.0, 0.0
        fout = open(f"pred_{self.rank}_{block}.txt", "w") if dump else None
        for batch, arrays in self._coordinated_batches(
            shards, with_plan=self._mesh_engine != "replicated",
            enforce_bad_rows=False, quarantine=False, track_health=False,
        ):
            arrays.pop("_shard", None)
            arrays = self._resolve_fullshard_overflow(batch, arrays)
            arrays = self._shard_batch(arrays)
            p = self._local_pctrs(self.eval_step(self.state.tables, arrays))
            rm = np.asarray(batch.row_mask) > 0
            y = np.asarray(batch.labels)[rm]
            p = np.asarray(p, np.float64)[rm]
            st = st.update(p, y)
            eps = 1e-15
            pc = np.clip(p, eps, 1.0 - eps)
            ll_sum += float((y * np.log(pc) + (1.0 - y) * np.log(1.0 - pc)).sum())
            n_rows += float(rm.sum())
            if fout:
                for pi, yi in zip(p, y):
                    fout.write(f"{pi:.6f}\t{int(1 - yi)}\t{int(yi)}\n")
        if fout:
            fout.close()
        stats = np.concatenate([st.pos, st.neg, [ll_sum, n_rows]])
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            # hi/lo float32 split keeps counts beyond 2^24 exact through
            # the (float32-only without x64) allgather: x = hi + lo with
            # hi = f32(x), lo = f32(x - hi); summed back in float64
            hi = stats.astype(np.float32)
            lo = (stats - hi.astype(np.float64)).astype(np.float32)
            gathered = np.asarray(
                multihost_utils.process_allgather(np.stack([hi, lo]))
            ).astype(np.float64)
            stats = gathered.reshape(-1, 2, stats.shape[0]).sum(axis=(0, 1))
        pos, neg = stats[:num_buckets], stats[num_buckets : 2 * num_buckets]
        ll_sum, n_rows = float(stats[-2]), float(stats[-1])
        decay = float(self.cfg.train.eval_window_decay)
        if decay > 0:
            # time-decayed sliding window (train.eval_window_decay):
            # fold the decayed accumulator from earlier eval passes into
            # this pass's counts (BucketAUC.decay — counts are plain
            # sums, so the fold is addition), then persist the folded
            # state for the next pass. Runs AFTER the cross-process
            # merge above, on identical allgathered stats, so every rank
            # holds the same window. A bucket-count change resets the
            # window (the histograms are not comparable).
            prev = self._eval_window
            if prev is not None and prev[0].pos.shape[0] == num_buckets:
                pst = prev[0].decay(decay)
                pos = pos + pst.pos
                neg = neg + pst.neg
                ll_sum += prev[1] * decay
                n_rows += prev[2] * decay
            self._eval_window = (BucketAUC(pos=pos, neg=neg), ll_sum, n_rows)
        if n_rows == 0:
            return float("nan"), float("nan")
        auc = BucketAUC(pos=pos, neg=neg).compute()
        return auc, ll_sum / n_rows

    # ------------------------------------------------------------- checkpoint
    def _data_state_record(self) -> dict:
        """The host-side data-pipeline position saved alongside every
        checkpoint (elastic recovery, docs/ROBUSTNESS.md) — the
        TOPOLOGY-INDEPENDENT v2 form: epoch index, the global
        coordinated batch offset (informational), per-SHARD consumed
        batch counts (`shard_batches` — the truth a resume at ANY world
        size reshards from), the shard set in play (`num_shards`), the
        GLOBAL cumulative example count, and the quarantine count.
        Per-rank example counts ride along as information only — they
        are meaningless across a topology change. `completed` marks a
        checkpoint written after the configured epochs all ran — a
        resume of a completed run is continuation training and starts a
        fresh pass instead of training nothing. The stream itself is
        deterministic file order (no shuffle stage yet); when one
        lands, its RNG state joins this record — the version field
        exists for exactly that."""
        from xflow_tpu.train.checkpoint import DATA_STATE_VERSION

        epoch, batches = self._epoch_pos
        reg = default_registry()
        world = jax.process_count()
        num_shards = max(self._num_shards, world, 1)
        local_shards = np.zeros(num_shards, np.int32)
        for idx, n in self._shard_pos.items():
            if 0 <= int(idx) < num_shards:
                local_shards[int(idx)] = min(int(n), 2**31 - 1)
        local_ex = np.int32(min(self._examples_seen, 2**31 - 1))
        if world > 1:
            from jax.experimental import multihost_utils

            # collective-safe: save_checkpoint is itself collective, so
            # every rank reaches this allgather at the same step. ONE
            # stacked [1 + num_shards]-int32 allgather carries both the
            # example counters and the shard offsets (each shard is
            # owned by exactly one rank, so the per-shard MAX is the
            # owner's count). int32: jax without x64 silently truncates
            # int64 inputs.
            stacked = np.concatenate([[local_ex], local_shards]).astype(np.int32)
            got = np.asarray(
                multihost_utils.process_allgather(stacked)
            ).reshape(world, -1)
            per_rank = [int(x) for x in got[:, 0]]
            shard_batches = got[:, 1:].max(axis=0)
            examples = int(self._examples_base) + sum(per_rank)
        else:
            per_rank = [int(local_ex)]
            shard_batches = local_shards
            examples = int(self._examples_base) + int(local_ex)
        return {
            "version": DATA_STATE_VERSION,
            "epoch": int(epoch),
            "batches": int(batches),
            "completed": bool(epoch >= self.cfg.train.epochs),
            "examples": examples,
            "examples_per_rank": per_rank,
            "shard_batches": {str(i): int(v) for i, v in enumerate(shard_batches)},
            "num_shards": int(num_shards),
            "world_size": int(world),
            "quarantined_rows": int(reg.counter("data.quarantined_rows").value),
        }

    def _consume_resume_position(self) -> tuple[int, dict]:
        """(start_epoch, {shard index -> batch offset}) for this fit(),
        consuming the data_state maybe_restore captured. Fresh runs,
        pre-v2 checkpoints, unreadable data_state, and COMPLETED
        checkpoints (continuation training) all start at (0, {}); an
        interrupted run's checkpoint resumes every shard's stream
        exactly where it stopped — whatever world size wrote it
        (checkpoint.normalize_data_state folds v1 records into the
        topology-independent form)."""
        ds_raw = self._resume_data_state
        self._resume_data_state = None
        from xflow_tpu.train.checkpoint import normalize_data_state

        if not isinstance(ds_raw, dict) or ds_raw.get("completed"):
            if isinstance(ds_raw, dict):
                # continuation training starts a fresh pass, but the
                # RECORD SET the completed checkpoint covered still
                # applies — a shrunk world keeps covering every shard
                try:
                    self._num_shards = max(
                        self._num_shards,
                        normalize_data_state(ds_raw)["num_shards"],
                    )
                except (TypeError, ValueError):
                    pass
            return 0, {}
        try:
            ds = normalize_data_state(ds_raw)
        except (TypeError, ValueError):
            print(
                "xflow: warning: checkpoint data_state is malformed; "
                "resuming with a fresh data stream",
                file=sys.stderr,
            )
            return 0, {}
        # GLOBAL example accounting survives any topology change: the
        # restored total becomes the base, and every rank's local
        # counter restarts at 0 for this generation
        self._examples_base = ds["examples"]
        self._examples_seen = 0
        self._num_shards = max(self._num_shards, ds["num_shards"])
        epoch, skips = ds["epoch"], ds["shard_batches"]
        world = jax.process_count()
        if epoch or any(skips.values()):
            from xflow_tpu.telemetry import resolve_restart_gen

            note = (
                f"; resharding {ds['num_shards']} shard(s) from "
                f"{ds['world_size']} rank(s) onto {world}"
                if ds["world_size"] != world
                else ""
            )
            print(
                f"resuming data stream at epoch {epoch}, shard offsets "
                f"{[skips.get(i, 0) for i in range(ds['num_shards'])]} "
                f"(restart generation {resolve_restart_gen()}){note}",
                file=sys.stderr,
            )
        return epoch, skips

    def _ckpt_span(self, name: str, t0_wall: float, t0: float,
                   step: int) -> None:
        """One kind="span" record per checkpoint save/restore
        (train.ckpt_spans): the checkpoint lifecycle joins the same
        span stream serving emits, so tools/request_trace.py --timeline
        can overlay saves/reloads against request-latency spikes."""
        if not self.cfg.train.ckpt_spans or not self.metrics.enabled:
            # enabled guards the tree walk + nbytes sum: with no
            # metrics sink the record would be built only to no-op
            return
        from xflow_tpu.tracing import emit_op_span

        emit_op_span(
            self.metrics, name, t0_wall, time.perf_counter() - t0,
            step=int(step),
            bytes=int(sum(
                x.nbytes
                for x in jax.tree.leaves(
                    (self.state.tables, self.state.opt_state)
                )
            )),
        )

    def _ckpt_async_on(self) -> bool:
        """train.ckpt_async, gated to single-process runs: _flatten's
        multihost gather is a collective no side thread may run. A
        multi-process run that asked for async falls back to synchronous
        saves with a one-time warning."""
        if not self.cfg.train.ckpt_async:
            return False
        if jax.process_count() > 1:
            if not getattr(self, "_ckpt_async_warned", False):
                self._ckpt_async_warned = True
                print(
                    "# checkpoint: train.ckpt_async is single-process "
                    "only (host-gather collectives cannot run on a side "
                    "thread); falling back to synchronous saves",
                    file=sys.stderr,
                )
            return False
        return True

    def _ensure_ckpt_writer(self):
        from xflow_tpu.train import checkpoint as ckpt

        if self._ckpt_writer is None:
            self._ckpt_writer = ckpt.AsyncCheckpointWriter(
                sink=self.metrics, ckpt_spans=self.cfg.train.ckpt_spans,
            )
        return self._ckpt_writer

    def save_checkpoint(self, publication: Optional[dict] = None,
                        wait: bool = False) -> bool:
        """Checkpoint the current state. Synchronous by default; with
        train.ckpt_async the fit loop only snapshots (device arrays are
        pinned + D2H transfers started, data_state captured HERE — its
        allgather is a collective) and the background writer owns the
        disk. Returns False only when an async submit was skipped
        because a save is still in flight; `wait=True` forces the save
        to be on disk when this returns (halt/signal/end-of-fit paths)."""
        from xflow_tpu.train import checkpoint as ckpt

        t0_wall, t0 = time.time(), time.perf_counter()
        data_state = self._data_state_record()
        if self._ckpt_async_on():
            w = self._ensure_ckpt_writer()
            if wait:
                # a final save must not be skippable: drain whatever is
                # in flight first, then the submit always lands. Re-stamp
                # the queue instant AFTER the drain — queued_ts is this
                # save's cadence instant, and the --check interval gate
                # (at most one save in flight) reads it against the
                # previous save's committed_ts
                w.drain()
                t0_wall = time.time()
            job = ckpt.SaveJob(
                snapshot=ckpt.SaveSnapshot(
                    self.state, self._logical_widths()
                ),
                ckpt_dir=self.cfg.train.checkpoint_dir,
                fmt=self.cfg.train.checkpoint_format,
                replica_dir=self.cfg.train.ckpt_replica_dir,
                keep=self.cfg.train.keep_checkpoints,
                keep_replica=self.cfg.train.keep_replica_checkpoints,
                data_state=data_state,
                publication=publication,
                queued_ts=t0_wall,
            )
            ok = w.submit(job)
            if wait:
                w.drain()
            return ok
        if self._ckpt_writer is not None:
            # a mode flip (or the final synchronous paths of an async
            # run) must not interleave with an in-flight async write
            self._ckpt_writer.drain()
        if self.cfg.train.checkpoint_format == "orbax":
            # orbax stores the device arrays in their NATIVE (possibly
            # packed) layout, shard-parallel; npz stores the LOGICAL
            # layout so export tools and differently-configured runs
            # read one format
            ckpt.save_orbax(
                self.cfg.train.checkpoint_dir, self.state,
                data_state=data_state, publication=publication,
            )
        else:
            ckpt.save(
                self.cfg.train.checkpoint_dir,
                self.state,
                self._logical_widths(),
                data_state=data_state,
                publication=publication,
            )
        self._ckpt_span("checkpoint_save", t0_wall, t0, int(self.state.step))
        # retention + stale-uncommitted sweep AFTER the commit: the save
        # that just landed proves no writer owns the swept debris
        ckpt.prune_checkpoints(
            self.cfg.train.checkpoint_dir,
            self.cfg.train.keep_checkpoints,
            fmt=self.cfg.train.checkpoint_format,
        )
        if self.cfg.train.ckpt_replica_dir and jax.process_index() == 0:
            # synchronous runs mirror inline (same commit contract, no
            # writer thread); a replica failure never harms the primary
            try:
                ckpt.mirror_step(
                    self.cfg.train.checkpoint_dir,
                    self.cfg.train.ckpt_replica_dir,
                    int(self.state.step),
                    fmt=self.cfg.train.checkpoint_format,
                )
                ckpt.prune_checkpoints(
                    self.cfg.train.ckpt_replica_dir,
                    self.cfg.train.keep_replica_checkpoints,
                    fmt=self.cfg.train.checkpoint_format,
                )
            except Exception as e:  # noqa: BLE001
                print(
                    f"# checkpoint: replica mirror of step "
                    f"{int(self.state.step)} failed "
                    f"({type(e).__name__}: {e}); the primary commit "
                    "stands",
                    file=sys.stderr,
                )
        return True

    def _logical_widths(self) -> dict:
        """{table: K} logical row widths, for unpacking packed storage."""
        return {
            name: trailing[0]
            for name, trailing in self.model.table_specs(self.cfg).items()
            if trailing
        }

    def export_sparse(self, out_path: str, table: str = "w") -> int:
        """Serving export of a table's nonzero rows, unpacking the live
        packed storage via the model's logical widths (checkpoint.export_sparse)."""
        from xflow_tpu.train import checkpoint as ckpt

        return ckpt.export_sparse(
            self.state, out_path, table, logical_widths=self._logical_widths()
        )

    def maybe_restore(self) -> bool:
        from xflow_tpu.train import checkpoint as ckpt

        if not (self.cfg.train.checkpoint_dir and self.cfg.train.resume):
            return False
        cdir = self.cfg.train.checkpoint_dir
        fmt = self.cfg.train.checkpoint_format
        # self-healing restore: the newest checkpoint failing to load
        # (truncated npz, corrupt orbax shard, a DIGEST mismatch against
        # the meta written at save — the silent-bit-flip case) walks
        # back to the previous committed step instead of killing the
        # resume (restore_any logs what it skipped and why). The
        # restore itself is topology-agnostic: each leaf lands on the
        # CURRENT state's sharding, whatever world size/engine wrote
        # the checkpoint. No checkpoint at all = fresh start; raises
        # only when checkpoints exist and NONE loads.
        t0_wall, t0 = time.time(), time.perf_counter()
        try:
            # the walk covers BOTH tiers: a primary step that is
            # missing or digest-poisoned restores from the replica
            # mirror (train.ckpt_replica_dir) before falling back to
            # an older step
            self.state, step, src = ckpt.restore_tiered(
                cdir, self.state, fmt=fmt,
                verify=self.cfg.train.checkpoint_verify,
                replica_dir=self.cfg.train.ckpt_replica_dir or None,
            )
        except FileNotFoundError:
            return False
        self._ckpt_span("checkpoint_restore", t0_wall, t0, int(step))
        # the data-stream position travels with the step that actually
        # restored (a walk-back must not pair step N-1's weights with
        # step N's stream offset) and from the TIER that restored it;
        # missing/unreadable data_state downgrades to a fresh stream
        # inside read_data_state
        self._resume_data_state = ckpt.read_data_state(src, step, fmt=fmt)
        return True


def _shard_batch_arrays(batch: dict, mesh):
    from xflow_tpu.parallel.mesh import batch_sharding

    sh = batch_sharding(mesh)
    if jax.process_count() > 1:
        # each process holds different rows (its own input shard): assemble a
        # global array from per-process local data (device_put would demand
        # identical values everywhere)
        return {
            k: jax.make_array_from_process_local_data(sh[k], np.asarray(v))
            for k, v in batch.items()
        }
    return {k: jax.device_put(jnp.asarray(v), sh[k]) for k, v in batch.items()}
