from xflow_tpu.train.state import TrainState, init_state
from xflow_tpu.train.step import make_train_step, make_eval_step, loss_fn

__all__ = ["TrainState", "init_state", "make_train_step", "make_eval_step", "loss_fn"]
