"""On-device parity gate for the sorted-window Pallas kernels.

Round 2's silent-MXU-bf16 bug (docs/CHANGES_R2.md "Precision
integrity") is the class of regression CPU / interpret-mode tests are
structurally blind to: the kernels are only *lowered through Mosaic* on
a real chip, and the MXU's default operand rounding only exists there.
This module re-checks, on whatever backend is live:

- `table_gather_sorted` (single-stream and multi-buffer) is BIT-exact
  against the XLA gather oracle — the 3-term bf16 decomposition's
  selection property (`_dot_f32`), not a tolerance;
- the windowed scatter VJPs match `jax.ops.segment_sum` within the
  reduction-reorder class (≤ ~1 ulp per accumulated term);
- `row_sums_sorted`'s scalar-core RMW matches segment_sum likewise;
- the opt-in bf16 fast mode is *approximately* right (2^-7 rel) — it
  must stay a rounding trade, never a wrong-window bug.

Run by `bench.py` on the real chip (BENCH_r*.json carries a
`kernel_parity` field) and by `tests/test_kernel_parity_tpu.py`, which
auto-skips off-TPU (the pytest conftest pins CPU; set
`XFLOW_TEST_PLATFORM=tpu` on a TPU host to include it).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp


def _rel_err(a: np.ndarray, b: np.ndarray, floor: float = 1e-30) -> float:
    """Max ELEMENTWISE relative error: with the table's deliberately huge
    dynamic range, a global-max denominator would hide wrong values on
    small-magnitude entries entirely. `floor` is the absolute scale
    below which differences count as absolute, not relative — reduction
    checks need it because a slot whose unit-scale terms cancel to ~0
    has unbounded *relative* reorder noise while a wrong-routing bug
    still moves O(1) mass (err >= ~1 >> any tolerance here)."""
    return float(np.max(np.abs(a - b) / (np.abs(b) + floor)))


def check_kernel_parity(
    log2_slots: int = 15,
    n_occ: int = 1 << 17,
    k: int = 11,
    batch: int = 4096,
    seed: int = 0,
) -> dict:
    """Returns {"ok": bool, "checks": {name: max_rel_err}, "backend": str}.

    Gather checks require rel err == 0.0 (bit-exact); scatter/rowsum
    allow 1e-4 over a 1e-2 floor (f32 reduction reorder on unit-scale
    terms); bf16 mode allows 2^-7.
    """
    from xflow_tpu.ops.sorted_table import (
        _gather_xla,
        _k8,
        CHUNK,
        WINDOW,
        plan_sorted_batch,
        row_sums_sorted,
        table_gather_sorted,
        table_gather_sorted_multi,
    )

    rng = np.random.default_rng(seed)
    S = 1 << log2_slots
    nnz = n_occ // batch
    slots = rng.integers(0, S, (batch, nnz)).astype(np.int32)
    mask = (rng.random((batch, nnz)) < 0.9).astype(np.float32)
    table = rng.standard_normal((S, k)).astype(np.float32)
    # exercise the full f32 mantissa: values whose hi/mid/lo bf16 terms
    # are all nonzero, plus denormal-adjacent magnitudes
    table *= np.exp(rng.uniform(-8, 8, (S, 1))).astype(np.float32)
    plan = plan_sorted_batch(slots, mask, S)
    Np = plan.sorted_slots.shape[0]
    checks: dict[str, float] = {}

    tbl = jnp.asarray(table)
    ss = jnp.asarray(plan.sorted_slots)
    wo = jnp.asarray(plan.win_off)

    # --- gather: bit-exact vs the XLA oracle on the same device
    got = np.asarray(jax.jit(lambda t, s, w: table_gather_sorted(t, s, w, False))(tbl, ss, wo))
    want = np.asarray(jax.jit(_gather_xla)(tbl, ss, wo))
    checks["gather_exact"] = _rel_err(got, want)

    # --- gather, bf16 opt-in: a rounding trade, not a routing bug
    got16 = np.asarray(jax.jit(lambda t, s, w: table_gather_sorted(t, s, w, True))(tbl, ss, wo))
    checks["gather_bf16"] = _rel_err(got16, want)

    # --- scatter (the gather VJP): reduction-reorder class vs segment_sum
    d_occ = rng.standard_normal((_k8(k), Np)).astype(np.float32)
    d_occ *= np.asarray(plan.sorted_mask)[None, :]

    def scat(t, s, w, d):
        _, vjp = jax.vjp(lambda tt: table_gather_sorted(tt, s, w, False), t)
        return vjp(d)[0]

    got_s = np.asarray(jax.jit(scat)(tbl, ss, wo, jnp.asarray(d_occ)))
    want_s = np.asarray(
        jax.jit(
            lambda d, s: jax.ops.segment_sum(d[:k].T, s, num_segments=S)
        )(jnp.asarray(d_occ), ss)
    )
    checks["scatter_exact"] = _rel_err(got_s, want_s, floor=1e-2)

    # --- multi-buffer gather/scatter (fullshard engine): split the
    # sorted stream in two, pad each buffer to a fixed capacity with
    # slot S-1 per the host contract (each half of a sorted stream is
    # itself sorted, so no re-sort is needed)
    cap = ((Np // 2) // CHUNK + 1) * CHUNK
    bufs, offs = [], []
    split = (Np // 2 // CHUNK) * CHUNK
    for part in (np.asarray(plan.sorted_slots)[:split],
                 np.asarray(plan.sorted_slots)[split:]):
        pad = np.full(cap - part.size, S - 1, np.int32)
        buf = np.concatenate([part.astype(np.int32), pad])
        off = np.searchsorted(buf, np.arange(0, S + 1, WINDOW)).astype(np.int32)
        off[-1] = cap  # pads ride in the last window
        bufs.append(buf)
        offs.append(off)
    mslots = jnp.asarray(np.concatenate(bufs))
    moff = jnp.asarray(np.stack(offs))
    got_m = np.asarray(
        jax.jit(lambda t, s, o: table_gather_sorted_multi(t, s, o, False))(tbl, mslots, moff)
    )
    want_m = np.asarray(jax.jit(_gather_xla)(tbl, mslots, jnp.zeros((1,), jnp.int32)))
    checks["gather_multi_exact"] = _rel_err(got_m, want_m)

    d_m = rng.standard_normal(got_m.shape).astype(np.float32)

    def scat_m(t, s, o, d):
        _, vjp = jax.vjp(lambda tt: table_gather_sorted_multi(tt, s, o, False), t)
        return vjp(d)[0]

    got_ms = np.asarray(jax.jit(scat_m)(tbl, mslots, moff, jnp.asarray(d_m)))
    want_ms = np.asarray(
        jax.jit(
            lambda d, s: jax.ops.segment_sum(d[:k].T, s, num_segments=S)
        )(jnp.asarray(d_m), mslots)
    )
    checks["scatter_multi_exact"] = _rel_err(got_ms, want_ms, floor=1e-2)

    # --- packed storage ([S/8, 8K], pack_table): gather BIT-exact vs
    # the logical-layout kernel, scatter equal to the packed logical
    # gradient — the packed one-hot + static sub-row select must not
    # change a single bit of what the MXU produces
    from xflow_tpu.ops.sorted_table import pack_table, unpack_table

    tbl_p = jnp.asarray(pack_table(table))
    got_p = np.asarray(
        jax.jit(lambda t, s, w: table_gather_sorted(t, s, w, False, 8))(tbl_p, ss, wo)
    )
    checks["gather_packed"] = _rel_err(got_p, got)

    def scat_p(t, s, w, d):
        _, vjp = jax.vjp(lambda tt: table_gather_sorted(tt, s, w, False, 8), t)
        return vjp(d)[0]

    got_ps = np.asarray(jax.jit(scat_p)(tbl_p, ss, wo, jnp.asarray(d_occ)))
    checks["scatter_packed"] = _rel_err(
        unpack_table(got_ps, k), got_s, floor=1e-2
    )

    # --- sublane-ALIGNED row width (K8 == K): the kernels' pad-to-K8
    # blend has no pad rows here, a branch Mosaic only sees at aligned
    # widths (a zero-row pad array failed to compile for every
    # 8-multiple K until round 4 — FFM/MVM widths like 96 or 128 hit it)
    k_al = 16
    tbl_al = jnp.asarray(
        pack_table(rng.standard_normal((S, k_al)).astype(np.float32))
    )
    got_al = np.asarray(
        jax.jit(lambda t, s, w: table_gather_sorted(t, s, w, False, 8))(
            tbl_al, ss, wo
        )
    )
    want_al = np.asarray(jax.jit(lambda t, s: _gather_xla(t, s, None, 8))(tbl_al, ss))
    checks["gather_aligned_k"] = _rel_err(got_al, want_al)

    def scat_al(t, s, w, d):
        _, vjp = jax.vjp(lambda tt: table_gather_sorted(tt, s, w, False, 8), t)
        return vjp(d)[0]

    d_al = (rng.standard_normal(got_al.shape).astype(np.float32)
            * np.asarray(plan.sorted_mask)[None, :])
    got_als = np.asarray(jax.jit(scat_al)(tbl_al, ss, wo, jnp.asarray(d_al)))
    want_als = np.asarray(
        jax.jit(
            lambda d, s: jax.ops.segment_sum(d.T, s, num_segments=S)
        )(jnp.asarray(d_al[:k_al]), ss)
    )
    # compare in the packed layout the kernel writes
    checks["scatter_aligned_k"] = _rel_err(
        unpack_table(got_als, k_al), want_als, floor=1e-2
    )

    # --- fused scatter+FTRL (optim.fused_scatter): the Pallas window
    # pass that applies the optimizer at the gradient block's write
    # point must match the two-pass composition (XLA scatter + dense
    # _update_one) it replaces — w through the soft-threshold, n, z
    from xflow_tpu.config import FTRLConfig
    from xflow_tpu.ops.sorted_table import _scatter_xla, scatter_ftrl_sorted
    from xflow_tpu.optim.ftrl import _update_one

    hp = FTRLConfig()
    w0_l = rng.standard_normal((S, k)).astype(np.float32) * 0.01
    n0_l = np.abs(rng.standard_normal((S, k))).astype(np.float32) * 0.1
    z0_l = rng.standard_normal((S, k)).astype(np.float32) * 1e-4
    # exercise the lazy-init guard (g==0 ∧ n==0 keeps w) on device: the
    # upper half of the table gets NO gradient (its occurrences' d
    # columns zeroed — scatter of exact zeros) and zero n/z state, so
    # without the guard the closed form would zero those w's; the fused
    # kernel must keep the inits bitwise like the two-pass reference
    n0_l[S // 2:] = 0.0
    z0_l[S // 2:] = 0.0
    w0 = pack_table(w0_l)
    n0 = pack_table(n0_l)
    z0 = pack_table(z0_l)
    d_f = (rng.standard_normal((_k8(k), Np)).astype(np.float32)
           * np.asarray(plan.sorted_mask)[None, :]
           * (np.asarray(plan.sorted_slots) < S // 2)[None, :])
    # the DISPATCHING wrapper: Pallas on TPU, the two-pass composition
    # elsewhere — so this gate keeps running (trivially) off-TPU, per
    # the module contract
    got_f = jax.jit(
        lambda d, s, w_, n_, z_: scatter_ftrl_sorted(
            d, s, wo, w_, n_, z_, k, hp, False, 8
        )
    )(jnp.asarray(d_f), ss, jnp.asarray(w0), jnp.asarray(n0), jnp.asarray(z0))
    g_ref = jax.jit(
        lambda d, s: _scatter_xla(d, s, None, S, k, 8)
    )(jnp.asarray(d_f), ss)
    want_f = jax.jit(
        lambda w_, n_, z_, g: _update_one(
            w_, n_, z_, g, hp.alpha, hp.beta, hp.lambda1, hp.lambda2
        )
    )(jnp.asarray(w0), jnp.asarray(n0), jnp.asarray(z0), g_ref)
    for i, name in ((0, "scatter_ftrl_w"), (1, "scatter_ftrl_n"), (2, "scatter_ftrl_z")):
        checks[name] = _rel_err(
            np.asarray(got_f[i]), np.asarray(want_f[i]), floor=1e-4
        )

    # --- row-sum kernel (the FM forward's occurrence->row reduction)
    ch = 24
    vals_t = (rng.standard_normal((ch, Np)).astype(np.float32)
              * np.asarray(plan.sorted_mask)[None, :])
    rows = jnp.asarray(plan.sorted_row)
    got_r = np.asarray(
        jax.jit(lambda v, r: row_sums_sorted(v, r, batch))(jnp.asarray(vals_t), rows)
    )
    want_r = np.asarray(
        jax.jit(lambda v, r: jax.ops.segment_sum(v.T, r, num_segments=batch))(
            jnp.asarray(vals_t), rows
        )
    )
    checks["rowsum"] = _rel_err(got_r, want_r, floor=1e-2)

    tol = {
        "gather_exact": 0.0,
        "gather_multi_exact": 0.0,
        "gather_bf16": 2.0 ** -7,
        # scatters sum duplicate-slot terms in kernel order, segment_sum
        # in its own — absolute reorder noise is ~1e-6 on unit-scale
        # terms (measured on-device); with the 1e-2 floor that reads as
        # <=1e-4, while a routing bug moves O(1) mass (err >= ~1)
        "scatter_exact": 1e-4,
        "scatter_multi_exact": 1e-4,
        "gather_packed": 0.0,
        "scatter_packed": 1e-4,
        "gather_aligned_k": 0.0,
        "scatter_aligned_k": 1e-4,
        # gradient reorder noise (scatter class) flows through FTRL's
        # sqrt/divide; same tolerance class as the plain scatters
        "scatter_ftrl_w": 1e-3,
        "scatter_ftrl_n": 1e-3,
        "scatter_ftrl_z": 1e-3,
        "rowsum": 1e-4,
    }
    ok = all(checks[name] <= tol[name] for name in tol)
    return {"ok": ok, "checks": checks, "backend": jax.default_backend()}


def main() -> int:
    import json
    import sys

    res = check_kernel_parity()
    if res["backend"] != "tpu":
        # every check would trivially compare the XLA path against
        # itself — "ok" here would be a false all-clear
        print(f"kernel_parity: backend is {res['backend']}, not tpu — "
              "the Pallas kernels were never executed", file=sys.stderr)
        print(json.dumps({**res, "ok": False, "error": "not on tpu"}))
        return 2
    print(json.dumps(res))
    return 0 if res["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
