"""Sparse-primitive microbench lab: one harness for every hot-path probe.

The perf arc accumulated six one-off probe scripts — microbench_tpu
(raw gather/scatter/segment-sum latencies), layout_probe (carry-threaded
layout/bandwidth), mosaic_probe (Pallas DMA slice-shape compilability),
scatter_experiment (windowed-matmul scatter design), rowsum_probe
(scalar-core RMW row reduction), hostplane_bench (parse/plan host-plane
scaling) — each with its own timing harness and print-only output that
nothing consolidated or gated. This module unifies them:

- the SHARED measurement harness: `timeit_carry` (the carry-threaded
  scan pattern that defeats loop-invariant hoisting/DCE — docs/PERF.md
  "Measurement hygiene"), `timeit_scan` (the fold-into-carry scan the
  original microbench used), and `try_compile` (the Mosaic
  compilability probe), all with host-read sync (block_until_ready does
  not reliably sync through the axon tunnel);
- the CORE SWEEP (`--suite core`): a deterministic matrix over
  gather / scatter-add / segment-sum x table size x nnz x dtype, each
  cell compiled through the telemetry.CompileRecorder so XLA's modeled
  flops/bytes (and the achieved bandwidth they imply) ride along, and
  emitted as ONE `BENCH_LAB.json` record that tools/perf_ledger.py
  consolidates and regression-gates — the measured baseline matrix the
  fused-Pallas-kernel milestone is judged against (ROADMAP [speed]),
  replacing docs/PERF.md's hand-derived ~11 ns/element figure with a
  cited cell;
- the six probes as SUITES (`--suite micro|layout|mosaic|scatter|
  rowsum|hostplane`): their bodies live here, and the original
  tools/*.py entry points remain as thin wrappers, so every published
  command line keeps working while the kernel arc has one entry point.

CPU-sized runs are first-class: the CI gate (tools/smoke_hotpath.sh)
sweeps small tables on the CPU backend — machine-local numbers, gated
only against their own metric names like every CPU smoke datapoint.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

CORE_OPS = ("gather", "scatter_add", "segment_sum")


# ----------------------------------------------------------- shared harness


def timeit_scan(fn, *args, iters=8, inner=4):
    """The original microbench pattern: `inner` applications inside one
    compiled lax.scan, the output folded into the carry so the loop
    cannot be elided, completion forced by a host scalar read. Beware
    the hoisting caveat (docs/PERF.md "Measurement hygiene"): fn's
    operands are loop-invariant here — prefer `timeit_carry` for ops
    XLA could hoist. Returns best seconds per application."""
    import jax

    @jax.jit
    def run(*a):
        def body(c, _):
            out = fn(*a)
            return c + out.ravel()[0].astype(np.float32), None

        c, _ = jax.lax.scan(body, np.float32(0.0), None, length=inner)
        return c

    r = run(*args)
    _ = float(r)
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        _ = float(run(*args))
        best = min(best, (time.perf_counter() - t0) / inner)
    return best


def timeit_carry(step, init, iters=6, inner=4, recorder=None, name=""):
    """The hoisting-proof harness (layout_probe's): thread the state
    through the lax.scan CARRY so each iteration depends on the
    previous one — loop-invariant hoisting and DCE cannot fire — and
    force completion with a host scalar read. `step`: carry -> carry
    (same pytree structure). With a telemetry.CompileRecorder, the scan
    program compiles through it (timed compile + XLA cost analysis for
    the cell). Returns best seconds per iteration."""
    import jax

    @jax.jit
    def run(c):
        return jax.lax.scan(lambda c, _: (step(c), None), c, None, length=inner)[0]

    call = run
    if recorder is not None and name:
        compiled = recorder.record(name, run, init)
        if compiled is not None:
            call = compiled
    c = call(init)
    _ = float(jax.tree.leaves(c)[0].ravel()[0])
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        c = call(c)
        _ = float(jax.tree.leaves(c)[0].ravel()[0])
        best = min(best, (time.perf_counter() - t0) / inner)
    return best


def try_compile(name, fn, *args) -> bool:
    """Lower+compile `fn` for these args and report OK/FAIL — the
    Mosaic slice-shape compilability probe. Never raises."""
    import jax

    try:
        jax.jit(fn).lower(*args).compile()
        print(f"{name}: OK")
        return True
    except Exception as e:
        msg = str(e).split("\n")[0][:140]
        print(f"{name}: FAIL — {msg}")
        return False


# --------------------------------------------------------------- core sweep


def core_cell(op, table_log2, nnz_log2, dtype, row_width, iters, inner,
              recorder, seed=0):
    """One sweep cell: build the (seeded, deterministic) operands, time
    the op carry-threaded, and attach the CompileRecorder's cost stamps.
    The cell dict is the `cells[]` element of BENCH_LAB.json
    (docs/OBSERVABILITY.md "Sparse-primitive lab")."""
    import jax
    import jax.numpy as jnp

    if dtype not in ("f32", "bf16"):
        # a silent float32 fallback would mislabel gated baseline cells
        raise ValueError(f"dtype={dtype!r}: expected f32|bf16")
    S, N, K = 1 << table_log2, 1 << nnz_log2, int(row_width)
    jdtype = jnp.bfloat16 if dtype == "bf16" else jnp.float32
    rng = np.random.default_rng(seed + (table_log2 << 16) + (nnz_log2 << 8))
    idx = jnp.asarray(rng.integers(0, S, N), jnp.int32)
    tab = jnp.zeros((S, K), jdtype)
    vals = jnp.asarray(rng.normal(size=(N, K)).astype(np.float32)).astype(jdtype)
    name = f"lab_{op}_s{table_log2}_n{nnz_log2}_{dtype}"

    if op == "gather":
        # index perturbation depends on the carry scalar (always 0 in
        # practice, opaque to XLA) so the gather cannot be hoisted
        def step(c):
            t_, s = c
            i = idx + jnp.where(s > 1e30, 1, 0).astype(jnp.int32)
            return t_, s + t_[i].astype(jnp.float32).sum()

        t = timeit_carry(step, (tab, jnp.float32(0)), iters=iters,
                         inner=inner, recorder=recorder, name=name)
    elif op == "scatter_add":
        # the table IS the carry: a true sequential dependency
        t = timeit_carry(lambda t_: t_.at[idx].add(vals), tab, iters=iters,
                         inner=inner, recorder=recorder, name=name)
    elif op == "segment_sum":
        def step(c):
            bump = jnp.where(c > 1e30, 1.0, 0.0).astype(vals.dtype)
            out = jax.ops.segment_sum(vals + bump, idx, num_segments=S)
            return c + out.astype(jnp.float32).ravel()[0]

        t = timeit_carry(step, jnp.float32(0), iters=iters, inner=inner,
                         recorder=recorder, name=name)
    else:
        raise ValueError(f"op={op!r}: expected one of {CORE_OPS}")

    elements = N * K
    cell = {
        "op": op,
        "table_log2": int(table_log2),
        "nnz_log2": int(nnz_log2),
        "dtype": dtype,
        "row_width": K,
        "time_ms": round(t * 1e3, 4),
        "ns_per_element": round(t / elements * 1e9, 4),
    }
    rec = recorder.latest(name) if recorder is not None else None
    if rec:
        cell["compile_time_s"] = rec.get("compile_time_s")
        for key, per in (("flops", "flops"), ("bytes_accessed", "bytes_accessed")):
            v = rec.get(key)
            if isinstance(v, (int, float)):
                # the recorded program runs `inner` applications
                cell[per] = round(v / inner, 1)
        ba = cell.get("bytes_accessed")
        if isinstance(ba, (int, float)) and t > 0:
            cell["achieved_gbps"] = round(ba / t / 1e9, 4)
    return cell


def suite_core(argv) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_lab --suite core",
        description="deterministic gather/scatter-add/segment-sum sweep "
        "matrix -> BENCH_LAB.json (the sparse-primitive baseline the "
        "kernel arc is measured against)",
    )
    ap.add_argument("--table-log2", default="22",
                    help="comma list of log2 table sizes (default 22)")
    ap.add_argument("--nnz-log2", default="21",
                    help="comma list of log2 occurrence counts (default 21)")
    ap.add_argument("--dtypes", default="f32",
                    help="comma list from {f32, bf16} (default f32)")
    ap.add_argument("--ops", default=",".join(CORE_OPS),
                    help=f"comma list from {CORE_OPS}")
    ap.add_argument("--row-width", type=int, default=11,
                    help="table row width K (default 11 = fused FM)")
    ap.add_argument("--iters", type=int, default=6)
    ap.add_argument("--inner", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--round", type=int, default=None,
                    help="trajectory round stamped into the record "
                         "(perf_ledger gates rounds)")
    ap.add_argument("--out", default="BENCH_LAB.json",
                    help="output path ('-' = stdout)")
    args = ap.parse_args(argv)

    import jax

    from xflow_tpu.telemetry import CompileRecorder, Registry

    recorder = CompileRecorder(registry=Registry())
    tables = [int(x) for x in args.table_log2.split(",") if x]
    nnzs = [int(x) for x in args.nnz_log2.split(",") if x]
    dtypes = [x.strip() for x in args.dtypes.split(",") if x.strip()]
    ops = [x.strip() for x in args.ops.split(",") if x.strip()]
    cells = []
    for op in ops:
        for tl in tables:
            for nl in nnzs:
                for dt in dtypes:
                    cell = core_cell(op, tl, nl, dt, args.row_width,
                                     args.iters, args.inner, recorder,
                                     seed=args.seed)
                    cells.append(cell)
                    print(
                        f"{op:12s} S=2^{tl:<2d} N=2^{nl:<2d} {dt:4s} "
                        f"{cell['time_ms']:10.3f} ms  "
                        f"{cell['ns_per_element']:8.3f} ns/elem"
                        + (f"  {cell['achieved_gbps']:7.2f} GB/s"
                           if "achieved_gbps" in cell else ""),
                        file=sys.stderr,
                    )
    # headline: the gather latency cell at the LARGEST swept shape —
    # the number the ledger's roofline extrapolation cites in place of
    # the hand-derived 11 ns/element (docs/PERF.md)
    heads = [c for c in cells if c["op"] == "gather" and c["dtype"] == "f32"]
    heads = heads or cells
    head = max(heads, key=lambda c: (c["table_log2"], c["nnz_log2"]))
    record = {
        "kind": "bench_lab",
        "device": str(jax.devices()[0]),
        "host_cores": os.cpu_count(),
        "metric": f"lab_{head['op']}_ns_per_element",
        "value": head["ns_per_element"],
        "unit": "ns/element",
        "headline_cell": f"lab_{head['op']}_s{head['table_log2']}"
                         f"_n{head['nnz_log2']}_{head['dtype']}",
        "row_width": args.row_width,
        "iters": args.iters,
        "inner": args.inner,
        "seed": args.seed,
        "cells": cells,
    }
    if args.round is not None:
        record["round"] = int(args.round)
    payload = json.dumps(record, indent=1)
    if args.out == "-":
        print(payload)
    else:
        with open(args.out, "w") as f:
            f.write(payload + "\n")
        print(f"bench_lab: wrote {len(cells)} cell(s) to {args.out}",
              file=sys.stderr)
    return 0


# --------------------------------------------------- suite: micro (raw ops)


def suite_micro(argv) -> int:
    """TPU microbenchmarks for the sparse-table hot ops (docs/PERF.md
    "Round-2 microbench") — the former tools/microbench_tpu.py body."""
    import jax
    import jax.numpy as jnp

    S, N, K = 1 << 22, 1 << 21, 11  # table slots, occurrences, row width
    rng = np.random.default_rng(0)
    idx = jnp.asarray(rng.integers(0, S, N), jnp.int32)
    idx_sorted = jnp.sort(idx)
    tab1 = jnp.zeros((S,), jnp.float32)
    tabk = jnp.zeros((S, K), jnp.float32)
    val1 = jnp.asarray(rng.normal(size=N).astype(np.float32))
    valk = jnp.asarray(rng.normal(size=(N, K)).astype(np.float32))

    res = {}
    res["gather_scalar_2M"] = timeit_scan(lambda t, i: t[i], tab1, idx)
    res["gather_rows_2M_x11"] = timeit_scan(lambda t, i: t[i], tabk, idx)
    res["scatter_add_scalar_2M"] = timeit_scan(
        lambda t, i, v: t.at[i].add(v), tab1, idx, val1
    )
    res["scatter_add_rows_2M_x11"] = timeit_scan(
        lambda t, i, v: t.at[i].add(v), tabk, idx, valk
    )
    res["scatter_add_rows_sorted"] = timeit_scan(
        lambda t, i, v: t.at[i].add(v), tabk, idx_sorted, valk
    )
    res["segment_sum_rows_to_table"] = timeit_scan(
        lambda v, i: jax.ops.segment_sum(v, i, num_segments=S), valk, idx
    )
    res["segment_sum_sorted_hint"] = timeit_scan(
        lambda v, i: jax.ops.segment_sum(v, i, num_segments=S,
                                         indices_are_sorted=True),
        valk,
        idx_sorted,
    )
    res["ftrl_elementwise_3xSxK"] = timeit_scan(lambda w, g: w + g * g, tabk, tabk)
    # dedup shape: U unique rows + re-gather occurrences from the small array
    for U_log in (17, 19):
        U = 1 << U_log
        uniq = jnp.asarray(rng.integers(0, S, U), jnp.int32)
        inv = jnp.asarray(rng.integers(0, U, N), jnp.int32)
        res[f"dedup_gather_U{U >> 10}k"] = timeit_scan(
            lambda t, u, i: t[u][i], tabk, uniq, inv
        )
        res[f"dedup_scatter_U{U >> 10}k"] = timeit_scan(
            lambda t, u, i, v: t.at[u].add(
                jax.ops.segment_sum(v, i, num_segments=U)
            ),
            tabk,
            uniq,
            inv,
            valk,
        )

    dev = jax.devices()[0]
    print(f"# device={dev}")
    for k, v in res.items():
        print(f"{k:32s} {v * 1e3:8.2f} ms")
    return 0


# ------------------------------------------------- suite: layout (carried)


def suite_layout(argv) -> int:
    """[S, k] vs flat layout/bandwidth probe, carry-threaded — the
    former tools/layout_probe.py body."""
    import jax
    import jax.numpy as jnp

    S, K, N = 1 << 22, 11, 1 << 21
    rng = np.random.default_rng(0)
    idx = jnp.asarray(rng.integers(0, S, N), jnp.int32)
    valk = jnp.asarray(rng.normal(size=(N, K)).astype(np.float32))

    a2d = jnp.full((S, K), 1.0, jnp.float32)
    aflat = jnp.full((S * K,), 1.0, jnp.float32)
    apack = jnp.full((S * K // 128, 128), 1.0, jnp.float32)

    r = {}
    mul = lambda x: x * 1.000001 + 1e-9
    r["elementwise [4M,11]"] = timeit_carry(mul, a2d)
    r["elementwise flat 44M"] = timeit_carry(mul, aflat)
    r["elementwise [344k,128]"] = timeit_carry(mul, apack)

    # gather rows: force each iteration to depend on the previous via a
    # scalar folded into the indices (cannot be constant-folded)
    def gather_step(c):
        t, s = c
        i = idx + jnp.where(s > 1e30, 1, 0).astype(jnp.int32)
        g = t[i]
        return t, s + g.sum()

    r["gather rows [S,11]"] = timeit_carry(gather_step, (a2d, jnp.float32(0)))

    def gather_flat_step(c):
        t, s = c
        i = idx + jnp.where(s > 1e30, 1, 0).astype(jnp.int32)
        g = t.reshape(S, K)[i]
        return t, s + g.sum()

    r["gather via reshape"] = timeit_carry(gather_flat_step, (aflat, jnp.float32(0)))

    # scatter-add rows: table is the carry — true sequential dependency
    r["scatter rows [S,11]"] = timeit_carry(lambda t: t.at[idx].add(valk), a2d)
    r["scatter via reshape"] = timeit_carry(
        lambda t: t.reshape(S, K).at[idx].add(valk).reshape(S * K), aflat
    )

    # FTRL-ish update: w,n,z carried, g fixed
    def ftrl_step(c):
        w, n, z = c
        g = valk.sum() * 0 + 1e-4  # scalar, negligible
        n2 = n + g * g
        z2 = z + g - (jnp.sqrt(n2) - jnp.sqrt(n)) * 20.0 * w
        w2 = jnp.where(jnp.abs(z2) <= 5e-5, 0.0,
                       -z2 / ((1.0 + jnp.sqrt(n2)) * 20.0 + 10.0))
        return w2, n2, z2

    r["ftrl pass [4M,11]x3"] = timeit_carry(ftrl_step, (a2d, a2d * 0.5, a2d * 0.1))
    r["ftrl pass flat x3"] = timeit_carry(ftrl_step, (aflat, aflat * 0.5, aflat * 0.1))

    print(f"# device={jax.devices()[0]}  (s/iter, carry-threaded)")
    for k, v in r.items():
        print(f"{k:24s} {v * 1e3:8.2f} ms")
    return 0


# ------------------------------------------------------ suite: mosaic (DMA)


def suite_mosaic(argv) -> int:
    """Pallas/Mosaic DMA slice-shape compilability probe — the former
    tools/mosaic_probe.py body (decides the sorted-table kernel data
    layout, ops/sorted_table.py)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    W, C, K = 512, 512, 11
    S, N = 1 << 14, 1 << 13

    table = jnp.zeros((S, K), jnp.float32)
    d_t = jnp.zeros((K, N), jnp.float32)
    sl_row = jnp.zeros((1, N), jnp.int32)
    d_rows = jnp.zeros((N, K), jnp.float32)
    off = jnp.zeros((S // W + 1,), jnp.int32)

    # A: BlockSpec windowed table input
    def kern_a(off_ref, tab_ref, out_ref):
        out_ref[:, :] = tab_ref[:, :] * 2.0

    def fa(off, table):
        gs = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(S // W,),
            in_specs=[pl.BlockSpec((W, K), lambda t, o: (t, 0))],
            out_specs=pl.BlockSpec((W, K), lambda t, o: (t, 0)),
        )
        return pl.pallas_call(kern_a, grid_spec=gs,
                              out_shape=jax.ShapeDtypeStruct((S, K), jnp.float32))(off, table)

    try_compile("A block (512,11) f32", fa, off, table)

    # B: DMA [K, C] col-slice of [K, N] f32 at dynamic 128-aligned offset
    def kern_b(off_ref, d_ref, out_ref, scr, sem):
        t = pl.program_id(0)
        start = (off_ref[t] // C) * C
        cp = pltpu.make_async_copy(d_ref.at[:, pl.ds(start, C)], scr, sem)
        cp.start()
        cp.wait()
        out_ref[0, 0] = scr[0, 0]

    def fb(off, d):
        gs = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(4,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
            scratch_shapes=[pltpu.VMEM((K, C), jnp.float32), pltpu.SemaphoreType.DMA(())],
        )
        return pl.pallas_call(kern_b, grid_spec=gs,
                              out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32))(off, d)

    try_compile("B dma [11,512] of [11,N] f32", fb, off, d_t)

    # C: DMA [1, C] col-slice of [1, N] int32
    def kern_c(off_ref, s_ref, out_ref, scr, sem):
        t = pl.program_id(0)
        start = (off_ref[t] // C) * C
        cp = pltpu.make_async_copy(s_ref.at[:, pl.ds(start, C)], scr, sem)
        cp.start()
        cp.wait()
        out_ref[0, 0] = scr[0, 0]

    def fc(off, s):
        gs = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(4,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
            scratch_shapes=[pltpu.VMEM((1, C), jnp.int32), pltpu.SemaphoreType.DMA(())],
        )
        return pl.pallas_call(kern_c, grid_spec=gs,
                              out_shape=jax.ShapeDtypeStruct((1, 1), jnp.int32))(off, s)

    try_compile("C dma [1,512] of [1,N] i32", fc, off, sl_row)

    # D: DMA [C, K] row-slice of [N, K] f32 at dynamic unaligned row offset
    def kern_d(off_ref, d_ref, out_ref, scr, sem):
        t = pl.program_id(0)
        start = off_ref[t]
        cp = pltpu.make_async_copy(d_ref.at[pl.ds(start, C), :], scr, sem)
        cp.start()
        cp.wait()
        out_ref[0, 0] = scr[0, 0]

    def fd(off, d):
        gs = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(4,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
            scratch_shapes=[pltpu.VMEM((C, K), jnp.float32), pltpu.SemaphoreType.DMA(())],
        )
        return pl.pallas_call(kern_d, grid_spec=gs,
                              out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32))(off, d)

    try_compile("D dma [512,11] of [N,11] f32 dyn-row", fd, off, d_rows)

    # E: transpose cost [4M, 11] <-> [11, 4M]
    big = jnp.zeros((1 << 22, K), jnp.float32) + 1.0

    @jax.jit
    def tr(x, s):
        y = (x + s).T
        return y, y[0, 0]

    y, v = tr(big, 0.0)
    _ = float(v)
    best = 1e9
    for i in range(4):
        t0 = time.perf_counter()
        y, v = tr(big, float(i))
        _ = float(v)
        best = min(best, time.perf_counter() - t0)
    print(f"E transpose [4M,11]->[11,4M]: {best * 1e3:.1f} ms")
    return 0


# ------------------------------------------- suite: scatter (windowed plan)


def host_sort_plan(slots_flat: np.ndarray, S: int, C: int = 1024, W: int = 2048):
    """(perm [M], sorted_slots [M], bases [M//C]) — chunks grid-aligned.

    perm maps sorted position -> occurrence index (N = dummy zero row).
    The windowed-matmul scatter design probe's host planner (the former
    tools/scatter_experiment.py helper)."""
    N = slots_flat.shape[0]
    order = np.argsort(slots_flat, kind="stable")
    ss = slots_flat[order]
    win = ss // W
    # chunk boundaries: every C occurrences, or window change
    M_cap = N + (S // W + 1) * C
    perm = np.full(M_cap, N, np.int32)
    srt = np.zeros(M_cap, np.int32)
    bases = []
    pos = 0
    i = 0
    while i < N:
        w = win[i]
        j = min(N, i + C)
        # shrink to this window only
        j = i + int(np.searchsorted(win[i:j], w + 1))
        take = j - i
        perm[pos: pos + take] = order[i:j]
        srt[pos: pos + take] = ss[i:j]
        srt[pos + take: pos + C] = w * W  # dummies point in-window
        bases.append(w * W)
        pos += C
        i = j
    nchunks = len(bases)
    return (
        perm[: nchunks * C],
        srt[: nchunks * C],
        np.asarray(bases, np.int32),
    )


def suite_scatter(argv) -> int:
    """Sorted windowed-matmul scatter design probe — the former
    tools/scatter_experiment.py body (docs/PERF.md lever)."""
    import jax
    import jax.numpy as jnp

    C, W = 1024, 2048
    S, N, K = 1 << 22, 1 << 21, 11
    rng = np.random.default_rng(0)
    slots = rng.integers(0, S, N).astype(np.int32)
    d_occ = rng.normal(size=(N, K)).astype(np.float32)

    t0 = time.perf_counter()
    perm, srt, bases = host_sort_plan(slots, S, C, W)
    t_host = time.perf_counter() - t0
    nchunks = len(bases)
    print(f"host plan: {t_host * 1e3:.1f} ms, nchunks={nchunks} "
          f"(pad {nchunks * C / N:.3f}x)")

    jperm = jnp.asarray(perm)
    jsrt = jnp.asarray(srt.reshape(nchunks, C))
    jbases = jnp.asarray(bases)
    jd = jnp.asarray(d_occ)
    jslots = jnp.asarray(slots)

    def timeit(f, *a, iters=5):
        out = f(*a)
        _ = float(jax.tree.leaves(out)[0].ravel()[0])
        best = float("inf")
        for _ in range(iters):
            t0 = time.perf_counter()
            out = f(*a)
            _ = float(jax.tree.leaves(out)[0].ravel()[0])
            best = min(best, time.perf_counter() - t0)
        return best

    # 1. permute gather: [M,K] from compact [N+1,K]
    @jax.jit
    def permute(d, p):
        dpad = jnp.concatenate([d, jnp.zeros((1, K), d.dtype)], 0)
        return dpad[p]

    t = timeit(permute, jd, jperm)
    print(f"permute gather [{len(perm)},{K}]: {t * 1e3:7.1f} ms")

    # 2. windowed matmul scatter via scan
    @jax.jit
    def windowed_scatter(d, p, srt2d, bases1d):
        dpad = jnp.concatenate([d, jnp.zeros((1, K), d.dtype)], 0)
        ds = dpad[p].reshape(nchunks, C, K)

        def body(tab, xs):
            dch, sch, base = xs
            onehot = (sch[:, None] == base + jax.lax.broadcasted_iota(
                jnp.int32, (C, W), 1)).astype(jnp.float32)
            upd = jax.lax.dot_general(
                onehot, dch, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [W, K]
            win = jax.lax.dynamic_slice(tab, (base, 0), (W, K))
            return jax.lax.dynamic_update_slice(tab, win + upd, (base, 0)), None

        tab = jnp.zeros((S, K), jnp.float32)
        tab, _ = jax.lax.scan(body, tab, (ds, srt2d, bases1d))
        return tab

    t = timeit(windowed_scatter, jd, jperm, jsrt, jbases)
    print(f"windowed scatter e2e   : {t * 1e3:7.1f} ms")

    # 3. XLA scatter baseline + equality
    @jax.jit
    def xla_scatter(d, s):
        return jnp.zeros((S, K), jnp.float32).at[s].add(d)

    t = timeit(xla_scatter, jd, jslots)
    print(f"xla scatter-add        : {t * 1e3:7.1f} ms")

    a = np.asarray(windowed_scatter(jd, jperm, jsrt, jbases))
    b = np.asarray(xla_scatter(jd, jslots))
    err = np.max(np.abs(a - b))
    print(f"max |windowed - xla|   : {err:.3e}")
    return 0


# --------------------------------------------- suite: rowsum (scalar RMW)


def suite_rowsum(argv) -> int:
    """Pallas scalar-core row-reduction probe — the former
    tools/rowsum_probe.py body (docs/PERF.md "row-reduction kernel")."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B = 65536
    CH = 24  # padded channel count (21 used)
    C = 512  # chunk
    Np = 2098176  # padded_len(65536*32)
    K = 4  # batches in the scan

    rng = np.random.default_rng(0)
    rows = rng.integers(0, B, (K, Np)).astype(np.int32)
    vals = rng.normal(size=(K, CH, Np)).astype(np.float32)

    n_chunks = Np // C

    def kernel(rows_ref, vals_ref, out_ref, acc2, vchunk, vt_ref, rchunk,
               sem_v, sem_r):
        out_ref[:, :] = jnp.zeros((B, CH), jnp.float32)
        acc2[:, :] = jnp.zeros((B, CH), jnp.float32)

        def chunk_step(c, carry):
            o = c * C
            cp_r = pltpu.make_async_copy(rows_ref.at[:, pl.ds(o, C)], rchunk, sem_r)
            cp_r.start()
            cp_v = pltpu.make_async_copy(vals_ref.at[:, pl.ds(o, C)], vchunk, sem_v)
            cp_v.start()
            cp_r.wait()
            cp_v.wait()
            vt_ref[:, :] = vchunk[:, :].T  # [C, CH] staged for row reads

            def inner(i, carry2):
                r0 = rchunk[0, 2 * i]
                r1 = rchunk[0, 2 * i + 1]
                out_ref[pl.ds(r0, 1), :] += vt_ref[pl.ds(2 * i, 1), :]
                acc2[pl.ds(r1, 1), :] += vt_ref[pl.ds(2 * i + 1, 1), :]
                return carry2

            jax.lax.fori_loop(0, C // 2, inner, 0)
            return carry

        jax.lax.fori_loop(0, n_chunks, chunk_step, 0)
        out_ref[:, :] += acc2[:, :]

    def rowsum_pallas(rows1, vals1):
        return pl.pallas_call(
            kernel,
            grid=(1,),
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=pl.BlockSpec((B, CH), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((B, CH), jnp.float32),
            scratch_shapes=[
                pltpu.VMEM((B, CH), jnp.float32),
                pltpu.VMEM((CH, C), jnp.float32),
                pltpu.VMEM((C, CH), jnp.float32),
                pltpu.SMEM((1, C), jnp.int32),
                pltpu.SemaphoreType.DMA(()),
                pltpu.SemaphoreType.DMA(()),
            ],
        )(rows1.reshape(1, Np), vals1)

    # correctness on a small case first (interpret on CPU would be slow;
    # run tiny on device)
    try:
        jit_rowsum = jax.jit(rowsum_pallas)
        small_out = jit_rowsum(jnp.asarray(rows[0]), jnp.asarray(vals[0]))
        got = np.asarray(small_out)
    except Exception as e:
        print(f"COMPILE/RUN FAIL: {str(e).splitlines()[0][:300]}")
        return 1
    want = np.zeros((B, CH), np.float32)
    np.add.at(want, rows[0], vals[0].T)
    err = np.abs(got - want).max()
    print(f"correctness: max abs err = {err:.2e}")

    @jax.jit
    def run_pallas(rows, vals):
        def body(c, b):
            out = rowsum_pallas(b[0], b[1])
            return c + out[::97, 0].sum() + out[::89, 5].sum(), None

        return jax.lax.scan(body, 0.0, (rows, vals))[0]

    @jax.jit
    def run_xla(rows, vals):
        def body(c, b):
            out = jax.ops.segment_sum(b[1].T, b[0], num_segments=B)
            return c + out[::97, 0].sum() + out[::89, 5].sum(), None

        return jax.lax.scan(body, 0.0, (rows, vals))[0]

    jrows, jvals = jnp.asarray(rows), jnp.asarray(vals)
    for name, fn in [("pallas scalar-RMW", run_pallas), ("xla segment_sum", run_xla)]:
        out = fn(jrows, jvals)
        _ = float(out)
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            out = fn(jrows, jvals)
            _ = float(out)
            best = min(best, (time.perf_counter() - t0) / K)
        print(f"{name}: {best * 1e3:.1f} ms ({best / Np * 1e9:.2f} ns/occurrence)")
    return 0


# ---------------------------------------------- suite: hostplane (CPU side)


def _hostplane_bench_parse(path: str, caps, cfg) -> dict:
    from xflow_tpu.config import override
    from xflow_tpu.data.pipeline import batch_iterator

    out = {}
    for cap in caps:
        c = override(cfg, **{"data.parser_threads": cap})
        # warm (page cache + pool spin-up)
        for _ in batch_iterator(path, c.data):
            pass
        t0 = time.perf_counter()
        n = 0
        for b in batch_iterator(path, c.data):
            n += b.num_rows
        dt = time.perf_counter() - t0
        out[f"parse_rows_per_sec_{cap}w"] = round(n / dt, 1)
    return out


def _hostplane_bench_plan(caps, batch: int, nnz: int, log2_slots: int,
                          num_sub: int) -> dict:
    from concurrent.futures import ThreadPoolExecutor

    from xflow_tpu.data.native import native_plan_sorted
    from xflow_tpu.ops.sorted_table import WINDOW, padded_len

    S = 1 << log2_slots
    rng = np.random.default_rng(0)
    bs = batch // num_sub
    subs = [
        np.ascontiguousarray(rng.integers(0, S, (bs, nnz)).astype(np.int32))
        for _ in range(num_sub)
    ]
    mask = np.ones((bs, nnz), np.float32)

    def one(i):
        return native_plan_sorted(subs[i], mask, None, S, WINDOW, padded_len(bs * nnz))

    out = {}
    for cap in caps:
        with ThreadPoolExecutor(max_workers=cap) as pool:
            list(pool.map(one, range(num_sub)))  # warm
            t0 = time.perf_counter()
            reps = 5
            for _ in range(reps):
                list(pool.map(one, range(num_sub)))
            dt = (time.perf_counter() - t0) / reps
        out[f"plan_rows_per_sec_{cap}w"] = round(batch / dt, 1)
    return out


def suite_hostplane(argv) -> int:
    """Host data-plane scaling harness — the former
    tools/hostplane_bench.py body (per-core parse/plan rates and the
    1/2/4-worker scaling curve; docs/PERF.md "Host data plane")."""
    import tempfile

    ap = argparse.ArgumentParser(prog="bench_lab --suite hostplane")
    ap.add_argument("--rows", type=int, default=500_000)
    ap.add_argument("--batch", type=int, default=65536)
    ap.add_argument("--nnz", type=int, default=18)
    ap.add_argument("--log2-slots", type=int, default=22)
    ap.add_argument("--num-sub", type=int, default=8,
                    help="concurrent sub-batch plans (the trainer's "
                         "parallelism unit)")
    ap.add_argument("--caps", default="1,2,4")
    args = ap.parse_args(argv)

    from xflow_tpu.config import Config, override
    from xflow_tpu.data.synth import generate_shards_bulk

    caps = [int(c) for c in args.caps.split(",")]
    record = {"host_cores": os.cpu_count()}
    with tempfile.TemporaryDirectory() as td:
        prefix = os.path.join(td, "t")
        generate_shards_bulk(prefix, 1, args.rows, num_fields=args.nnz,
                             ids_per_field=200_000, seed=0)
        cfg = override(
            Config(),
            **{"data.batch_size": args.batch, "data.max_nnz": args.nnz,
               "data.log2_slots": args.log2_slots,
               "model.num_fields": args.nnz},
        )
        record.update(_hostplane_bench_parse(prefix + "-00000", caps, cfg))
    record.update(
        _hostplane_bench_plan(caps, args.batch, args.nnz, args.log2_slots,
                              args.num_sub)
    )
    print(json.dumps(record))
    return 0


# -------------------------------------------------------------------- main


SUITES = {
    "core": suite_core,
    "micro": suite_micro,
    "layout": suite_layout,
    "mosaic": suite_mosaic,
    "scatter": suite_scatter,
    "rowsum": suite_rowsum,
    "hostplane": suite_hostplane,
}


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    ap = argparse.ArgumentParser(
        description="sparse-primitive microbench lab: the unified probe "
        "harness (docs/PERF.md, docs/OBSERVABILITY.md \"Sparse-primitive "
        "lab\")"
    )
    ap.add_argument("--suite", default="core", choices=sorted(SUITES),
                    help="which probe suite to run (default: the core "
                         "sweep matrix -> BENCH_LAB.json)")
    args, rest = ap.parse_known_args(argv)
    return int(SUITES[args.suite](rest) or 0)


if __name__ == "__main__":
    sys.exit(main())
