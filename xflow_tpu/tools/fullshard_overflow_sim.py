"""Pod-scale fullshard overflow accounting (host-only, no devices).

The fullshard engine sizes its per-(source shard, owner block)
exchange buffers as ``slack x uniform-hash expectation + one spare
CHUNK`` (parallel/sorted_fullshard.fullshard_capacity). On skewed data
a hot key concentrates occurrences in ONE owner block, and when any
buffer overflows, the whole batch falls back — rank-symmetrically —
to the GSPMD row-major step (trainer._resolve_fullshard_overflow). A
v5e-64 run should know its expected fallback rate BEFORE production,
not discover it; this tool plans synthetic Zipf batches against
virtual owner-block grids and reports overflow rates per slack.

Why overflow is FUNDAMENTAL at high skew + many blocks, not a tuning
failure: a bounded power law with exponent alpha over N slots gives
the hottest slot a share p1 = 1/H(alpha, N) of ALL occurrences
(H the generalized harmonic number — e.g. alpha=1.05, N=2^24:
H~10.9 so p1~9%). Those occurrences all land in the hot slot's owner
block, so the needed slack is at least p1 x (D x T) x (occurrences
per source) / expectation = p1 x D x T: at D x T = 512 that is ~47x —
a 47x memory overprovision to never fall back. The engineering answer
at that scale is a modest slack that absorbs the TAIL (every block
whose load is near-uniform) plus the coordinated fallback for the
hot-head batches, whose rate this tool measures. The reference never
dies on a hot key either — its parameter server just serves it slowly
(`/root/reference/src/optimizer/ftrl.h:54-79`).

Usage:
    python -m xflow_tpu.tools.fullshard_overflow_sim [--quick]

Prints a markdown table (docs/DISTRIBUTED.md "Hot keys" carries the
committed copy) plus one JSON line with the raw rates.
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np

# mirror of ops/sorted_table.CHUNK and the capacity rule, kept import-
# light so the sim never touches jax (CI runs it as a plain host test)
CHUNK = 512


def capacity(slack: float, rows_src: int, nnz: int, d: int, t: int) -> int:
    expect = rows_src * nnz / (d * t)
    cap = int(np.ceil(slack * expect / CHUNK)) * CHUNK
    return max(cap, CHUNK) + CHUNK


_CDF_CACHE: dict = {}


def zipf_cdf(num_slots: int, alpha: float) -> np.ndarray:
    key = (num_slots, alpha)
    if key not in _CDF_CACHE:
        pmf = 1.0 / np.arange(1, num_slots + 1, dtype=np.float64) ** alpha
        _CDF_CACHE[key] = np.cumsum(pmf / pmf.sum())
    return _CDF_CACHE[key]


def zipf_slots(rng, num_slots: int, alpha: float, n: int) -> np.ndarray:
    """Bounded power-law ranks scrambled by a multiplicative bijection
    mod num_slots — frequency skew survives, index locality does not
    (bench.py draw_slots' scheme; hashed id streams have no locality)."""
    ranks = np.searchsorted(zipf_cdf(num_slots, alpha), rng.random(n))
    return ((ranks * 2654435761) % num_slots).astype(np.int64)


def batch_max_counts(
    rng, alpha: float, d: int, t: int, num_slots: int, rows_src: int,
    nnz: int, batches: int,
) -> np.ndarray:
    """[batches] max per-(source, owner) occurrence count. Each of the
    `d` source shards draws its own rows; owner block = slot //
    (num_slots / (d*t)) — the engine's block map. One pass serves every
    slack value (overflow ⇔ max count > slack budget)."""
    s_block = num_slots // (d * t)
    out = np.empty(batches, np.int64)
    for b in range(batches):
        mx = 0
        for _src in range(d):
            slots = zipf_slots(rng, num_slots, alpha, rows_src * nnz)
            mx = max(mx, int(np.bincount(slots // s_block,
                                         minlength=d * t).max()))
        out[b] = mx
    return out


def run(quick: bool = False) -> dict:
    num_slots = (1 << 20) if quick else (1 << 24)  # north-star per-pod shape
    nnz = 18  # Criteo-ish
    global_rows = 1 << 16
    batches = 3 if quick else 20
    slacks = [1.5, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0]
    grids = [(8, 1), (8, 8), (64, 8)]  # D*T = 8 / 64 / 512
    alphas = [1.05, 1.1, 1.3]
    rng = np.random.default_rng(0)
    rows = {}
    for alpha in alphas:
        for (d, t) in grids:
            rows_src = max(global_rows // d, 1024)
            mx = batch_max_counts(rng, alpha, d, t, num_slots, rows_src,
                                  nnz, batches)
            # the engine raises only when a block's REAL occurrences
            # exceed the FULL cap (fullshard_buffers clamps spans to
            # n_real first, so the spare CHUNK is usable headroom)
            rates = [
                float((mx > capacity(s, rows_src, nnz, d, t)).mean())
                for s in slacks
            ]
            rows[f"a{alpha}_dt{d * t}"] = {
                "rates": rates,
                # the slack that would have held every batch: the worst
                # buffer load over the trial vs the uniform expectation
                "needed_slack": round(
                    float(mx.max()) / (rows_src * nnz / (d * t)), 1
                ),
            }
    return {"slacks": slacks, "grids": [d * t for d, t in grids],
            "alphas": alphas, "rows": rows, "batches": batches,
            "num_slots": num_slots}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args(argv)
    res = run(args.quick)
    slacks = res["slacks"]
    print(
        "| skew \\ slack | "
        + " | ".join(str(s) for s in slacks)
        + " | needed |"
    )
    print("|---" * (len(slacks) + 2) + "|")
    for key, row in res["rows"].items():
        cells = " | ".join(f"{r:.0%}" for r in row["rates"])
        print(f"| {key} | {cells} | {row['needed_slack']} |")
    print(json.dumps(res))
    return 0


if __name__ == "__main__":
    sys.exit(main())
