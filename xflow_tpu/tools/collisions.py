"""Hash-collision measurement.

The reference accepts silent collisions from raw `std::hash` over the
full 64-bit key space (`load_data_from_disk.cc:151`); this framework
additionally folds keys into `2**log2_slots` dense slots, which adds
collisions (SURVEY.md §7 hard part e: "match that behavior but measure
collision rate"). This tool reports, for a dataset and slot budget:

- distinct feature-id tokens seen
- distinct 64-bit hashes (pre-fold collisions — FNV-1a birthday regime)
- distinct slots (post-fold)
- collision rate = 1 − distinct_slots / distinct_tokens
"""

from __future__ import annotations

import json

import numpy as np

from xflow_tpu.hashing import fnv1a64, slots_of


def measure(paths: list[str], log2_slots: int, salt: int = 0) -> dict:
    tokens: set[str] = set()
    for path in paths:
        with open(path) as f:
            for line in f:
                parts = line.rstrip("\n").split("\t", 1)
                if len(parts) < 2:
                    parts = line.rstrip("\n").split(" ", 1)
                    if len(parts) < 2:
                        continue
                for tok in parts[1].split():
                    pieces = tok.split(":")
                    if len(pieces) >= 2:
                        tokens.add(pieces[1])
    hashes = np.array([fnv1a64(t.encode(), salt) for t in tokens], dtype=np.uint64)
    slots = slots_of(hashes, log2_slots)
    n_tok = len(tokens)
    n_hash = len(np.unique(hashes))
    n_slot = len(np.unique(slots))
    return {
        "distinct_tokens": n_tok,
        "distinct_hash64": n_hash,
        "distinct_slots": n_slot,
        "log2_slots": log2_slots,
        "table_occupancy": n_slot / float(1 << log2_slots),
        "collision_rate": 1.0 - (n_slot / n_tok) if n_tok else 0.0,
    }


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description="measure feature-hash collision rate")
    ap.add_argument("paths", nargs="+")
    ap.add_argument("--log2-slots", type=int, default=22)
    ap.add_argument("--salt", type=int, default=0)
    args = ap.parse_args(argv)
    print(json.dumps(measure(args.paths, args.log2_slots, args.salt)))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
