"""Criteo/Avazu raw-TSV → libffm converter (streaming, stdlib-only).

BASELINE.md configs 2–4 name real datasets (Criteo Kaggle 45M, Avazu
40M, Criteo-1TB) that the zero-egress build environment cannot
download; this tool is the documented ingestion recipe for when one IS
mounted (docs/DATASETS.md). The reference consumes libffm lines
(`label\\tfield:feature:value`, `/root/reference/data/small_train-00000`
shape) and so do we — raw Criteo display-advertising TSV
(`label \\t I1..I13 \\t C1..C26`) converts with the standard transform:

- integer feature Ii (field i-1): token ``i-1:I<i-1>_<bucket>:1`` with
  ``bucket = floor(log2(v+1))`` for v ≥ 0 (the log2 binning every
  public Criteo pipeline uses — caps the per-field vocabulary at ~40)
  and a dedicated ``NEG`` bucket for negative values; missing → no
  token.
- categorical feature Cj (field 13+j-1): token ``f:C<f>_<hex>:1``;
  missing → no token.

The FIELD INDEX IS FOLDED INTO THE FEATURE TEXT (``I3_2``, ``C17_ab``):
the framework — like the reference, `load_data_from_disk.cc:151` —
hashes ONLY the feature token, not the field, so without the fold the
same value in two fields would alias to one table slot (all 13 integer
fields would share ~41 weights). The synthetic generator globalizes
per-field ids for the same reason (data/synth.py). No global id
assignment pass is needed — the converter is single-pass, streaming,
constant-memory, and shards round-robin into the `-%05d` files rank k
reads.

Avazu (`id,click,hour,C1,...` CSV) converts with --format avazu: every
column after `click` becomes one categorical field.

Usage:
    python -m xflow_tpu.tools.criteo_convert train.txt /data/criteo/train \\
        --shards 64
    python -m xflow_tpu.tools.criteo_convert avazu_train.csv /data/avazu/train \\
        --format avazu --shards 64
"""

from __future__ import annotations

import argparse
import math
import sys
from typing import Iterator, Optional

N_INT, N_CAT = 13, 26

# libffm token structure: whitespace separates tokens, ':' separates
# field/feature/value. A raw categorical value containing either would
# emit a line the downstream parser silently MIS-tokenizes (not skips),
# so dirty values are escaped injectively: '%' + 2-hex-digit byte for
# each structural character ('%' itself included so no clean value can
# collide with an escaped one).
_BAD = set(" \t\n\r\x0b\x0c:%")


def _sanitize(v: str) -> str:
    if not any(c in _BAD for c in v):
        return v
    return "".join("%%%02X" % ord(c) if c in _BAD else c for c in v)


def criteo_line_to_libffm(line: str) -> Optional[str]:
    """One raw Criteo TSV line -> one libffm line (None = malformed)."""
    parts = line.rstrip("\n").split("\t")
    if len(parts) != 1 + N_INT + N_CAT:
        return None
    label = parts[0]
    if label not in ("0", "1"):
        return None
    toks = []
    for i in range(N_INT):
        v = parts[1 + i]
        if not v:
            continue
        try:
            iv = int(v)
        except ValueError:
            return None
        bucket = "NEG" if iv < 0 else str(int(math.log2(iv + 1)))
        toks.append("%d:I%d_%s:1" % (i, i, bucket))
    for j in range(N_CAT):
        v = parts[1 + N_INT + j]
        if not v:
            continue
        f = N_INT + j
        toks.append("%d:C%d_%s:1" % (f, f, _sanitize(v)))
    if not toks:
        return None
    return "%s\t%s" % (label, " ".join(toks))


def avazu_line_to_libffm(line: str, n_fields: int) -> Optional[str]:
    """One Avazu CSV line (id,click,col2..) -> libffm (None = malformed).
    Field index folded into the token (A<f>_<v>) — see module docstring."""
    parts = line.rstrip("\n").split(",")
    if len(parts) != n_fields + 2 or parts[1] not in ("0", "1"):
        return None
    toks = [
        "%d:A%d_%s:1" % (f, f, _sanitize(v)) for f, v in enumerate(parts[2:]) if v
    ]
    if not toks:
        return None
    return "%s\t%s" % (parts[1], " ".join(toks))


def convert(
    src,
    out_prefix: str,
    num_shards: int,
    fmt: str = "criteo",
    limit: int = 0,
    header: bool = True,
) -> dict:
    """Stream `src` (an iterable of lines) into `<out_prefix>-%05d`
    libffm shards, round-robin by row (every shard sees the same label
    mix — the rank-sharded files the trainer reads are statistically
    interchangeable). Returns {'rows': n, 'skipped': m, 'fields': nf}.

    Avazu: the first line defines the column count; with `header=True`
    (the raw Kaggle file) it is consumed as the header, with
    `header=False` (pre-split / tail'ed chunks) it is ALSO converted as
    data — nothing is silently dropped either way."""
    # round-robin writes touch every shard continuously, so all shard
    # files stay open for the whole run: check the fd budget up front
    # instead of dying with EMFILE after validation already passed
    try:
        import resource

        soft = resource.getrlimit(resource.RLIMIT_NOFILE)[0]
        if soft != resource.RLIM_INFINITY and num_shards > soft - 16:
            raise ValueError(
                f"--shards {num_shards} needs {num_shards} simultaneously "
                f"open files but the process fd limit is {soft}; raise it "
                f"(`ulimit -n {num_shards + 64}`) or convert in chunks"
            )
    except ImportError:  # non-POSIX: let the OS report it
        pass
    outs = [open("%s-%05d" % (out_prefix, s), "w") for s in range(num_shards)]
    rows = skipped = 0
    n_fields = N_INT + N_CAT
    avazu_cols = None
    pending = []
    try:
        it: Iterator[str] = iter(src)
        if fmt == "avazu":
            first = next(it, "")
            avazu_cols = max(0, len(first.rstrip("\n").split(",")) - 2)
            n_fields = avazu_cols
            if not header and first:
                pending.append(first)
        import itertools

        for line in itertools.chain(pending, it):
            if fmt == "criteo":
                conv = criteo_line_to_libffm(line)
            else:
                conv = avazu_line_to_libffm(line, avazu_cols)
            if conv is None:
                skipped += 1
                continue
            outs[rows % num_shards].write(conv + "\n")
            rows += 1
            if limit and rows >= limit:
                break
    finally:
        for f in outs:
            f.close()
    return {"rows": rows, "skipped": skipped, "fields": n_fields}


def _add_cache_args(ap: argparse.ArgumentParser) -> None:
    """The hash parameters a packed cache is built FOR (they are baked
    into the stored slot ids — docs/DATA.md): must match the training
    config's data.* values or the trainer will reject the cache as
    stale."""
    ap.add_argument("--log2-slots", type=int, default=22,
                    help="table size the slots fold into (data.log2_slots)")
    ap.add_argument("--hash-salt", type=int, default=0,
                    help="feature-hash salt (data.hash_salt)")
    ap.add_argument("--max-nnz", type=int, default=32,
                    help="padded per-row feature capacity (data.max_nnz)")
    ap.add_argument("--cache-dir", default="",
                    help="where .xfc files go ('' = sibling of each shard; "
                         "data.cache_dir)")


def cache_main(argv) -> int:
    """`criteo_convert cache <prefix>`: pack existing libffm text
    shards into the binary shard cache (data/shardcache.py) — the
    hash-at-convert-time pass that makes train-time batch assembly an
    mmap offset computation (docs/DATA.md)."""
    ap = argparse.ArgumentParser(
        prog="criteo_convert cache",
        description="pack <prefix>-NNNNN libffm shards into .xfc binary "
                    "caches (pre-hashed, crc32-digested, mmap'd at train "
                    "time; docs/DATA.md)",
    )
    ap.add_argument("prefix", help="libffm shard prefix (reads <prefix>-NNNNN)")
    _add_cache_args(ap)
    ap.add_argument("--force", action="store_true",
                    help="rebuild caches that are already fresh")
    args = ap.parse_args(argv)
    from xflow_tpu.config import Config, override
    from xflow_tpu.data.shardcache import build_cache

    cfg = override(Config(), **{
        "data.log2_slots": args.log2_slots,
        "data.hash_salt": args.hash_salt,
        "data.max_nnz": args.max_nnz,
        "data.cache_dir": args.cache_dir,
    }).data
    stats = build_cache(args.prefix, cfg, force=args.force)
    import json

    print(json.dumps(stats))
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    # git-style precedence: a literal first argument `cache` IS the
    # subcommand; a raw dump actually named "cache" must be passed as
    # `./cache` (the help says so)
    if argv[:1] == ["cache"]:
        return cache_main(argv[1:])
    ap = argparse.ArgumentParser(
        description="stream raw Criteo/Avazu into rank-sharded libffm files "
                    "(subcommand `cache`: pack existing libffm shards into "
                    "the binary shard cache)"
    )
    ap.add_argument("src", help="raw file path, or - for stdin (zcat | ...); "
                                "a file literally named 'cache' must be "
                                "passed as ./cache (bare 'cache' selects "
                                "the subcommand)")
    ap.add_argument("out_prefix", help="writes <out_prefix>-%%05d")
    ap.add_argument("--shards", type=int, default=8,
                    help="one per training rank (rank k reads shard k)")
    ap.add_argument("--format", default="criteo", choices=("criteo", "avazu"))
    ap.add_argument("--limit", type=int, default=0, help="stop after N rows (smoke runs)")
    ap.add_argument("--no-header", action="store_true",
                    help="avazu: the stream has no CSV header (pre-split "
                         "chunks); the first line is data")
    ap.add_argument("--cache", action="store_true",
                    help="also build the binary shard cache in the same "
                         "invocation (equivalent to a follow-up "
                         "`criteo_convert cache <out_prefix>`)")
    _add_cache_args(ap)
    args = ap.parse_args(argv)
    src = sys.stdin if args.src == "-" else open(args.src)
    try:
        stats = convert(src, args.out_prefix, args.shards, args.format,
                        args.limit, header=not args.no_header)
    finally:
        if src is not sys.stdin:
            src.close()
    if args.cache:
        from xflow_tpu.config import Config, override
        from xflow_tpu.data.shardcache import build_cache

        ccfg = override(Config(), **{
            "data.log2_slots": args.log2_slots,
            "data.hash_salt": args.hash_salt,
            "data.max_nnz": args.max_nnz,
            "data.cache_dir": args.cache_dir,
        }).data
        stats["cache"] = build_cache(args.out_prefix, ccfg, force=True)
    import json

    print(json.dumps(stats))
    return 0


if __name__ == "__main__":
    sys.exit(main())
