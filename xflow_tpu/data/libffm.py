"""libffm-format reader (pure-Python reference path).

Format: ``label\\tfield:feature:value [field:feature:value ...]`` — one
example per line (see `/root/reference/data/small_train-00000`).

Semantics preserved from the reference parser
(`/root/reference/src/io/load_data_from_disk.cc:103-210`):

- the label token is parsed as a float; label = 1 iff > 1e-7
  (`load_data_from_disk.cc:131-134`);
- each feature token contributes ``(fgid, hash(feature_id_string))``;
  the *value* field is never parsed (`:150-153` break after field 1) —
  features are binary;
- the feature id is hashed as a *string* (`:151`); we use the framework
  hash (hashing.fnv1a64) instead of implementation-defined `std::hash`;
- reading is block-buffered with partial-line carry (`:108-124`); the
  Python path just streams lines (the C++ native parser keeps the
  block-buffered design for throughput).

The reference's per-rank shard convention ``"%s-%05d" % (prefix, rank)``
(`lr_worker.cc:210`) is provided by `shard_path`.
"""

from __future__ import annotations

import os
import re
from typing import Iterator, Optional

import numpy as np

from xflow_tpu.hashing import fnv1a64, slot_of
from xflow_tpu.jsonl import JsonlAppender

_NUM_PREFIX = re.compile(r"^[+-]?(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?")
_HEX_PREFIX = re.compile(r"^[+-]?0[xX][0-9a-fA-F]+(?:\.[0-9a-fA-F]*)?(?:[pP][+-]?\d+)?")
_INFNAN_PREFIX = re.compile(r"^[+-]?(?:infinity|inf|nan(?:\([a-zA-Z0-9_]*\))?)", re.IGNORECASE)
# ASCII whitespace only: C code (and strtod) never treats unicode
# whitespace specially, so the Python path must not either
_ASCII_WS = " \t\r\n\v\f"
_TOKEN_SEP = re.compile(r"[ \t\r\v\f]+")


def _strtod(tok: str) -> float:
    """C strtod semantics: parse the longest numeric prefix, 0.0 for junk.

    The native parser uses strtod for labels and field ids
    (`native/parser.cc`); the Python path must parse the same file to the
    same batches (round-1 divergence: `int(float(...))` raised on junk
    fgids while the native path yielded 0 and continued). Covers the
    strtod corners Python's float() handles differently: hex floats
    (C99, float() rejects) and underscore digit groups (float() accepts,
    strtod stops at the underscore)."""
    tok = tok.strip(_ASCII_WS)
    if "_" not in tok:
        try:
            return float(tok)  # fast path; also covers inf/nan like strtod
        except ValueError:
            pass
    m = _HEX_PREFIX.match(tok)
    if m:
        return float.fromhex(m.group(0))
    m = _INFNAN_PREFIX.match(tok)
    if m:
        # strtod parses 'inf'/'infinity'/'nan(...)' prefixes with junk after
        return float(re.sub(r"\(.*\)", "", m.group(0)))
    m = _NUM_PREFIX.match(tok)
    return float(m.group(0)) if m else 0.0


def _fgid_i32(x: float) -> int:
    """Field id as int32 with explicit nan→0 and saturation — the defined
    semantics both parsers implement (a raw C cast would be UB here)."""
    if x != x:
        return 0
    if x >= 2147483647.0:
        return 2147483647
    if x <= -2147483648.0:
        return -2147483648
    return int(x)


def shard_path(prefix: str, rank: int) -> str:
    """Reference shard naming: `<prefix>-%05d` (`lr_worker.cc:210`)."""
    return "%s-%05d" % (prefix, rank)


def split_line(
    line: str,
) -> Optional[tuple[float, list[int], list[str]]]:
    """The PARSE half of `parse_line`: label + token split, feature id
    strings still unhashed → (label, fields, feature-id strings).

    Split out so the pipeline profiler (telemetry.PipelineProfiler) can
    attribute parse and hash time separately. NOTE: `parse_line` does
    NOT compose these halves — it keeps its own fused single-pass loop
    so the un-profiled hot path pays nothing for the split — so any
    token-rule change must be made in BOTH places; the parity is pinned
    by tests/test_hotpath.py::test_parse_line_matches_profiled_halves
    and the counter/parser parity suite."""
    line = line.strip(_ASCII_WS)
    if not line:
        return None
    parts = line.split("\t", 1)
    if len(parts) == 1:
        # tolerate space-separated label too
        parts = line.split(" ", 1)
        if len(parts) == 1:
            return None
    label = 1.0 if _strtod(parts[0]) > 1e-7 else 0.0
    fields: list[int] = []
    ids: list[str] = []
    for tok in _TOKEN_SEP.split(parts[1]):
        pieces = tok.split(":")
        if len(pieces) < 2:
            continue
        fields.append(_fgid_i32(_strtod(pieces[0])))
        ids.append(pieces[1])
    return label, fields, ids


def hash_ids(ids: list[str], log2_slots: int, salt: int = 0) -> np.ndarray:
    """The HASH half: feature-id strings → folded slot ids (int32)."""
    return np.asarray(
        [slot_of(fnv1a64(t.encode("utf-8"), salt), log2_slots) for t in ids],
        dtype=np.int32,
    )


def parse_line(
    line: str, log2_slots: int, salt: int = 0
) -> Optional[tuple[float, np.ndarray, np.ndarray]]:
    """Parse one libffm line → (label, fields[int32], slots[int32]).

    Deliberately the FUSED single-pass loop (hash inline, no
    intermediate id-string list) — this is the Python fallback parser's
    hot path, and the profiled split through `split_line` + `hash_ids`
    must cost the un-profiled path nothing. The three functions share
    the token rules; parity is pinned by tests/test_libffm.py and the
    counter/parser parity suite."""
    line = line.strip(_ASCII_WS)
    if not line:
        return None
    parts = line.split("\t", 1)
    if len(parts) == 1:
        # tolerate space-separated label too
        parts = line.split(" ", 1)
        if len(parts) == 1:
            return None
    label = 1.0 if _strtod(parts[0]) > 1e-7 else 0.0
    fields = []
    slots = []
    for tok in _TOKEN_SEP.split(parts[1]):
        pieces = tok.split(":")
        if len(pieces) < 2:
            continue
        fields.append(_fgid_i32(_strtod(pieces[0])))
        slots.append(slot_of(fnv1a64(pieces[1].encode("utf-8"), salt), log2_slots))
    return (
        label,
        np.asarray(fields, dtype=np.int32),
        np.asarray(slots, dtype=np.int32),
    )


def iter_examples(
    path: str, log2_slots: int, salt: int = 0, profiler=None
) -> Iterator[tuple[float, np.ndarray, np.ndarray]]:
    """Stream (label, fields, slots) examples from a libffm file.

    `profiler` (telemetry.PipelineProfiler, optional) attributes wall
    time to the read / parse / hash stages; the per-line accumulations
    batch locally and flush to the (locked) profiler every few hundred
    lines so attribution never contends per row. None = the exact
    historical loop."""
    if profiler is not None:
        yield from _profiled_iter_examples(path, log2_slots, salt, profiler)
        return
    with open(path, "r") as f:
        for line in f:
            ex = parse_line(line, log2_slots, salt)
            if ex is not None:
                yield ex


def _profiled_iter_examples(
    path: str, log2_slots: int, salt: int, profiler
) -> Iterator[tuple[float, np.ndarray, np.ndarray]]:
    import time

    pc = time.perf_counter
    acc = {"read": 0.0, "parse": 0.0, "hash": 0.0}
    pending = 0
    try:
        with open(path, "r") as f:
            while True:
                t0 = pc()
                line = f.readline()
                acc["read"] += pc() - t0
                if not line:
                    return
                t0 = pc()
                t = split_line(line)
                acc["parse"] += pc() - t0
                if t is None:
                    continue
                label, fields, ids = t
                t0 = pc()
                slots = hash_ids(ids, log2_slots, salt)
                acc["hash"] += pc() - t0
                pending += 1
                if pending >= 512:
                    profiler.add_many(acc)
                    acc = {"read": 0.0, "parse": 0.0, "hash": 0.0}
                    pending = 0
                yield label, np.asarray(fields, dtype=np.int32), slots
    finally:
        # flush the tail (and the abandonment path: prefetch's close()
        # cascade raises GeneratorExit through the yield above)
        profiler.add_many(acc)


def read_examples(
    path: str, log2_slots: int, salt: int = 0
) -> list[tuple[float, np.ndarray, np.ndarray]]:
    return list(iter_examples(path, log2_slots, salt))


def count_rows(path: str) -> int:
    """Count the examples `iter_examples` would yield, without parsing
    tokens — `parse_line` yields a row iff the stripped line still
    contains a label separator (tab or space)."""
    n = 0
    with open(path, "r") as f:
        for line in f:
            s = line.strip(_ASCII_WS)
            if s and ("\t" in s or " " in s):
                n += 1
    return n


class QuarantineWriter(JsonlAppender):
    """Append-only JSONL sink for bad (feature-less) records
    (data.quarantine_path; docs/ROBUSTNESS.md).

    One line per bad row: source path, batch/row position, label — enough
    to locate the offending region of a shard for offline triage without
    re-parsing the whole file. Lifecycle (lazy open with parent-dir
    creation, flush-per-record, reopen-safe close) AND the ts/rank/run_id
    provenance stamp come from the shared appender (xflow_tpu/jsonl.py),
    so quarantine records join the metrics stream on (run_id, rank, ts).
    Written rows also tick the telemetry registry
    (`data.quarantined_rows`), surfacing in metrics window records."""

    def __init__(self, path: str = ""):
        super().__init__(path)
        self.written = 0

    def write(self, source: str, batch_index: int, row: int, label: float) -> None:
        if not self._path:
            return
        self.append(
            {"source": source, "batch": batch_index, "row": row, "label": label}
        )
        self.written += 1
        from xflow_tpu.telemetry import default_registry

        default_registry().counter("data.quarantined_rows").inc()


def available_shards(prefix: str) -> list[str]:
    """All `<prefix>-NNNNN` shard files that exist, in rank order."""
    out = []
    rank = 0
    while True:
        p = shard_path(prefix, rank)
        if not os.path.exists(p):
            break
        out.append(p)
        rank += 1
    return out
