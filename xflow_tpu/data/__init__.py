from xflow_tpu.data.schema import SparseBatch
from xflow_tpu.data.libffm import iter_examples, read_examples
from xflow_tpu.data.pipeline import batch_iterator, examples_to_batches

__all__ = [
    "SparseBatch",
    "iter_examples",
    "read_examples",
    "batch_iterator",
    "examples_to_batches",
]
