"""Deterministic synthetic libffm data generator.

Produces data shaped like the reference's bundled fixture
(`/root/reference/data/small_train-0000{0..2}`: libffm lines with 18
fields, feature ids ≤ 1e4, L2-normalized float values) but generated
from a fixed seed so the repo carries no copied data. Labels follow a
planted sparse-LR ground truth so that training should beat AUC 0.5 by
a wide margin — giving tests a learnability signal, not just parity.
"""

from __future__ import annotations

import os

import numpy as np


def generate_shards(
    out_prefix: str,
    num_shards: int,
    rows_per_shard: int,
    num_fields: int = 18,
    # 500 keeps the default 10k-row dataset dense enough that train and
    # test SHARE features (10k ids/field made them near-disjoint: a run
    # with defaults evaluated at AUC ~0.50 and looked like a non-learner)
    ids_per_field: int = 500,
    seed: int = 0,
    noise: float = 1.0,
    truth_density: float = 1.0,
    truth_seed: int | None = None,
    zipf_alpha: float = 0.0,
) -> list[str]:
    """Write `<out_prefix>-%05d` libffm shards; returns the paths.

    `seed` drives row sampling; the planted ground-truth weights come
    from `truth_seed` (default: `seed`). Generate train and test splits
    with the same `truth_seed` but different `seed` so they share the
    underlying concept.

    `zipf_alpha > 0` draws per-field feature ids from a Zipf-like power
    law (P(rank r) ∝ 1/r^alpha) instead of uniform — the shape of real
    CTR data (Criteo/Avazu categorical frequencies are heavy-tailed),
    where a few hot features dominate every batch. Uniform sampling is
    the worst case for gather locality and hides the wins from
    batch-level key dedup (BASELINE.md configs 2-3; round-1 verdict
    item 9). alpha≈1.1 approximates Criteo-like skew.
    """
    rng = np.random.default_rng(seed)
    truth_rng = np.random.default_rng(seed if truth_seed is None else truth_seed)
    # planted ground-truth weight per (field, id); density<1 zeroes a fraction
    truth = truth_rng.normal(0.0, 1.0, size=(num_fields, ids_per_field))
    if truth_density < 1.0:
        truth = truth * (truth_rng.random((num_fields, ids_per_field)) < truth_density)
    value = 1.0 / np.sqrt(num_fields)
    zipf_cdf = None
    if zipf_alpha > 0.0:
        pmf = 1.0 / np.arange(1, ids_per_field + 1, dtype=np.float64) ** zipf_alpha
        zipf_cdf = np.cumsum(pmf / pmf.sum())
    paths = []
    os.makedirs(os.path.dirname(out_prefix) or ".", exist_ok=True)
    for shard in range(num_shards):
        path = "%s-%05d" % (out_prefix, shard)
        with open(path, "w") as f:
            for _ in range(rows_per_shard):
                if zipf_cdf is not None:
                    # inverse-CDF sampling; rank r maps to feature id r-1,
                    # so low ids are the hot head of every field
                    ids = np.searchsorted(zipf_cdf, rng.random(num_fields))
                else:
                    ids = rng.integers(0, ids_per_field, size=num_fields)
                logit = truth[np.arange(num_fields), ids].sum() + rng.normal(0.0, noise)
                label = 1 if logit > 0 else 0
                # feature-id strings are globalized per field (fg*ids_per_field
                # + id): models hash the id token alone (as the reference does),
                # so per-field ids must not collide across fields
                toks = " ".join(
                    "%d:%d:%.4f" % (fg, fg * ids_per_field + ids[fg], value)
                    for fg in range(num_fields)
                )
                f.write("%d\t%s\n" % (label, toks))
        paths.append(path)
    return paths


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description="generate synthetic libffm shards")
    ap.add_argument("out_prefix")
    ap.add_argument("--shards", type=int, default=3)
    ap.add_argument("--rows", type=int, default=1000)
    ap.add_argument("--fields", type=int, default=18)
    ap.add_argument("--ids-per-field", type=int, default=500)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--zipf-alpha", type=float, default=0.0,
                    help="power-law feature skew (0 = uniform; ~1.1 ≈ CTR-like)")
    args = ap.parse_args()
    paths = generate_shards(
        args.out_prefix, args.shards, args.rows, args.fields, args.ids_per_field, args.seed,
        zipf_alpha=args.zipf_alpha,
    )
    print("\n".join(paths))


if __name__ == "__main__":
    main()
