"""Deterministic synthetic libffm data generator.

Produces data shaped like the reference's bundled fixture
(`/root/reference/data/small_train-0000{0..2}`: libffm lines with 18
fields, feature ids ≤ 1e4, L2-normalized float values) but generated
from a fixed seed so the repo carries no copied data. Labels follow a
planted sparse-LR ground truth so that training should beat AUC 0.5 by
a wide margin — giving tests a learnability signal, not just parity.
"""

from __future__ import annotations

import os

import numpy as np


def _planted_truth(truth_rng, num_fields, ids_per_field, truth_density):
    """Shared planted-truth weights — ONE implementation so the per-row
    and bulk writers can never diverge on the concept they plant."""
    truth = truth_rng.normal(0.0, 1.0, size=(num_fields, ids_per_field))
    if truth_density < 1.0:
        truth = truth * (truth_rng.random((num_fields, ids_per_field)) < truth_density)
    return truth


def _planted_ffm_truth(truth_rng, num_fields, ids_per_field, dim=3):
    """Field-PAIR interaction ground truth (BASELINE.json config 5's
    learnability gate): per-feature latent u ∈ R^dim shared across
    pairs, with an independent ±1 sign per unordered FIELD pair —
    logit(row) = scale · Σ_{a<b} s_ab ⟨u_a[i_a], u_b[i_b]⟩.

    The sign matrix is (with overwhelming probability for ≥3 fields)
    NOT separable as s_ab = σ_a·σ_b, so a plain FM — whose ⟨v_i, v_j⟩
    is field-blind — cannot represent the concept with the same latent
    budget, while FFM fits it directly (v_{i,b} = ±u_i). `scale` keeps
    logit variance ≈ num_fields, matching the linear truth's SNR."""
    u = truth_rng.normal(0.0, 1.0, size=(num_fields, ids_per_field, dim))
    s = np.triu(
        np.where(truth_rng.random((num_fields, num_fields)) < 0.5, 1.0, -1.0), 1
    )
    n_pairs = num_fields * (num_fields - 1) // 2
    scale = np.sqrt(num_fields / max(n_pairs * dim, 1))
    return u, s, scale


def _zipf_cdf(ids_per_field, zipf_alpha):
    if zipf_alpha <= 0.0:
        return None
    pmf = 1.0 / np.arange(1, ids_per_field + 1, dtype=np.float64) ** zipf_alpha
    return np.cumsum(pmf / pmf.sum())


def generate_shards(
    out_prefix: str,
    num_shards: int,
    rows_per_shard: int,
    num_fields: int = 18,
    # 500 keeps the default 10k-row dataset dense enough that train and
    # test SHARE features (10k ids/field made them near-disjoint: a run
    # with defaults evaluated at AUC ~0.50 and looked like a non-learner)
    ids_per_field: int = 500,
    seed: int = 0,
    noise: float = 1.0,
    truth_density: float = 1.0,
    truth_seed: int | None = None,
    zipf_alpha: float = 0.0,
    truth: str = "linear",
) -> list[str]:
    """Write `<out_prefix>-%05d` libffm shards; returns the paths.

    `seed` drives row sampling; the planted ground-truth weights come
    from `truth_seed` (default: `seed`). Generate train and test splits
    with the same `truth_seed` but different `seed` so they share the
    underlying concept.

    `zipf_alpha > 0` draws per-field feature ids from a Zipf-like power
    law (P(rank r) ∝ 1/r^alpha) instead of uniform — the shape of real
    CTR data (Criteo/Avazu categorical frequencies are heavy-tailed),
    where a few hot features dominate every batch. Uniform sampling is
    the worst case for gather locality and hides the wins from
    batch-level key dedup (BASELINE.md configs 2-3; round-1 verdict
    item 9). alpha≈1.1 approximates Criteo-like skew.

    `truth="ffm"` plants the field-PAIR interaction concept
    (`_planted_ffm_truth`) instead of the linear one — the learnability
    gate for field-aware models (BASELINE.json config 5): FFM fits it
    directly, a field-blind FM cannot with the same latent budget.
    """
    rng = np.random.default_rng(seed)
    truth_rng = np.random.default_rng(seed if truth_seed is None else truth_seed)
    if truth not in ("linear", "ffm"):
        raise ValueError(f"truth={truth!r}: expected linear|ffm")
    ffm_truth = truth == "ffm"
    if ffm_truth:
        u, s_pairs, scale = _planted_ffm_truth(truth_rng, num_fields, ids_per_field)
    else:
        w_truth = _planted_truth(truth_rng, num_fields, ids_per_field, truth_density)
    value = 1.0 / np.sqrt(num_fields)
    zipf_cdf = _zipf_cdf(ids_per_field, zipf_alpha)
    paths = []
    os.makedirs(os.path.dirname(out_prefix) or ".", exist_ok=True)
    for shard in range(num_shards):
        path = "%s-%05d" % (out_prefix, shard)
        with open(path, "w") as f:
            for _ in range(rows_per_shard):
                if zipf_cdf is not None:
                    # inverse-CDF sampling; rank r maps to feature id r-1,
                    # so low ids are the hot head of every field
                    ids = np.searchsorted(zipf_cdf, rng.random(num_fields))
                else:
                    ids = rng.integers(0, ids_per_field, size=num_fields)
                if ffm_truth:
                    # Σ_{a<b} s_ab ⟨u_a[i_a], u_b[i_b]⟩ via one gram matrix
                    ur = u[np.arange(num_fields), ids]  # [nf, d]
                    logit = scale * float(
                        (s_pairs * (ur @ ur.T)).sum()
                    ) + rng.normal(0.0, noise)
                else:
                    logit = w_truth[np.arange(num_fields), ids].sum() + rng.normal(0.0, noise)
                label = 1 if logit > 0 else 0
                # feature-id strings are globalized per field (fg*ids_per_field
                # + id): models hash the id token alone (as the reference does),
                # so per-field ids must not collide across fields
                toks = " ".join(
                    "%d:%d:%.4f" % (fg, fg * ids_per_field + ids[fg], value)
                    for fg in range(num_fields)
                )
                f.write("%d\t%s\n" % (label, toks))
        paths.append(path)
    return paths


def generate_shards_bulk(
    out_prefix: str,
    num_shards: int,
    rows_per_shard: int,
    num_fields: int = 18,
    ids_per_field: int = 500,
    seed: int = 0,
    noise: float = 1.0,
    truth_density: float = 1.0,
    truth_seed: int | None = None,
    zipf_alpha: float = 0.0,
    chunk_rows: int = 200_000,
    track_seen: bool = False,
    truth: str = "linear",
):
    """Chunked vectorized writer for realistic-scale datasets (≥10M rows,
    BASELINE.md configs 2-3): same planted-truth model as
    `generate_shards` but sampled whole chunks at a time and formatted
    through NumPy's vectorized string kernels — ~30× the per-row loop,
    which at 10M rows is the difference between minutes and hours on one
    core. A separate function (not a fast-path inside `generate_shards`)
    because the RNG stream differs: golden tests pin the per-row
    stream's exact output.

    Returns (paths, seen) — `seen` is a [num_fields * ids_per_field]
    bool array marking every feature id actually emitted (None unless
    `track_seen`), which makes exact collision accounting free at
    generation time instead of a 180M-token file re-scan.
    """
    rng = np.random.default_rng(seed)
    truth_rng = np.random.default_rng(seed if truth_seed is None else truth_seed)
    if truth not in ("linear", "ffm"):
        raise ValueError(f"truth={truth!r}: expected linear|ffm")
    ffm_truth = truth == "ffm"
    if ffm_truth:
        # same planted concept as generate_shards' truth="ffm" (field-
        # pair interactions a field-blind FM cannot fit); scored per
        # CHUNK through one gram einsum instead of per row
        u, s_pairs, scale = _planted_ffm_truth(truth_rng, num_fields, ids_per_field)
    else:
        w_truth = _planted_truth(truth_rng, num_fields, ids_per_field, truth_density)
    value_suffix = ":%.4f" % (1.0 / np.sqrt(num_fields))
    zipf_cdf = _zipf_cdf(ids_per_field, zipf_alpha)
    seen = (
        np.zeros(num_fields * ids_per_field, bool) if track_seen else None
    )
    offsets = (np.arange(num_fields) * ids_per_field)[None, :]
    # token prefix per field: " fg:" (leading space separates tokens;
    # the first token's space rides after the label tab and is stripped
    # by any split-on-whitespace parser, but keep the exact libffm shape
    # by prefixing the first field without the space)
    prefixes = ["%d:" % fg if fg == 0 else " %d:" % fg for fg in range(num_fields)]
    paths = []
    os.makedirs(os.path.dirname(out_prefix) or ".", exist_ok=True)
    add = np.strings.add if hasattr(np, "strings") else np.char.add
    for shard in range(num_shards):
        path = "%s-%05d" % (out_prefix, shard)
        with open(path, "w") as f:
            left = rows_per_shard
            while left > 0:
                c = min(chunk_rows, left)
                left -= c
                if zipf_cdf is not None:
                    ids = np.searchsorted(
                        zipf_cdf, rng.random((c, num_fields))
                    ).astype(np.int64)
                else:
                    ids = rng.integers(0, ids_per_field, size=(c, num_fields))
                if ffm_truth:
                    ur = u[np.arange(num_fields)[None, :], ids]  # [c, nf, d]
                    gram = np.einsum("cad,cbd->cab", ur, ur)
                    logit = scale * (gram * s_pairs[None]).sum(axis=(1, 2))
                else:
                    logit = w_truth[np.arange(num_fields)[None, :], ids].sum(axis=1)
                logit = logit + rng.normal(0.0, noise, size=c)
                labels = (logit > 0).astype(np.int64)
                gids = ids + offsets
                if seen is not None:
                    seen[gids.ravel()] = True
                # string width sized to the largest possible gid — a fixed
                # "U9" would silently truncate ids past 10^9
                gid_width = len(str(num_fields * ids_per_field - 1))
                lines = add(labels.astype("U1"), "\t")
                for fg in range(num_fields):
                    lines = add(lines, prefixes[fg])
                    lines = add(lines, gids[:, fg].astype(f"U{gid_width}"))
                    lines = add(lines, value_suffix)
                f.write("\n".join(lines.tolist()))
                f.write("\n")
        paths.append(path)
    return paths, seen


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description="generate synthetic libffm shards")
    ap.add_argument("out_prefix")
    ap.add_argument("--shards", type=int, default=3)
    ap.add_argument("--rows", type=int, default=1000)
    ap.add_argument("--fields", type=int, default=18)
    ap.add_argument("--ids-per-field", type=int, default=500)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--zipf-alpha", type=float, default=0.0,
                    help="power-law feature skew (0 = uniform; ~1.1 ≈ CTR-like)")
    ap.add_argument("--bulk", action="store_true",
                    help="chunked vectorized writer (realistic-scale datasets; "
                         "different RNG stream than the default per-row writer)")
    args = ap.parse_args()
    if args.bulk:
        paths, _ = generate_shards_bulk(
            args.out_prefix, args.shards, args.rows, args.fields,
            args.ids_per_field, args.seed, zipf_alpha=args.zipf_alpha,
        )
    else:
        paths = generate_shards(
            args.out_prefix, args.shards, args.rows, args.fields,
            args.ids_per_field, args.seed, zipf_alpha=args.zipf_alpha,
        )
    print("\n".join(paths))


if __name__ == "__main__":
    main()
