"""Sparse batch schema: fixed-capacity padded COO.

The reference's in-memory batch is ragged
(`Data{fea_matrix: vector<vector<kv>>, label: vector<int>}`,
`/root/reference/src/io/io.h:61-65`). XLA wants static shapes, so a
batch here is a dense ``[batch, max_nnz]`` block padded with masked
zeros (SURVEY.md §7 hard part a):

- ``slots``  int32 ``[B, F]`` — table slot per feature occurrence
  (hashed feature id folded into ``2**log2_slots``; pad = 0, masked).
- ``fields`` int32 ``[B, F]`` — libffm field-group id (``kv.fgid``,
  `/root/reference/src/io/io.h:18-22`); needed by MVM, pad = 0.
- ``mask``   float32 ``[B, F]`` — 1.0 for real feature occurrences.
- ``labels`` float32 ``[B]`` — {0.0, 1.0}.
- ``row_mask`` float32 ``[B]`` — 1.0 for real rows (the reference
  *drops* remainder rows when a block doesn't divide by thread count,
  `lr_worker.cc:190-194`; we pad-and-mask instead).

Feature *values* are intentionally absent: the reference parser never
reads the value token (`load_data_from_disk.cc:150-153` breaks after the
feature id) and no model consumes `kv.val`, so features are binary.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np


class SparseBatch(NamedTuple):
    slots: np.ndarray  # int32 [B, F]
    fields: np.ndarray  # int32 [B, F]
    mask: np.ndarray  # float32 [B, F]
    labels: np.ndarray  # float32 [B]
    row_mask: np.ndarray  # float32 [B]

    @property
    def batch_size(self) -> int:
        return self.slots.shape[0]

    @property
    def max_nnz(self) -> int:
        return self.slots.shape[1]

    @property
    def num_rows(self) -> int:
        return int(self.row_mask.sum())


def make_batch(
    rows_fields: list[np.ndarray],
    rows_slots: list[np.ndarray],
    labels: list[float],
    batch_size: int,
    max_nnz: int,
) -> SparseBatch:
    """Pack ragged rows into one padded SparseBatch.

    Rows longer than ``max_nnz`` are truncated (with a deterministic
    prefix, matching no reference behavior — the reference has no cap —
    so pick ``max_nnz`` ≥ the dataset's true max row length; the parser
    reports truncation via pipeline stats).
    """
    n = len(labels)
    assert n <= batch_size
    slots = np.zeros((batch_size, max_nnz), dtype=np.int32)
    fields = np.zeros((batch_size, max_nnz), dtype=np.int32)
    mask = np.zeros((batch_size, max_nnz), dtype=np.float32)
    lab = np.zeros((batch_size,), dtype=np.float32)
    row_mask = np.zeros((batch_size,), dtype=np.float32)
    for i in range(n):
        k = min(len(rows_slots[i]), max_nnz)
        slots[i, :k] = rows_slots[i][:k]
        fields[i, :k] = rows_fields[i][:k]
        mask[i, :k] = 1.0
        lab[i] = labels[i]
        row_mask[i] = 1.0
    return SparseBatch(slots=slots, fields=fields, mask=mask, labels=lab, row_mask=row_mask)
