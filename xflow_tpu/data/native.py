"""ctypes bindings for the C++ data plane (native/parser.cc).

The shared library is compiled on demand with g++ (no pybind11 in the
image; plain C ABI + ctypes keeps the binding dependency-free) and
cached next to the source keyed by a source hash. `batch_iterator`
prefers this path automatically (DataConfig.use_native_parser) and
falls back to the pure-Python parser if the toolchain is missing.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Iterator

import numpy as np

from xflow_tpu.config import DataConfig
from xflow_tpu.data.schema import SparseBatch

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native", "parser.cc")
_LIB = None


def _build_lib() -> str:
    # XFLOW_NATIVE_SANITIZE=thread|address,undefined|… rebuilds the data
    # plane under the named sanitizer(s) — the MT parser is the one
    # concurrent C++ component (SURVEY.md §5 "race detection" plan;
    # tests/test_native_sanitizers.py runs the MT parity check under
    # TSan and ASan+UBSan). The flag value joins the cache key so
    # sanitized and plain builds never collide; the host process must
    # LD_PRELOAD the matching runtime before loading a sanitized .so.
    sanitize = os.environ.get("XFLOW_NATIVE_SANITIZE", "")
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(
            f.read() + sanitize.encode()
        ).hexdigest()[:16]
    cache_dir = os.environ.get(
        "XFLOW_NATIVE_CACHE",
        os.path.join(os.path.dirname(_SRC), "_build"),
    )
    os.makedirs(cache_dir, exist_ok=True)
    so_path = os.path.join(cache_dir, f"libxfparser_{digest}.so")
    if os.path.exists(so_path):
        return so_path
    tmp = tempfile.mktemp(suffix=".so", dir=cache_dir)
    cmd = ["g++", "-O3", "-std=c++17", "-pthread", "-shared", "-fPIC", "-o", tmp, _SRC]
    if sanitize:
        cmd[1:1] = [f"-fsanitize={sanitize}", "-g", "-fno-omit-frame-pointer"]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, so_path)  # atomic: concurrent builders race benignly
    return so_path


def get_lib() -> ctypes.CDLL:
    global _LIB
    if _LIB is None:
        lib = ctypes.CDLL(_build_lib())
        lib.xf_hash64.restype = ctypes.c_uint64
        lib.xf_hash64.argtypes = [ctypes.c_char_p, ctypes.c_long, ctypes.c_uint64]
        lib.xf_slot.restype = ctypes.c_uint64
        lib.xf_slot.argtypes = [ctypes.c_uint64, ctypes.c_int]
        lib.xf_parser_open.restype = ctypes.c_void_p
        lib.xf_parser_open.argtypes = [ctypes.c_char_p, ctypes.c_long]
        lib.xf_parser_next_batch.restype = ctypes.c_long
        lib.xf_parser_next_batch.argtypes = [
            ctypes.c_void_p,
            ctypes.c_long,
            ctypes.c_long,
            ctypes.c_int,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
        ]
        lib.xf_parser_truncated.restype = ctypes.c_long
        lib.xf_parser_truncated.argtypes = [ctypes.c_void_p]
        lib.xf_parser_close.restype = None
        lib.xf_parser_close.argtypes = [ctypes.c_void_p]
        lib.xf_count_rows.restype = ctypes.c_long
        lib.xf_count_rows.argtypes = [ctypes.c_char_p, ctypes.c_long]
        lib.xf_mt_open.restype = ctypes.c_void_p
        lib.xf_mt_open.argtypes = [
            ctypes.c_char_p,
            ctypes.c_long,
            ctypes.c_int,
            ctypes.c_long,
            ctypes.c_int,
            ctypes.c_uint64,
        ]
        lib.xf_mt_next_batch.restype = ctypes.c_long
        lib.xf_mt_next_batch.argtypes = [
            ctypes.c_void_p,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
        ]
        lib.xf_mt_truncated.restype = ctypes.c_long
        lib.xf_mt_truncated.argtypes = [ctypes.c_void_p]
        lib.xf_mt_close.restype = None
        lib.xf_mt_close.argtypes = [ctypes.c_void_p]
        lib.xf_plan_sorted.restype = ctypes.c_long
        lib.xf_plan_sorted.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_long,
            ctypes.c_long,
            ctypes.c_long,
            ctypes.c_long,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.xf_plan_sorted_wire.restype = ctypes.c_long
        lib.xf_plan_sorted_wire.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_long,
            ctypes.c_long,
            ctypes.c_long,
            ctypes.c_long,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint16),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int32),
        ]
        _LIB = lib
    return _LIB


def _plan_sorted_call(slots, mask, fields, num_slots: int, window: int,
                      np_len: int, wire: bool):
    """Shared marshalling for the two C plan emitters — ONE place for
    the size validation and pointer plumbing; only the output dtypes
    and entry point differ."""
    lib = get_lib()
    slots = np.ascontiguousarray(slots, np.int32)
    mask_flat = np.ascontiguousarray(mask, np.float32).ravel()
    B, F = slots.shape
    n = B * F
    # C reads n entries from each buffer: a size mismatch that would be a
    # loud IndexError in the numpy path must not become an OOB heap read
    if mask_flat.size != n:
        raise ValueError(f"mask size {mask_flat.size} != slots size {n}")
    if fields is not None and np.asarray(fields).size != n:
        raise ValueError(f"fields size {np.asarray(fields).size} != slots size {n}")
    row_dt, mask_dt, f_dt = (
        (np.uint16, np.uint8, np.uint8) if wire else (np.int32, np.float32, np.int32)
    )
    out_slots = np.empty(np_len, np.int32)
    out_row = np.empty(np_len, row_dt)
    out_mask = np.empty(np_len, mask_dt)
    out_fields = np.empty(np_len, f_dt) if fields is not None else None
    win_off = np.empty(num_slots // window + 1, np.int32)
    i32p = ctypes.POINTER(ctypes.c_int32)
    f32p = ctypes.POINTER(ctypes.c_float)
    rowp = ctypes.POINTER(ctypes.c_uint16 if wire else ctypes.c_int32)
    maskp = ctypes.POINTER(ctypes.c_uint8 if wire else ctypes.c_float)
    fp = ctypes.POINTER(ctypes.c_uint8 if wire else ctypes.c_int32)
    fields_c = (
        np.ascontiguousarray(fields, np.int32).ctypes.data_as(i32p)
        if fields is not None
        else None
    )
    fn = lib.xf_plan_sorted_wire if wire else lib.xf_plan_sorted
    rc = fn(
        slots.ctypes.data_as(i32p),
        mask_flat.ctypes.data_as(f32p),
        fields_c,
        n,
        F,
        num_slots,
        window,
        np_len,
        out_slots.ctypes.data_as(i32p),
        out_row.ctypes.data_as(rowp),
        out_mask.ctypes.data_as(maskp),
        out_fields.ctypes.data_as(fp) if out_fields is not None else None,
        win_off.ctypes.data_as(i32p),
    )
    if rc == -2:
        raise ValueError(
            "xf_plan_sorted_wire: data violated the wire contract "
            "(row ≥ 2^16, field ≥ 2^8, or a non-0/1 mask) — the caller's "
            "config-derived bounds disagree with the batch"
        )
    if rc != 0:
        raise ValueError(
            f"{'xf_plan_sorted_wire' if wire else 'xf_plan_sorted'} "
            f"failed (rc={rc})"
        )
    return out_slots, out_row, out_mask, out_fields, win_off


def native_plan_sorted(slots, mask, fields, num_slots: int, window: int, np_len: int):
    """C radix-sort plan builder (xf_plan_sorted). Returns the plan
    arrays (sorted_slots, sorted_row, sorted_mask, sorted_fields|None,
    win_off) or raises on toolchain/library failure. ctypes releases the
    GIL during the call, so stacked sub-batch plans can run in parallel
    host threads."""
    return _plan_sorted_call(slots, mask, fields, num_slots, window, np_len,
                             wire=False)


def native_plan_sorted_wire(slots, mask, fields, num_slots: int, window: int,
                            np_len: int):
    """C radix-sort plan builder emitting WIRE dtypes directly
    (xf_plan_sorted_wire): uint16 rows, uint8 mask/fields — the
    compact_plan_wire numpy passes never run. Callers must have
    checked the CONFIG bounds (rows ≤ 2^16, fields < 2^8); rc=-2
    means a bound or the 0/1-mask contract was violated by the data —
    a pipeline bug, raised loudly."""
    return _plan_sorted_call(slots, mask, fields, num_slots, window, np_len,
                             wire=True)


def native_count_rows(path: str, block_bytes: int) -> int:
    """Rows the native parser would produce for `path` (same predicate,
    no token parsing); raises on missing file / read error."""
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    n = int(get_lib().xf_count_rows(path.encode(), block_bytes))
    if n < 0:
        raise OSError(f"xf_count_rows failed for {path}")
    return n


def native_hash(token: bytes, salt: int = 0) -> int:
    return int(get_lib().xf_hash64(token, len(token), salt))


def native_slot(key: int, log2_slots: int) -> int:
    return int(get_lib().xf_slot(key, log2_slots))


class _NativeBatchStream:
    """Eagerly-opened batch stream (construction fails fast on a missing
    file/toolchain, so batch_iterator's guarded construction works).

    `threads=1` uses the sequential block-buffered parser; any other value
    opens the multi-threaded parser pool (N workers over newline-aligned
    file blocks, reassembled in file order — byte-identical output, the
    hashing/strtod cost parallelized; reference analog: the worker thread
    pool `thread_pool.h:70-86`). 0 = auto (hardware concurrency)."""

    def __init__(self, path: str, cfg: DataConfig, batch_size: int):
        self.lib = get_lib()
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        resolved = cfg.parser_threads if cfg.parser_threads > 0 else (os.cpu_count() or 1)
        self.mt = resolved > 1  # 1 available core: sequential parser wins
        if self.mt:
            self.handle = self.lib.xf_mt_open(
                path.encode(), cfg.block_bytes, cfg.parser_threads,
                cfg.max_nnz, cfg.log2_slots, cfg.hash_salt,
            )
        else:
            self.handle = self.lib.xf_parser_open(path.encode(), cfg.block_bytes)
        if not self.handle:
            raise OSError(f"native parser open failed for {path}")
        self.cfg = cfg
        self.batch_size = batch_size
        self.closed = False
        self.started = False
        self.truncated = 0

    def __iter__(self) -> Iterator[SparseBatch]:
        # single-shot stream: re-iterating would call into the freed C handle
        if self.started or self.closed:
            raise RuntimeError("native batch stream is single-use; re-open the file")
        self.started = True
        return self._generate()

    def _generate(self) -> Iterator[SparseBatch]:
        cfg, B, F = self.cfg, self.batch_size, self.cfg.max_nnz
        i32p = ctypes.POINTER(ctypes.c_int32)
        f32p = ctypes.POINTER(ctypes.c_float)
        try:
            while True:
                slots = np.zeros((B, F), np.int32)
                fields = np.zeros((B, F), np.int32)
                mask = np.zeros((B, F), np.float32)
                labels = np.zeros((B,), np.float32)
                row_mask = np.zeros((B,), np.float32)
                if self.mt:
                    n = self.lib.xf_mt_next_batch(
                        self.handle,
                        B,
                        slots.ctypes.data_as(i32p),
                        fields.ctypes.data_as(i32p),
                        mask.ctypes.data_as(f32p),
                        labels.ctypes.data_as(f32p),
                        row_mask.ctypes.data_as(f32p),
                    )
                else:
                    n = self.lib.xf_parser_next_batch(
                        self.handle,
                        B,
                        F,
                        cfg.log2_slots,
                        cfg.hash_salt,
                        slots.ctypes.data_as(i32p),
                        fields.ctypes.data_as(i32p),
                        mask.ctypes.data_as(f32p),
                        labels.ctypes.data_as(f32p),
                        row_mask.ctypes.data_as(f32p),
                    )
                if n < 0:
                    raise OSError(f"native parser I/O error reading batches (ferror)")
                if n == 0:
                    return
                if n < B and cfg.drop_remainder:
                    return
                yield SparseBatch(slots, fields, mask, labels, row_mask)
                if n < B:
                    return
        finally:
            self.close()

    def close(self) -> None:
        if not self.closed:
            if self.mt:
                self.truncated = int(self.lib.xf_mt_truncated(self.handle))
                self.lib.xf_mt_close(self.handle)
            else:
                self.truncated = int(self.lib.xf_parser_truncated(self.handle))
                self.lib.xf_parser_close(self.handle)
            self.closed = True
            if self.truncated:
                import sys

                print(
                    f"xflow: warning: {self.truncated} feature occurrence(s) "
                    f"truncated by data.max_nnz={self.cfg.max_nnz}",
                    file=sys.stderr,
                )


def native_batch_iterator(path: str, cfg: DataConfig, batch_size: int):
    return iter(_NativeBatchStream(path, cfg, batch_size))
