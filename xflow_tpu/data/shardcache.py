"""Packed shard cache: device-rate binary input (docs/DATA.md).

The measured ~28x host gap (BENCH_SCALE.json: 62.5k ex/s e2e vs 1.75M
device-bound; the per-stage decomposition in BENCH_PIPELINE.json) is
all repeated host work: every epoch re-reads libffm text, re-tokenizes
every line, and re-hashes every feature id on one host core. The
reference never pays this twice either — its workers ship pre-hashed
(feature_id -> value) pairs over the wire, never raw text (PAPER.md
L3/L4). This module does that work ONCE, at convert time:

    text shard <prefix>-NNNNN   --write-->   <prefix>-NNNNN.xfc

and makes train-time batch assembly an offset computation over
`np.memmap` views — zero copies, zero parsing, zero hashing on the hot
path. The cached rows are byte-identical to what the parser would have
produced (same truncation/padding as `make_batch`, bad feature-less
rows preserved), so cache-path batches are bitwise-equal to text-path
batches (pinned by tests/test_shardcache.py) and everything downstream
— bad-record monitoring, `assign_shards`, `skip_batches` resume,
quarantine — works unchanged.

On-disk format v1 (all integers little-endian; see docs/DATA.md):

    [0:4]   magic  b"XFSC"
    [4:8]   u32 version (1)
    [64:]   sections, each 64-byte aligned, row-major:
              slots  int32   [rows, max_nnz]
              fields int32   [rows, max_nnz]
              mask   float32 [rows, max_nnz]
              labels float32 [rows]
    [tail]  footer JSON (sorted keys), then u32 footer length, then
            magic b"XFSC" — the last 8 bytes locate the footer, so the
            writer can STREAM sections in one pass (constant memory)
            and still record their crc32 digests.

The footer carries the hash parameters the slots were folded with
(`log2_slots`, `hash_salt`, `max_nnz`) — a cache is only valid for the
config that wrote it — plus the source shard's byte size (staleness
check) and one crc32 digest per section (the PR-5 checkpoint-integrity
convention, train/checkpoint.py array_digest). A digest mismatch at
open time raises `ShardCacheDigestError`; the pipeline quarantines the
shard and falls back to the text path — never a crash
(data/pipeline.py, docs/DATA.md failure matrix).

Nothing here stamps a timestamp or any other run-local value into the
file: converting the same input twice yields byte-identical caches,
which is what makes the digests meaningful (tests/test_shardcache.py
pins byte-stability; tests/test_criteo_convert.py pins it for the text
converter upstream).
"""

from __future__ import annotations

import dataclasses
import json
import os
import struct
import sys
import zlib
from typing import Iterator, Optional

import numpy as np

from xflow_tpu.config import DataConfig
from xflow_tpu.data.schema import SparseBatch

MAGIC = b"XFSC"
VERSION = 1
ALIGN = 64
CACHE_SUFFIX = ".xfc"
# section order is part of the format: the writer streams them at
# fixed offsets computed from the row count alone
SECTIONS = ("slots", "fields", "mask", "labels")
_DTYPES = {
    "slots": np.int32,
    "fields": np.int32,
    "mask": np.float32,
    "labels": np.float32,
}
_CRC_CHUNK = 4 << 20  # digest verification reads 4 MiB at a time


class ShardCacheError(RuntimeError):
    """A cache file that cannot be used (truncated, bad magic/version,
    unreadable footer). The pipeline treats this like a digest
    mismatch: quarantine + text fallback, never a crash."""


class ShardCacheDigestError(ShardCacheError):
    """A section's bytes no longer match the crc32 digest the footer
    recorded at write time — silent corruption (bit rot, torn copy).
    Carries `section` so the quarantine record can name it."""

    def __init__(self, msg: str, section: str = "?"):
        super().__init__(msg)
        self.section = section


class ShardCacheStale(ShardCacheError):
    """The cache does not match the current config or source file
    (different hash parameters, the text shard changed size) — not
    corruption, but not usable either. `reason` says why."""


def cache_path_for(text_path: str, cache_dir: str = "") -> str:
    """Where `text_path`'s cache lives: an `.xfc` sibling by default,
    or `<cache_dir>/<basename>-<pathhash>.xfc` when `data.cache_dir`
    is set (a fast local disk for caches of shards on slow shared
    storage). The short hash of the ABSOLUTE source path keys caches
    from different datasets apart — every converter emits
    `<prefix>-NNNNN` names, so a shared cache dir keyed on basename
    alone would let /data/a/train-00000 and /data/b/train-00000
    clobber (or, at equal byte sizes, silently serve) each other. The
    cost: the same dataset reached via a different mount/symlink path
    rebuilds rather than reuses — the safe direction."""
    if cache_dir:
        import hashlib

        tag = hashlib.sha1(
            os.path.abspath(text_path).encode("utf-8")
        ).hexdigest()[:10]
        base = os.path.basename(text_path)
        return os.path.join(cache_dir, f"{base}-{tag}{CACHE_SUFFIX}")
    return text_path + CACHE_SUFFIX


def _align(n: int) -> int:
    return (n + ALIGN - 1) // ALIGN * ALIGN


def _layout(rows: int, max_nnz: int) -> tuple[dict, int]:
    """{section: (offset, shape, nbytes)}, data end — from the row
    count alone, which is what lets the writer stream."""
    out = {}
    off = ALIGN  # sections start past the 8-byte prologue, aligned
    for name in SECTIONS:
        shape = (rows,) if name == "labels" else (rows, max_nnz)
        nbytes = int(np.prod(shape, dtype=np.int64)) * 4
        out[name] = (off, shape, nbytes)
        off = _align(off + nbytes)
    return out, off


def _crc(running: int, arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes(), running)


def write_shard_cache(
    text_path: str, cfg: DataConfig, cache_path: str = ""
) -> dict:
    """Parse one libffm text shard ONCE and write its packed cache;
    returns {'rows': n, 'bytes': total}.

    Streaming and constant-memory: the row count is taken up front with
    the parser-matched counter (the same predicate `count_batches`
    coordinates multi-process steps with), section offsets follow from
    it, and parsed chunks are written straight into an `np.memmap` over
    the target region while the per-section crc32 digests accumulate.
    The write is atomic (temp + rename): a crashed build never leaves a
    file `open_shard_cache` would accept.

    Parsing goes through the exact `_raw_batch_iterator` path the
    trainer uses (native parser when built, Python fallback — both emit
    identical batches, pinned by the parser-parity suite) with the
    cache branch forced off, so the stored rows ARE the rows a text-path
    run would have trained on, padding and truncation included.
    """
    from xflow_tpu.data.pipeline import _raw_batch_iterator, count_batches

    cache_path = cache_path or cache_path_for(text_path, cfg.cache_dir)
    # force the text path (no cache recursion), keep every row (the
    # read side applies drop_remainder at batch-slicing time), and
    # parse in writer-sized chunks regardless of the train batch size
    wcfg = dataclasses.replace(cfg, cache="off", drop_remainder=False)
    chunk = 8192
    rows = count_batches(text_path, wcfg, batch_size=1)
    layout, data_end = _layout(rows, cfg.max_nnz)
    parent = os.path.dirname(cache_path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = "%s.tmp.%d" % (cache_path, os.getpid())
    crcs = {name: 0 for name in SECTIONS}
    pos = 0
    try:
        with open(tmp, "wb") as f:
            f.write(MAGIC)
            f.write(struct.pack("<I", VERSION))
            f.truncate(data_end)
        mms = {
            name: np.memmap(
                tmp, dtype=_DTYPES[name], mode="r+",
                offset=layout[name][0], shape=layout[name][1],
            )
            for name in SECTIONS
        } if rows else {}
        for batch in _raw_batch_iterator(text_path, wcfg, batch_size=chunk):
            n = int(np.asarray(batch.row_mask).sum())
            if n == 0:
                continue
            if pos + n > rows:
                raise ShardCacheError(
                    f"{text_path!r}: parser produced more rows than the "
                    f"counter predicted ({pos + n} > {rows}) — the file "
                    "changed mid-build, or the counter/parser predicates "
                    "disagree (bug)"
                )
            for name in SECTIONS:
                arr = np.asarray(getattr(batch, name))[:n]
                mms[name][pos : pos + n] = arr
                crcs[name] = _crc(crcs[name], arr)
            pos += n
        if pos != rows:
            raise ShardCacheError(
                f"{text_path!r}: counted {rows} row(s) but the parser "
                f"produced {pos} — the file changed mid-build, or the "
                "counter/parser predicates disagree (bug)"
            )
        for mm in mms.values():
            mm.flush()
        del mms
        footer = {
            "version": VERSION,
            "rows": rows,
            "max_nnz": int(cfg.max_nnz),
            "log2_slots": int(cfg.log2_slots),
            "hash_salt": int(cfg.hash_salt),
            "source": os.path.basename(text_path),
            "source_bytes": os.path.getsize(text_path),
            "sections": [
                {
                    "name": name,
                    "dtype": np.dtype(_DTYPES[name]).name,
                    "shape": list(layout[name][1]),
                    "offset": layout[name][0],
                    "nbytes": layout[name][2],
                    "crc32": "crc32:%08x" % (crcs[name] & 0xFFFFFFFF),
                }
                for name in SECTIONS
            ],
        }
        blob = json.dumps(footer, sort_keys=True, separators=(",", ":")).encode()
        with open(tmp, "r+b") as f:
            f.seek(data_end)
            f.write(blob)
            f.write(struct.pack("<I", len(blob)))
            f.write(MAGIC)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, cache_path)  # atomic commit
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return {"rows": rows, "bytes": os.path.getsize(cache_path)}


def build_cache(prefix: str, cfg: DataConfig, force: bool = False) -> dict:
    """Cache every existing `<prefix>-NNNNN` text shard. Shards whose
    cache is already fresh for this config are skipped unless `force`
    (incremental rebuilds after appending shards). Returns
    {'shards': n, 'rows': total, 'bytes': total, 'skipped': m}."""
    from xflow_tpu.data.libffm import available_shards

    paths = available_shards(prefix)
    if not paths:
        raise FileNotFoundError(
            f"{prefix!r}: no <prefix>-NNNNN text shards to cache"
        )
    shards = rows = total = skipped = 0
    for p in paths:
        cpath = cache_path_for(p, cfg.cache_dir)
        if not force and os.path.exists(cpath):
            try:
                sc = open_shard_cache(cpath)
                sc.check_compatible(cfg, text_path=p)
                # digests too: an explicit cache build is the operator's
                # REPAIR path for a bit-rotted cache — skipping on
                # staleness alone would report a corrupt file as fresh
                # and leave every train run on the quarantine fallback
                sc.verify()
                skipped += 1
                continue
            except ShardCacheError:
                pass  # stale/corrupt: rebuild
        stats = write_shard_cache(p, cfg, cpath)
        shards += 1
        rows += stats["rows"]
        total += stats["bytes"]
    return {"shards": shards, "rows": rows, "bytes": total, "skipped": skipped}


class ShardCache:
    """An open cache file: parsed footer + lazily-created memmaps.

    `verify()` streams every section through crc32 once (GB/s — noise
    against the parse it replaces) and raises `ShardCacheDigestError`
    on the first mismatch; `iter_batches` then yields zero-copy
    `SparseBatch` views."""

    def __init__(self, path: str, footer: dict):
        self.path = path
        self.footer = footer
        self.rows = int(footer["rows"])
        self.max_nnz = int(footer["max_nnz"])
        self._sections = {s["name"]: s for s in footer["sections"]}
        self._mms: Optional[dict] = None

    # ------------------------------------------------------------ access
    def arrays(self) -> dict:
        if self._mms is None:
            self._mms = {
                name: np.memmap(
                    self.path,
                    dtype=np.dtype(sec["dtype"]),
                    mode="r",
                    offset=int(sec["offset"]),
                    shape=tuple(sec["shape"]),
                )
                for name, sec in self._sections.items()
            }
        return self._mms

    # ------------------------------------------------------- validation
    def check_compatible(
        self, cfg: DataConfig, text_path: str = ""
    ) -> None:
        """Raise ShardCacheStale unless this cache was written with the
        run's hash parameters and still matches its source file. The
        slots were folded at write time — a different `log2_slots` or
        `hash_salt` would need a re-hash, which is exactly the work the
        cache exists to not do; `max_nnz` fixes the padded row shape.
        Staleness: the source's byte size is compared when the text
        shard is still present (the normal layout — the text file is
        both the fallback and the shard-existence marker); a cache
        whose source grew or shrank is stale, not corrupt."""
        f = self.footer
        for key in ("log2_slots", "hash_salt", "max_nnz"):
            want = int(getattr(cfg, key))
            got = int(f.get(key, -1))
            if got != want:
                raise ShardCacheStale(
                    f"{self.path!r}: cache {key}={got} != config "
                    f"{key}={want}; rebuild with "
                    "`python -m xflow_tpu.tools.criteo_convert cache ...`"
                )
        if text_path and os.path.exists(text_path):
            size = os.path.getsize(text_path)
            if size != int(f.get("source_bytes", -1)):
                raise ShardCacheStale(
                    f"{self.path!r}: source {text_path!r} is "
                    f"{size} bytes but the cache was built from "
                    f"{f.get('source_bytes')} — the text shard changed; "
                    "rebuild the cache"
                )

    def verify(self) -> None:
        """Stream every section through crc32 against the footer digests
        (the PR-5 checkpoint convention). One full sequential read per
        open — still ~50x cheaper than the parse it replaces."""
        with open(self.path, "rb") as fh:
            for name, sec in self._sections.items():
                fh.seek(int(sec["offset"]))
                left = int(sec["nbytes"])
                running = 0
                while left > 0:
                    block = fh.read(min(left, _CRC_CHUNK))
                    if not block:
                        raise ShardCacheDigestError(
                            f"{self.path!r}: section {name!r} truncated "
                            f"({left} byte(s) missing)",
                            section=name,
                        )
                    running = zlib.crc32(block, running)
                    left -= len(block)
                got = "crc32:%08x" % (running & 0xFFFFFFFF)
                if got != sec.get("crc32"):
                    raise ShardCacheDigestError(
                        f"{self.path!r}: section {name!r} digest mismatch "
                        f"(stored {sec.get('crc32')}, computed {got}) — "
                        "silent corruption; the shard will be quarantined "
                        "and the text path used instead",
                        section=name,
                    )

    # -------------------------------------------------------- iteration
    def iter_batches(
        self,
        batch_size: int,
        drop_remainder: bool = False,
        profiler=None,
    ) -> Iterator[SparseBatch]:
        """Yield padded SparseBatches as zero-copy memmap slices.

        A full batch is five views into the file (an offset computation
        — the whole point); the final partial batch is the one copy,
        padded exactly like `make_batch` pads it (zeros beyond the real
        rows), so cache batches are bitwise-equal to text batches.
        `profiler` attributes slice construction to the `cache_read`
        stage (telemetry.PIPELINE_PRODUCER_STAGES)."""
        mms = self.arrays()
        slots, fields, mask, labels = (
            mms["slots"], mms["fields"], mms["mask"], mms["labels"],
        )
        B = int(batch_size)
        full, rem = self.rows // B, self.rows % B
        ones = np.ones((B,), np.float32)
        if profiler is None:
            for i in range(full):
                s = slice(i * B, (i + 1) * B)
                yield SparseBatch(slots[s], fields[s], mask[s], labels[s], ones)
            if rem and not drop_remainder:
                yield self._tail_batch(B, full * B, rem)
            return
        import time

        pc = time.perf_counter
        for i in range(full):
            t0 = pc()
            s = slice(i * B, (i + 1) * B)
            b = SparseBatch(slots[s], fields[s], mask[s], labels[s], ones)
            profiler.add("cache_read", pc() - t0)
            profiler.count_batch(B)
            yield b
        if rem and not drop_remainder:
            t0 = pc()
            b = self._tail_batch(B, full * B, rem)
            profiler.add("cache_read", pc() - t0)
            profiler.count_batch(rem)
            yield b

    def _tail_batch(self, B: int, start: int, n: int) -> SparseBatch:
        mms = self.arrays()
        F = self.max_nnz
        slots = np.zeros((B, F), np.int32)
        fields = np.zeros((B, F), np.int32)
        mask = np.zeros((B, F), np.float32)
        labels = np.zeros((B,), np.float32)
        row_mask = np.zeros((B,), np.float32)
        end = start + n
        slots[:n] = mms["slots"][start:end]
        fields[:n] = mms["fields"][start:end]
        mask[:n] = mms["mask"][start:end]
        labels[:n] = mms["labels"][start:end]
        row_mask[:n] = 1.0
        return SparseBatch(slots, fields, mask, labels, row_mask)


def open_shard_cache(path: str) -> ShardCache:
    """Parse prologue + footer; raise ShardCacheError on anything that
    is not a committed v1 cache file."""
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as fh:
            head = fh.read(8)
            if len(head) < 8 or head[:4] != MAGIC:
                raise ShardCacheError(f"{path!r}: not a shard cache (bad magic)")
            (version,) = struct.unpack("<I", head[4:8])
            if version != VERSION:
                raise ShardCacheError(
                    f"{path!r}: cache format v{version} (this build reads "
                    f"v{VERSION}); rebuild the cache"
                )
            if size < 16:
                raise ShardCacheError(f"{path!r}: truncated cache file")
            fh.seek(size - 8)
            tail = fh.read(8)
            (flen,) = struct.unpack("<I", tail[:4])
            if tail[4:8] != MAGIC or flen <= 0 or size - 8 - flen < 8:
                raise ShardCacheError(
                    f"{path!r}: missing/garbled footer (interrupted write?)"
                )
            fh.seek(size - 8 - flen)
            footer = json.loads(fh.read(flen).decode("utf-8"))
    except ShardCacheError:
        raise
    except (OSError, ValueError, struct.error, UnicodeDecodeError) as e:
        raise ShardCacheError(f"{path!r}: unreadable cache: {e}") from e
    if not isinstance(footer, dict) or not isinstance(footer.get("sections"), list):
        raise ShardCacheError(f"{path!r}: malformed footer")
    names = {s.get("name") for s in footer["sections"] if isinstance(s, dict)}
    if names != set(SECTIONS):
        raise ShardCacheError(
            f"{path!r}: footer sections {sorted(names)} != {sorted(SECTIONS)}"
        )
    # geometry cross-check: the crc32 digests cover the SECTION bytes,
    # not the footer itself — a flipped digit in a shape/offset/rows
    # field would otherwise survive open+verify and blow up later as a
    # bare ValueError inside the prefetch thread's np.memmap, outside
    # the quarantine net (the 'corruption degrades, never crashes'
    # contract, docs/DATA.md failure matrix)
    try:
        rows = int(footer.get("rows", -1))
        nnz = int(footer.get("max_nnz", -1))
    except (TypeError, ValueError) as e:
        raise ShardCacheError(f"{path!r}: malformed footer: {e}") from e
    if rows < 0 or nnz <= 0:
        raise ShardCacheError(
            f"{path!r}: footer rows={rows} max_nnz={nnz} out of range"
        )
    for sec in footer["sections"]:
        try:
            name = sec["name"]
            shape = tuple(int(x) for x in sec["shape"])
            offset, nbytes = int(sec["offset"]), int(sec["nbytes"])
            itemsize = np.dtype(sec["dtype"]).itemsize
        except (KeyError, TypeError, ValueError) as e:
            raise ShardCacheError(f"{path!r}: malformed footer: {e}") from e
        want_shape = (rows,) if name == "labels" else (rows, nnz)
        if shape != want_shape:
            raise ShardCacheError(
                f"{path!r}: section {name!r} shape {shape} != {want_shape} "
                "(footer corrupted?)"
            )
        if nbytes != int(np.prod(shape, dtype=np.int64)) * itemsize:
            raise ShardCacheError(
                f"{path!r}: section {name!r} nbytes {nbytes} inconsistent "
                "with its shape (footer corrupted?)"
            )
        if offset < ALIGN or offset + nbytes > size:
            raise ShardCacheError(
                f"{path!r}: section {name!r} [{offset}, {offset + nbytes}) "
                f"falls outside the {size}-byte file (footer corrupted?)"
            )
    return ShardCache(path, footer)


def resolve_cache(path: str, cfg: DataConfig) -> Optional[ShardCache]:
    """The pipeline's auto-detect seam (data.cache, docs/DATA.md):
    the VERIFIED cache for text shard `path`, or None to take the text
    path. Raising semantics are the policy matrix:

    - `off`: never looked at (the pipeline does not call this).
    - `auto`: a missing cache is simply the text path; a stale one
      (config/source mismatch) warns once per file and falls back; a
      CORRUPT one (bad digest / unreadable) raises
      ShardCacheDigestError / ShardCacheError for the pipeline to
      quarantine and fall back — the caller owns the quarantine sink.
    - `on`: the operator asserted cached input — a missing or stale
      cache raises FileNotFoundError/ShardCacheStale loudly at open.
      Corruption still only raises the digest error: the pipeline's
      fallback keeps even a forced-cache run training (docs/DATA.md
      failure matrix — integrity failures degrade, never crash).
    """
    cpath = cache_path_for(path, cfg.cache_dir)
    if not os.path.exists(cpath):
        if cfg.cache == "on":
            raise FileNotFoundError(
                f"data.cache=on but {cpath!r} does not exist; build it: "
                f"python -m xflow_tpu.tools.criteo_convert cache <prefix> "
                f"--log2-slots {cfg.log2_slots} --max-nnz {cfg.max_nnz}"
            )
        return None
    sc = open_shard_cache(cpath)  # ShardCacheError -> caller quarantines
    try:
        sc.check_compatible(cfg, text_path=path)
    except ShardCacheStale:
        if cfg.cache == "on":
            raise
        print(
            f"xflow: warning: ignoring stale shard cache {cpath!r} "
            "(config or source changed; rebuild with criteo_convert cache)",
            file=sys.stderr,
        )
        return None
    sc.verify()  # ShardCacheDigestError -> caller quarantines + falls back
    return sc
