"""Batching pipeline: libffm examples → padded SparseBatch stream.

The reference couples its minibatch size to the IO block size (however
many lines fit in a 2 MiB fread block, `lr_worker.cc:184-188`) and then
silently drops remainder rows when the block doesn't divide by the
thread count (`lr_worker.cc:190-194`). Here batches are a fixed
``batch_size`` rows (static XLA shapes) and the final partial batch is
padded and masked rather than dropped (configurable via
``drop_remainder`` for strict reference emulation).

`prefetch_to_device` overlaps host parsing with device compute — the
TPU analog of the reference's double-duty IO/compute threads.
"""

from __future__ import annotations

import queue
import subprocess
import threading
from typing import Iterable, Iterator, Optional

import numpy as np

from xflow_tpu.config import DataConfig
from xflow_tpu.data.schema import SparseBatch, make_batch
from xflow_tpu.data.libffm import iter_examples


def examples_to_batches(
    examples: Iterable[tuple[float, np.ndarray, np.ndarray]],
    batch_size: int,
    max_nnz: int,
    drop_remainder: bool = False,
) -> Iterator[SparseBatch]:
    labels: list[float] = []
    fields: list[np.ndarray] = []
    slots: list[np.ndarray] = []
    for label, f, s in examples:
        labels.append(label)
        fields.append(f)
        slots.append(s)
        if len(labels) == batch_size:
            yield make_batch(fields, slots, labels, batch_size, max_nnz)
            labels, fields, slots = [], [], []
    if labels and not drop_remainder:
        yield make_batch(fields, slots, labels, batch_size, max_nnz)


def batch_iterator(
    path: str,
    cfg: DataConfig,
    batch_size: Optional[int] = None,
) -> Iterator[SparseBatch]:
    """Stream padded batches from a libffm file, preferring the native parser."""
    bs = batch_size or cfg.batch_size
    if cfg.use_native_parser:
        native_iter = None
        try:
            # only import/construction is guarded: a failure mid-iteration
            # must surface, not silently restart the file with the Python
            # parser (which would duplicate already-yielded batches)
            from xflow_tpu.data.native import native_batch_iterator

            native_iter = native_batch_iterator(path, cfg, bs)
        except FileNotFoundError:
            raise  # a missing input is the user's error, not a fallback case
        except (ImportError, OSError, RuntimeError, subprocess.SubprocessError):
            native_iter = None
        if native_iter is not None:
            yield from native_iter
            return
    yield from examples_to_batches(
        iter_examples(path, cfg.log2_slots, cfg.hash_salt),
        bs,
        cfg.max_nnz,
        cfg.drop_remainder,
    )


def count_batches(path: str, cfg: DataConfig, batch_size: Optional[int] = None) -> int:
    """Number of batches `batch_iterator` will yield for `path`.

    Uses the row counter matching the parser that will actually run
    (native predicate for the native path, parse_line predicate for the
    Python path) so multi-process step coordination can be computed with
    ONE collective per epoch instead of one allgather per step.
    """
    bs = batch_size or cfg.batch_size
    rows = None
    if cfg.use_native_parser:
        try:
            from xflow_tpu.data.native import native_count_rows

            rows = native_count_rows(path, cfg.block_bytes)
        except FileNotFoundError:
            raise
        except (ImportError, OSError, RuntimeError, subprocess.SubprocessError):
            rows = None  # toolchain missing: the Python parser will run
    if rows is None:
        from xflow_tpu.data.libffm import count_rows

        rows = count_rows(path)
    return rows // bs if cfg.drop_remainder else -(-rows // bs)


def prefetch(iterator: Iterator[SparseBatch], depth: int = 2) -> Iterator[SparseBatch]:
    """Run the parse/batch pipeline in a background thread with a bounded queue."""
    q: queue.Queue = queue.Queue(maxsize=depth)
    _END = object()

    def worker() -> None:
        try:
            for item in iterator:
                q.put(item)
            q.put(_END)
        except BaseException as e:  # re-raised in the consumer
            q.put(e)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    while True:
        item = q.get()
        if item is _END:
            break
        if isinstance(item, BaseException):
            raise item
        yield item
