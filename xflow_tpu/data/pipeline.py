"""Batching pipeline: libffm examples → padded SparseBatch stream.

The reference couples its minibatch size to the IO block size (however
many lines fit in a 2 MiB fread block, `lr_worker.cc:184-188`) and then
silently drops remainder rows when the block doesn't divide by the
thread count (`lr_worker.cc:190-194`). Here batches are a fixed
``batch_size`` rows (static XLA shapes) and the final partial batch is
padded and masked rather than dropped (configurable via
``drop_remainder`` for strict reference emulation).

`prefetch_to_device` overlaps host parsing with device compute — the
TPU analog of the reference's double-duty IO/compute threads.
"""

from __future__ import annotations

import dataclasses
import os
import queue
import subprocess
import threading
import time
from typing import Iterable, Iterator, Optional

import sys

import numpy as np

from xflow_tpu.config import DataConfig
from xflow_tpu.data.schema import SparseBatch, make_batch
from xflow_tpu.data.libffm import QuarantineWriter, iter_examples
from xflow_tpu.jsonl import JsonlAppender


class BadRecordError(RuntimeError):
    """A file pass produced more feature-less rows than data.max_bad_rows
    allows — the input is likely garbage (wrong format, truncated upload,
    corrupted shard) and training on it would silently learn nothing from
    those rows. Raised BEFORE the epoch completes (docs/ROBUSTNESS.md)."""


def bad_row_indices(batch: SparseBatch):
    """Rows that are REAL (row_mask on) but parsed to ZERO features.

    Both parsers keep such rows (a labeled line is an example even when
    every feature token is malformed — reference parity,
    `load_data_from_disk.cc:150-153`), so this batch-level predicate is
    parser-agnostic by construction: the Python and native paths count
    bad rows identically because the count is taken from the batches
    they both emit, not from their internal line handling."""
    rm = np.asarray(batch.row_mask) > 0
    has_feature = np.asarray(batch.mask).max(axis=1) > 0 if batch.mask.size else rm
    return np.nonzero(rm & ~has_feature)[0]


def monitor_bad_rows(
    batches: Iterator[SparseBatch],
    cfg: DataConfig,
    path: str,
    enforce: bool = True,
    quarantine: bool = True,
) -> Iterator[SparseBatch]:
    """Count (and optionally quarantine) feature-less rows in a batch
    stream; with `enforce`, raise BadRecordError the moment the budget
    is exceeded.

    Bad rows are NOT dropped — dropping would break the row-counter /
    parser parity the multi-process step coordination depends on
    (`count_batches` counts every labeled line). They are counted,
    appended to data.quarantine_path when set (and `quarantine` is on —
    the trainer quarantines only the FIRST training pass over a path, so
    the file holds one record per bad row, not one per epoch), and a
    one-line stderr summary fires at end of stream. `enforce=False`
    (eval/predict passes) still counts and warns but never raises: the
    budget exists to stop garbage from TRAINING in, not to destroy a
    finished model's eval. Multi-process note: the budget check runs on
    each rank's own shard, so an over-budget shard aborts that rank
    loudly (and the job with it) — a garbage shard is a data bug, not a
    condition to coordinate around."""
    from xflow_tpu.telemetry import default_registry

    budget = cfg.max_bad_rows
    qw = QuarantineWriter(cfg.quarantine_path if quarantine else "")
    # pipeline counters (telemetry registry): run totals the trainer
    # snapshots into every metrics-JSONL window record, so batch/row
    # progress and bad-row counts ride the same stream the step
    # decomposition does. Incremented HERE (the prefetch thread) —
    # Counter is lock-protected against the fit loop's snapshot reads.
    reg = default_registry()
    c_batches = reg.counter("data.batches")
    c_rows = reg.counter("data.rows")
    c_bad = reg.counter("data.bad_rows")
    total = 0
    try:
        for bi, batch in enumerate(batches):
            c_batches.inc()
            c_rows.inc(batch.num_rows)
            idx = bad_row_indices(batch)
            if idx.size:
                c_bad.inc(int(idx.size))
                labels = np.asarray(batch.labels)
                for r in idx:
                    qw.write(path, bi, int(r), float(labels[r]))
                total += int(idx.size)
                if enforce and 0 <= budget < total:
                    raise BadRecordError(
                        f"{path!r}: {total} feature-less row(s) exceed "
                        f"data.max_bad_rows={budget} — the shard is likely "
                        "malformed (wrong format / truncation / corruption); "
                        "inspect it (data.quarantine_path records the bad "
                        "rows) or raise the budget"
                    )
            yield batch
        if total:
            print(
                f"xflow: warning: {path}: {total} row(s) parsed to zero "
                f"features (budget data.max_bad_rows={budget})"
                + (f"; quarantined to {cfg.quarantine_path}" if qw.written else ""),
                file=sys.stderr,
            )
    finally:
        qw.close()


def examples_to_batches(
    examples: Iterable[tuple[float, np.ndarray, np.ndarray]],
    batch_size: int,
    max_nnz: int,
    drop_remainder: bool = False,
    profiler=None,
) -> Iterator[SparseBatch]:
    if profiler is not None:
        yield from _profiled_examples_to_batches(
            examples, batch_size, max_nnz, drop_remainder, profiler
        )
        return
    labels: list[float] = []
    fields: list[np.ndarray] = []
    slots: list[np.ndarray] = []
    for label, f, s in examples:
        labels.append(label)
        fields.append(f)
        slots.append(s)
        if len(labels) == batch_size:
            yield make_batch(fields, slots, labels, batch_size, max_nnz)
            labels, fields, slots = [], [], []
    if labels and not drop_remainder:
        yield make_batch(fields, slots, labels, batch_size, max_nnz)


def _profiled_examples_to_batches(
    examples, batch_size: int, max_nnz: int, drop_remainder: bool, profiler
) -> Iterator[SparseBatch]:
    """`examples_to_batches` with the batch-assembly ("batch": the
    per-example row accumulation) and padding ("pad": make_batch's
    padded-array fill) stages attributed (telemetry.PipelineProfiler).
    The pull of each example from the iterator is NOT timed here — that
    wall belongs to the upstream read/parse/hash stages."""
    pc = time.perf_counter
    labels: list[float] = []
    fields: list[np.ndarray] = []
    slots: list[np.ndarray] = []
    acc = 0.0
    for label, f, s in examples:
        t0 = pc()
        labels.append(label)
        fields.append(f)
        slots.append(s)
        acc += pc() - t0
        if len(labels) == batch_size:
            t0 = pc()
            b = make_batch(fields, slots, labels, batch_size, max_nnz)
            profiler.add("pad", pc() - t0)
            profiler.add("batch", acc)
            acc = 0.0
            profiler.count_batch(b.num_rows)
            labels, fields, slots = [], [], []
            yield b
    profiler.add("batch", acc)
    if labels and not drop_remainder:
        t0 = pc()
        b = make_batch(fields, slots, labels, batch_size, max_nnz)
        profiler.add("pad", pc() - t0)
        profiler.count_batch(b.num_rows)
        yield b


def assign_shards(
    prefix: str, rank: int, world: int, num_shards: int = 0
) -> list[tuple[int, str]]:
    """Round-robin shard ownership for a topology-elastic world:
    [(shard index, path)] for rank `rank` of `world`.

    `num_shards` is the shard set in play — for a fresh run it equals
    the world size, so rank k owns exactly shard k and this degrades to
    the legacy one-shard-per-rank contract (`lr_worker.cc:210`)
    byte-for-byte. On an elastic resume the trainer passes the
    checkpoint data_state's `num_shards` (the ORIGINAL record set):

    - shrink (world M < num_shards N): rank k owns shards k, k+M,
      k+2M, ... — the surviving ranks cover the full record set, each
      shard resuming at its own stored offset (`skip_batches`), so no
      record trains twice and none is dropped;
    - grow (world M > num_shards N): ranks N..M-1 own the shard of
      their own index, which joins the record set if its file exists
      (a missing shard is the existing ragged-shard tolerance: the
      rank pads with empty batches).

    Shard files need not exist — the batch counters treat a missing
    path as 0 batches, matching the reference's idle-worker behavior.
    """
    n = max(int(num_shards), int(world), 1)
    from xflow_tpu.data.libffm import shard_path

    return [(s, shard_path(prefix, s)) for s in range(int(rank), n, int(world))]


def skip_batches(
    batches: Iterator[SparseBatch], n: int
) -> Iterator[SparseBatch]:
    """Fast-skip the first `n` batches of a stream — the exact-resume
    seam (docs/ROBUSTNESS.md "Elastic recovery"): a resumed run
    re-parses the already-trained prefix (parsing is the cheap part)
    but the skipped batches bypass EVERYTHING downstream — the
    bad-record monitor (no duplicate quarantine records, no double
    budget counting), sorted-plan building, health bitmaps, and the
    device transfer — so the stream continues at the stored offset
    instead of replaying it. Placed UNDER monitor_bad_rows on purpose;
    the generator form keeps prefetch's close() cascade intact."""
    for i, batch in enumerate(batches):
        if i >= n:
            yield batch


def batch_iterator(
    path: str,
    cfg: DataConfig,
    batch_size: Optional[int] = None,
    enforce_bad_rows: bool = True,
    quarantine: bool = True,
    skip: int = 0,
    profiler=None,
) -> Iterator[SparseBatch]:
    """Stream padded batches from a libffm file, preferring the native
    parser. Every batch passes through the bad-record monitor
    (`monitor_bad_rows`): feature-less rows are counted/quarantined
    identically for both parser paths, and exceeding data.max_bad_rows
    raises before an epoch of garbage trains in (eval passes set
    `enforce_bad_rows=False`: count and warn, never kill a finished
    model's predict pass). `skip` fast-forwards the stream past its
    first `skip` batches (checkpointed data_state resume,
    `skip_batches`) — skipped batches are neither monitored nor
    quarantined; they were already, in the run being resumed.
    `profiler` (telemetry.PipelineProfiler) attributes per-stage wall
    time; None = the exact historical path."""
    raw = _raw_batch_iterator(path, cfg, batch_size, profiler=profiler)
    if skip > 0:
        raw = skip_batches(raw, skip)
    yield from monitor_bad_rows(
        raw, cfg, path,
        enforce=enforce_bad_rows, quarantine=quarantine,
    )


def _cache_batch_iterator(
    path: str, cfg: DataConfig, bs: int, profiler=None
) -> Optional[Iterator[SparseBatch]]:
    """The packed-shard-cache fast path (data.cache, docs/DATA.md):
    the verified cache's zero-copy batch iterator for text shard
    `path`, or None to take the text path.

    Failure routing is the quarantine philosophy (docs/ROBUSTNESS.md):
    a cache that fails its digest check — or cannot even be opened —
    is recorded to data.quarantine_path (source/cache/reason/section,
    the same stamped JSONL stream bad rows land in), counted
    (`data.cache_fallbacks`), logged to stderr, and the shard falls
    back to read/parse/hash — NEVER a crash, even under data.cache=on.
    Only a MISSING or config-stale cache under "on" raises (the
    operator asserted cached input; silently re-parsing text would
    un-measure the very gap they forced the cache for)."""
    if cfg.cache not in ("auto", "on"):
        if cfg.cache != "off":
            raise ValueError(
                f"data.cache={cfg.cache!r}: expected auto|on|off"
            )
        return None
    from xflow_tpu.data.shardcache import (
        ShardCacheDigestError,
        ShardCacheError,
        ShardCacheStale,
        cache_path_for,
        resolve_cache,
    )
    from xflow_tpu.telemetry import default_registry

    reg = default_registry()
    try:
        sc = resolve_cache(path, cfg)
    except ShardCacheStale:
        # only reaches here under cache=on (auto folds staleness into
        # a warn-and-return-None inside resolve_cache): the operator
        # asserted cached input and the cache is stale — loud, never a
        # silent text fallback (it would re-measure the very path the
        # cache was forced to replace). Staleness is not corruption:
        # no quarantine record.
        raise
    except ShardCacheError as e:
        section = getattr(e, "section", "?")
        reg.counter("data.cache_fallbacks").inc()
        qw = JsonlAppender(cfg.quarantine_path)
        qw.append({
            "source": path,
            "cache": cache_path_for(path, cfg.cache_dir),
            "reason": (
                "cache_digest_mismatch"
                if isinstance(e, ShardCacheDigestError)
                else "cache_unreadable"
            ),
            "section": section,
        })
        qw.close()
        print(
            f"xflow: warning: shard cache for {path!r} failed integrity "
            f"({e}); quarantined, falling back to the text path",
            file=sys.stderr,
        )
        return None
    if sc is None:
        return None
    reg.counter("data.cache_shards").inc()
    return sc.iter_batches(bs, cfg.drop_remainder, profiler=profiler)


def _raw_batch_iterator(
    path: str,
    cfg: DataConfig,
    batch_size: Optional[int] = None,
    profiler=None,
) -> Iterator[SparseBatch]:
    bs = batch_size or cfg.batch_size
    cached = _cache_batch_iterator(path, cfg, bs, profiler=profiler)
    if cached is not None:
        yield from cached
        return
    if cfg.use_native_parser:
        native_iter = None
        try:
            # only import/construction is guarded: a failure mid-iteration
            # must surface, not silently restart the file with the Python
            # parser (which would duplicate already-yielded batches)
            from xflow_tpu.data.native import native_batch_iterator

            native_iter = native_batch_iterator(path, cfg, bs)
        except FileNotFoundError:
            raise  # a missing input is the user's error, not a fallback case
        except (ImportError, OSError, RuntimeError, subprocess.SubprocessError):
            native_iter = None
        if native_iter is not None:
            if profiler is None:
                yield from native_iter
                return
            # the C parser does read+parse+hash+assembly+pad inside one
            # next_batch call — attributed as "parse", the honest
            # resolution this path offers (docs/OBSERVABILITY.md)
            pc = time.perf_counter
            while True:
                t0 = pc()
                b = next(native_iter, None)
                profiler.add("parse", pc() - t0)
                if b is None:
                    return
                profiler.count_batch(b.num_rows)
                yield b
    yield from examples_to_batches(
        iter_examples(path, cfg.log2_slots, cfg.hash_salt, profiler=profiler),
        bs,
        cfg.max_nnz,
        cfg.drop_remainder,
        profiler=profiler,
    )


def count_batches(path: str, cfg: DataConfig, batch_size: Optional[int] = None) -> int:
    """Number of batches `batch_iterator` will yield for `path`.

    Uses the row counter matching the parser that will actually run
    (native predicate for the native path, parse_line predicate for the
    Python path) so multi-process step coordination can be computed with
    ONE collective per epoch instead of one allgather per step.
    """
    bs = batch_size or cfg.batch_size
    rows = None
    if cfg.use_native_parser:
        try:
            from xflow_tpu.data.native import native_count_rows

            rows = native_count_rows(path, cfg.block_bytes)
        except FileNotFoundError:
            raise
        except (ImportError, OSError, RuntimeError, subprocess.SubprocessError):
            rows = None  # toolchain missing: the Python parser will run
    if rows is None:
        from xflow_tpu.data.libffm import count_rows

        rows = count_rows(path)
    return rows // bs if cfg.drop_remainder else -(-rows // bs)


def prefetch(
    iterator: Iterator[SparseBatch], depth: int = 2, profiler=None
) -> Iterator[SparseBatch]:
    """Run the parse/batch pipeline in a background thread with a bounded queue.

    Abandonment-safe: when the consumer drops the generator mid-epoch
    (an exception in the fit loop, an early break), its `close()`/GC
    signals the worker through `stop` and drains the queue so a worker
    blocked on a full `q.put` wakes, notices the flag, closes the
    underlying iterator (releasing native parser handles / quarantine
    files promptly), and exits — previously it blocked on `q.put`
    forever, leaking one thread (and pinning its batch buffers) per
    abandoned epoch.

    `profiler` (telemetry.PipelineProfiler) exposes the queue's
    counters: time the WORKER spends blocked in `q.put` is
    `producer_wait` (the consumer/device is the bottleneck —
    cumulative in the `pipeline.producer_blocked_s` gauge), and both
    sides sample `q.qsize()` into the `pipeline.queue_depth` gauge.
    The CONSUMER-side starvation signal (`queue_wait`) is attributed by
    the fit loop as the batch's full data-wait — not here — so the
    consumer stages tile the loop with nothing counted twice. None =
    the exact historical path."""
    q: queue.Queue = queue.Queue(maxsize=depth)
    _END = object()
    stop = threading.Event()

    def worker() -> None:
        pc = time.perf_counter
        try:
            for item in iterator:
                if profiler is None:
                    q.put(item)
                else:
                    t0 = pc()
                    q.put(item)
                    profiler.add("producer_wait", pc() - t0)
                    profiler.observe_queue(q.qsize(), depth)
                if stop.is_set():
                    return
            q.put(_END)
        except BaseException as e:  # re-raised in the consumer
            q.put(e)
        finally:
            if stop.is_set():
                close = getattr(iterator, "close", None)
                if close is not None:
                    close()

    t = threading.Thread(target=worker, daemon=True, name="xflow-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if profiler is not None:
                profiler.observe_queue(q.qsize(), depth)
            if item is _END:
                break
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        # unblock a worker stuck in q.put: after the drain there is at
        # least one free slot, so its pending put completes, it sees the
        # flag, and exits (putting at most one more item, which fits)
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                break
        t.join(timeout=10.0)


# --------------------------------------------------------------- streaming
@dataclasses.dataclass(frozen=True)
class IngestSegment:
    """One sealed unit of tail-followed input (data.stream=tail): the
    newly COMPLETED lines of a watched shard, spooled into an immutable
    segment file (plus its .xfc cache when conversion is on) and
    stamped with the ingest trace context the freshness tooling follows
    across the train/serve boundary (docs/SERVING.md "Freshness")."""

    trace: str       # 16-hex ingest trace id (tracing.new_id)
    seq: int         # monotone segment number within this follower
    source: str      # the watched text shard the bytes came from
    offset: int      # byte offset of the segment's start in `source`
    rows: int        # labeled examples in the segment
    bytes: int       # segment length in bytes
    path: str        # the sealed spool file (immutable once yielded)
    cache: str       # its .xfc sidecar ("" = text path)
    ingest_ts: float # wall anchor: when the segment sealed


def stream_dir_for(prefix: str, cfg: DataConfig) -> str:
    """Where a tail follower spools segments: data.stream_dir, or an
    `.xfstream` dir next to the watched shards."""
    if cfg.stream_dir:
        return cfg.stream_dir
    return os.path.join(os.path.dirname(prefix) or ".", ".xfstream")


class TailFollower:
    """Follow-the-tail streaming source (data.stream=tail).

    Watches the `<prefix>-NNNNN` shard set (or `prefix` itself when it
    is a file) for new or growing libffm files. Each poll cuts every
    shard's newly completed lines — a trailing row without its newline
    is DEFERRED until more bytes land, never quarantined: a writer
    mid-append is the normal case, not a malformed input — into one
    immutable spool segment, converts it on arrival into a packed .xfc
    cache (data.cache auto/on) so streamed data rides the same
    device-rate path batch training does, and stamps it with a fresh
    ingest trace id + wall anchor carried as a `kind="ingest"` record.
    Consumers iterate sealed segments only, so the batch-count drift
    guard downstream never sees a file change mid-pass.

    Rotation: a shard whose size SHRANK below the follower's offset was
    rotated/recreated — the offset resets to 0 and the new contents
    stream from the top. `data.stream_idle_s` bounds the follow: no new
    complete rows for that long ends the stream (0 = follow forever).

    `clock`/`wall` are injectable for tests (monotonic pacing vs the
    wall anchor stamped into records)."""

    def __init__(
        self,
        prefix: str,
        cfg: DataConfig,
        appender: Optional[JsonlAppender] = None,
        clock=time.monotonic,
        wall=time.time,
    ):
        self._prefix = prefix
        self._cfg = cfg
        self._app = appender
        self._poll_s = max(float(cfg.stream_poll_s), 0.01)
        self._idle_s = max(float(cfg.stream_idle_s), 0.0)
        self._dir = stream_dir_for(prefix, cfg)
        self._clock = clock
        self._wall = wall
        self._offsets: dict[str, int] = {}
        self._seq = 0
        self._stop = threading.Event()

    def _sources(self) -> list[str]:
        from xflow_tpu.data.libffm import available_shards

        if os.path.isfile(self._prefix):
            return [self._prefix]
        return available_shards(self._prefix)

    def poll(self) -> list[IngestSegment]:
        """One directory scan: seal and return every shard's newly
        completed lines (possibly empty)."""
        segs: list[IngestSegment] = []
        for src in self._sources():
            try:
                size = os.path.getsize(src)
            except OSError:
                continue  # raced a rotation; next poll sees the truth
            off = self._offsets.get(src, 0)
            if size < off:
                # rotation/truncation: the file restarted under us —
                # follow the NEW contents from the top
                off = self._offsets[src] = 0
            if size <= off:
                continue
            with open(src, "rb") as f:
                f.seek(off)
                data = f.read(size - off)
            nl = data.rfind(b"\n")
            if nl < 0:
                continue  # truncated tail row: defer, never quarantine
            chunk = data[: nl + 1]
            seg = self._seal(src, off, chunk)
            self._offsets[src] = off + len(chunk)
            if seg is not None:
                segs.append(seg)
        return segs

    def _seal(self, src: str, off: int, chunk: bytes) -> Optional[IngestSegment]:
        from xflow_tpu.data.libffm import count_rows
        from xflow_tpu.telemetry import default_registry
        from xflow_tpu.tracing import new_id

        os.makedirs(self._dir, exist_ok=True)
        spool = os.path.join(self._dir, "segment-%06d" % self._seq)
        seq, self._seq = self._seq, self._seq + 1
        tmp = spool + ".tmp"
        with open(tmp, "wb") as f:
            f.write(chunk)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, spool)
        rows = count_rows(spool)
        if rows == 0:
            return None  # blank/label-less lines: the offset still advances
        cache = ""
        if self._cfg.cache in ("auto", "on"):
            from xflow_tpu.data.shardcache import cache_path_for, write_shard_cache

            try:
                write_shard_cache(spool, self._cfg)
                cache = cache_path_for(spool, self._cfg.cache_dir)
            except Exception as e:
                # conversion is an optimization: a failed build logs
                # and the segment trains through the text path
                print(
                    f"xflow: warning: convert-on-arrival failed for "
                    f"{spool!r} ({e}); training the segment from text",
                    file=sys.stderr,
                )
        seg = IngestSegment(
            trace=new_id(), seq=seq, source=src, offset=off, rows=rows,
            bytes=len(chunk), path=spool, cache=cache,
            ingest_ts=round(self._wall(), 6),
        )
        reg = default_registry()
        reg.counter("data.ingest_segments").inc()
        reg.counter("data.ingest_rows").inc(rows)
        if self._app is not None:
            self._app.append({
                "kind": "ingest",
                "trace": seg.trace,
                "seq": seg.seq,
                "source": seg.source,
                "offset": seg.offset,
                "rows": seg.rows,
                "bytes": seg.bytes,
                "cache": seg.cache,
                "ingest_ts": seg.ingest_ts,
            })
        return seg

    def segments(self) -> Iterator[IngestSegment]:
        """The blocking segment stream: polls at stream_poll_s, ends on
        close() or after stream_idle_s without new complete rows."""
        last_new = self._clock()
        while not self._stop.is_set():
            segs = self.poll()
            if segs:
                last_new = self._clock()
                for seg in segs:
                    yield seg
                continue
            if self._idle_s and self._clock() - last_new >= self._idle_s:
                return
            self._stop.wait(self._poll_s)

    def close(self) -> None:
        self._stop.set()
