"""Feature hashing.

The reference hashes the feature-id *string* with `std::hash<std::string>`
into a 64-bit ps-lite key (`/root/reference/src/io/load_data_from_disk.cc:151`)
and accepts silent collisions (SURVEY.md §7 hard part e). `std::hash` is
implementation-defined, so there is nothing to match bit-for-bit; we use a
fixed, salted FNV-1a 64-bit hash over the feature-id token bytes so that
Python, NumPy, and the C++ native parser all agree exactly, then map keys
into a dense ``2**log2_slots`` table with a mask (the TPU analog of the
ps-lite key-range shard: a dense sharded axis instead of a hash map).
"""

from __future__ import annotations

import numpy as np

FNV_OFFSET = 0xCBF29CE484222325
FNV_PRIME = 0x100000001B3
_MASK64 = (1 << 64) - 1


def fnv1a64(data: bytes, salt: int = 0) -> int:
    """Salted FNV-1a 64-bit hash. Must stay in lockstep with native/parser.cc."""
    h = (FNV_OFFSET ^ (salt & _MASK64)) & _MASK64
    for b in data:
        h ^= b
        h = (h * FNV_PRIME) & _MASK64
    return h


def hash_token(token: str, salt: int = 0) -> int:
    return fnv1a64(token.encode("utf-8"), salt)


_FINALIZE_MUL = 0xD6E8FEB86659FD93  # splitmix64-style finalizer constant


def slot_of(key: int, log2_slots: int) -> int:
    """Map a 64-bit key to a table slot.

    Applies a mix (xor-shift, multiply, xor-shift) before masking so
    every bit of the hash influences the slot index for any table size.
    Must stay in lockstep with slots_of and native/parser.cc.
    """
    x = (key ^ (key >> 32)) & _MASK64
    x = (x * _FINALIZE_MUL) & _MASK64
    x ^= x >> 32
    return x & ((1 << log2_slots) - 1)


def slots_of(keys: np.ndarray, log2_slots: int) -> np.ndarray:
    """Vectorized `slot_of` over a uint64 array."""
    x = keys.astype(np.uint64)
    x = x ^ (x >> np.uint64(32))
    with np.errstate(over="ignore"):
        x = x * np.uint64(_FINALIZE_MUL)
    x = x ^ (x >> np.uint64(32))
    return (x & np.uint64((1 << log2_slots) - 1)).astype(np.int64)


def hash_tokens(tokens: list[str], salt: int = 0) -> np.ndarray:
    return np.array([hash_token(t, salt) for t in tokens], dtype=np.uint64)


def hash_int_tokens(values: np.ndarray, salt: int = 0) -> np.ndarray:
    """Vectorized `fnv1a64` over the DECIMAL string forms of nonnegative
    ints — bit-identical to hashing each `str(v)` (parity-tested), but
    a handful of vector passes instead of a Python byte loop per token.
    Used for collision accounting over ~10M-distinct-feature datasets
    (tools/scale_bench.py), where the scalar path takes minutes."""
    v = np.asarray(values, np.uint64)
    # exact integer digit count: float log10 misrounds at 10^15+ (the
    # +0.5 vanishes in the mantissa), silently dropping a digit
    ndig = np.ones(v.shape, np.int64)
    for k in range(1, 20):  # uint64 max is 1.8e19: 20 digits
        ndig += v >= np.uint64(10) ** np.uint64(k)
    out = np.empty(v.shape, np.uint64)
    with np.errstate(over="ignore"):
        for d in np.unique(ndig):
            sel = ndig == d
            x = v[sel]
            h = np.full(x.shape, FNV_OFFSET ^ (salt & _MASK64), np.uint64)
            for i in range(int(d) - 1, -1, -1):
                digit = (x // np.uint64(10) ** np.uint64(i)) % np.uint64(10)
                h = (h ^ (digit + np.uint64(ord("0")))) * np.uint64(FNV_PRIME)
            out[sel] = h
    return out
