"""Feature hashing.

The reference hashes the feature-id *string* with `std::hash<std::string>`
into a 64-bit ps-lite key (`/root/reference/src/io/load_data_from_disk.cc:151`)
and accepts silent collisions (SURVEY.md §7 hard part e). `std::hash` is
implementation-defined, so there is nothing to match bit-for-bit; we use a
fixed, salted FNV-1a 64-bit hash over the feature-id token bytes so that
Python, NumPy, and the C++ native parser all agree exactly, then map keys
into a dense ``2**log2_slots`` table with a mask (the TPU analog of the
ps-lite key-range shard: a dense sharded axis instead of a hash map).
"""

from __future__ import annotations

import numpy as np

FNV_OFFSET = 0xCBF29CE484222325
FNV_PRIME = 0x100000001B3
_MASK64 = (1 << 64) - 1


def fnv1a64(data: bytes, salt: int = 0) -> int:
    """Salted FNV-1a 64-bit hash. Must stay in lockstep with native/parser.cc."""
    h = (FNV_OFFSET ^ (salt & _MASK64)) & _MASK64
    for b in data:
        h ^= b
        h = (h * FNV_PRIME) & _MASK64
    return h


def hash_token(token: str, salt: int = 0) -> int:
    return fnv1a64(token.encode("utf-8"), salt)


_FINALIZE_MUL = 0xD6E8FEB86659FD93  # splitmix64-style finalizer constant


def slot_of(key: int, log2_slots: int) -> int:
    """Map a 64-bit key to a table slot.

    Applies a mix (xor-shift, multiply, xor-shift) before masking so
    every bit of the hash influences the slot index for any table size.
    Must stay in lockstep with slots_of and native/parser.cc.
    """
    x = (key ^ (key >> 32)) & _MASK64
    x = (x * _FINALIZE_MUL) & _MASK64
    x ^= x >> 32
    return x & ((1 << log2_slots) - 1)


def slots_of(keys: np.ndarray, log2_slots: int) -> np.ndarray:
    """Vectorized `slot_of` over a uint64 array."""
    x = keys.astype(np.uint64)
    x = x ^ (x >> np.uint64(32))
    with np.errstate(over="ignore"):
        x = x * np.uint64(_FINALIZE_MUL)
    x = x ^ (x >> np.uint64(32))
    return (x & np.uint64((1 << log2_slots) - 1)).astype(np.int64)


def hash_tokens(tokens: list[str], salt: int = 0) -> np.ndarray:
    return np.array([hash_token(t, salt) for t in tokens], dtype=np.uint64)
