"""Factorization machine.

Reference: `/root/reference/src/model/fm/fm_worker.cc`. Its forward
(`calculate_loss`, `fm_worker.cc:159-202`) computes
σ(wx + S² − Q) where S and Q accumulate v and v² over *both* the
feature and the latent axes (`fm_worker.cc:178-196`: `v_sum[sid]` is
indexed by row only, inside the k loop), i.e. latent dims are coupled
through one scalar — and its hand-written w-gradient is accumulated
once per latent dim (`fm_worker.cc:134-148`), scaling it by k. Both are
accidents relative to Rendle's FM (SURVEY.md §7: fix, not replicate).

Default here is the standard FM second-order term, per latent dim:
  ½ Σₖ [(Σᵢ v_{ik})² − Σᵢ v²_{ik}]
with `cfg.model.fm_half=False` dropping the ½ (the reference also omits
it) and `cfg.model.fm_standard=False` reproducing the reference's
coupled form exactly for parity experiments. Gradients are exact
(`jax.grad`), not the reference's approximation.
"""

from __future__ import annotations

import jax.numpy as jnp

from xflow_tpu.models.base import Model, register_model


def _table_specs(cfg):
    return {"w": (), "v": (cfg.model.v_dim,)}


def forward(tables, batch, cfg):
    w, v = tables["w"], tables["v"]
    mask = batch["mask"]
    wg = w[batch["slots"]]  # [B, F]
    wx = (wg * mask).sum(axis=-1)
    vg = v[batch["slots"]] * mask[..., None]  # [B, F, k]
    if cfg.model.fm_standard:
        s = vg.sum(axis=1)  # [B, k]
        q = (vg * vg).sum(axis=1)  # [B, k]
        second = (s * s - q).sum(axis=-1)
        if cfg.model.fm_half:
            second = 0.5 * second
    else:
        # reference-coupled form: one scalar accumulator across (i, k)
        s = vg.sum(axis=(1, 2))
        q = (vg * vg).sum(axis=(1, 2))
        second = s * s - q
    return wx + second


MODEL = register_model(Model(name="fm", table_specs=_table_specs, forward=forward))
