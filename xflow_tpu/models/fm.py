"""Factorization machine.

Reference: `/root/reference/src/model/fm/fm_worker.cc`. Its forward
(`calculate_loss`, `fm_worker.cc:159-202`) computes
σ(wx + S² − Q) where S and Q accumulate v and v² over *both* the
feature and the latent axes (`fm_worker.cc:178-196`: `v_sum[sid]` is
indexed by row only, inside the k loop), i.e. latent dims are coupled
through one scalar — and its hand-written w-gradient is accumulated
once per latent dim (`fm_worker.cc:134-148`), scaling it by k. Both are
accidents relative to Rendle's FM (SURVEY.md §7: fix, not replicate).

Default here is the standard FM second-order term, per latent dim:
  ½ Σₖ [(Σᵢ v_{ik})² − Σᵢ v²_{ik}]
with `cfg.model.fm_half=False` dropping the ½ (the reference also omits
it) and `cfg.model.fm_standard=False` reproducing the reference's
coupled form exactly for parity experiments. Gradients are exact
(`jax.grad`), not the reference's approximation.

Table layout: ONE fused ``wv [S, 1+k]`` table (column 0 = w, columns
1..k = v) instead of the reference's two server tables
(`fm_worker.cc:227-242` pulls/pushes w and v separately). The step's
cost is dominated by latency-bound table row gathers/scatters (
docs/PERF.md), so fusing halves the number of gather+scatter passes —
a row of 1+k floats costs about the same as a scalar. FTRL/SGD are
elementwise, so optimizing the fused table is exactly equivalent to
optimizing the two tables separately. `cfg.model.fm_fused=False` (or
passing explicit {"w","v"} tables) keeps the two-table layout for
parity experiments; both layouts compute the same math.
"""

from __future__ import annotations

import jax.numpy as jnp

from xflow_tpu.models.base import Model, register_model


def _table_specs(cfg):
    if cfg.model.fm_fused:
        return {"wv": (1 + cfg.model.v_dim,)}
    return {"w": (), "v": (cfg.model.v_dim,)}


def _second_order(vg, cfg):
    """vg: [B, F, k] masked latent gathers -> [B] second-order term."""
    if cfg.model.fm_standard:
        s = vg.sum(axis=1)  # [B, k]
        q = (vg * vg).sum(axis=1)  # [B, k]
        second = (s * s - q).sum(axis=-1)
        if cfg.model.fm_half:
            second = 0.5 * second
    else:
        # reference-coupled form: one scalar accumulator across (i, k)
        s = vg.sum(axis=(1, 2))
        q = (vg * vg).sum(axis=(1, 2))
        second = s * s - q
    return second


def stack_channels(occm_t, K):
    """[K, Np] masked rows -> [ch, Np] (w, latents, squares, zero pad to a
    sublane multiple) — the channel layout `fm_logits_from_sums` expects."""
    from xflow_tpu.ops.sorted_table import _k8

    nch = 2 * K - 1  # w + k latents + k squares
    ch = _k8(nch)  # row_sums_sorted wants a sublane multiple
    return jnp.concatenate(
        [occm_t, occm_t[1:] ** 2,
         jnp.zeros((ch - nch, occm_t.shape[1]), occm_t.dtype)],
        axis=0,
    )


def fm_logits_from_sums(sums, K, cfg):
    """[rows, ch] per-row channel sums -> [rows] logits. Shared by the
    single-device sorted path and the sharded engine
    (parallel/sorted_sharded.py) so the second-order math cannot drift."""
    nch = 2 * K - 1
    wx = sums[:, 0]
    s, q = sums[:, 1:K], sums[:, K:nch]  # [rows, k] each
    if cfg.model.fm_standard:
        second = (s * s - q).sum(axis=-1)
        if cfg.model.fm_half:
            second = 0.5 * second
    else:
        s_all, q_all = s.sum(axis=-1), q.sum(axis=-1)
        second = s_all * s_all - q_all
    return wx + second


def _row_side_sorted(occ_t, sorted_row, sorted_mask, rows, cfg):
    from xflow_tpu.ops.sorted_table import row_sums_sorted, wire_mask, wire_rows

    K = 1 + cfg.model.v_dim  # logical row width (storage may be packed)
    sorted_row, sorted_mask = wire_rows(sorted_row), wire_mask(sorted_mask)
    # transposed throughout: [K8, Np] keeps the minor dim wide (full lanes)
    occm_t = occ_t[:K] * sorted_mask[None, :]
    stacked = stack_channels(occm_t, K)  # [ch, Np]
    sums = row_sums_sorted(stacked, sorted_row, rows)  # [rows, ch]
    return fm_logits_from_sums(sums, K, cfg)


def _forward_sorted(tables, batch, cfg):
    """Sorted-window path (ops/sorted_table.py): occurrences arrive
    slot-sorted from the host; the table gather/scatter streams W-slot
    windows with MXU one-hot matmuls (no random HBM access at table
    scale) and per-row sums cross through small [B, k] segment arrays.
    Sorted arrays may arrive stacked [NS, Np_sub] (plan_sorted_stacked):
    the row side maps over row-contiguous sub-batches while the table
    side runs once (sorted_gather_map; FM's row state is already
    cache-resident at NS=1, so auto keeps NS=1)."""
    from xflow_tpu.ops.sorted_table import sorted_gather_map

    wv = tables["wv"]
    return sorted_gather_map(
        wv, batch, ("sorted_row", "sorted_mask"), batch["labels"].shape[0],
        lambda occ, sr, sm, rows: _row_side_sorted(occ, sr, sm, rows, cfg),
        1 + cfg.model.v_dim, cfg.data.sorted_bf16,
    )


def forward(tables, batch, cfg):
    if "sorted_slots" in batch and "wv" in tables:
        return _forward_sorted(tables, batch, cfg)
    from xflow_tpu.ops.sorted_table import batch_rows

    mask = batch["mask"]
    if "wv" in tables:
        # fused: ONE row gather for w and v (and one scatter in backward);
        # batch_rows is layout-blind and honors host dedup (data.dedup)
        wvg = batch_rows(tables["wv"], batch, 1 + cfg.model.v_dim)
        wx = (wvg[..., 0] * mask).sum(axis=-1)
        vg = wvg[..., 1:] * mask[..., None]
    else:
        w, v = tables["w"], tables["v"]
        wg = batch_rows(w, batch, 1)  # [B, F]
        wx = (wg * mask).sum(axis=-1)
        vg = batch_rows(v, batch, cfg.model.v_dim) * mask[..., None]
    return wx + _second_order(vg, cfg)


MODEL = register_model(Model(name="fm", table_specs=_table_specs, forward=forward))
