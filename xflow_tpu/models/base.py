"""Model interface.

A model is a set of named parameter tables plus a pure forward function
from (tables, batch) to logits. Tables are dense ``[num_slots]`` or
``[num_slots, v_dim]`` arrays sharded on the slot axis (the TPU analog
of ps-lite's key-range-sharded server tables, SURVEY.md §2 C2/C13).
Gradients come from `jax.grad` through the table gathers — the gather
is the reference's Pull, its transpose (scatter-add) is the Push.

The reference's model zoo and table usage:
- LR: table w (dim 1)            (`/root/reference/src/model/lr/`)
- FM: tables w (dim 1) + v (dim k) (`/root/reference/src/model/fm/`)
- MVM: table v (dim k) only        (`/root/reference/src/model/mvm/`,
  pushes only v: `mvm_worker.cc:270`)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict

import jax
import jax.numpy as jnp

from xflow_tpu.config import Config


@dataclass(frozen=True)
class Model:
    name: str
    # table name -> trailing dims ( () for scalar table, (v_dim,) for latent )
    table_specs: Callable[[Config], Dict[str, tuple]]
    # (tables, batch_arrays, cfg) -> logits [B]
    forward: Callable


_REGISTRY: Dict[str, Model] = {}


def register_model(model: Model) -> Model:
    _REGISTRY[model.name] = model
    return model


def get_model(name: str) -> Model:
    if name not in _REGISTRY:
        raise KeyError(f"unknown model {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def init_tables(model: Model, cfg: Config, key: jax.Array) -> Dict[str, jax.Array]:
    """Build dense parameter tables.

    w-tables init to 0 (reference: default-constructed FTRL entries,
    `ftrl.h:27-36`). v-tables init ~N(0,1)*v_init_scale for FTRL
    (`ftrl.h:117`) or constant v_init_sgd for SGD (`sgd.h:69`) — the
    reference does this lazily per touched key; dense pre-init is
    equivalent because the FTRL update preserves never-touched slots
    (g=0 ∧ n=0 keeps w, see `optim/ftrl.py:_update_one`) and SGD with
    g=0 is a no-op.
    """
    from xflow_tpu.ops.sorted_table import PACK

    # packed [S/8, 8K] storage for vector tables (pack_table docstring:
    # the (8,128) HBM tiling makes logical [S, 11] storage 11.6x its
    # bytes). Created DIRECTLY in packed shape — building [S, K] first
    # and reshaping would materialize the padded buffer this exists to
    # avoid. The init distribution is elementwise iid, so the packed
    # init is distribution-identical (not bitwise: the RNG->element map
    # differs between layouts).
    mode = cfg.data.packed_tables
    if mode not in ("auto", "on", "off"):
        raise ValueError(f"data.packed_tables={mode!r}: expected auto|on|off")
    if mode == "on" and cfg.num_slots % PACK != 0:
        raise ValueError(
            f"data.packed_tables=on needs num_slots divisible by {PACK}; "
            f"got 2^{cfg.data.log2_slots}"
        )
    pack = PACK if mode != "off" and cfg.num_slots % PACK == 0 else 1
    tables = {}
    specs = model.table_specs(cfg)
    for tname, trailing in sorted(specs.items()):
        if trailing == ():
            tables[tname] = jnp.zeros((cfg.num_slots,), dtype=jnp.float32)
            continue
        K = trailing[0]
        shape = (cfg.num_slots // pack, pack * K)
        key, sub = jax.random.split(key)
        if cfg.optim.name == "sgd":
            t = jnp.full(shape, cfg.optim.v_init_sgd, dtype=jnp.float32)
        else:
            t = jax.random.normal(sub, shape, dtype=jnp.float32) * cfg.optim.v_init_scale
        if tname == "wv":
            # fused FM layout: logical column 0 is the linear w (zero-init
            # like a scalar w-table) — every pack*K-row position j with
            # j % K == 0 in packed storage
            t = t.at[:, ::K].set(0.0) if pack > 1 else t.at[:, 0].set(0.0)
        tables[tname] = t
    return tables
