"""Field-aware factorization machine (FFM).

BASELINE.json config 5 — "Field-aware FM (extend src/model) on Criteo"
— the one driver config the reference leaves unimplemented. The
semantic base is the reference's FM worker
(`/root/reference/src/model/fm/fm_worker.cc:80-86`), extended per Juan
et al.'s FFM: feature i carries one latent vector PER opposing field,
and the pair (i, j) interacts through its field-crossed vectors:

    ŷ = wx + Σ_{i<j} ⟨v_{i, f_j}, v_{j, f_i}⟩

Table layout: ONE fused ``wv [S, 1 + nf·k]`` row per feature — column 0
is w, then nf contiguous k-blocks, block c holding the feature's vector
against field c (the same fused-table argument as models/fm.py: the
step cost is table row traffic, and FFM's whole point is that a row is
wide, so never pay two gathers).

TPU shape — the field-sum formulation: with

    S[b, c1, c2, :] = Σ_{i : f_i = c1} v_{i, c2}      ([B, nf, nf, k])

the pairwise term is

    ½ ( Σ_{c1,c2} ⟨S[b,c1,c2,:], S[b,c2,c1,:]⟩ − Σ_i ‖v_{i, f_i}‖² )

S comes from a one-hot MXU contraction (row-major path) or a
per-(row, field) segment-sum over the slot-sorted occurrence stream
(sorted path — the same engine class as MVM's segment mode), and the
double-field contraction is one einsum. For one-feature-per-field rows
this reduces to the textbook FFM sum; for multi-valued fields it
generalizes it exactly — same-field feature pairs i, j ∈ c interact
through ⟨v_{i,c}, v_{j,c}⟩, which IS the textbook term since f_j = c.
(Proof: the c1↔c2 sum counts every unordered cross-field pair twice
and the diagonal counts same-field pairs twice plus the self terms;
halving and subtracting the selves leaves exactly Σ_{i<j}.)

Memory note: S is [B, nf, nf, k] — ~332 MB at B = 64k, nf = 18, k = 4
(transient; fine on a 16 GB chip, and the fullshard mesh path never
builds it).

Path choice (measured, docs/PERF.md round-4 #5): on ONE device the
row-major MXU path is FASTER than the sorted segment engine at the
practical shape (193k vs 123k ex/s), so `sorted_layout=auto` keeps FFM
row-major; the segment mode is the fullshard MESH engine's row side,
where the no-replication layout requires it. Known limitation of the
FORCED single-device sorted path (`sorted_layout=on`): at very wide
fused rows with large batches (observed at nf·k = 128, B = 64k,
2^22 slots) XLA's TPU compiler crashes building the fused program —
the windowed kernels and the segment row side each compile fine in
isolation at that exact shape, so this is a compiler-scale issue, not
a kernel one. The default (`auto`) path and the practical bench shape
(nf·k = 72) are unaffected.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from xflow_tpu.models.base import Model, register_model


def _dims(cfg):
    return cfg.model.num_fields, cfg.model.v_dim


def _table_specs(cfg):
    nf, k = _dims(cfg)
    return {"wv": (1 + nf * k,)}


def ffm_logits_from_sums(sums, nf: int, k: int):
    """[rows, ch] per-(row·field) sums folded to [rows, nf, ch] →
    logits. Channel layout (ffm channel contract, shared by the
    single-device sorted path and the fullshard engine): 0 = w,
    1..nf·k = the v blocks, nf·k+1 = ‖v_self‖². `sums[r, c1, ...]` is
    the sum over the row's field-c1 occurrences."""
    K = 1 + nf * k
    wx = sums[:, :, 0].sum(axis=1)  # [rows]
    S = sums[:, :, 1:K].reshape(sums.shape[0], nf, nf, k)
    qsum = sums[:, :, K].sum(axis=1)  # [rows]
    full = jnp.einsum(
        "bcdk,bdck->b", S, S, precision=jax.lax.Precision.HIGHEST
    )
    return wx + 0.5 * (full - qsum)


def ffm_occurrence_channels(occ_t, mask, fields, nf: int, k: int):
    """[K8, Np] raw gathered rows + mask + per-occurrence field ids →
    [K+1, Np] channel stream for the per-(row, field) segment-sum:
    masked w, masked v blocks, then channel K = ‖v_{occ, f_occ}‖² (the
    self term — an own-field block select via a one-hot sum, never a
    gather; the mask is already folded into every channel)."""
    K = 1 + nf * k
    occm = occ_t[:K] * mask[None, :]
    v3 = occm[1:].reshape(nf, k, occm.shape[1])  # [nf, k, Np]
    onehot = (fields[None, :] == jnp.arange(nf)[:, None]).astype(occm.dtype)
    vself = (v3 * onehot[:, None, :]).sum(axis=0)  # [k, Np]
    q = (vself * vself).sum(axis=0)  # [Np]
    return jnp.concatenate([occm, q[None, :]], axis=0)  # [K+1, Np]


def make_ffm_row_op(reduce_segments, broadcast_rows, nf: int, k: int,
                    restore_dl=None):
    """Build the FFM row-side op:

        op(occ_t [K8, Np], mask [Np], fields [Np], rows [Np]) -> logits [R]

    computed through `reduce_segments(data [K+1, Np], seg [Np]) ->
    [R, nf, K+1]` (the occurrence→(row, field) reduction:
    `segment_sum_channels` on one device; segment-sum + owner_reduce in
    the fullshard engine) — with a HAND-WRITTEN VJP that is exact at
    structural zeros:

        d v_i[c,·] = dl_b · (S[b, c, f_i, ·] − [c == f_i]·v_i[c,·])
        d w_i      = dl_b

    The two terms live in ONE subtraction, so when S[b, c, f_i] is
    bitwise v_i (a single-occupant field — the diagonal self-pair that
    must contribute nothing) or exactly 0 (an absent opposing field),
    the gradient is EXACTLY zero. jax.grad through the
    full-minus-self formulation computes the same two terms along
    different graph paths, and backend fusion leaves ~1e-11 residues
    that flip FTRL's lazy-init guard (g==0 ∧ n==0 keeps the initial
    weight) — observed as engine divergence on the (1, 8) fullshard
    mesh; the same failure class MVM's product op solves the same way
    (models/mvm.py make_row_products). `broadcast_rows` is the bwd's
    row-aggregate transport (identity on one device; all_gather over
    'data' in the fullshard engine — the same traffic class as the
    plain path's d_sums transpose). `restore_dl` undoes any
    replication-split the engine's transpose applies to the incoming
    cotangent (fullshard: the shard_map transpose hands each 'table'
    copy dl/T — the plain autodiff path restores it through
    owner_reduce's psum transpose, which a custom bwd bypasses; the
    hook is a psum over 'table'). None = identity (single device)."""
    K = 1 + nf * k
    restore_dl = restore_dl or (lambda x: x)

    @jax.custom_vjp
    def op(occ_t, mask, fields, rows):
        return _fwd(occ_t, mask, fields, rows)[0]

    def _fwd(occ_t, mask, fields, rows):
        data = ffm_occurrence_channels(occ_t, mask, fields, nf, k)
        sums = reduce_segments(data, rows * nf + fields)  # [R, nf, K+1]
        return ffm_logits_from_sums(sums, nf, k), (occ_t, mask, fields, rows, sums)

    def _bwd(res, dl):
        occ_t, mask, fields, rows, sums = res
        R = sums.shape[0]
        dl = restore_dl(dl)
        # ship the small per-row aggregates; build the (row, f)-major
        # transpose locally after transport
        packed = broadcast_rows(
            jnp.concatenate([dl[:, None], sums.reshape(R, -1)], axis=1)
        )  # [R_all, 1 + nf*(K+1)]
        dl_all, sums_all = packed[:, 0], packed[:, 1:]
        R_all = sums_all.shape[0]
        A = sums_all.reshape(R_all, nf, K + 1)[:, :, 1:K].reshape(R_all, nf, nf, k)
        # Tmat[b*nf + f, c*k + kk] = S[b, c, f, kk]
        Tmat = A.transpose(0, 2, 1, 3).reshape(R_all * nf, nf * k)
        G = jnp.take(Tmat, rows * nf + fields, axis=0).T  # [nf*k, Np]
        occm_v = occ_t[1:K] * mask[None, :]
        blockmask = jnp.repeat(
            (fields[None, :] == jnp.arange(nf)[:, None]).astype(occ_t.dtype),
            k, axis=0,
        )  # [nf*k, Np]
        dl_occ = jnp.take(dl_all, rows) * mask  # [Np]
        d_v = (G - occm_v * blockmask) * dl_occ[None, :]
        d_w = dl_occ[None, :]
        pad = jnp.zeros((occ_t.shape[0] - K, occ_t.shape[1]), occ_t.dtype)
        return jnp.concatenate([d_w, d_v, pad], axis=0), None, None, None

    op.defvjp(lambda o, m, f, r: _fwd(o, m, f, r), _bwd)
    return op


def _row_side_sorted(occ_t, sorted_row, sorted_mask, sorted_fields, rows, cfg):
    """One sub-batch's row side from raw gathered rows: one segment-sum
    keyed on `row·nf + field` → [rows·nf, K+1] field sums → logits. The
    same engine class as MVM's segment mode (models/mvm.py), with FFM's
    wide channel set and the exact-at-zeros hand VJP (make_ffm_row_op)."""
    from xflow_tpu.ops.sorted_table import (
        segment_sum_channels,
        wire_mask,
        wire_rows,
    )

    nf, k = _dims(cfg)
    K = 1 + nf * k
    sorted_row, sorted_mask = wire_rows(sorted_row), wire_mask(sorted_mask)
    fields = wire_rows(sorted_fields)
    op = make_ffm_row_op(
        lambda data, seg: segment_sum_channels(data, seg, rows * nf).reshape(
            rows, nf, K + 1
        ),
        lambda arr: arr,
        nf, k,
    )
    return op(occ_t, sorted_mask, fields, sorted_row)


def _forward_sorted(tables, batch, cfg):
    from xflow_tpu.ops.sorted_table import sorted_gather_map

    wv = tables["wv"]
    nf, k = _dims(cfg)
    return sorted_gather_map(
        wv, batch, ("sorted_row", "sorted_mask", "sorted_fields"),
        batch["labels"].shape[0],
        lambda occ, sr, sm, sf, rows: _row_side_sorted(occ, sr, sm, sf, rows, cfg),
        1 + nf * k, cfg.data.sorted_bf16,
    )


def forward(tables, batch, cfg):
    if "sorted_slots" in batch:
        return _forward_sorted(tables, batch, cfg)
    from xflow_tpu.ops.sorted_table import batch_rows

    nf, k = _dims(cfg)
    mask = batch["mask"]
    wvg = batch_rows(tables["wv"], batch, 1 + nf * k)  # [B, F, 1+nf*k]
    wx = (wvg[..., 0] * mask).sum(axis=-1)
    B, F = mask.shape
    v = (wvg[..., 1:] * mask[..., None]).reshape(B, F, nf, k)
    onehot = (batch["fields"][..., None] == jnp.arange(nf)).astype(v.dtype)
    onehot = onehot * mask[..., None]  # [B, F, nf]
    # S[b, c1, c2, :]: one MXU contraction over the occurrence axis
    S = jnp.einsum(
        "bfc,bfdk->bcdk", onehot, v, precision=jax.lax.Precision.HIGHEST
    )
    full = jnp.einsum(
        "bcdk,bdck->b", S, S, precision=jax.lax.Precision.HIGHEST
    )
    vself = jnp.take_along_axis(
        v, batch["fields"][..., None, None].astype(jnp.int32), axis=2
    )[:, :, 0, :]  # [B, F, k] — v_{i, f_i}
    qsum = ((vself * vself).sum(axis=-1) * mask).sum(axis=-1)
    return wx + 0.5 * (full - qsum)


MODEL = register_model(Model(name="ffm", table_specs=_table_specs, forward=forward))
