"""Field-aware factorization machine (FFM).

BASELINE.json config 5 — "Field-aware FM (extend src/model) on Criteo"
— the one driver config the reference leaves unimplemented. The
semantic base is the reference's FM worker
(`/root/reference/src/model/fm/fm_worker.cc:80-86`), extended per Juan
et al.'s FFM: feature i carries one latent vector PER opposing field,
and the pair (i, j) interacts through its field-crossed vectors:

    ŷ = wx + Σ_{i<j} ⟨v_{i, f_j}, v_{j, f_i}⟩

Table layout: ONE fused ``wv [S, 1 + nf·k]`` row per feature — column 0
is w, then nf contiguous k-blocks, block c holding the feature's vector
against field c (the same fused-table argument as models/fm.py: the
step cost is table row traffic, and FFM's whole point is that a row is
wide, so never pay two gathers).

TPU shape — the field-sum formulation: with

    S[b, c1, c2, :] = Σ_{i : f_i = c1} v_{i, c2}      ([B, nf, nf, k])

the pairwise term is

    ½ ( Σ_{c1,c2} ⟨S[b,c1,c2,:], S[b,c2,c1,:]⟩ − Σ_i ‖v_{i, f_i}‖² )

S comes from a one-hot MXU contraction (row-major path) or a
per-(row, field) segment-sum over the slot-sorted occurrence stream
(sorted path — the same engine class as MVM's segment mode), and the
double-field contraction is one einsum. For one-feature-per-field rows
this reduces to the textbook FFM sum; for multi-valued fields it
generalizes it exactly — same-field feature pairs i, j ∈ c interact
through ⟨v_{i,c}, v_{j,c}⟩, which IS the textbook term since f_j = c.
(Proof: the c1↔c2 sum counts every unordered cross-field pair twice
and the diagonal counts same-field pairs twice plus the self terms;
halving and subtracting the selves leaves exactly Σ_{i<j}.)

Path choice (round 5, measured — docs/PERF.md): on ONE device
`sorted_layout=auto` (and `on`) runs the ALIGNED HYBRID sorted engine
(`make_ffm_aligned_op` below): windowed table gather + host placement
permutation + layout-friendly MXU row side + fused scatter+FTRL —
623k ex/s at B = 64k / 742k at the 128k practical batch (843k with
`data.sorted_bf16`), 2^22 slots, vs 193k for the round-4 row-major
einsum path at its 16k cap.
Batches with duplicate (row, field) occurrences fall back per batch
to the row-major einsum path in `forward` (the general form, itself
layout-rewritten this round: 282k at 16k where round 4's 4-D einsum
formulation measured 191k and OOM'd at 64k). The per-(row, field)
SEGMENT engine (`make_ffm_row_op`) is the fullshard MESH engine's row
side only, where the no-replication layout requires it — the round-4
single-device forced-sorted segment path (and the XLA compiler crash
it hit at nf·k = 128, B = 64k) no longer exists: `sorted_layout=on`
now means the hybrid, and rejects non-aligned batches with a clear
error (trainer._resolve_ffm_aligned).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from xflow_tpu.models.base import Model, register_model


def _dims(cfg):
    return cfg.model.num_fields, cfg.model.v_dim


def _table_specs(cfg):
    nf, k = _dims(cfg)
    return {"wv": (1 + nf * k,)}


def ffm_logits_from_sums(sums, nf: int, k: int):
    """[rows, ch] per-(row·field) sums folded to [rows, nf, ch] →
    logits. Channel layout (ffm channel contract, shared by the
    single-device sorted path and the fullshard engine): 0 = w,
    1..nf·k = the v blocks, nf·k+1 = ‖v_self‖². `sums[r, c1, ...]` is
    the sum over the row's field-c1 occurrences."""
    K = 1 + nf * k
    wx = sums[:, :, 0].sum(axis=1)  # [rows]
    S = sums[:, :, 1:K].reshape(sums.shape[0], nf, nf, k)
    qsum = sums[:, :, K].sum(axis=1)  # [rows]
    full = jnp.einsum(
        "bcdk,bdck->b", S, S, precision=jax.lax.Precision.HIGHEST
    )
    return wx + 0.5 * (full - qsum)


def ffm_occurrence_channels(occ_t, mask, fields, nf: int, k: int):
    """[K8, Np] raw gathered rows + mask + per-occurrence field ids →
    [K+1, Np] channel stream for the per-(row, field) segment-sum:
    masked w, masked v blocks, then channel K = ‖v_{occ, f_occ}‖² (the
    self term — an own-field block select via a one-hot sum, never a
    gather; the mask is already folded into every channel)."""
    K = 1 + nf * k
    occm = occ_t[:K] * mask[None, :]
    v3 = occm[1:].reshape(nf, k, occm.shape[1])  # [nf, k, Np]
    onehot = (fields[None, :] == jnp.arange(nf)[:, None]).astype(occm.dtype)
    vself = (v3 * onehot[:, None, :]).sum(axis=0)  # [k, Np]
    q = (vself * vself).sum(axis=0)  # [Np]
    return jnp.concatenate([occm, q[None, :]], axis=0)  # [K+1, Np]


def make_ffm_row_op(reduce_segments, broadcast_rows, nf: int, k: int,
                    restore_dl=None):
    """Build the FFM row-side op:

        op(occ_t [K8, Np], mask [Np], fields [Np], rows [Np]) -> logits [R]

    computed through `reduce_segments(data [K+1, Np], seg [Np]) ->
    [R, nf, K+1]` (the occurrence→(row, field) reduction:
    `segment_sum_channels` on one device; segment-sum + owner_reduce in
    the fullshard engine) — with a HAND-WRITTEN VJP that is exact at
    structural zeros:

        d v_i[c,·] = dl_b · (S[b, c, f_i, ·] − [c == f_i]·v_i[c,·])
        d w_i      = dl_b

    The two terms live in ONE subtraction, so when S[b, c, f_i] is
    bitwise v_i (a single-occupant field — the diagonal self-pair that
    must contribute nothing) or exactly 0 (an absent opposing field),
    the gradient is EXACTLY zero. jax.grad through the
    full-minus-self formulation computes the same two terms along
    different graph paths, and backend fusion leaves ~1e-11 residues
    that flip FTRL's lazy-init guard (g==0 ∧ n==0 keeps the initial
    weight) — observed as engine divergence on the (1, 8) fullshard
    mesh; the same failure class MVM's product op solves the same way
    (models/mvm.py make_row_products). `broadcast_rows` is the bwd's
    row-aggregate transport (identity on one device; all_gather over
    'data' in the fullshard engine — the same traffic class as the
    plain path's d_sums transpose). `restore_dl` undoes any
    replication-split the engine's transpose applies to the incoming
    cotangent (fullshard: the shard_map transpose hands each 'table'
    copy dl/T — the plain autodiff path restores it through
    owner_reduce's psum transpose, which a custom bwd bypasses; the
    hook is a psum over 'table'). None = identity (single device)."""
    K = 1 + nf * k
    restore_dl = restore_dl or (lambda x: x)

    @jax.custom_vjp
    def op(occ_t, mask, fields, rows):
        return _fwd(occ_t, mask, fields, rows)[0]

    def _fwd(occ_t, mask, fields, rows):
        data = ffm_occurrence_channels(occ_t, mask, fields, nf, k)
        sums = reduce_segments(data, rows * nf + fields)  # [R, nf, K+1]
        return ffm_logits_from_sums(sums, nf, k), (occ_t, mask, fields, rows, sums)

    def _bwd(res, dl):
        occ_t, mask, fields, rows, sums = res
        R = sums.shape[0]
        dl = restore_dl(dl)
        # ship the small per-row aggregates; build the (row, f)-major
        # transpose locally after transport
        packed = broadcast_rows(
            jnp.concatenate([dl[:, None], sums.reshape(R, -1)], axis=1)
        )  # [R_all, 1 + nf*(K+1)]
        dl_all, sums_all = packed[:, 0], packed[:, 1:]
        R_all = sums_all.shape[0]
        A = sums_all.reshape(R_all, nf, K + 1)[:, :, 1:K].reshape(R_all, nf, nf, k)
        # Tmat[b*nf + f, c*k + kk] = S[b, c, f, kk]
        Tmat = A.transpose(0, 2, 1, 3).reshape(R_all * nf, nf * k)
        G = jnp.take(Tmat, rows * nf + fields, axis=0).T  # [nf*k, Np]
        occm_v = occ_t[1:K] * mask[None, :]
        blockmask = jnp.repeat(
            (fields[None, :] == jnp.arange(nf)[:, None]).astype(occ_t.dtype),
            k, axis=0,
        )  # [nf*k, Np]
        dl_occ = jnp.take(dl_all, rows) * mask  # [Np]
        d_v = (G - occm_v * blockmask) * dl_occ[None, :]
        d_w = dl_occ[None, :]
        pad = jnp.zeros((occ_t.shape[0] - K, occ_t.shape[1]), occ_t.dtype)
        return jnp.concatenate([d_w, d_v, pad], axis=0), None, None, None

    op.defvjp(lambda o, m, f, r: _fwd(o, m, f, r), _bwd)
    return op


def _row_side_sorted(occ_t, sorted_row, sorted_mask, sorted_fields, rows, cfg):
    """One sub-batch's row side from raw gathered rows: one segment-sum
    keyed on `row·nf + field` → [rows·nf, K+1] field sums → logits. The
    same engine class as MVM's segment mode (models/mvm.py), with FFM's
    wide channel set and the exact-at-zeros hand VJP (make_ffm_row_op)."""
    from xflow_tpu.ops.sorted_table import (
        segment_sum_channels,
        wire_mask,
        wire_rows,
    )

    nf, k = _dims(cfg)
    K = 1 + nf * k
    sorted_row, sorted_mask = wire_rows(sorted_row), wire_mask(sorted_mask)
    fields = wire_rows(sorted_fields)
    op = make_ffm_row_op(
        lambda data, seg: segment_sum_channels(data, seg, rows * nf).reshape(
            rows, nf, K + 1
        ),
        lambda arr: arr,
        nf, k,
    )
    return op(occ_t, sorted_mask, fields, sorted_row)


def _forward_sorted(tables, batch, cfg):
    from xflow_tpu.ops.sorted_table import sorted_gather_map

    wv = tables["wv"]
    nf, k = _dims(cfg)
    if "ffm_invperm" in batch:
        return _forward_sorted_aligned(wv, batch, cfg)
    return sorted_gather_map(
        wv, batch, ("sorted_row", "sorted_mask", "sorted_fields"),
        batch["labels"].shape[0],
        lambda occ, sr, sm, sf, rows: _row_side_sorted(occ, sr, sm, sf, rows, cfg),
        1 + nf * k, cfg.data.sorted_bf16,
    )


# ---------------------------------------------------------------------------
# Aligned hybrid path (the single-device FFM engine since round 5).
#
# On aligned batches — at most ONE masked occurrence per (row, field),
# libffm's natural shape and what the bundled/bench data always is —
# the per-(row, field) "segment sum" is a pure PLACEMENT, so the row
# side never needs the segment engine: the windowed sorted gather
# (table streamed once per step) hands occ_t [K8, Np] in slot order,
# one host-planned inverse permutation places it as A [B, nfp, K8]
# (nfp = nf rounded up to the 8-sublane multiple, so [B·nfp, K8] →
# [B, nfp, K8] is a free view — no lane-boundary reshape anywhere),
# and the pairwise term is ONE MXU contraction against a static 0/1
# selector built in-graph (never a captured constant: jit-embedded
# arrays ship through the remote-compile tunnel).
#
# Measured at B = 64k, 2^22 slots (round-5 probes, docs/PERF.md):
# round-4 row-major 4-D einsum path OOMs; the layout-fixed row-major
# path runs 240k ex/s; this hybrid runs 512k exact / 565k with
# data.sorted_bf16 — the step decomposition is gather 21.8 ms +
# place 16 + row math 28 + backward 32 + fused scatter+FTRL 31.
# ---------------------------------------------------------------------------


def nf_padded(nf: int) -> int:
    """nf rounded to the 8-sublane multiple (see the layout note) —
    the same rounding rule as the kernels' channel padding."""
    from xflow_tpu.ops.sorted_table import _k8

    return _k8(nf)


def ffm_invperm(sorted_row, sorted_fields, sorted_mask, rows: int, nf: int):
    """HOST-side placement permutation for an aligned plan: int32
    [rows·nfp] mapping destination (row, field) → its sorted position,
    absent pairs → Np-1 (always a pad position: plans carry one spare
    chunk, ops/sorted_table.padded_len). Raises on duplicate (row,
    field) pairs — callers route those batches elsewhere
    (resolve_ffm_aligned)."""
    import numpy as np

    nfp = nf_padded(nf)
    Np = sorted_row.shape[0]
    inv = np.full(rows * nfp, Np - 1, np.int32)
    real = np.asarray(sorted_mask) > 0
    dest = (
        np.asarray(sorted_row)[real].astype(np.int64) * nfp
        + np.asarray(sorted_fields)[real]
    )
    inv[dest] = np.nonzero(real)[0].astype(np.int32)
    # duplicate detection without a sort: duplicates overwrite one slot,
    # so fewer occupied destinations than real occurrences ⇔ collision
    # (real positions are never Np-1 — the plan's spare pad chunk)
    if int((inv != Np - 1).sum()) != dest.size:
        raise ValueError(
            "ffm_invperm: duplicate (row, field) occurrence in an "
            "aligned plan — route duplicate-field batches to the "
            "general path (resolve_ffm_aligned)"
        )
    return inv


def has_field_duplicates(fields, mask) -> bool:
    """True when any row carries two masked occurrences of one field
    (shared host check — same definition as models/mvm.py's)."""
    from xflow_tpu.models.mvm import has_field_duplicates as _h

    return _h(fields, mask)


def resolve_ffm_aligned(batch_fields, batch_mask) -> bool:
    """Route one FFM batch: aligned hybrid (True) or the row-major
    general path (False). Host-side per batch, like MVM's product
    routing: the hybrid requires ≤1 masked occurrence per (row, field).
    Duplicate-field batches run the layout-fixed row-major einsum path
    (the general form; measured 282k ex/s at 16k vs the sorted segment
    engine's 123k — docs/PERF.md round 5)."""
    return not has_field_duplicates(batch_fields, batch_mask)


def _pair_selector(nf: int, k: int, nfp: int, k8: int, dtype):
    """Static 0/1 selector tensors for the aligned row side, built
    IN-GRAPH from iota/compares (a captured 14.7 MB constant would ship
    through the tunnel's remote_compile on every cache miss):

      T [nfp, k8, nfp, k8]: T[c1, 1+c2·k+kk, c2, 1+c1·k+kk] = 1
      Q [nfp, k8]:          own-block select (column block c of row c)
      W [nfp, k8]:          the w channel (column 0, real fields only)
    """
    c = jnp.arange(nfp)[:, None, None, None]  # c1
    e = jnp.arange(k8)[None, :, None, None]
    d = jnp.arange(nfp)[None, None, :, None]  # c2
    f = jnp.arange(k8)[None, None, None, :]
    ke = e - 1 - d * k  # kk from e given c2=d
    kf = f - 1 - c * k  # kk from f given c1=c
    T = (
        (ke == kf) & (ke >= 0) & (ke < k) & (c < nf) & (d < nf)
    ).astype(dtype)
    cq = jnp.arange(nfp)[:, None]
    eq = jnp.arange(k8)[None, :]
    kq = eq - 1 - cq * k
    Q = ((kq >= 0) & (kq < k) & (cq < nf)).astype(dtype)
    W = ((eq == 0) & (cq < nf)).astype(dtype)
    return T, Q, W


def make_ffm_aligned_op(nf: int, k: int, k8: int, rows: int):
    """Build the aligned row-side op:

        op(occ_t [K8, Np], invperm [rows·nfp], src [Np], smask [Np])
            -> logits [rows]

    occ_t is the slot-sorted windowed gather output; `invperm` places
    it (ffm_invperm); `src` = sorted_row·nfp + sorted_field is the
    reverse map. The placement carries a HAND-WRITTEN VJP: the
    transpose of a (partial) permutation gather is the reverse gather —
    d_occ[:, p] = d_A[src[p]]·smask[p] — never an XLA scatter (which
    would pay ~35 ns/row random-write latency for what is a
    permutation).

    Exactness at FTRL's zeros (the lazy-init parity class both sibling
    ops document): d_A = dl·(X − A·Q + W) with X = T(A); for a
    single-occupant field, X at the self position is bitwise A (the
    selector row is one-hot, and the f32-exact 3-pass contraction
    reconstructs the operand exactly), so the subtraction is EXACTLY
    zero; absent fields have A = 0 ⇒ X = 0. Equality-tested against
    the row-major oracle path."""
    nfp = nf_padded(nf)

    def rowmath(A, T, Q, W):
        # HIGHEST is the measured optimum here: a 3-pass bf16 selector
        # split (the gather kernels' _dot_f32 trick — T is 0/1 and each
        # output selects one A element, so it would be exact) benched
        # SLOWER (195 vs 177 ms/step at B=128k) — the hi/mid/lo split's
        # extra elementwise passes over [B, nfp, k8] cost more than the
        # MXU passes they save on this skinny contraction
        X = jnp.einsum(
            "bce,cedf->bdf", A, T, precision=jax.lax.Precision.HIGHEST
        )
        full = (A * X).sum((-1, -2))
        qsum = (A * A * Q[None]).sum((-1, -2))
        wx = (A * W[None]).sum((-1, -2))
        return wx + 0.5 * (full - qsum)

    @jax.custom_vjp
    def place(occ_t, invperm, src, smask):
        dead = (invperm != occ_t.shape[1] - 1).astype(occ_t.dtype)
        return (occ_t.T[invperm] * dead[:, None]).reshape(rows, nfp, k8)

    def _fwd(occ_t, invperm, src, smask):
        return place(occ_t, invperm, src, smask), (src, smask)

    def _bwd(res, d_A):
        src, smask = res
        d_occ = (d_A.reshape(rows * nfp, k8)[src] * smask[:, None]).T
        return d_occ, None, None, None

    place.defvjp(_fwd, _bwd)

    def op(occ_t, invperm, src, smask):
        T, Q, W = _pair_selector(nf, k, nfp, k8, occ_t.dtype)
        A = place(occ_t, invperm, src, smask)
        return rowmath(A, T, Q, W)

    return op


def ffm_aligned_logits(occ_t, batch, cfg):
    """Row-side logits for an aligned-hybrid batch, from the gathered
    occ_t — shared by the fused train step (train/step.py), the plain
    autodiff forward below, and eval."""
    from xflow_tpu.ops.sorted_table import _k8, wire_mask, wire_rows

    nf, k = _dims(cfg)
    nfp = nf_padded(nf)
    rows = batch["labels"].shape[0]
    smask = wire_mask(batch["sorted_mask"])
    src = wire_rows(batch["sorted_row"]) * nfp + wire_rows(batch["sorted_fields"])
    op = make_ffm_aligned_op(nf, k, _k8(1 + nf * k), rows)
    return op(occ_t, batch["ffm_invperm"], src, smask)


def _forward_sorted_aligned(wv, batch, cfg):
    from xflow_tpu.ops.sorted_table import pack_of, table_gather_sorted

    nf, k = _dims(cfg)
    K = 1 + nf * k
    occ_t = table_gather_sorted(
        wv, batch["sorted_slots"], batch["win_off"], cfg.data.sorted_bf16,
        pack_of(wv, K),
    )
    return ffm_aligned_logits(occ_t, batch, cfg)


def block_transpose_perm(nf: int, k: int):
    """Static involution on the flattened [nf·nf·k] S index:
    (c1, c2, kk) ↔ (c2, c1, kk). Applying it as a minor-dim gather is
    how the pairwise contraction avoids ever materializing S as a 4-D
    [B, nf, nf, k] tensor — see `forward`'s layout note."""
    import numpy as np

    c1, c2, kk = np.meshgrid(
        np.arange(nf), np.arange(nf), np.arange(k), indexing="ij"
    )
    return jnp.asarray(
        (c2 * nf * k + c1 * k + kk).reshape(-1).astype(np.int32)
    )


def forward(tables, batch, cfg):
    """Row-major FFM forward in LAYOUT-FRIENDLY 3-D shapes.

    TPU HBM buffers are (8, 128)-tiled, so any tensor whose minor dim
    is the latent width k (4 at the practical shape) is stored at
    128/k× its logical bytes. The original formulation materialized
    [B, F, nf, k] and [B, nf, nf, k] einsum operands — ~3.5 GB EACH at
    B = 16k once padded, which made fwd+bwd the measured step wall
    (round-5 probe: fwd 30 ms, bwd 46 ms of an 86 ms step) and OOM'd
    outright at B = 64k. This formulation keeps every operand's minor
    dim ≥ nf·k = 72:

      vm [B, F, nf·k]   masked v blocks (block c = the feature's vector
                        against field c)
      S  [B, nf, nf·k]  = einsum over occurrences with the field
                        one-hot — S[b, c1, c2·k+kk] = S4[b, c1, c2, kk]
      full              = Σ Sf · Sf[:, PERM] where PERM is the static
                        (c1,c2)-block-transpose involution
                        (block_transpose_perm) on the flattened minor
                        dim — the pairwise ⟨S[c1,c2], S[c2,c1]⟩ sum
                        with no 4-D transpose ever stored
      qsum              = Σ (vm²·own-block select), the self-norm term,
                        one fused elementwise pass

    Same math as the module docstring's field-sum proof; the einsums
    run f32-exact (HIGHEST)."""
    if "sorted_slots" in batch:
        return _forward_sorted(tables, batch, cfg)
    from xflow_tpu.ops.sorted_table import batch_rows

    nf, k = _dims(cfg)
    E = nf * k
    mask = batch["mask"]
    wvg = batch_rows(tables["wv"], batch, 1 + E)  # [B, F, 1+nf*k]
    wx = (wvg[..., 0] * mask).sum(axis=-1)
    B, F = mask.shape
    vm = wvg[..., 1:] * mask[..., None]  # [B, F, E]
    onehot = (batch["fields"][..., None] == jnp.arange(nf)).astype(vm.dtype)
    onehot = onehot * mask[..., None]  # [B, F, nf]
    S = jnp.einsum(
        "bfc,bfe->bce", onehot, vm, precision=jax.lax.Precision.HIGHEST
    )  # [B, nf, E]
    Sf = S.reshape(B, nf * E)
    full = (Sf * Sf[:, block_transpose_perm(nf, k)]).sum(axis=-1)
    # own-field block select per occurrence: blocksel[b,f,c·k+kk] =
    # onehot[b,f,c] (a static minor-dim gather that fuses); mask is 0/1
    # and already folded into both vm and onehot
    blocksel = jnp.repeat(onehot, k, axis=-1)  # [B, F, E]
    qsum = (vm * vm * blocksel).sum(axis=(-1, -2))
    return wx + 0.5 * (full - qsum)


MODEL = register_model(Model(name="ffm", table_specs=_table_specs, forward=forward))
