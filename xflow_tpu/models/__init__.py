from xflow_tpu.models.base import Model, get_model, register_model
from xflow_tpu.models import lr, fm, mvm, ffm  # noqa: F401  (registration side effects)

__all__ = ["Model", "get_model", "register_model"]
