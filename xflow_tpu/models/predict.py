"""The ONE pctr forward shared by offline eval and online serving.

The reference computes pCTR twice: once in the worker's predict pass
(`lr_worker.cc:207-217`) and once — re-implemented — in the serving C
API it never finished (`/root/reference/src/c_api`, disabled in its
build). Two implementations of the same sigmoid forward is exactly how
offline/online skew is born, so here the function is factored once:

    predict_fn(tables, batch_arrays) -> pctr [B]

and BOTH consumers delegate to it — `train/step.make_eval_step` (the
trainer's evaluate pass) and `serve/runner.ServeRunner` (the online
path). A serve response and an `evaluate()` probability on the same row
are the same jitted program over the same tables; the parity test in
tests/test_serve.py pins it.

The forward is `reference_pctr(model.forward(...))` — the reference's
clamped sigmoid (`base.h:54-63`) over the model's logits, consuming the
row-major batch arrays (slots/fields/mask). Sorted-plan batches work
too (the model forwards dispatch on the plan keys), but serving always
ships row-major: request batches are tiny next to training batches and
the host sort would sit on the latency path.
"""

from __future__ import annotations

from typing import Callable

import jax

from xflow_tpu.config import Config
from xflow_tpu.models.base import Model


def predict_fn(tables, batch: dict, model: Model, cfg: Config):
    """Pure (tables, batch arrays) -> pctr [B] (reference-clamped σ)."""
    from xflow_tpu.metrics import reference_pctr

    return reference_pctr(model.forward(tables, batch, cfg))


def make_predict_fn(model: Model, cfg: Config, jit: bool = True,
                    recorder=None, name: str = "predict") -> Callable:
    """Returns pctr_step(tables, batch_arrays) -> pctr [B].

    The single factory behind `make_eval_step` AND the serve runner —
    offline eval and online serving cannot drift because they compile
    the same function. `recorder` (telemetry.CompileRecorder) routes
    the jit through the compile-accounting seam under `name`."""

    def step(tables, batch: dict):
        return predict_fn(tables, batch, model, cfg)

    if not jit:
        return step
    jitted = jax.jit(step)
    return recorder.wrap(name, jitted) if recorder is not None else jitted
