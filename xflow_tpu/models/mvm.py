"""Multi-view machine.

Reference: `/root/reference/src/model/mvm/mvm_worker.cc`. Per latent
dim k it sums v over the features of each libffm field ("view"):
`v_sum[k][row][fgid] += v` (`mvm_worker.cc:182-196`), takes the product
over fields (`:198-205`), sums over k (`:207-212`), and applies σ.

Reference accidents not replicated (SURVEY.md §7):
- per-row field range is `[0, max_fgid)` sized by the *max* field id
  seen, so the max field's accumulation writes one past the vector end
  (`mvm_worker.cc:43` vs `:75` — out-of-bounds UB); we use the
  configured `num_fields` and multiply only over fields present in the
  row (absent fields contribute the multiplicative identity rather than
  a hard 0);
- its hand gradient divides by `1 + v_sum` while the forward's product
  has no `1 +` (`mvm_worker.cc:153-157` vs `:202` — the `1+` variant is
  commented out at `:201`), and zero-guards inconsistently; we use the
  exact gradient via `jax.grad`;
- predict iterates `v_multi.size()` = k rows instead of the batch
  (`mvm_worker.cc:96`), truncating evaluation to 10 rows per block.

The per-(row, field) segment-sum is expressed as a one-hot einsum —
a [F, num_fields] × [F, k] batched matmul that XLA maps onto the MXU —
rather than a scatter, keeping the hot path dense and fusible.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from xflow_tpu.models.base import Model, register_model


def _table_specs(cfg):
    return {"v": (cfg.model.v_dim,)}


def _forward_sorted_one(v, sorted_slots, sorted_row, sorted_mask, sorted_fields,
                        win_off, rows, nf, bf16=False):
    """One sub-batch: [K8, Np] windowed gather + one segment-sum keyed on
    `row * nf + field` → logits [rows]."""
    from xflow_tpu.ops.sorted_table import table_gather_sorted

    k = v.shape[1]
    seg = sorted_row * nf + sorted_fields  # [Np]
    occ_t = table_gather_sorted(v, sorted_slots, win_off, bf16)  # [K8, Np]
    occm_t = occ_t[:k] * sorted_mask[None, :]
    # stack the mask as one extra channel: its segment-sum is the
    # per-(row, field) occurrence count, giving `present` in the same op
    stacked = jnp.concatenate([occm_t, sorted_mask[None, :]], axis=0)  # [k+1, Np]
    sums_t = jax.vmap(
        lambda r: jax.ops.segment_sum(r, seg, num_segments=rows * nf)
    )(stacked)  # [k+1, rows*nf]
    s = sums_t[:k].reshape(k, rows, nf)
    present = (sums_t[k] > 0).reshape(rows, nf)
    factors = jnp.where(present[None, :, :], s, 1.0)  # [k, rows, nf]
    return jnp.prod(factors, axis=-1).sum(axis=0)  # [rows]


def _forward_sorted(tables, batch, cfg):
    """Sorted-window path (ops/sorted_table.py): the v-table gather and
    its gradient scatter stream slot windows through the Pallas one-hot
    MXU kernels; the per-(row, field) view sums become one segment-sum
    keyed on `row * num_fields + field`.

    MVM's row-side aggregate is [B·nf, k] — ~47 MB at B=64k — which
    falls out of cache residency and makes the segment-sum/its backward
    gather ~8× slower per element (docs/PERF.md). Sorted arrays may
    therefore arrive STACKED [NS, Np_sub] (`plan_sorted_stacked`): the
    forward maps over row-contiguous sub-batches whose [B/NS·nf, k]
    aggregates stay resident, and XLA accumulates the table cotangent
    across the map. Semantics are identical to NS=1 (row order is
    preserved; the loss/optimizer still see one batch)."""
    from xflow_tpu.ops.sorted_table import map_sub_batches

    v = tables["v"]
    nf = cfg.model.num_fields
    bf16 = cfg.data.sorted_bf16
    return map_sub_batches(
        lambda ss, sr, sm, sf, wo, rows: _forward_sorted_one(
            v, ss, sr, sm, sf, wo, rows, nf, bf16
        ),
        batch,
        ("sorted_slots", "sorted_row", "sorted_mask", "sorted_fields", "win_off"),
        batch["labels"].shape[0],
    )


def forward(tables, batch, cfg):
    if "sorted_slots" in batch:
        return _forward_sorted(tables, batch, cfg)
    v = tables["v"]
    nf = cfg.model.num_fields
    mask = batch["mask"]
    vg = v[batch["slots"]] * mask[..., None]  # [B, F, k]
    onehot = (batch["fields"][..., None] == jnp.arange(nf)) * mask[..., None]  # [B, F, nf]
    # full-precision einsum: the contraction is tiny (F × nf × k) and the
    # downstream product-of-fields amplifies any bf16 rounding
    s = jnp.einsum("bfn,bfk->bnk", onehot, vg, precision=jax.lax.Precision.HIGHEST)
    present = onehot.sum(axis=1) > 0  # [B, nf]
    factors = jnp.where(present[..., None], s, 1.0)
    return jnp.prod(factors, axis=1).sum(axis=-1)  # [B]


MODEL = register_model(Model(name="mvm", table_specs=_table_specs, forward=forward))
