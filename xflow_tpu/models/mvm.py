"""Multi-view machine.

Reference: `/root/reference/src/model/mvm/mvm_worker.cc`. Per latent
dim k it sums v over the features of each libffm field ("view"):
`v_sum[k][row][fgid] += v` (`mvm_worker.cc:182-196`), takes the product
over fields (`:198-205`), sums over k (`:207-212`), and applies σ.

Reference accidents not replicated (SURVEY.md §7):
- per-row field range is `[0, max_fgid)` sized by the *max* field id
  seen, so the max field's accumulation writes one past the vector end
  (`mvm_worker.cc:43` vs `:75` — out-of-bounds UB); we use the
  configured `num_fields` and multiply only over fields present in the
  row (absent fields contribute the multiplicative identity rather than
  a hard 0);
- its hand gradient divides by `1 + v_sum` while the forward's product
  has no `1 +` (`mvm_worker.cc:153-157` vs `:202` — the `1+` variant is
  commented out at `:201`), and zero-guards inconsistently; we use the
  exact gradient via `jax.grad`;
- predict iterates `v_multi.size()` = k rows instead of the batch
  (`mvm_worker.cc:96`), truncating evaluation to 10 rows per block.

The per-(row, field) segment-sum is expressed as a one-hot einsum —
a [F, num_fields] × [F, k] batched matmul that XLA maps onto the MXU —
rather than a scatter, keeping the hot path dense and fusible.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from xflow_tpu.models.base import Model, register_model


def _table_specs(cfg):
    return {"v": (cfg.model.v_dim,)}


def forward(tables, batch, cfg):
    v = tables["v"]
    nf = cfg.model.num_fields
    mask = batch["mask"]
    vg = v[batch["slots"]] * mask[..., None]  # [B, F, k]
    onehot = (batch["fields"][..., None] == jnp.arange(nf)) * mask[..., None]  # [B, F, nf]
    # full-precision einsum: the contraction is tiny (F × nf × k) and the
    # downstream product-of-fields amplifies any bf16 rounding
    s = jnp.einsum("bfn,bfk->bnk", onehot, vg, precision=jax.lax.Precision.HIGHEST)
    present = onehot.sum(axis=1) > 0  # [B, nf]
    factors = jnp.where(present[..., None], s, 1.0)
    return jnp.prod(factors, axis=1).sum(axis=-1)  # [B]


MODEL = register_model(Model(name="mvm", table_specs=_table_specs, forward=forward))
