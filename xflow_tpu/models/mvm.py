"""Multi-view machine.

Reference: `/root/reference/src/model/mvm/mvm_worker.cc`. Per latent
dim k it sums v over the features of each libffm field ("view"):
`v_sum[k][row][fgid] += v` (`mvm_worker.cc:182-196`), takes the product
over fields (`:198-205`), sums over k (`:207-212`), and applies σ.

Reference accidents not replicated (SURVEY.md §7):
- per-row field range is `[0, max_fgid)` sized by the *max* field id
  seen, so the max field's accumulation writes one past the vector end
  (`mvm_worker.cc:43` vs `:75` — out-of-bounds UB); we use the
  configured `num_fields` and multiply only over fields present in the
  row (absent fields contribute the multiplicative identity rather than
  a hard 0);
- its hand gradient divides by `1 + v_sum` while the forward's product
  has no `1 +` (`mvm_worker.cc:153-157` vs `:202` — the `1+` variant is
  commented out at `:201`), and zero-guards inconsistently; we use the
  exact gradient via `jax.grad`;
- predict iterates `v_multi.size()` = k rows instead of the batch
  (`mvm_worker.cc:96`), truncating evaluation to 10 rows per block.

The per-(row, field) segment-sum is expressed as a one-hot einsum —
a [F, num_fields] × [F, k] batched matmul that XLA maps onto the MXU —
rather than a scatter, keeping the hot path dense and fusible.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from xflow_tpu.models.base import Model, register_model

# Exclusive-fields product path constants (see mvm_product_channels):
# LOG_TINY guards ln(0) — EXACT zeros are tracked separately in the Z
# channel, and because every formula uses ln-sums DIFFERENCES (S, or the
# exclusive S - L_j), the clamped value cancels wherever it matters.
# The S clip bounds exp: products past e^60 are a diverged model (logits
# saturate the ±30 reference sigmoid clamp long before), and below e^-87
# f32 underflows to the 0 the true product rounds to anyway.
MVM_LOG_TINY = 1e-30
MVM_LOG_CLIP = (-87.0, 60.0)


def _table_specs(cfg):
    return {"v": (cfg.model.v_dim,)}


def has_field_duplicates(fields: np.ndarray, mask: np.ndarray) -> bool:
    """Host-side check: does any row carry two masked occurrences of the
    same field? The exclusive-fields product path requires it false (the
    per-(row, field) view sum then has at most one term, so the product
    over fields equals the product over the row's occurrences). Real
    libffm CTR data is one-feature-per-field by construction; multi-
    valued fields route to the segment-sum path instead.

    Bitmask popcount when field ids fit 64 bits (~3 vector passes), else
    a per-row sort."""
    f = np.asarray(fields)
    m = np.asarray(mask) > 0
    if f.size == 0 or f.shape[1] <= 1:
        return False
    if int(f.max(initial=0)) < 64 and hasattr(np, "bitwise_count"):
        # np.bitwise_count is NumPy >= 2.0; older NumPy (still JAX-
        # supported) takes the sort path below
        bits = np.where(m, np.uint64(1) << f.astype(np.uint64), np.uint64(0))
        distinct = np.bitwise_count(np.bitwise_or.reduce(bits, axis=1))
        return bool((distinct.astype(np.int64) < m.sum(axis=1)).any())
    # wide field spaces: masked-out entries get distinct negative keys so
    # they can never form an adjacent equal pair
    keyed = np.where(m, f.astype(np.int64), -1 - np.arange(f.shape[1])[None, :])
    s = np.sort(keyed, axis=1)
    return bool(((s[:, 1:] == s[:, :-1]) & (s[:, 1:] >= 0)).any())


def resolve_mvm_product(mvm_exclusive: str, has_dup: bool, num_processes: int) -> bool:
    """Route one batch: product path (True) or segment-sum path (False).

    Callers: single-process routing (any engine) and `mvm_exclusive=on`
    everywhere. The multi-process fullshard engine does NOT call this
    under `auto` — it plans with fields and coordinates the per-batch
    choice through a rank-symmetric flag allgather
    (trainer._resolve_fullshard_overflow), so a local data-dependent
    raise can never strand peer ranks in their collectives. Under `on`
    duplicates raise by contract (the user asserted exclusive fields).
    """
    if mvm_exclusive == "off":
        return False
    if mvm_exclusive not in ("auto", "on"):
        raise ValueError(
            f"model.mvm_exclusive={mvm_exclusive!r}: expected auto|on|off"
        )
    if has_dup:
        if mvm_exclusive == "on" or num_processes > 1:
            raise ValueError(
                "MVM exclusive-fields product path: a row carries two masked "
                "occurrences of the same field. Set model.mvm_exclusive=off "
                "to use the segment-sum path"
                + (
                    " (this multi-process configuration cannot fall back per "
                    "batch: the two paths' collective sequences differ across "
                    "ranks — only the fullshard engine's `auto` coordinates "
                    "the choice. Peer ranks that hit no duplicate may block "
                    "in their collectives until the launcher's fail-fast "
                    "teardown — set mvm_exclusive=off up front)"
                    if num_processes > 1
                    else ""
                )
            )
        return False
    return True


def mvm_product_channels(occ_t_k, sorted_mask, k: int):
    """[k, Np] RAW gathered v rows + [Np] mask -> [ch, Np] channels whose
    row sums carry the per-row factor products in log space.

    With exclusive fields, Π_f s[c,r,f] = Π_{occ∈r} v_c[occ] (absent
    fields are the multiplicative identity; masked pads contribute 0 to
    every channel). Channels per latent dim: ln|v| (zeros clamped to
    ln(LOG_TINY) — the Z channel is the truth about zeros, and every
    consumer uses ln-sum DIFFERENCES so the clamp cancels), negative
    count (sign parity), exact-zero count; zero-padded to a sublane
    multiple. The row state is a cache-resident [B, ~32] array — the
    same class as FM's, replacing the [B·nf, k+1] segment aggregate that
    was the MVM step's measured wall (docs/PERF.md 3a)."""
    from xflow_tpu.ops.sorted_table import _k8

    m = sorted_mask[None, :]
    L = m * jnp.log(jnp.maximum(jnp.abs(occ_t_k), MVM_LOG_TINY))
    N = m * (occ_t_k < 0.0)
    Z = m * (occ_t_k == 0.0)
    ch = _k8(3 * k)
    pad = jnp.zeros((ch - 3 * k, occ_t_k.shape[1]), occ_t_k.dtype)
    return jnp.concatenate([L, N, Z, pad], axis=0)


def _products_from_sums(S, NC, ZC):
    """(ln-sum, negative count, zero count) -> signed products. Counts
    are integer-valued floats ≤ max_nnz, exact in f32."""
    sign = 1.0 - 2.0 * jnp.mod(NC, 2.0)
    return jnp.where(ZC > 0, 0.0, sign * jnp.exp(jnp.clip(S, *MVM_LOG_CLIP)))


def make_row_products(reduce_rows, broadcast_rows, k: int, restore_dP=None):
    """Build the exclusive-fields product op:

        op(occ_t_k [k, Np], mask [Np], rows [Np]) -> P [R, k]

    with P[r, c] = Π over r's masked occurrences of v_c — computed in
    log space through `reduce_rows` (the occurrence→row reduction:
    `row_sums_sorted` on one device; rowsum + psum_scatter + psum in the
    fullshard engine) — and a HAND-WRITTEN VJP that is exact at FTRL's
    exact zeros in both directions:

      dP/dv_j = (exclusive product of the row's OTHER factors)
              = sign_ex · exp(S - L_j) · [ZC - Z_j == 0]

    A zero occurrence keeps its nonzero reactivation gradient (the
    clamped ln cancels in S - L_j), and the other occurrences of a
    zero-containing row get EXACTLY zero — matching the oracle bitwise
    in the zero pattern, which FTRL's lazy-init parity guard (g==0 ∧
    n==0 keeps the initial weight) depends on; an epsilon-perturbation
    scheme instead leaves ~1e-34 gradient residues that mark untouched
    slots as touched. `broadcast_rows` is the bwd's row-aggregate
    transport (identity on one device; all_gather over 'data' in the
    fullshard engine — the same small-row-cotangent traffic class as
    FM's backward). `restore_dP` undoes any replication-split the
    engine's transpose applies to the incoming cotangent — the SAME
    hook, for the same reason, as make_ffm_row_op's `restore_dl`: the
    fullshard shard_map transpose hands each 'table' copy dP/T (the
    plain autodiff path restores it through owner_reduce's psum
    transpose, which a custom bwd bypasses), so the engine passes a
    psum over 'table'. None = identity (single device). This was NOT a
    theoretical hole: without the hook the fullshard product path's
    updates diverged from single-device at every T>1 (measured at
    (4,2)/(2,4)/(1,8) after 3 steps: loss 0.693127/137/143 vs
    0.693108, table maxabs err up to 7e-4 and growing with T; exact at
    (8,1)) — covered by test_sorted_fullshard's product-mode
    parametrization.
    """
    restore_dP = restore_dP or (lambda x: x)

    @jax.custom_vjp
    def op(occ_t_k, mask, rows):
        P, _ = _fwd(occ_t_k, mask, rows)
        return P

    def _fwd(occ_t_k, mask, rows):
        sums = reduce_rows(mvm_product_channels(occ_t_k, mask, k), rows)
        S, NC, ZC = sums[:, :k], sums[:, k : 2 * k], sums[:, 2 * k : 3 * k]
        P = _products_from_sums(S, NC, ZC)
        return P, (occ_t_k, mask, rows, sums)

    def _bwd(res, dP):
        occ_t_k, mask, rows, sums = res
        dP = restore_dP(dP)
        per = jnp.take(
            broadcast_rows(jnp.concatenate([dP, sums[:, : 3 * k]], axis=1)),
            rows,
            axis=0,
        ).T  # [4k, Np]
        dPo, S, NC, ZC = (per[i * k : (i + 1) * k] for i in range(4))
        m = mask[None, :]
        L = jnp.log(jnp.maximum(jnp.abs(occ_t_k), MVM_LOG_TINY))
        S_ex = S - m * L
        NC_ex = NC - m * (occ_t_k < 0.0)
        ZC_ex = ZC - m * (occ_t_k == 0.0)
        sign_ex = 1.0 - 2.0 * jnp.mod(NC_ex, 2.0)
        P_ex = jnp.where(
            ZC_ex > 0, 0.0, sign_ex * jnp.exp(jnp.clip(S_ex, *MVM_LOG_CLIP))
        )
        return dPo * P_ex * m, None, None

    op.defvjp(lambda o, m_, r: _fwd(o, m_, r), _bwd)
    return op


def _segment_row_side(occ_t, sorted_row, sorted_mask, sorted_fields,
                      rows, nf, k, plus=0.0):
    """One sub-batch's row side from raw gathered rows: one segment-sum
    keyed on `row * nf + field` → logits [rows]."""
    from xflow_tpu.ops.sorted_table import (
        segment_sum_channels,
        wire_mask,
        wire_rows,
    )

    sorted_row, sorted_mask = wire_rows(sorted_row), wire_mask(sorted_mask)
    seg = sorted_row * nf + wire_rows(sorted_fields)  # [Np]
    occm_t = occ_t[:k] * sorted_mask[None, :]
    # stack the mask as one extra channel: its segment-sum is the
    # per-(row, field) occurrence count, giving `present` in the same op
    stacked = jnp.concatenate([occm_t, sorted_mask[None, :]], axis=0)  # [k+1, Np]
    sums = segment_sum_channels(stacked, seg, rows * nf)  # [rows*nf, k+1]
    s = sums[:, :k].reshape(rows, nf, k)
    present = (sums[:, k] > 0).reshape(rows, nf)
    factors = jnp.where(present[..., None], s + plus, 1.0)  # [rows, nf, k]
    return jnp.prod(factors, axis=1).sum(axis=-1)  # [rows]


def _product_row_side(occ_t, sorted_row, sorted_mask, rows, k, plus=0.0):
    """One sub-batch's row side on the exclusive-fields product path:
    the SAME [rows, ~32] row-sum kernel FM uses — no per-(row, field)
    segment space exists at all."""
    from xflow_tpu.ops.sorted_table import row_sums_sorted, wire_mask, wire_rows

    sorted_row, sorted_mask = wire_rows(sorted_row), wire_mask(sorted_mask)
    op = make_row_products(
        lambda stacked, rows_: row_sums_sorted(stacked, rows_, rows),
        lambda arr: arr,
        k,
    )
    # plus-one form: the per-occurrence factor is (plus + v) — with
    # exclusive fields this equals the per-field (plus + s), so the
    # same exclusive-product op covers both factor forms
    P = op(occ_t[:k] + plus, sorted_mask, sorted_row)  # [rows, k]
    return P.sum(axis=1)


def _forward_sorted(tables, batch, cfg):
    """Sorted-window path (ops/sorted_table.py), two row-side forms:

    - PRODUCT (no `sorted_fields` in the batch): the host verified every
      masked (row, field) has at most one occurrence (the natural libffm
      shape; `has_field_duplicates`), so each view sum is a single v and
      the field product collapses to a product over the row's
      occurrences — computed in log space through `row_sums_sorted`'s
      cache-resident [B, ~24] accumulator, exactly like FM.
    - SEGMENT (`sorted_fields` present): general multi-valued fields via
      one segment-sum keyed on `row * num_fields + field`. Its
      [B·nf, k+1] aggregate falls out of cache at B=64k (the backward
      gather was the measured MVM wall, docs/PERF.md 3a), so sorted
      arrays may arrive STACKED [NS, Np_sub] (`plan_sorted_stacked`) and
      the ROW side maps over row-contiguous sub-batches — the table
      side runs as ONE window-major multi-buffer gather/scatter
      (`sorted_gather_map`), so the table crosses HBM once per step,
      not once per sub-batch. NS-invariant math either way.
    """
    from xflow_tpu.ops.sorted_table import sorted_gather_map

    v = tables["v"]
    bf16 = cfg.data.sorted_bf16
    plus = 1.0 if cfg.model.mvm_plus_one else 0.0
    k = cfg.model.v_dim
    B = batch["labels"].shape[0]
    if "sorted_fields" not in batch:
        return sorted_gather_map(
            v, batch, ("sorted_row", "sorted_mask"), B,
            lambda occ, sr, sm, rows: _product_row_side(occ, sr, sm, rows, k, plus),
            k, bf16,
        )
    nf = cfg.model.num_fields
    return sorted_gather_map(
        v, batch, ("sorted_row", "sorted_mask", "sorted_fields"), B,
        lambda occ, sr, sm, sf, rows: _segment_row_side(
            occ, sr, sm, sf, rows, nf, k, plus
        ),
        k, bf16,
    )


def forward(tables, batch, cfg):
    if "sorted_slots" in batch:
        return _forward_sorted(tables, batch, cfg)
    from xflow_tpu.ops.sorted_table import batch_rows

    v = tables["v"]
    nf = cfg.model.num_fields
    mask = batch["mask"]
    vg = batch_rows(v, batch, cfg.model.v_dim) * mask[..., None]
    onehot = (batch["fields"][..., None] == jnp.arange(nf)) * mask[..., None]  # [B, F, nf]
    # full-precision einsum: the contraction is tiny (F × nf × k) and the
    # downstream product-of-fields amplifies any bf16 rounding
    s = jnp.einsum("bfn,bfk->bnk", onehot, vg, precision=jax.lax.Precision.HIGHEST)
    present = onehot.sum(axis=1) > 0  # [B, nf]
    plus = 1.0 if cfg.model.mvm_plus_one else 0.0
    factors = jnp.where(present[..., None], s + plus, 1.0)
    return jnp.prod(factors, axis=1).sum(axis=-1)  # [B]


MODEL = register_model(Model(name="mvm", table_specs=_table_specs, forward=forward))
