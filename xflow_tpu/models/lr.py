"""Sparse logistic regression.

Reference: `/root/reference/src/model/lr/lr_worker.cc` — forward is
σ(Σᵢ w[fidᵢ]) per row (`calculate_loss`, `lr_worker.cc:121-143`, via a
sorted merge-join of the pulled weights against per-row keys). Here the
same contraction is one masked gather-sum, and the reference's explicit
gradient (residual scattered back per key then divided by batch size,
`lr_worker.cc:100-119`) falls out of `jax.grad` of the mean logloss.
"""

from __future__ import annotations

import jax.numpy as jnp

from xflow_tpu.models.base import Model, register_model


def _table_specs(cfg):
    return {"w": ()}


def forward(tables, batch, cfg):
    from xflow_tpu.ops.sorted_table import batch_rows

    w = tables["w"]
    # Pull ≡ gather. [B, F] weights for every feature occurrence —
    # through the host-deduped two-level gather when attached
    # (data.dedup; the reference's unique-key Pull, lr_worker.cc:150-165)
    wg = batch_rows(w, batch, 1)
    return (wg * batch["mask"]).sum(axis=-1)


MODEL = register_model(Model(name="lr", table_specs=_table_specs, forward=forward))
