"""Front-tier failover router for the serving fleet (docs/SERVING.md).

One replica is one process: one SIGKILL, one slow checkpoint swap, or
one wedged device thread is a user-visible outage. The router is the
tier that turns N replicas into one service — it speaks the SAME HTTP
protocol the replicas do (POST /predict, GET /healthz, /stats), so a
client cannot tell a fleet from a solo server, and it owns four
failure-handling jobs:

- **Health-checked membership**: a poll loop GETs every replica's
  /healthz; requests round-robin across healthy replicas only.
- **Circuit breaking**: `eject_failures` CONSECUTIVE failures (failed
  forwards or failed health checks) eject a replica into OPEN state —
  no traffic at all, so a dying replica cannot burn a retry per
  request. After `circuit_open_s` the next health poll is the
  HALF_OPEN probe: one probe in flight at a time, success closes the
  circuit, failure re-opens it.
- **Transparent retries + deadline**: a connect failure or 503 (the
  coalescer's documented "retry later" — serve/coalescer.py finally
  gets its retrier) is retried on a DIFFERENT replica while the
  per-request `route_deadline_ms` budget lasts; budget exhausted is an
  honest 503 back to the client.
- **Tail-latency hedging** (`route_hedge_ms` > 0): a request
  outstanding that long fires a duplicate at another healthy replica
  and the first answer wins — the classic p99 amputation for one
  replica mid-GC/mid-reload.

Everything is socket-level std-lib (http.client / ThreadingHTTPServer)
and clock-injectable; tests drive the breaker and the routing against
fake replicas with no checkpoint anywhere (tests/test_serve_fleet.py).
Telemetry rides the same kind="serve" stream as the replicas: event
records (circuit_open / circuit_close / hedge / drain / fleet_start /
fleet_final), stamped rank=-1 like the launcher watchdog.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from collections import deque
from typing import Callable, Optional

from xflow_tpu.jsonl import JsonlAppender
from xflow_tpu.tracing import (
    FORCE_HEADER,
    PARENT_HEADER,
    TRACE_HEADER,
    Tracer,
    clean_id,
    new_id,
)

# circuit states (docs/SERVING.md "Fleet failure matrix")
CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitBreaker:
    """Per-replica consecutive-failure breaker.

    CLOSED: traffic flows; `fail_threshold` CONSECUTIVE failures ->
    OPEN. OPEN: `allow()` is False until `open_s` elapsed, then the
    breaker moves to HALF_OPEN and hands out exactly ONE probe
    permit. HALF_OPEN: probe success -> CLOSED (counters reset), probe
    failure -> OPEN again with a fresh timer; while the probe is in
    flight every other `allow()`/`allow_probe()` is False (one probe
    at a time — a thundering herd of probes IS the outage pattern the
    breaker exists to stop).

    Thread-safe; `clock` injectable (tests pin transitions without
    sleeping)."""

    def __init__(
        self,
        fail_threshold: int = 3,
        open_s: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.fail_threshold = max(int(fail_threshold), 1)
        self.open_s = float(open_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self.opened_count = 0  # lifetime OPEN transitions (telemetry)

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    def _maybe_half_open_locked(self) -> None:
        if self._state == OPEN and self._clock() - self._opened_at >= self.open_s:
            self._state = HALF_OPEN
            self._probe_inflight = False

    def allow(self) -> bool:
        """May a normal request go to this replica? Only CLOSED — the
        half-open probe is requested explicitly via allow_probe(), so
        real traffic never rides a maybe-dead replica."""
        with self._lock:
            self._maybe_half_open_locked()
            return self._state == CLOSED

    def allow_probe(self) -> bool:
        """Claim the single half-open probe permit (the health loop
        calls this; a True return MUST be followed by record_success or
        record_failure). CLOSED probes are always allowed — they are
        ordinary health checks."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and not self._probe_inflight:
                self._probe_inflight = True
                return True
            return False

    def record_success(self, probe: bool = False) -> bool:
        """Returns True when THIS success closed a non-CLOSED circuit
        (the caller emits the one matching circuit_close event). A
        plain (non-probe) success landing while OPEN is a stale
        in-flight forward launched before the trip — the breaker
        opened on fresher evidence, so recovery stays gated on the
        half-open probe instead of a straggler's 200 skipping the
        open_s hold."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == OPEN and not probe:
                return False
            closed_now = self._state != CLOSED
            self._consecutive = 0
            self._probe_inflight = False
            self._state = CLOSED
            return closed_now

    def record_failure(self, probe: bool = False) -> bool:
        """Returns True when THIS failure tripped the circuit open
        (the caller emits one circuit_open event, not one per
        failure). `probe=True` marks the health loop's sample (the
        allow_probe permit holder). The mirror of record_success's
        stale-success guard: a non-probe failure landing while OPEN or
        HALF_OPEN is a straggler forward launched before the trip —
        evidence about the OLD process — so it neither steals the
        probe permit nor restarts the open_s timer (it would push a
        recovered replica's rejoin back open_s per straggler)."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == HALF_OPEN:
                if not probe:
                    return False
                # failed probe: straight back to OPEN, fresh timer
                self._probe_inflight = False
                self._state = OPEN
                self._opened_at = self._clock()
                return False
            if self._state == OPEN:
                return False
            self._consecutive += 1
            if self._consecutive >= self.fail_threshold:
                self._state = OPEN
                self._opened_at = self._clock()
                self.opened_count += 1
                return True
            return False


class ConnectError(Exception):
    """A forward that never produced an HTTP response (connect refused,
    reset, timeout) — always retryable: the request may not even have
    reached the replica."""


class Backend:
    """One replica as the router sees it: address (mutable — a fleet
    restart keeps the port, but set_address supports movers), breaker,
    and a small keep-alive connection pool."""

    def __init__(self, idx: int, host: str, port: int,
                 breaker: Optional[CircuitBreaker] = None):
        self.idx = int(idx)
        self._lock = threading.Lock()
        self._addr = (host, int(port))
        self.breaker = breaker or CircuitBreaker()
        self._pool: deque = deque()
        self.requests = 0
        self.failures = 0
        # freshness probe cache (docs/SERVING.md "Freshness"): the
        # health loop parses each /healthz body and stores the
        # replica's reported data_freshness_s + checkpoint step here,
        # so the fleet /healthz can report min/max freshness across
        # replicas (staggered reloads make them genuinely differ).
        # None = the replica serves an unpublished checkpoint (or
        # predates the field) — it simply stays out of the fleet Δ.
        self.freshness_s: Optional[float] = None
        self.health_step: int = -1

    def note_health(self, body: bytes) -> None:
        """Cache the freshness surface of one 200 /healthz body. A
        malformed body is ignored (the probe already proved liveness;
        freshness is observability, never an ejection signal)."""
        try:
            h = json.loads(body.decode("utf-8"))
        except (UnicodeDecodeError, ValueError):
            return
        if not isinstance(h, dict):
            return
        step = h.get("step")
        if isinstance(step, int):
            self.health_step = step
        f = h.get("data_freshness_s")
        self.freshness_s = (
            float(f)
            if isinstance(f, (int, float)) and not isinstance(f, bool)
            else None
        )

    @property
    def addr(self) -> tuple:
        with self._lock:
            return self._addr

    def set_address(self, host: str, port: int) -> None:
        with self._lock:
            if (host, int(port)) != self._addr:
                self._addr = (host, int(port))
                # stale sockets point at the old address
                while self._pool:
                    try:
                        self._pool.popleft().close()
                    except Exception:
                        pass

    def _get_conn(self, timeout: float) -> http.client.HTTPConnection:
        with self._lock:
            if self._pool:
                conn = self._pool.popleft()
                conn.timeout = timeout
                if conn.sock is not None:
                    conn.sock.settimeout(timeout)
                return conn
            host, port = self._addr
        return http.client.HTTPConnection(host, port, timeout=timeout)

    def _put_conn(self, conn: http.client.HTTPConnection) -> None:
        with self._lock:
            if len(self._pool) < 8:
                self._pool.append(conn)
                return
        try:
            conn.close()
        except Exception:
            pass

    def request(
        self,
        method: str,
        path: str,
        body: Optional[bytes] = None,
        headers: Optional[dict] = None,
        timeout: float = 5.0,
    ) -> tuple[int, bytes]:
        """One HTTP round trip to this replica. Returns (status, body);
        raises ConnectError when no response arrived (retryable by
        construction). The breaker is NOT touched here — routing policy
        decides what counts as a failure (a 400 is the client's
        problem, not the replica's)."""
        conn = self._get_conn(timeout)
        try:
            conn.request(method, path, body, headers or {})
            resp = conn.getresponse()
            data = resp.read()
        except Exception as e:
            try:
                conn.close()
            except Exception:
                pass
            # a connect-level failure means every pooled keep-alive
            # socket to this replica is suspect (a SIGKILLed replica
            # leaves up to pool-size dead sockets; each one would burn
            # a half-open probe and re-open the circuit, stalling
            # rejoin of the restarted replica by open_s per socket)
            self.close()
            raise ConnectError(f"replica {self.idx}: {type(e).__name__}: {e}")
        self._put_conn(conn)
        return resp.status, data

    def close(self) -> None:
        with self._lock:
            while self._pool:
                try:
                    self._pool.popleft().close()
                except Exception:
                    pass


class Router:
    """Health-checked round-robin failover over a set of Backends.

    `handle_predict` is socket-free (the HTTP front end in
    make_router_http_server calls it; tests call it directly)."""

    def __init__(
        self,
        backends: list,
        deadline_ms: float = 2000.0,
        retries: int = 2,
        hedge_ms: float = 0.0,
        health_poll_s: float = 0.5,
        appender: Optional[JsonlAppender] = None,
        clock: Callable[[], float] = time.monotonic,
        tracer: Optional[Tracer] = None,
    ):
        self.backends = list(backends)
        self.deadline_s = max(float(deadline_ms), 1.0) / 1e3
        self.retries = max(int(retries), 0)
        self.hedge_s = max(float(hedge_ms), 0.0) / 1e3
        self.health_poll_s = max(float(health_poll_s), 0.05)
        self._app = appender or JsonlAppender("")
        self._clock = clock
        # request tracing (docs/OBSERVABILITY.md "Request tracing"):
        # the router's spans — one per request, one per attempt/hedge
        # leg — ride its own rank=-1 stream; None/rate-0 = off, and no
        # tracing branch runs
        self.tracer = tracer
        self._rr_lock = threading.Lock()
        self._rr = 0
        self._stop = threading.Event()
        self._draining = False
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True, name="xflow-router-health"
        )
        # counters surfaced in /stats and the drain event
        self._stats_lock = threading.Lock()
        self.stats = {
            "requests": 0, "retries": 0, "hedges": 0, "hedge_wins": 0,
            "deadline_exceeded": 0, "retries_exhausted": 0,
            "no_backend": 0, "failovers": 0,
        }

    # ----------------------------------------------------------- telemetry
    def _event(self, name: str, **extra) -> None:
        self._app.append({"kind": "serve", "event": name, **extra})

    def _count(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self.stats[key] += n

    # ------------------------------------------------------------- health
    def start(self) -> None:
        self._health_thread.start()

    def _probe(self, b: Backend) -> None:
        """One health check = one breaker sample. In HALF_OPEN this IS
        the recovery probe (allow_probe gates it to one at a time)."""
        if not b.breaker.allow_probe():
            return
        try:
            status, body = b.request(
                "GET", "/healthz", timeout=min(self.health_poll_s * 4, 5.0)
            )
            ok = status == 200
            if ok:
                b.note_health(body)
        except ConnectError:
            ok = False
        if ok:
            if b.breaker.record_success(probe=True):
                self._event(
                    "circuit_close", backend=b.idx, port=b.addr[1],
                )
        else:
            tripped = b.breaker.record_failure(probe=True)
            if tripped:
                self._event(
                    "circuit_open", backend=b.idx, port=b.addr[1],
                    reason="health_check",
                )

    def _health_loop(self) -> None:
        while not self._stop.wait(self.health_poll_s):
            for b in self.backends:
                if self._stop.is_set():
                    return
                self._probe(b)

    def healthy(self) -> list:
        return [b for b in self.backends if b.breaker.allow()]

    def pick(self, exclude: Optional[set] = None) -> Optional[Backend]:
        """Round-robin over healthy backends, skipping `exclude` (the
        replicas this request already failed on). Falls back to an
        excluded-but-healthy backend when nothing else is left — one
        replica serving is better than refusing outright."""
        healthy = self.healthy()
        if not healthy:
            return None
        pool = [b for b in healthy if not exclude or b.idx not in exclude]
        if not pool:
            pool = healthy
        with self._rr_lock:
            self._rr += 1
            return pool[self._rr % len(pool)]

    # ------------------------------------------------------------- routing
    def _forward(
        self, b: Backend, body: bytes, headers: dict, timeout: float
    ) -> tuple[int, bytes]:
        b.requests += 1
        status, data = b.request(
            "POST", "/predict", body,
            {"Content-Type": "application/json", **headers},
            timeout=timeout,
        )
        return status, data

    def _try_one(
        self, b: Backend, body: bytes, headers: dict, timeout: float
    ) -> tuple[bool, int, bytes]:
        """(retryable_failure, status, data). Retryable: connect-level
        failure, 503 (shed/backlog/shutting down — 'retry later' is
        its documented meaning), or any other 5xx (the replica's
        fault, and /predict is idempotent). The breaker sees connect
        failures and non-503 5xx; a 503 ANSWER stays out of it: it
        proves the replica alive (ejecting shedding replicas would
        amplify a fleet-wide brownout into a total outage)."""
        try:
            status, data = self._forward(b, body, headers, timeout)
        except ConnectError as e:
            b.failures += 1
            if b.breaker.record_failure():
                self._event(
                    "circuit_open", backend=b.idx, port=b.addr[1],
                    reason=f"forward: {e}",
                )
            return True, 503, json.dumps({"error": str(e)}).encode()
        if status == 503:
            # the replica ANSWERED — it is alive, just shedding
            # (brownout / backlog / drain). Retry elsewhere, but keep
            # the breaker out of it: ejecting every replica under a
            # fleet-wide brownout turns load shedding into a total
            # "no healthy replica" outage for the normal-priority
            # traffic the replicas would have accepted. A genuinely
            # wedged replica still ejects via connect/timeout failures
            # and failed health checks.
            b.failures += 1
            return True, status, data
        if status >= 500:
            # a non-503 5xx is the replica FAILING the request (device
            # error, broken tables after a bad reshard) — retry
            # elsewhere and feed the breaker, so a replica whose every
            # predict 500s gets ejected instead of round-robined into
            # forever (its /healthz can still be 200: the generation
            # loaded, the device path is what's broken)
            b.failures += 1
            if b.breaker.record_failure():
                self._event(
                    "circuit_open", backend=b.idx, port=b.addr[1],
                    reason=f"http_{status}",
                )
            return True, status, data
        if b.breaker.record_success():
            # a stale HALF_OPEN-window success closed the circuit:
            # pair the earlier circuit_open event
            self._event("circuit_close", backend=b.idx, port=b.addr[1])
        return False, status, data

    def handle_predict(self, body: bytes, headers: Optional[dict] = None
                       ) -> tuple[int, bytes]:
        """Route one /predict: pick -> forward -> retry elsewhere on a
        retryable failure -> hedge when configured -> 503 when the
        deadline budget runs out. Returns (status, response bytes)."""
        headers = headers or {}
        with self._inflight_cv:
            # admission and the in-flight count move under ONE lock, so
            # drain() can never observe zero in-flight while an admitted
            # request has yet to count itself
            if self._draining:
                return 503, json.dumps({"error": "router is draining"}).encode()
            self._inflight += 1
        try:
            return self._route(body, headers)
        finally:
            with self._inflight_cv:
                self._inflight -= 1
                self._inflight_cv.notify_all()

    def _traced_leg(
        self, ctx: Optional[dict], b: Backend, body: bytes, headers: dict,
        timeout: float, leg: str,
    ) -> tuple[bool, int, bytes]:
        """One forward leg, wrapped in an `attempt` span when the
        request is traced: the leg's replica/port/outcome land in the
        span, and the replica sees X-Parent-Span (its server span
        parents here) plus X-Trace-Force on retry/hedge legs — the
        replica cannot know the ROUTER's tail verdict, so forced legs
        tell it to keep its side of the trace."""
        if ctx is None:
            return self._try_one(b, body, headers, timeout)
        tr = ctx["tr"]
        sp = tr.span(
            ctx["tid"], "attempt", parent=ctx["root"]["span"],
            backend=b.idx, port=b.addr[1], leg=leg,
        )
        hdrs = {**headers, TRACE_HEADER: ctx["tid"], PARENT_HEADER: sp["span"]}
        if leg != "primary":
            hdrs[FORCE_HEADER] = "1"
        retryable, status, data = self._try_one(b, body, hdrs, timeout)
        tr.end(sp, status=status, retryable=bool(retryable))
        return retryable, status, data

    def _route(self, body: bytes, headers: dict) -> tuple[int, bytes]:
        self._count("requests")
        tid = clean_id(headers.get(TRACE_HEADER))
        tr = self.tracer
        ctx: Optional[dict] = None
        if tr is not None and tr.enabled and tid:
            ctx = {"tr": tr, "tid": tid, "root": tr.span(tid, "request"),
                   "forced": False}
        try:
            status, data = self._route_attempts(body, headers, ctx)
        finally:
            if ctx is not None:
                rec = tr.end(ctx["root"], status=ctx.get("status", 0))
                # tail verdict: retries/hedges/errors/sheds/slow are
                # exemplars regardless of the head-sampling decision
                tr.finish(
                    tid,
                    force=ctx["forced"]
                    or ctx.get("status", 0) >= 500  # incl. 503 sheds
                    or rec["dur_ms"] / 1e3 > tr.slow_s,
                )
        return status, data

    def _route_attempts(
        self, body: bytes, headers: dict, ctx: Optional[dict]
    ) -> tuple[int, bytes]:
        def done(status: int, data: bytes) -> tuple[int, bytes]:
            if ctx is not None:
                ctx["status"] = status
            return status, data

        t0 = self._clock()
        deadline = t0 + self.deadline_s
        tried: set = set()
        last: Optional[tuple[int, bytes]] = None
        prev_idx: Optional[int] = None
        for attempt in range(self.retries + 1):
            left = deadline - self._clock()
            if left <= 0:
                break
            b = self.pick(exclude=tried)
            if b is None:
                self._count("no_backend")
                return done(503, json.dumps(
                    {"error": "no healthy replica"}
                ).encode())
            tried.add(b.idx)
            if attempt > 0:
                self._count("retries")
                if ctx is not None:
                    ctx["forced"] = True  # a retried request is a tail exemplar
                if b.idx != prev_idx:
                    # a failover is a retry that actually SWITCHED
                    # replica; pick falls back to the same one when it
                    # is the only healthy choice left
                    self._count("failovers")
            prev_idx = b.idx
            if self.hedge_s > 0 and left > self.hedge_s:
                retryable, status, data = self._try_hedged(
                    b, body, headers, left, tried, ctx,
                    first_leg="retry" if attempt > 0 else "primary",
                )
            else:
                retryable, status, data = self._traced_leg(
                    ctx, b, body, headers, left,
                    "retry" if attempt > 0 else "primary",
                )
            if not retryable:
                return done(status, data)
            last = (status, data)
        # two distinct overload signals with opposite operator fixes:
        # the budget actually expiring (deadline too small / replicas
        # too slow) vs every retry burning on a retryable failure with
        # budget to spare (fleet-wide shedding / dead replicas)
        if deadline - self._clock() <= 0:
            self._count("deadline_exceeded")
        else:
            self._count("retries_exhausted")
        if last is not None:
            return done(*last)
        return done(503, json.dumps(
            {"error": f"deadline exceeded ({self.deadline_s * 1e3:.0f}ms)"}
        ).encode())

    def _try_hedged(
        self, primary: Backend, body: bytes, headers: dict,
        timeout: float, tried: set, ctx: Optional[dict] = None,
        first_leg: str = "primary",
    ) -> tuple[bool, int, bytes]:
        """Fire at `primary`; after hedge_s with no answer, fire the
        SAME request at one more healthy replica — first non-retryable
        answer wins, a retryable one waits for the other leg. Safe
        because /predict is idempotent (pure function of the rows).
        Traced legs each get their own attempt span; a losing leg's
        span lands when its thread finishes — possibly after the
        request's verdict, the late-span path the tracer keeps."""
        import queue

        results: "queue.Queue" = queue.Queue()
        # the caller's `timeout` IS the remaining deadline budget: every
        # wait below is bounded by this absolute point, so two wedged
        # legs cost the client at most the budget, never 2x it
        t_end = self._clock() + timeout

        def leg(b: Backend, to: float, name: str = "primary") -> None:
            results.put((b, self._traced_leg(ctx, b, body, headers, to, name)))

        # a retry entering the hedged path is still a retry leg: the
        # name puts X-Trace-Force on the wire, so the replica side of
        # the retried request's trace survives its local head-drop
        threading.Thread(
            target=leg, args=(primary, timeout, first_leg), daemon=True
        ).start()
        legs = 1
        hedged = False
        try:
            got = results.get(timeout=self.hedge_s)
        except queue.Empty:
            got = None
            hedge_b = self.pick(exclude=tried)
            if hedge_b is not None:
                hedged = True
                if ctx is not None:
                    ctx["forced"] = True  # a hedged request is a tail exemplar
                tried.add(hedge_b.idx)
                self._count("hedges")
                self._event(
                    "hedge", backend=primary.idx, hedge_backend=hedge_b.idx
                )
                threading.Thread(
                    target=leg, args=(hedge_b, timeout, "hedge"), daemon=True
                ).start()
                legs += 1
        best: Optional[tuple[bool, int, bytes]] = None
        for i in range(legs):
            if got is None:
                left = t_end - self._clock()
                if left <= 0:
                    break
                try:
                    got = results.get(timeout=left)
                except queue.Empty:
                    break
            b, (retryable, status, data) = got
            got = None
            if not retryable:
                if hedged and b is not primary:
                    self._count("hedge_wins")
                return False, status, data
            best = (retryable, status, data)
        return best if best is not None else (True, 503, json.dumps(
            {"error": "hedged request timed out"}
        ).encode())

    # ------------------------------------------------------ health surface
    def health(self) -> dict:
        reps = []
        fresh: list = []
        for b in self.backends:
            rep = {
                "replica": b.idx,
                "port": b.addr[1],
                "state": b.breaker.state,
                "requests": b.requests,
                "failures": b.failures,
            }
            if b.freshness_s is not None:
                # last-probe snapshot (the poll cadence bounds its age);
                # step rides along so an operator can see WHICH
                # checkpoint the stale replica is pinned on
                rep["data_freshness_s"] = round(b.freshness_s, 3)
                rep["step"] = b.health_step
                fresh.append((b.freshness_s, b.idx))
            reps.append(rep)
        healthy = sum(1 for r in reps if r["state"] == CLOSED)
        out = {
            "ok": healthy > 0 and not self._draining,
            "router": True,
            "healthy": healthy,
            "replicas": reps,
            "draining": self._draining,
            "inflight": self._inflight,
        }
        if fresh:
            # the fleet freshness spread (docs/SERVING.md "Freshness"):
            # staggered reloads make replicas legitimately differ by up
            # to the stagger + reload time; a replica stuck FAR behind
            # the others is the failure the stalest pointer names
            out["freshness_min_s"] = round(min(f for f, _ in fresh), 3)
            out["freshness_max_s"] = round(max(f for f, _ in fresh), 3)
            out["stalest_replica"] = max(fresh)[1]
        return out

    def stats_view(self) -> dict:
        with self._stats_lock:
            return {**self.health(), "routing": dict(self.stats)}

    # --------------------------------------------------------------- drain
    def drain(self, timeout_s: float = 30.0) -> bool:
        """Deploy-style shutdown, step 1 (docs/SERVING.md "Fleet
        drain"): stop ADMITTING (new predicts get a retryable 503 — the
        LB above has already been told, this is the belt), then wait
        for every in-flight request to finish. Only AFTER this returns
        do the replicas get their SIGTERM, so an admitted request
        always finds its replica alive. Returns False when in-flight
        requests remained at timeout."""
        with self._inflight_cv:
            self._draining = True
        self._event("drain", inflight=self._inflight)
        deadline = time.monotonic() + timeout_s
        with self._inflight_cv:
            while self._inflight > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._inflight_cv.wait(min(left, 0.5))
        return True

    def close(self) -> None:
        self._stop.set()
        if self._health_thread.is_alive():
            self._health_thread.join(timeout=5.0)
        for b in self.backends:
            b.close()


def make_router_http_server(router: Router, host: str, port: int):
    """The router's client-facing HTTP server: same endpoints, same
    wire shapes as a solo replica (serve/server.py) — /predict is
    proxied with failover, /healthz and /stats report FLEET health."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from xflow_tpu.serve.server import _QuietDisconnects

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _reply(self, status: int, data: bytes, trace: str = "") -> None:
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            if trace:
                # trace-id echo: whatever id the request carried (or
                # the router minted) returns with the response
                self.send_header(TRACE_HEADER, trace)
            self.end_headers()
            self.wfile.write(data)

        def do_POST(self):  # noqa: N802
            if self.path != "/predict":
                self._reply(
                    404,
                    json.dumps({"error": f"no such endpoint {self.path!r}"}).encode(),
                )
                return
            try:
                n = int(self.headers.get("Content-Length", "0"))
            except ValueError:
                n = 0
            body = self.rfile.read(n) if n > 0 else b""
            fwd = {}
            pr = self.headers.get("X-Request-Priority")
            if pr is not None:
                fwd["X-Request-Priority"] = pr
            # trace identity (docs/OBSERVABILITY.md "Request tracing"):
            # a client-sent X-Trace-Id wins; else the router mints one
            # when tracing is on — this is the fleet's id birthplace
            tid = clean_id(self.headers.get(TRACE_HEADER))
            if not tid and router.tracer is not None and router.tracer.enabled:
                tid = new_id()
            if tid:
                fwd[TRACE_HEADER] = tid
            status, data = router.handle_predict(body, headers=fwd)
            self._reply(status, data, trace=tid)

        def do_GET(self):  # noqa: N802
            if self.path == "/healthz":
                h = router.health()
                self._reply(200 if h["ok"] else 503, json.dumps(h).encode())
            elif self.path == "/stats":
                self._reply(200, json.dumps(router.stats_view()).encode())
            else:
                self._reply(
                    404,
                    json.dumps({"error": f"no such endpoint {self.path!r}"}).encode(),
                )

        def log_message(self, fmt, *args):
            pass

    class _Server(_QuietDisconnects, ThreadingHTTPServer):
        daemon_threads = True

    return _Server((host, port), Handler)
