"""The serve runner: checkpoint-backed online prediction with hot reload.

Loads any COMMITTED checkpoint into device-resident tables and answers
pCTR batches through the SAME jitted forward the trainer's evaluate
uses (models/predict.py — one function, so offline eval and online
serving cannot drift). Three properties carry the design:

- **Reshard-on-load** (PR 5): the restore paths place every leaf onto
  whatever devices serving has, so an N-rank training checkpoint loads
  on a 1-chip serving box or a serving mesh without conversion. The
  template the restore fills is built with `jax.eval_shape` — shapes
  and shardings only, no throwaway allocation — and for npz the
  optimizer state is skipped entirely (serving never reads n/z; the
  tables-only template makes the restore read 1/3 of the bytes).

- **Hot reload, double-buffered**: a background CheckpointWatcher polls
  the run dir for a NEWER committed step and loads it OFF the request
  path; the swap is one reference assignment (`self._gen = new`).
  In-flight requests captured the previous Generation object and
  finish on the old tables; new requests see the new one. No lock is
  held across a predict, nothing blocks, nothing drops. Every response
  carries the generation + checkpoint step that answered it.

- **Bad checkpoint ≠ outage**: a reload that fails (corrupt file,
  digest mismatch, torn copy) logs + emits a `reload_failed` event and
  KEEPS SERVING the current generation — restore_any's walk-back means
  a corrupt newest step quietly restores the previous committed one,
  and the runner refuses to "reload" backwards to the step it already
  serves (docs/SERVING.md failure matrix).
"""

from __future__ import annotations

import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from xflow_tpu.config import Config


class BadRequest(ValueError):
    """A request the server answers with 400: malformed row, no
    parseable features. The serving analog of the data pipeline's
    bad-record quarantine (data/pipeline.py): reject and count the
    record, never crash the process."""


def parse_rows(rows: list, dcfg) -> tuple[list, list]:
    """Parse request rows (libffm feature lists, optional leading label
    ignored) into per-row (fields, slots) int32 arrays using the SAME
    hash path training used (data/libffm.parse_line), so a served
    feature lands in the same table slot it trained into.

    Raises BadRequest on a non-string row or a row with zero parseable
    features — the quarantine philosophy: a row whose features ALL
    failed to parse must not silently predict the bias."""
    from xflow_tpu.data.libffm import parse_line

    fields_rows, slots_rows = [], []
    for i, row in enumerate(rows):
        if not isinstance(row, str):
            raise BadRequest(f"row {i}: expected a string, got {type(row).__name__}")
        # no tab = features-only (the serving shape); a tab means the
        # client sent a full libffm line and the label is ignored
        line = row if "\t" in row else "0\t" + row
        parsed = parse_line(line, dcfg.log2_slots, dcfg.hash_salt)
        if parsed is None or parsed[2].size == 0:
            raise BadRequest(f"row {i}: no parseable field:feature tokens in {row!r}")
        _, f, s = parsed
        fields_rows.append(f)
        slots_rows.append(s)
    return fields_rows, slots_rows


@dataclass
class Generation:
    """One loaded model generation: the serving tables + provenance.

    `publication` is the checkpoint's publication.json sidecar when the
    trainer published it (train.publish_every, checkpoint
    .read_publication): the ingest trace id + timestamps that make the
    generation's DATA FRESHNESS measurable (docs/SERVING.md
    "Freshness"). None for unpublished checkpoints — every freshness
    surface (gauge, spans, /healthz field) simply stays absent, keeping
    the off-path byte-identical. `reload_span` is the span id of the
    swap that installed this generation (when a span sink is bound) —
    the parent the first-served-prediction span links under."""

    tables: dict
    step: int
    gen: int
    loaded_ts: float = field(default_factory=time.time)
    publication: Optional[dict] = None
    reload_span: str = ""

    def freshness_s(self, now: Optional[float] = None) -> Optional[float]:
        """Seconds between the served model's newest ingested row and
        `now` — the data_freshness_s gauge. None when this generation
        carries no publication (or a malformed one): absence means
        "not measurable", never a fake 0."""
        pub = self.publication
        ts = pub.get("ingest_ts") if isinstance(pub, dict) else None
        if not isinstance(ts, (int, float)) or not np.isfinite(ts):
            return None
        return max((time.time() if now is None else now) - float(ts), 0.0)


class ServeRunner:
    """Checkpoint-backed pCTR prediction (single process; the serving
    mesh is whatever local devices exist — pass `mesh` to pjit-shard
    the tables over them, None for single-device)."""

    def __init__(self, cfg: Config, mesh=None, recorder=None):
        from xflow_tpu.models import get_model
        from xflow_tpu.optim import get_optimizer

        self.cfg = cfg
        self.mesh = mesh
        self.model = get_model(cfg.model.name)
        self._optimizer = get_optimizer(cfg.optim.name)
        self._gen: Optional[Generation] = None
        self._gen_counter = 0
        self._reload_lock = threading.Lock()  # one loader at a time
        # hot-reload spans (docs/OBSERVABILITY.md "Request tracing"):
        # when serve_main binds a stamped appender here, every reload
        # swap emits one kind="span" record (start/end + bytes), so
        # request_trace --timeline can overlay swaps against latency
        # spikes. None (default) = no span, byte-identical streams.
        self.span_sink = None
        # compile accounting (train.compile_metrics): the predict
        # program routes through the same CompileRecorder seam the
        # trainer's engines use, so a serving run's stream carries its
        # kind="compile" record too (serve_main binds the sink)
        if recorder is None and cfg.train.compile_metrics:
            from xflow_tpu.telemetry import CompileRecorder

            recorder = CompileRecorder()
        self.compile_recorder = recorder
        if mesh is not None:
            from xflow_tpu.parallel.mesh import batch_sharding
            from xflow_tpu.parallel.train_step import make_sharded_eval_step

            self._predict_step = make_sharded_eval_step(
                self.model, cfg, mesh, recorder=recorder
            )
            bsh = batch_sharding(mesh)
            import jax

            self._put = lambda arrays: {
                k: jax.device_put(np.asarray(v), bsh[k]) for k, v in arrays.items()
            }
            # the batch-shape ladder is solo-only: a sharded eval step's
            # batch axis must divide the mesh, so the mesh path keeps
            # its single [max_batch] program (docs/SERVING.md)
            self.rungs = (int(cfg.serve.max_batch),)
            self._predict_steps = {self.rungs[0]: self._predict_step}
        else:
            from xflow_tpu.models.predict import make_predict_fn
            from xflow_tpu.serve.autotune import parse_ladder

            # the precompiled batch-shape ladder (serve/autotune.py):
            # one jitted program PER rung, each with its own program
            # name so compile accounting stays exactly-once per
            # (program, signature). An unconfigured ladder collapses to
            # the single "predict.serve" program — byte-identical
            # compile records to the pre-ladder build.
            self.rungs = parse_ladder(cfg.serve)
            if len(self.rungs) == 1:
                names = {self.rungs[0]: "predict.serve"}
            else:
                names = {r: f"predict.serve.b{r}" for r in self.rungs}
            self._predict_steps = {
                r: make_predict_fn(
                    self.model, cfg, recorder=recorder, name=names[r]
                )
                for r in self.rungs
            }
            self._predict_step = self._predict_steps[self.rungs[-1]]
            import jax

            self._put = jax.device_put

    # ------------------------------------------------------------- loading
    def _template(self):
        """An allocation-free restore template: the state's shapes (and
        shardings, on a mesh) as ShapeDtypeStructs. npz skips the
        optimizer state (restore() fills exactly what the template
        asks for); orbax restores the full tree (its tree-structure
        contract) and the opt arrays drop right after."""
        import jax

        from xflow_tpu.train.state import TrainState, init_state

        abstract = jax.eval_shape(
            lambda: init_state(self.model, self._optimizer, self.cfg)
        )
        if self.mesh is not None:
            from xflow_tpu.parallel.mesh import state_shardings

            sh = state_shardings(abstract, self.mesh)
            abstract = jax.tree.map(
                lambda s, shd: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=shd),
                abstract,
                sh,
            )
        if self.cfg.train.checkpoint_format != "orbax":
            abstract = TrainState(
                tables=abstract.tables, opt_state={}, step=abstract.step
            )
        return abstract

    @property
    def generation(self) -> Optional[Generation]:
        return self._gen

    @property
    def step(self) -> int:
        return self._gen.step if self._gen else -1

    def latest_committed_step(self) -> Optional[int]:
        """Newest committed step across BOTH checkpoint tiers — the
        replica (train.ckpt_replica_dir) counts, so a degraded trainer
        writing replica-only still advances the watcher."""
        from xflow_tpu.train import checkpoint as ckpt

        fmt = self.cfg.train.checkpoint_format
        dirs = [self.cfg.train.checkpoint_dir]
        rdir = self.cfg.train.ckpt_replica_dir
        if rdir and rdir not in dirs:
            dirs.append(rdir)
        steps = [s for d in dirs for s in ckpt.tier_steps(d, fmt)]
        return max(steps, default=None)

    def load(self) -> Generation:
        """Load the newest committed checkpoint (walk-back on corrupt
        newer steps, digest-verified per train.checkpoint_verify) and
        swap it in. Raises when no checkpoint loads at all — at
        STARTUP that is fatal; the watcher wraps reloads so a later
        failure never kills serving."""
        from xflow_tpu.train import checkpoint as ckpt

        with self._reload_lock:
            is_reload = self._gen is not None
            t0_wall, t0 = time.time(), time.perf_counter()
            # tiered walk: a digest-poisoned primary step restores from
            # the replica mirror before falling back to an older step —
            # serving never drops a request over one bad volume
            state, step, src = ckpt.restore_tiered(
                self.cfg.train.checkpoint_dir,
                self._template(),
                fmt=self.cfg.train.checkpoint_format,
                verify=self.cfg.train.checkpoint_verify,
                replica_dir=self.cfg.train.ckpt_replica_dir or None,
            )
            if self._gen is not None and step <= self._gen.step:
                # restore_any walked back to (or re-found) what we
                # already serve — swapping would REGRESS the generation
                raise RuntimeError(
                    f"newest loadable checkpoint is step {step}, already "
                    f"serving step {self._gen.step} — keeping the current "
                    "generation"
                )
            self._gen_counter += 1
            # publication sidecar (train.publish_every): best-effort —
            # read_publication returns None for unpublished steps and
            # logs-and-downgrades on a damaged sidecar; a publication
            # must never gate the swap itself
            # read the sidecar from the tier that actually restored
            pub = ckpt.read_publication(
                src, int(step),
                fmt=self.cfg.train.checkpoint_format,
            )
            gen = Generation(
                tables=state.tables, step=int(step), gen=self._gen_counter,
                publication=pub if isinstance(pub, dict) else None,
            )
            # the swap: one reference assignment — in-flight requests
            # hold the old Generation and finish on the old tables
            self._gen = gen
            if self.span_sink is not None:
                # the span covers restore-read through swap — exactly
                # the window a reload can lengthen request queues in
                import jax

                nbytes = int(sum(
                    x.nbytes for x in jax.tree.leaves(state.tables)
                ))
                trace = pub.get("trace") if isinstance(pub, dict) else None
                if isinstance(trace, str) and trace:
                    # a PUBLISHED step's swap CONTINUES the ingest trace
                    # (parented under the trainer's publish span) — the
                    # publish→swap edge of the freshness Δ
                    from xflow_tpu.tracing import emit_linked_span

                    rec = emit_linked_span(
                        self.span_sink,
                        "reload" if is_reload else "serve_load",
                        t0_wall,
                        time.perf_counter() - t0,
                        trace=trace,
                        parent=pub.get("span") or None,
                        step=gen.step,
                        generation=gen.gen,
                        bytes=nbytes,
                    )
                    gen.reload_span = rec["span"]
                else:
                    from xflow_tpu.tracing import emit_op_span

                    emit_op_span(
                        self.span_sink,
                        "reload" if is_reload else "serve_load",
                        t0_wall,
                        time.perf_counter() - t0,
                        step=gen.step,
                        generation=gen.gen,
                        bytes=nbytes,
                    )
            return gen

    def maybe_reload(self) -> Optional[Generation]:
        """Reload iff a COMMITTED step newer than the serving one
        exists. Returns the new Generation, or None (nothing newer, or
        the reload failed — logged, old generation keeps serving)."""
        try:
            latest = self.latest_committed_step()
            if latest is None or (self._gen and latest <= self._gen.step):
                return None
            gen = self.load()
            print(
                f"serve: hot reload: now serving step {gen.step} "
                f"(generation {gen.gen})",
                file=sys.stderr,
            )
            return gen
        except Exception as e:  # noqa: BLE001 — ANY reload failure
            # (torn copy, digest mismatch, walk-back to the serving
            # step) keeps the current generation serving
            print(
                f"serve: reload failed ({type(e).__name__}: {e}); "
                f"keeping generation {self._gen.gen if self._gen else '?'} "
                f"(step {self.step})",
                file=sys.stderr,
            )
            return None

    # ----------------------------------------------------------- predicting
    def predict(self, arrays: dict) -> tuple[np.ndarray, Generation]:
        """One device batch: row-major {slots, fields, mask, row_mask}
        -> (pctr [B] host array, the Generation that answered). The
        generation is captured ONCE before dispatch so a concurrent
        swap cannot split a batch across models."""
        gen = self._gen
        if gen is None:
            raise RuntimeError("no checkpoint loaded; call load() first")
        # ladder dispatch: the batch's leading dim picks its rung's
        # compiled program; an off-ladder shape (direct predict()
        # callers) falls back to jit's own shape specialization
        fn = self._predict_steps.get(
            int(arrays["slots"].shape[0]), self._predict_step
        )
        p = fn(gen.tables, self._put(arrays))
        return np.asarray(p), gen

    def warmup(self) -> int:
        """AOT-compile every ladder rung before traffic arrives: one
        all-padding batch per rung through the real predict path, so
        the first real request at any rung never pays its compile.
        Returns the number of rungs warmed (serve_main logs it)."""
        from xflow_tpu.serve.coalescer import assemble_batch

        if self._gen is None:
            raise RuntimeError("no checkpoint loaded; call load() first")
        for r in self.rungs:
            arrays, _ = assemble_batch([], r, self.cfg.data.max_nnz)
            self.predict(arrays)
        return len(self.rungs)

    def predict_rows(self, rows: list) -> tuple[np.ndarray, Generation]:
        """Convenience (C API / tests): parse + pad + predict a list of
        libffm feature rows, chunking by serve.max_batch so the compiled
        batch shape stays fixed. Returns (pctr [len(rows)], generation)."""
        from xflow_tpu.serve.coalescer import PendingRequest, assemble_batch

        fields_rows, slots_rows = parse_rows(rows, self.cfg.data)
        B = self.cfg.serve.max_batch
        out = np.empty((len(rows),), np.float32)
        gen = None
        for lo in range(0, len(rows), B):
            req = PendingRequest(
                fields=fields_rows[lo : lo + B], slots=slots_rows[lo : lo + B]
            )
            arrays, _ = assemble_batch([req], B, self.cfg.data.max_nnz)
            p, gen = self.predict(arrays)
            out[lo : lo + req.num_rows] = p[: req.num_rows]
        return out, gen


class CheckpointWatcher(threading.Thread):
    """Polls the checkpoint dir every `poll_s` for a newer COMMITTED
    step and hot-reloads it off the request path. `on_reload(gen)` /
    `on_failed()` hooks feed the serve telemetry stream.

    `stagger_s` (the fleet's reload stagger, docs/SERVING.md "Fleet"):
    delay acting on a NEWLY noticed step by this long. A reload pauses
    the replica's request path for the restore's read time; if every
    replica of a fleet reloads the instant a step commits, the whole
    fleet pauses at once — the one synchronized hiccup the fleet
    exists to remove. `xflow serve-fleet` gives replica k a stagger of
    k * serve.reload_stagger_s (replica 0 reloads immediately), so at
    most one replica is swapping at any moment."""

    def __init__(
        self,
        runner: ServeRunner,
        poll_s: float = 2.0,
        on_reload=None,
        on_failed=None,
        stagger_s: float = 0.0,
    ):
        super().__init__(daemon=True, name="xflow-serve-watcher")
        self._runner = runner
        self._poll = max(float(poll_s), 0.05)
        self._stagger_s = max(float(stagger_s), 0.0)
        self._stop_evt = threading.Event()
        self._on_reload = on_reload
        self._on_failed = on_failed
        self._failed_step = None  # newest step that failed to load:
        # retry only when a DIFFERENT step commits — a permanently
        # corrupt checkpoint must not re-read the whole previous
        # checkpoint from disk (and spam reload_failed) every poll
        self.reloads = 0
        self.failures = 0

    def run(self) -> None:
        while not self._stop_evt.wait(self._poll):
            try:
                latest = self._runner.latest_committed_step()
            except Exception:
                continue
            if (
                latest is None
                or latest <= self._runner.step
                or latest == self._failed_step
            ):
                continue
            if self._stagger_s > 0 and self._stop_evt.wait(self._stagger_s):
                return  # shutdown mid-stagger: skip the reload
            gen = self._runner.maybe_reload()
            if gen is not None:
                self._failed_step = None
                self.reloads += 1
                if self._on_reload:
                    self._on_reload(gen)
            else:
                self._failed_step = latest
                self.failures += 1
                if self._on_failed:
                    self._on_failed()

    def close(self) -> None:
        self._stop_evt.set()
        if self.is_alive():
            self.join(timeout=10.0)
