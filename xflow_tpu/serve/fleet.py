"""The serving fleet: N supervised replicas behind the failover router
(`xflow serve-fleet`, docs/SERVING.md "Fleet").

This is the serving analog of PR 4's supervised training launch: the
training tier's premise — no single process may take the job down —
applied to the tier that faces users. One fleet process owns:

- **N replica subprocesses**, each a plain `xflow serve` on its own
  (pre-picked, stable) port, each wrapped in its OWN supervision loop
  (launch/supervise.supervise: restart budget, exponential backoff,
  min-uptime crash-loop stop). A SIGKILLed replica relaunches with the
  NEXT restart generation stamped into every JSONL record it writes
  (XFLOW_RESTART_GEN — the PR 4 machinery verbatim), while its
  siblings keep serving: the client sees retries, not an outage.
- **Stable identity**: replica k exports XFLOW_REPLICA=k,
  XFLOW_REPLICA_PORT=<port>, XFLOW_PROCESS_ID=k under ONE shared
  XFLOW_RUN_ID, so the fleet's serve streams are distinct per replica
  and joinable per run (tools/metrics_report.py gates on it).
- **Staggered hot reload**: replica k exports XFLOW_RELOAD_STAGGER_S =
  k * serve.reload_stagger_s, so a newly committed checkpoint swaps
  through the fleet one replica at a time — never every replica paused
  on the same restore.
- **The router** (serve/router.py), in-process: health-checked
  round-robin, circuit breaking, retries, hedging — the client-facing
  port.
- **Ordered drain**: SIGTERM drains the ROUTER first (stop admitting,
  finish in-flight), and only then SIGTERMs the replicas (each drains
  its own backlog) — a deploy-style shutdown drops zero requests. The
  ordering lives in `drain_fleet` so tests pin it with fakes.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Optional

from xflow_tpu.config import Config


def _free_port(host: str = "127.0.0.1") -> int:
    s = socket.socket()
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


def replica_env(
    base: dict, idx: int, port: int, run_id: str, gen: int, stagger_s: float,
    world: int = 1,
) -> dict:
    """The env one replica attempt launches with — the fleet's whole
    identity/stagger contract in one testable place."""
    env = dict(base)
    env.update(
        XFLOW_RUN_ID=run_id,
        # rank stamp = replica index: serve streams key (run_id, rank)
        # apart without any report-tool change
        XFLOW_PROCESS_ID=str(idx),
        # the fleet's `world` = its replica count (rank < world holds in
        # metrics_report --check); serving never rendezvouses, so the
        # var only feeds the telemetry stamp here
        XFLOW_NUM_PROCESSES=str(max(int(world), 1)),
        XFLOW_RESTART_GEN=str(gen),
        XFLOW_REPLICA=str(idx),
        XFLOW_REPLICA_PORT=str(port),
        XFLOW_RELOAD_STAGGER_S=str(idx * max(stagger_s, 0.0)),
        # replicas default to CPU like launch-local's children: N serve
        # processes inheriting one ambient accelerator would fight over
        # it; real accelerator fleets opt in via XFLOW_LAUNCH_PLATFORM
        JAX_PLATFORMS=env.get("XFLOW_LAUNCH_PLATFORM", env.get("JAX_PLATFORMS", "cpu")),
    )
    return env


class ReplicaSupervisor:
    """One replica's supervision loop on its own thread.

    Each attempt: spawn `xflow serve --port <fixed>` with the fleet
    identity env, wait for the ready line (startup failure = nonzero
    attempt), then wait for exit. The port never changes across
    restarts, so the router's backend address stays valid through every
    relaunch — recovery is the health loop noticing /healthz answers
    again, no re-registration step."""

    def __init__(
        self,
        idx: int,
        port: int,
        serve_args: list,
        run_id: str,
        stagger_s: float,
        world: int = 1,
        max_restarts: int = 0,
        restart_backoff: float = 1.0,
        min_uptime_s: float = 0.0,
        log_path: str = "",
        on_ready=None,
    ):
        self.idx = int(idx)
        self.port = int(port)
        self._serve_args = list(serve_args)
        self._run_id = run_id
        self._stagger_s = stagger_s
        self._world = world
        self._max_restarts = max_restarts
        self._restart_backoff = restart_backoff
        self._min_uptime_s = min_uptime_s
        self._log_path = log_path
        self._on_ready = on_ready
        self._proc: Optional[subprocess.Popen] = None
        self._proc_lock = threading.Lock()
        self._stopping = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"xflow-fleet-replica{idx}"
        )
        self.rc: Optional[int] = None
        self.generations = 0  # attempts launched (restarts = gens - 1)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        self._thread.start()

    def _spawn(self, gen: int) -> subprocess.Popen:
        env = replica_env(
            os.environ, self.idx, self.port, self._run_id, gen,
            self._stagger_s, world=self._world,
        )
        cmd = [
            sys.executable, "-m", "xflow_tpu", "serve",
            *self._serve_args, "--port", str(self.port),
        ]
        log = (
            open(self._log_path, "a")
            if self._log_path
            else subprocess.DEVNULL
        )
        try:
            return subprocess.Popen(
                cmd, env=env, stdout=subprocess.PIPE, stderr=log, text=True
            )
        finally:
            if log is not subprocess.DEVNULL:
                log.close()  # the child holds its own fd now

    def _attempt(self, gen: int) -> int:
        if self._stopping.is_set():
            return 0  # woken out of a backoff by shutdown: no relaunch
        self.generations = gen + 1
        proc = self._spawn(gen)
        with self._proc_lock:
            self._proc = proc
        ready = None
        if proc.stdout is not None:
            # scan stdout for the ready JSON line, tolerating stray
            # non-JSON noise (a dependency warning must not read as a
            # failed startup)
            for line in proc.stdout:
                line = line.strip()
                if not line:
                    continue
                try:
                    parsed = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(parsed, dict):
                    ready = parsed
                    break
            # keep the pipe drained afterwards: a chatty child blocked
            # on a full pipe is indistinguishable from a wedged one
            threading.Thread(
                target=lambda f=proc.stdout: deque(f, maxlen=0),
                daemon=True,
                name=f"xflow-fleet-replica{self.idx}-stdout",
            ).start()
        if ready and self._on_ready:
            self._on_ready(self.idx, gen, ready)
        rc = proc.wait()
        with self._proc_lock:
            self._proc = None
        if self._stopping.is_set():
            # an exit during fleet shutdown is the shutdown, not a
            # fault — do NOT let the supervision loop relaunch it
            return 0
        return rc

    def _run(self) -> None:
        from xflow_tpu.launch.supervise import supervise

        self.rc = supervise(
            self._attempt,
            max_restarts=self._max_restarts,
            restart_backoff=self._restart_backoff,
            min_uptime_s=self._min_uptime_s,
            label=f"serve-fleet replica {self.idx}",
            # backoff sleeps must wake on shutdown or terminate() races
            # a pending relaunch
            sleep=lambda s: self._stopping.wait(s),
        )

    # ------------------------------------------------------------- shutdown
    def terminate(self, sig=signal.SIGTERM) -> None:
        """Stop supervising (no relaunch) and signal the live attempt."""
        self._stopping.set()
        with self._proc_lock:
            proc = self._proc
        if proc is not None and proc.poll() is None:
            try:
                proc.send_signal(sig)
            except OSError:
                pass

    def join(self, timeout_s: float = 30.0) -> None:
        self._thread.join(timeout=timeout_s)
        with self._proc_lock:
            proc = self._proc
        if proc is not None and proc.poll() is None:
            proc.kill()

    @property
    def alive(self) -> bool:
        with self._proc_lock:
            return self._proc is not None and self._proc.poll() is None


def drain_fleet(router, supervisors, drain_timeout_s: float = 30.0,
                out=None) -> bool:
    """THE deploy-shutdown ordering (pinned by tests): (1) router stops
    admitting and waits out every in-flight request; (2) only then the
    replicas get SIGTERM (each drains its own queued futures). A
    replica that died before its router-admitted request finished would
    turn a clean deploy into client-visible 503s — the ordering is the
    zero-drop guarantee. Returns router.drain()'s verdict."""
    err = out or sys.stderr
    print("serve-fleet: draining router (stop admitting, finish "
          "in-flight)", file=err)
    drained = router.drain(timeout_s=drain_timeout_s)
    if not drained:
        print("serve-fleet: drain timeout — in-flight requests remained",
              file=err)
    print("serve-fleet: stopping replicas", file=err)
    for sup in supervisors:
        sup.terminate()
    return drained


def fleet_main(cfg: Config, serve_args: list, run_dir: str = "",
               max_restarts: int = 0, restart_backoff: float = 1.0,
               min_uptime_s: float = 0.0, ready_out=None) -> int:
    """The `xflow serve-fleet` body: spawn N supervised replicas on
    pre-picked ports, start the router over them, print ONE ready line
    (router address + per-replica ports/pids), serve until SIGTERM/
    SIGINT, then drain router-first."""
    from xflow_tpu.jsonl import JsonlAppender
    from xflow_tpu.launch.local import resolve_launch_run_id
    from xflow_tpu.serve.router import Backend, CircuitBreaker, Router, \
        make_router_http_server

    scfg = cfg.serve
    n = int(scfg.replicas)
    if n < 1:
        print("serve-fleet: need >= 1 replica", file=sys.stderr)
        return 2
    run_id = resolve_launch_run_id()
    # the router's own appender stamps run_id/world from env like every
    # other sink; rank is pinned to -1 (control plane) explicitly
    os.environ["XFLOW_RUN_ID"] = run_id
    os.environ["XFLOW_NUM_PROCESSES"] = str(n)
    if run_dir:
        os.makedirs(run_dir, exist_ok=True)

    ports = [_free_port(scfg.host) for _ in range(n)]
    ready_info = {}
    ready_evt = threading.Event()

    def on_ready(idx: int, gen: int, ready: dict) -> None:
        if gen > 0:
            print(
                f"serve-fleet: replica {idx} rejoined (restart "
                f"generation {gen}, step {ready.get('step')})",
                file=sys.stderr,
            )
        # the FIRST ready per replica satisfies the startup gate,
        # whatever its generation — a replica that needed one
        # supervised restart to come up is a recovery, not a startup
        # failure
        if idx not in ready_info:
            ready_info[idx] = ready
            if len(ready_info) == n:
                ready_evt.set()

    supervisors = []
    for idx in range(n):
        args = list(serve_args)
        if run_dir:
            args += [
                "--metrics-path",
                os.path.join(run_dir, f"serve_replica{idx}.jsonl"),
            ]
        supervisors.append(
            ReplicaSupervisor(
                idx, ports[idx], args, run_id,
                stagger_s=scfg.reload_stagger_s,
                world=n,
                max_restarts=max_restarts,
                restart_backoff=restart_backoff,
                min_uptime_s=min_uptime_s,
                log_path=(
                    os.path.join(run_dir, f"replica{idx}.log") if run_dir else ""
                ),
                on_ready=on_ready,
            )
        )
    for sup in supervisors:
        sup.start()

    # startup gate: every replica's generation-0 ready line, or a
    # supervisor giving up (rc set) — partial fleets don't serve
    deadline = time.monotonic() + 600.0
    while not ready_evt.wait(0.2):
        if time.monotonic() > deadline or any(
            s.rc is not None and s.rc != 0 for s in supervisors
        ):
            print("serve-fleet: replicas failed to start", file=sys.stderr)
            for sup in supervisors:
                sup.terminate()
            for sup in supervisors:
                sup.join(10.0)
            return 1

    router_jsonl = (
        os.path.join(run_dir, "serve_router.jsonl") if run_dir else ""
    )
    # rank -1 = control-plane stream, the launcher-watchdog
    # convention (metrics_report exempts it from rank<world); capped
    # like the replica streams (serve.metrics_max_bytes)
    router_app = JsonlAppender(
        router_jsonl, stamp={"rank": -1, "run_id": run_id},
        max_bytes=scfg.metrics_max_bytes,
    )
    from xflow_tpu.tracing import Tracer

    router = Router(
        [
            Backend(
                idx, scfg.host, ports[idx],
                breaker=CircuitBreaker(
                    fail_threshold=scfg.eject_failures,
                    open_s=scfg.circuit_open_s,
                ),
            )
            for idx in range(n)
        ],
        deadline_ms=scfg.route_deadline_ms,
        retries=scfg.route_retries,
        hedge_ms=scfg.route_hedge_ms,
        health_poll_s=scfg.health_poll_s,
        appender=router_app,
        # request tracing: the router is where a fleet's trace ids are
        # born (docs/OBSERVABILITY.md "Request tracing"); rate 0 = off
        tracer=Tracer(
            router_app,
            sample_rate=scfg.trace_sample_rate,
            slow_ms=scfg.trace_slow_ms,
        ),
    )
    router.start()
    try:
        srv = make_router_http_server(router, scfg.host, max(scfg.port, 0))
    except Exception:
        # a router-tier failure (EADDRINUSE on the client-facing port)
        # must not orphan N replica subprocesses: their supervisor
        # threads are daemons and die with us, but the `xflow serve`
        # children are separate OS processes that would keep running
        # with nothing left to terminate them
        router.close()
        for sup in supervisors:
            sup.terminate()
        for sup in supervisors:
            sup.join(10.0)
        raise
    srv_thread = threading.Thread(target=srv.serve_forever, daemon=True)
    srv_thread.start()
    router._event("fleet_start", replicas=n,
                  ports=ports, router_port=srv.server_address[1])

    ready = {
        "serving": True,
        "fleet": True,
        "router_host": srv.server_address[0],
        "router_port": srv.server_address[1],
        "run_id": run_id,
        "pid": os.getpid(),
        "replicas": [
            {
                "replica": idx,
                "port": ports[idx],
                "step": ready_info.get(idx, {}).get("step"),
                "pid": ready_info.get(idx, {}).get("pid"),
            }
            for idx in range(n)
        ],
    }
    out = ready_out or sys.stdout
    print(json.dumps(ready), file=out, flush=True)

    stop = threading.Event()
    prev = {}

    def on_signal(signum, frame):
        stop.set()
        for s, h in prev.items():
            signal.signal(s, h)

    for s in (signal.SIGTERM, signal.SIGINT):
        prev[s] = signal.signal(s, on_signal)
    try:
        while not stop.wait(0.2):
            if all(s.rc is not None for s in supervisors):
                # every supervision loop gave up: nothing left to route
                print(
                    "serve-fleet: all replica supervisors exhausted; "
                    "shutting down",
                    file=sys.stderr,
                )
                return max(s.rc or 0 for s in supervisors) or 1
        return 0
    finally:
        drain_fleet(router, supervisors)
        srv.shutdown()
        for sup in supervisors:
            sup.join(30.0)
        router._event("fleet_final")
        router.close()
        srv.server_close()
