"""Request microbatching: the coalescing window.

A TPU answers one 256-row padded batch in roughly the time it answers
one 1-row batch — per-request dispatch wastes the device. The
MicroBatcher queues concurrent requests and releases them as ONE
group when either (a) the queued rows reach `max_rows` (size flush) or
(b) the OLDEST queued request has waited `window_s` (deadline flush) —
so an idle server adds at most one window of latency and a busy server
fills its batches. The reference's closest analog is the worker's
per-minibatch unique-key Pull (`lr_worker.cc:150-165`): amortize the
parameter-plane round trip over many rows.

Requests stay WHOLE: a group never splits a request across two device
batches (its rows would otherwise answer at two generations mid-swap).
A request larger than `max_rows` is rejected at submit — the client
splits, the server's compiled batch shape stays fixed.

Everything here is socket-free and clock-injectable: the HTTP layer
(serve/server.py) calls `submit`, the device worker calls `take`, and
the unit tests (tests/test_serve.py) drive both with a fake clock.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Callable, Optional

import numpy as np

if TYPE_CHECKING:  # config type only — no runtime import cycle
    from xflow_tpu.config import ServeConfig


class RejectedRequest(Exception):
    """A request the coalescer will not queue. `client_error` carries
    the HTTP status class explicitly (serve/server.py): True = the
    CLIENT's mistake (empty/oversized — 400, don't retry unchanged);
    False = load shedding (backlog full, brownout shed, shutting down —
    503, retry later). `shed` marks the brownout's priority shed so the
    server can count it apart from the hard backlog cliff. Either way a
    visible signal, never a crash."""

    def __init__(self, message: str, client_error: bool = False,
                 shed: bool = False):
        super().__init__(message)
        self.client_error = client_error
        self.shed = shed


@dataclass(frozen=True)
class BrownoutPolicy:
    """Admission-control thresholds (docs/SERVING.md "Brownout").

    The hard `max_queue_rows` 503 is a cliff: every submit beyond it
    fails, whatever its priority, and by the time the backlog is there
    the p99 is already blown. Brownout is the graded slope before it:
    backlog >= `high_rows` sustained `after_s` enters brownout — the
    coalescing window shrinks by `window_factor` (smaller batches,
    drained sooner) and low-priority submits shed with a retryable 503
    — and backlog <= `low_rows` sustained `after_s` exits. The
    hysteresis band (high != low) plus the sustain window keep a bursty
    backlog from flapping the mode per request."""

    high_rows: int
    low_rows: int
    after_s: float = 0.25
    window_factor: float = 0.25

    @staticmethod
    def from_config(scfg: "ServeConfig") -> "BrownoutPolicy":
        q = int(scfg.max_queue_rows)
        return BrownoutPolicy(
            high_rows=max(int(q * scfg.brownout_high_frac), 1),
            low_rows=max(int(q * scfg.brownout_low_frac), 0),
            after_s=float(scfg.brownout_after_s),
            window_factor=float(scfg.brownout_window_factor),
        )


@dataclass
class PendingRequest:
    """One queued request: ragged rows awaiting a device batch."""

    fields: list  # per-row int32 arrays
    slots: list  # per-row int32 arrays
    future: Future = field(default_factory=Future)
    t_submit: float = 0.0
    priority: int = 0  # < 0 = sheddable under brownout (request header)
    # request tracing (xflow_tpu/tracing.py): the trace id and the
    # server-side parent span id ride the queue so the device worker
    # can emit this request's queue/device spans and link them to the
    # shared device_batch span. "" = untraced (zero worker-side cost).
    trace: str = ""
    span: str = ""

    @property
    def num_rows(self) -> int:
        return len(self.slots)


class MicroBatcher:
    def __init__(
        self,
        max_rows: int,
        window_s: float,
        max_queue_rows: int = 8192,
        clock: Callable[[], float] = time.perf_counter,
        brownout: Optional[BrownoutPolicy] = None,
        on_brownout: Optional[Callable[[bool, int], None]] = None,
    ):
        if max_rows <= 0:
            raise ValueError(f"max_rows={max_rows}: need >= 1")
        self.max_rows = int(max_rows)
        self.window_s = float(window_s)
        # release target (<= max_rows): the rows that trigger a size
        # flush and cap a popped group. Distinct from max_rows — the
        # autotuner moves THIS (the active ladder rung) while the
        # per-request row cap (and the compiled top-rung shape) stays
        # max_rows, so no in-flight client contract changes under it.
        self._release_rows = int(max_rows)
        self.max_queue_rows = int(max_queue_rows)
        self._clock = clock
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._q: deque = deque()
        self._queued_rows = 0
        self._closed = False
        # brownout admission control (docs/SERVING.md "Brownout"):
        # None = off (solo-server default keeps the original cliff-only
        # behavior); `on_brownout(active, queued_rows)` fires OUTSIDE
        # the lock on each mode change (telemetry events)
        self._brownout_policy = brownout
        self._on_brownout = on_brownout
        self._brownout = False
        self._over_since: Optional[float] = None
        self._under_since: Optional[float] = None

    @property
    def queued_rows(self) -> int:
        with self._lock:
            return self._queued_rows

    @property
    def brownout(self) -> bool:
        with self._lock:
            return self._brownout

    def _update_brownout_locked(self, now: float) -> Optional[bool]:
        """Advance the brownout state machine; returns the new mode on
        a transition (for the callback), else None. Hysteresis: enter
        at >= high_rows sustained after_s, exit at <= low_rows
        sustained after_s — a single burst or a single drained batch
        must not flap the mode."""
        p = self._brownout_policy
        if p is None:
            return None
        q = self._queued_rows
        if not self._brownout:
            self._under_since = None
            if q >= p.high_rows:
                if self._over_since is None:
                    self._over_since = now
                if now - self._over_since >= p.after_s:
                    self._brownout = True
                    self._over_since = None
                    return True
            else:
                self._over_since = None
        else:
            self._over_since = None
            if q <= p.low_rows:
                if self._under_since is None:
                    self._under_since = now
                if now - self._under_since >= p.after_s:
                    self._brownout = False
                    self._under_since = None
                    return False
            else:
                self._under_since = None
        return None

    def _effective_window_locked(self) -> float:
        if self._brownout and self._brownout_policy is not None:
            return self.window_s * self._brownout_policy.window_factor
        return self.window_s

    @property
    def effective_window_s(self) -> float:
        """The coalescing window currently in force — brownout shrinks
        it by window_factor. Read-only snapshot for telemetry/tracing
        (the device-batch span's flush classification must judge a
        deadline flush against the window that actually applied)."""
        with self._lock:
            return self._effective_window_locked()

    @property
    def release_rows(self) -> int:
        with self._lock:
            return self._release_rows

    # ------------------------------------------------- autotuner setters
    # (serve/autotune.py): the controller runs on the device-worker
    # thread while submit/take touch the same fields from handler
    # threads — both setters hold the lock and wake the worker, since a
    # shrink can make the oldest queued request releasable RIGHT NOW
    def set_window_s(self, window_s: float) -> None:
        with self._lock:
            self.window_s = max(float(window_s), 0.0)
            self._cv.notify_all()

    def set_release_rows(self, rows: int) -> None:
        """Move the active release rung; clamped to [1, max_rows]."""
        with self._lock:
            self._release_rows = max(1, min(int(rows), self.max_rows))
            self._cv.notify_all()

    def submit(self, fields_rows: list, slots_rows: list,
               priority: int = 0, trace: str = "", span: str = "") -> Future:
        """Queue one request's rows; returns the Future its caller
        blocks on. Raises RejectedRequest (never queues half a request)
        when the request is empty/oversized, the backlog is full, the
        batcher is closed, or brownout is shedding its priority class
        (priority < 0 while the backlog runs hot). `trace`/`span` carry
        the request's tracing identity to the device worker."""
        n = len(slots_rows)
        if n == 0:
            raise RejectedRequest("request has no rows", client_error=True)
        if n > self.max_rows:
            raise RejectedRequest(
                f"request has {n} rows > serve.max_batch={self.max_rows}; "
                "split the request",
                client_error=True,
            )
        now = self._clock()
        req = PendingRequest(
            fields=list(fields_rows), slots=list(slots_rows),
            t_submit=now, priority=int(priority), trace=trace, span=span,
        )
        flipped = None
        try:
            with self._lock:
                if self._closed:
                    raise RejectedRequest("server is shutting down")
                flipped = self._update_brownout_locked(now)
                if self._brownout and req.priority < 0:
                    raise RejectedRequest(
                        f"brownout: shedding low-priority requests "
                        f"({self._queued_rows} rows backlogged); retry later",
                        shed=True,
                    )
                if self._queued_rows + n > self.max_queue_rows:
                    raise RejectedRequest(
                        f"queue full ({self._queued_rows} rows backlogged, "
                        f"limit {self.max_queue_rows}); retry later"
                    )
                self._q.append(req)
                self._queued_rows += n
                if flipped is None:
                    # the append itself may push the backlog over the
                    # high-water line — start the sustain timer NOW, not
                    # at the next submit's pre-check
                    flipped = self._update_brownout_locked(now)
                self._cv.notify_all()
        finally:
            # the mode-change callback runs OUTSIDE the lock (it
            # appends telemetry), and fires even when this submit shed
            if flipped is not None and self._on_brownout is not None:
                self._on_brownout(flipped, self.queued_rows)
        return req.future

    def take(self, timeout: Optional[float] = None) -> Optional[list]:
        """Block until a group is releasable, then pop and return it
        ([PendingRequest]). Returns None on timeout with nothing queued,
        or when closed and drained — the device worker's exit signal.

        Release rule: queued rows >= max_rows (size flush), the oldest
        request has aged past window_s (deadline flush; under brownout
        the window shrinks by the policy's window_factor — drain the
        backlog in smaller, sooner batches), or the batcher closed
        (drain everything pending). The popped group is the longest
        whole-request prefix fitting max_rows."""
        deadline = None if timeout is None else self._clock() + timeout
        flipped = None
        with self._lock:
            while True:
                now = self._clock()
                if flipped is None:
                    flipped = self._update_brownout_locked(now)
                if self._q:
                    flush_at = self._q[0].t_submit + self._effective_window_locked()
                    if (
                        self._queued_rows >= self._release_rows
                        or now >= flush_at
                        or self._closed
                    ):
                        group = self._pop_group_locked()
                        break
                    if deadline is not None and now >= deadline:
                        group = None  # caller's timeout: window still open
                        break
                    # sleep until the window deadline (or the caller's
                    # timeout, or a submit that fills the batch)
                    wake = flush_at if deadline is None else min(flush_at, deadline)
                    self._cv.wait(max(wake - now, 0.0))
                    continue
                if self._closed:
                    group = None
                    break
                if deadline is not None:
                    left = deadline - now
                    if left <= 0:
                        group = None
                        break
                    self._cv.wait(left)
                else:
                    self._cv.wait()
        # mode changes observed here (e.g. the backlog draining below
        # low_rows) report outside the lock, same as submit's
        if flipped is not None and self._on_brownout is not None:
            self._on_brownout(flipped, self.queued_rows)
        return group

    def _pop_group_locked(self) -> list:
        # cap at the release rung, but ALWAYS pop the head request: a
        # request legitimately bigger than the current rung (but within
        # max_rows, the submit contract) releases alone and simply
        # assembles at the next rung that fits — never wedges the queue
        cap = max(self._release_rows,
                  self._q[0].num_rows if self._q else 0)
        group = []
        rows = 0
        while self._q and rows + self._q[0].num_rows <= cap:
            req = self._q.popleft()
            rows += req.num_rows
            group.append(req)
        self._queued_rows -= rows
        return group

    def close(self) -> None:
        """Stop accepting; wake the worker so it drains the backlog
        (every queued future still resolves) and then sees None."""
        with self._lock:
            self._closed = True
            self._cv.notify_all()


def assemble_batch(
    group: list, batch_size: int, max_nnz: int
) -> tuple[dict, list]:
    """Pack a group's ragged rows into ONE padded row-major batch.

    Returns (arrays, spans): `arrays` is the {slots, fields, mask,
    row_mask} dict the predict step consumes — fixed [batch_size,
    max_nnz] shape so the jitted program compiles ONCE — and `spans`
    is [(request, start, stop)] mapping each request back to its row
    slice of the pctr output. Rows longer than max_nnz truncate to a
    deterministic prefix (the training parser's contract,
    data/schema.make_batch); padding rows are fully masked.
    """
    slots = np.zeros((batch_size, max_nnz), dtype=np.int32)
    fields = np.zeros((batch_size, max_nnz), dtype=np.int32)
    mask = np.zeros((batch_size, max_nnz), dtype=np.float32)
    row_mask = np.zeros((batch_size,), dtype=np.float32)
    spans = []
    i = 0
    for req in group:
        start = i
        for rf, rs in zip(req.fields, req.slots):
            k = min(len(rs), max_nnz)
            slots[i, :k] = rs[:k]
            fields[i, :k] = rf[:k]
            mask[i, :k] = 1.0
            row_mask[i] = 1.0
            i += 1
        spans.append((req, start, i))
    if i > batch_size:
        raise ValueError(f"group rows {i} > batch_size {batch_size} (bug)")
    return (
        {"slots": slots, "fields": fields, "mask": mask, "row_mask": row_mask},
        spans,
    )
