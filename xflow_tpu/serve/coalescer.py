"""Request microbatching: the coalescing window.

A TPU answers one 256-row padded batch in roughly the time it answers
one 1-row batch — per-request dispatch wastes the device. The
MicroBatcher queues concurrent requests and releases them as ONE
group when either (a) the queued rows reach `max_rows` (size flush) or
(b) the OLDEST queued request has waited `window_s` (deadline flush) —
so an idle server adds at most one window of latency and a busy server
fills its batches. The reference's closest analog is the worker's
per-minibatch unique-key Pull (`lr_worker.cc:150-165`): amortize the
parameter-plane round trip over many rows.

Requests stay WHOLE: a group never splits a request across two device
batches (its rows would otherwise answer at two generations mid-swap).
A request larger than `max_rows` is rejected at submit — the client
splits, the server's compiled batch shape stays fixed.

Everything here is socket-free and clock-injectable: the HTTP layer
(serve/server.py) calls `submit`, the device worker calls `take`, and
the unit tests (tests/test_serve.py) drive both with a fake clock.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np


class RejectedRequest(Exception):
    """A request the coalescer will not queue. `client_error` carries
    the HTTP status class explicitly (serve/server.py): True = the
    CLIENT's mistake (empty/oversized — 400, don't retry unchanged);
    False = load shedding (backlog full, shutting down — 503, retry
    later). Either way a visible signal, never a crash."""

    def __init__(self, message: str, client_error: bool = False):
        super().__init__(message)
        self.client_error = client_error


@dataclass
class PendingRequest:
    """One queued request: ragged rows awaiting a device batch."""

    fields: list  # per-row int32 arrays
    slots: list  # per-row int32 arrays
    future: Future = field(default_factory=Future)
    t_submit: float = 0.0

    @property
    def num_rows(self) -> int:
        return len(self.slots)


class MicroBatcher:
    def __init__(
        self,
        max_rows: int,
        window_s: float,
        max_queue_rows: int = 8192,
        clock: Callable[[], float] = time.perf_counter,
    ):
        if max_rows <= 0:
            raise ValueError(f"max_rows={max_rows}: need >= 1")
        self.max_rows = int(max_rows)
        self.window_s = float(window_s)
        self.max_queue_rows = int(max_queue_rows)
        self._clock = clock
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._q: deque = deque()
        self._queued_rows = 0
        self._closed = False

    @property
    def queued_rows(self) -> int:
        with self._lock:
            return self._queued_rows

    def submit(self, fields_rows: list, slots_rows: list) -> Future:
        """Queue one request's rows; returns the Future its caller
        blocks on. Raises RejectedRequest (never queues half a request)
        when the request is empty/oversized, the backlog is full, or
        the batcher is closed."""
        n = len(slots_rows)
        if n == 0:
            raise RejectedRequest("request has no rows", client_error=True)
        if n > self.max_rows:
            raise RejectedRequest(
                f"request has {n} rows > serve.max_batch={self.max_rows}; "
                "split the request",
                client_error=True,
            )
        req = PendingRequest(
            fields=list(fields_rows), slots=list(slots_rows),
            t_submit=self._clock(),
        )
        with self._lock:
            if self._closed:
                raise RejectedRequest("server is shutting down")
            if self._queued_rows + n > self.max_queue_rows:
                raise RejectedRequest(
                    f"queue full ({self._queued_rows} rows backlogged, "
                    f"limit {self.max_queue_rows}); retry later"
                )
            self._q.append(req)
            self._queued_rows += n
            self._cv.notify_all()
        return req.future

    def take(self, timeout: Optional[float] = None) -> Optional[list]:
        """Block until a group is releasable, then pop and return it
        ([PendingRequest]). Returns None on timeout with nothing queued,
        or when closed and drained — the device worker's exit signal.

        Release rule: queued rows >= max_rows (size flush), the oldest
        request has aged past window_s (deadline flush), or the batcher
        closed (drain everything pending). The popped group is the
        longest whole-request prefix fitting max_rows."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._lock:
            while True:
                now = self._clock()
                if self._q:
                    flush_at = self._q[0].t_submit + self.window_s
                    if (
                        self._queued_rows >= self.max_rows
                        or now >= flush_at
                        or self._closed
                    ):
                        return self._pop_group_locked()
                    if deadline is not None and now >= deadline:
                        return None  # caller's timeout: window still open
                    # sleep until the window deadline (or the caller's
                    # timeout, or a submit that fills the batch)
                    wake = flush_at if deadline is None else min(flush_at, deadline)
                    self._cv.wait(max(wake - now, 0.0))
                    continue
                if self._closed:
                    return None
                if deadline is not None:
                    left = deadline - now
                    if left <= 0:
                        return None
                    self._cv.wait(left)
                else:
                    self._cv.wait()

    def _pop_group_locked(self) -> list:
        group = []
        rows = 0
        while self._q and rows + self._q[0].num_rows <= self.max_rows:
            req = self._q.popleft()
            rows += req.num_rows
            group.append(req)
        self._queued_rows -= rows
        return group

    def close(self) -> None:
        """Stop accepting; wake the worker so it drains the backlog
        (every queued future still resolves) and then sees None."""
        with self._lock:
            self._closed = True
            self._cv.notify_all()


def assemble_batch(
    group: list, batch_size: int, max_nnz: int
) -> tuple[dict, list]:
    """Pack a group's ragged rows into ONE padded row-major batch.

    Returns (arrays, spans): `arrays` is the {slots, fields, mask,
    row_mask} dict the predict step consumes — fixed [batch_size,
    max_nnz] shape so the jitted program compiles ONCE — and `spans`
    is [(request, start, stop)] mapping each request back to its row
    slice of the pctr output. Rows longer than max_nnz truncate to a
    deterministic prefix (the training parser's contract,
    data/schema.make_batch); padding rows are fully masked.
    """
    slots = np.zeros((batch_size, max_nnz), dtype=np.int32)
    fields = np.zeros((batch_size, max_nnz), dtype=np.int32)
    mask = np.zeros((batch_size, max_nnz), dtype=np.float32)
    row_mask = np.zeros((batch_size,), dtype=np.float32)
    spans = []
    i = 0
    for req in group:
        start = i
        for rf, rs in zip(req.fields, req.slots):
            k = min(len(rs), max_nnz)
            slots[i, :k] = rs[:k]
            fields[i, :k] = rf[:k]
            mask[i, :k] = 1.0
            row_mask[i] = 1.0
            i += 1
        spans.append((req, start, i))
    if i > batch_size:
        raise ValueError(f"group rows {i} > batch_size {batch_size} (bug)")
    return (
        {"slots": slots, "fields": fields, "mask": mask, "row_mask": row_mask},
        spans,
    )
