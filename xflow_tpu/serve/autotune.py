"""Closed-loop SLO autotuning: serve telemetry drives the coalescer.

PR 9 built the measurement half of serving observability — every
flushed kind="serve" window decomposes the latency budget into
queue-wait (coalescing delay) vs device (predict step) p50/p99. This
module closes the loop: `AutotuneController` consumes each flushed
window and steers the coalescer toward `serve.slo_p99_ms`, the same
design lesson the reference's async workers carry (a fixed global
cadence cannot match a changing load — the batching cadence must
adapt):

- **queue-wait dominates while over the SLO** -> the coalescing window
  is the latency: shrink `window_ms` (multiplicative, damped).
- **device dominates while over the SLO** -> the batch shape is the
  latency: step the active ladder rung DOWN (smaller padded batches).
- **device dominates while under the SLO** -> there is headroom to
  amortize: grow `window_ms` (bigger batches, fewer device calls),
  after restoring any previously lowered rung.
- **inside the hysteresis band** -> no decision. The band plus
  step-size damping (every direction reversal halves the knob's step)
  makes the controller converge instead of flapping.
- **unattainable SLO** -> the controller pins at the window floor and
  emits ONE `floor_pinned` warning decision, then stays quiet until
  load changes direction (docs/SERVING.md failure matrix).

The batch-shape ladder (`parse_ladder`/`pick_rung`) is the second half
of the tentpole: instead of one padded `[max_batch, max_nnz]` program,
`serve.ladder` names a rung set (e.g. "16,64,256") that the runner
AOT-compiles at startup (one CompileRecorder program per rung, so the
exactly-once compile gate stays green per rung) and each device batch
flushes at the smallest rung that fits — small batches stop paying
full-batch padding, and the controller can move the release rung.

Everything is clock-injectable and socket-free: the device worker
(serve/server.py) feeds `observe()` the window records ServeMetrics
returns from `maybe_flush`, applies the returned decisions to the
MicroBatcher, and ships each as a stamped kind="autotune" JSONL record
plus an operational span (visible in `request_trace.py --timeline`,
audited by `metrics_report --check`). `/stats` serves `state()`.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import TYPE_CHECKING, Callable, Optional

if TYPE_CHECKING:  # config type only — no runtime import cycle
    from xflow_tpu.config import ServeConfig

# the knob vocabulary (metrics_report --check rejects records naming
# any other knob; keep docs/OBSERVABILITY.md in sync)
AUTOTUNE_KNOBS = ("window_ms", "rung")

# decision reasons (documented in docs/OBSERVABILITY.md; the --health
# verdict reads floor_pinned as the unattainable-SLO signal)
REASONS = (
    "queue_dominated",   # over SLO, queue-wait dominates: window shrinks
    "device_dominated",  # over SLO, device dominates: rung steps down
    "device_headroom",   # under SLO, device dominates: window grows
    "rung_restore",      # under SLO: a previously lowered rung steps up
    "floor_pinned",      # over SLO at the window floor: pin + ONE warning
)

# damping never erases a knob's step entirely — a later load change
# must still be able to move it
MIN_STEP_FRAC = 0.02


def parse_ladder(scfg: "ServeConfig") -> tuple:
    """`serve.ladder` ("16,64,256") -> ascending rung tuple.

    Rungs above `serve.max_batch` clamp to it; `serve.max_batch` always
    joins as the top rung (the compiled shape every request is promised
    to fit); "" (default) = the single max_batch rung — exactly the
    pre-ladder behavior. Raises ValueError on a non-positive or
    non-integer rung: a typo'd ladder must fail startup, not serve."""
    top = int(scfg.max_batch)
    rungs = {top}
    text = str(scfg.ladder).strip()
    if text:
        for tok in text.split(","):
            tok = tok.strip()
            if not tok:
                continue
            try:
                r = int(tok)
            except ValueError:
                raise ValueError(
                    f"serve.ladder: rung {tok!r} is not an integer"
                ) from None
            if r <= 0:
                raise ValueError(f"serve.ladder: rung {r} must be >= 1")
            rungs.add(min(r, top))
    return tuple(sorted(rungs))


def pick_rung(n_rows: int, rungs: tuple) -> int:
    """The smallest rung that fits `n_rows` (the top rung otherwise —
    the batcher never releases a group beyond max_batch rows)."""
    for r in rungs:
        if n_rows <= r:
            return r
    return rungs[-1]


@dataclass(frozen=True)
class Decision:
    """One knob move: `knob` steps `old` -> `new` because `reason`.
    `old == new` only for the floor_pinned warning (the pin itself is
    the information; the knob did not move)."""

    knob: str
    old: float
    new: float
    reason: str


class AutotuneController:
    """The SLO controller. `observe(window)` -> [Decision] runs on the
    device-worker thread (serve/server.py applies the decisions);
    `state()` snapshots for `/stats` on HTTP handler threads — the lock
    covers exactly that cross-thread read. `clock` is injectable so
    tests script time like the MicroBatcher's."""

    def __init__(
        self,
        scfg: "ServeConfig",
        rungs: Optional[tuple] = None,
        clock: Callable[[], float] = time.perf_counter,
    ):
        self.slo_ms = float(scfg.slo_p99_ms)
        if self.slo_ms <= 0:
            raise ValueError(
                f"serve.slo_p99_ms={self.slo_ms}: the autotuner needs a "
                "positive latency target"
            )
        self.band_frac = max(float(scfg.autotune_band_frac), 0.0)
        self.min_window_ms = max(float(scfg.autotune_min_window_ms), 0.0)
        # the growth ceiling is derived, not another knob: a coalescing
        # delay equal to the whole p99 budget is already unserveable
        self.max_window_ms = max(self.slo_ms, self.min_window_ms)
        self.rungs = tuple(rungs) if rungs else parse_ladder(scfg)
        self._clock = clock
        self._lock = threading.Lock()
        self.window_ms = float(scfg.window_ms)
        self.rung = self.rungs[-1]
        step0 = min(max(float(scfg.autotune_step_frac), MIN_STEP_FRAC), 0.9)
        self._step = {"window_ms": step0, "rung": step0}
        self._last_dir = {"window_ms": 0, "rung": 0}
        self._floor_warned = False
        self.windows_seen = 0
        self.decision_count = 0
        self._last_decision_t: Optional[float] = None

    # ------------------------------------------------------------ policy
    def _damped(self, knob: str, direction: int) -> float:
        """Advance the knob's damping state and return its current
        step fraction: a direction reversal halves the step (floored),
        a same-direction move keeps it — overshoots decay."""
        prev = self._last_dir[knob]
        if prev != 0 and prev != direction:
            self._step[knob] = max(self._step[knob] * 0.5, MIN_STEP_FRAC)
        self._last_dir[knob] = direction
        return self._step[knob]

    def _rung_step(self, up: bool) -> Optional[Decision]:
        i = self.rungs.index(self.rung)
        j = i + 1 if up else i - 1
        if j < 0 or j >= len(self.rungs):
            return None
        old, self.rung = self.rung, self.rungs[j]
        self._damped("rung", 1 if up else -1)
        return Decision(
            knob="rung", old=float(old), new=float(self.rung),
            reason="rung_restore" if up else "device_dominated",
        )

    def observe(self, window: dict) -> list:
        """One flushed kind="serve" window record -> the decisions it
        justifies (possibly empty). The caller applies them to the
        batcher and ships the telemetry."""
        p99 = window.get("total_p99_ms")
        qw = window.get("queue_wait_p99_ms")
        dev = window.get("device_p99_ms")
        if p99 is None or qw is None or dev is None:
            return []  # a window without latency evidence steers nothing
        with self._lock:
            self.windows_seen += 1
            decisions = self._steer_locked(float(p99), float(qw), float(dev))
            if decisions:
                self.decision_count += len(decisions)
                self._last_decision_t = self._clock()
            return decisions

    def _steer_locked(self, p99: float, qw: float, dev: float) -> list:
        hi = self.slo_ms * (1.0 + self.band_frac)
        lo = self.slo_ms * (1.0 - self.band_frac)
        if p99 > hi:
            if qw >= dev:
                return self._shrink_window_locked()
            d = self._rung_step(up=False)
            if d is not None:
                return [d]
            # already at the bottom rung: the window is the only lever
            return self._shrink_window_locked()
        if p99 < lo:
            # headroom: restore a previously lowered rung first (the
            # cheap, exactly-reversible move), then amortize the device
            if self.rung != self.rungs[-1]:
                d = self._rung_step(up=True)
                return [d] if d is not None else []
            if dev >= qw:
                return self._grow_window_locked()
        return []  # inside the hysteresis band: converged, hold

    def _shrink_window_locked(self) -> list:
        if self.window_ms <= self.min_window_ms:
            if self._floor_warned:
                return []  # pinned: warn once, never flap
            self._floor_warned = True
            v = self.window_ms
            return [Decision(knob="window_ms", old=v, new=v,
                             reason="floor_pinned")]
        step = self._damped("window_ms", -1)
        old = self.window_ms
        self.window_ms = max(old * (1.0 - step), self.min_window_ms)
        return [Decision(knob="window_ms", old=old, new=self.window_ms,
                         reason="queue_dominated")]

    def _grow_window_locked(self) -> list:
        if self.window_ms >= self.max_window_ms:
            return []
        step = self._damped("window_ms", +1)
        old = self.window_ms
        self.window_ms = min(old * (1.0 + step), self.max_window_ms)
        # growth means the floor episode (if any) ended: a NEW
        # unattainable stretch warns again
        self._floor_warned = False
        return [Decision(knob="window_ms", old=old, new=self.window_ms,
                         reason="device_headroom")]

    # ------------------------------------------------------------- state
    def state(self) -> dict:
        """Live controller state for `GET /stats` (and tests)."""
        with self._lock:
            last = self._last_decision_t
            return {
                "slo_p99_ms": self.slo_ms,
                "band_frac": self.band_frac,
                "window_ms": round(self.window_ms, 4),
                "min_window_ms": self.min_window_ms,
                "rung": self.rung,
                "rungs": list(self.rungs),
                "windows_seen": self.windows_seen,
                "decisions": self.decision_count,
                "floor_pinned": self._floor_warned,
                "step_frac": {k: round(v, 4)
                              for k, v in self._step.items()},
                "since_last_decision_s": (
                    round(self._clock() - last, 3)
                    if last is not None else None
                ),
            }
