"""HTTP / unix-socket front end + the device worker loop.

Request path (docs/SERVING.md):

    HTTP handler thread: parse JSON -> parse rows (same hash path as
      training) -> MicroBatcher.submit -> block on the request Future
    device worker thread: MicroBatcher.take (coalescing window) ->
      assemble ONE padded batch -> ServeRunner.predict -> scatter pctr
      slices + generation provenance back to every request's Future

One device batch per coalescing window, whatever the concurrency — the
microbatching contract. The handler threads only parse and wait; the
single worker thread owns the device, so predict calls never interleave
and the jitted program compiles exactly once (fixed [max_batch,
max_nnz] shape).

Failure semantics: malformed body/rows -> 400 with the reason (the
quarantine philosophy — reject the record, never crash the server);
backlog full / shutdown -> 503 (load shedding is explicit); an
unexpected predict error fails ONLY the futures of that batch (500),
the worker keeps going. `GET /healthz` reports generation/step;
`GET /stats` snapshots the telemetry registry.
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import threading
import time
from concurrent.futures import TimeoutError as FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from xflow_tpu.config import Config
from xflow_tpu.serve.autotune import AutotuneController, pick_rung
from xflow_tpu.serve.coalescer import (
    BrownoutPolicy,
    MicroBatcher,
    RejectedRequest,
    assemble_batch,
)
from xflow_tpu.serve.metrics import ServeMetrics
from xflow_tpu.serve.runner import BadRequest, CheckpointWatcher, ServeRunner, parse_rows
from xflow_tpu.tracing import (
    FORCE_HEADER,
    PARENT_HEADER,
    TRACE_HEADER,
    Tracer,
    clean_id,
    emit_op_span,
    new_id,
)

# request-priority header (docs/SERVING.md "Brownout"): "low" marks a
# request sheddable under sustained backlog; anything else (or absence)
# is normal priority. Header-based so retrying proxies/the router can
# forward it untouched.
PRIORITY_HEADER = "X-Request-Priority"


def parse_priority(value: Optional[str]) -> int:
    """Header value -> internal priority: < 0 shed under brownout."""
    return -1 if value is not None and value.strip().lower() == "low" else 0


def _freshness(gen) -> Optional[float]:
    """Duck-typed: a generation without the freshness surface (test
    fakes, pre-publication runners) reads as not-measurable, never an
    error — absence of the gauge is the documented off state."""
    fn = getattr(gen, "freshness_s", None)
    return fn() if callable(fn) else None


class ServeApp:
    """Wires runner + batcher + metrics + the device worker thread.
    Socket-free by itself (tests drive `handle_predict` directly); the
    HTTP servers below call into it."""

    def __init__(self, cfg: Config, runner: ServeRunner, metrics: Optional[ServeMetrics] = None):
        self.cfg = cfg
        self.runner = runner
        scfg = cfg.serve
        self.metrics = metrics or ServeMetrics(
            scfg.metrics_path, every_s=scfg.metrics_every_s,
            batch_size=scfg.max_batch, max_bytes=scfg.metrics_max_bytes,
        )
        # request tracing (docs/OBSERVABILITY.md "Request tracing"):
        # spans ride the same stamped serve stream; rate 0 = off, and
        # the handler/worker paths skip every tracing branch
        self.tracer = Tracer(
            self.metrics.appender,
            sample_rate=scfg.trace_sample_rate,
            slow_ms=scfg.trace_slow_ms,
        )

        def on_brownout(active: bool, queued_rows: int) -> None:
            # the admission-control timeline rides the serve stream
            # (kind="serve" events, like reload/reload_failed)
            self.metrics.event(
                "brownout_enter" if active else "brownout_exit",
                queued_rows=queued_rows,
            )

        self.batcher = MicroBatcher(
            max_rows=scfg.max_batch,
            window_s=scfg.window_ms / 1e3,
            max_queue_rows=scfg.max_queue_rows,
            brownout=BrownoutPolicy.from_config(scfg),
            on_brownout=on_brownout,
        )
        # the batch-shape ladder + SLO controller (serve/autotune.py):
        # each flushed metrics window the worker loop feeds the
        # controller steers window_ms / the release rung toward
        # serve.slo_p99_ms. Off (default) = no controller object, no
        # autotune records/spans, rung == max_batch everywhere — the
        # stream stays byte-identical to a pre-autotune build.
        self._rungs = tuple(getattr(runner, "rungs", ())) or (
            int(scfg.max_batch),
        )
        self.autotuner = (
            AutotuneController(scfg, rungs=self._rungs)
            if scfg.autotune
            else None
        )
        self._timeout_s = scfg.request_timeout_s
        self._stop = threading.Event()
        self._worker = threading.Thread(
            target=self._worker_loop, daemon=True, name="xflow-serve-device"
        )
        # chaos-drill injectors (testing/faults.serve_faults_from_env):
        # resolved ONCE here — zero per-batch cost when unset
        from xflow_tpu.testing.faults import serve_faults_from_env

        self._fault_delay_s, self._fault_kill_batches = serve_faults_from_env()
        self._batches_served = 0
        # first-served-prediction marker (docs/SERVING.md "Freshness"):
        # the newest generation a batch has ANSWERED with — the worker
        # emits one serve_first span when it advances, closing the
        # ingest -> ... -> served-prediction trace
        self._first_served_gen = -1
        self.t_start = time.perf_counter()

    def start(self) -> None:
        self._worker.start()

    # ------------------------------------------------------- device worker
    def _worker_loop(self) -> None:
        cfg = self.cfg
        while True:
            group = self.batcher.take(timeout=0.1)
            if group is None:
                if self._stop.is_set():
                    return
                # idle tick: windows still flush on schedule (and a
                # window that flushes here still steers the controller)
                gen = self.runner.generation
                if gen is not None:
                    self._autotune(self.metrics.maybe_flush(
                        gen.gen, gen.step, freshness_s=_freshness(gen),
                    ))
                continue
            t_batch = time.perf_counter()
            if self._fault_delay_s > 0:
                # slow-replica injector: the device "runs slow" without
                # real overload — circuit/hedge drills use this
                time.sleep(self._fault_delay_s)
            # flush at the smallest precompiled rung that fits — small
            # batches stop paying full-max_batch padding (the single
            # unconfigured rung IS max_batch, the pre-ladder shape)
            rung = pick_rung(sum(r.num_rows for r in group), self._rungs)
            try:
                arrays, spans = assemble_batch(
                    group, rung, cfg.data.max_nnz
                )
                # predict's np.asarray readback IS the device sync: the
                # worker (not the handler threads) pays the batch's
                # device time, shared by all its requests
                p, gen = self.runner.predict(arrays)
            except Exception as e:  # noqa: BLE001 — fail THIS batch's
                # futures, keep the worker alive for the next window
                for req in group:
                    if not req.future.done():
                        req.future.set_exception(e)
                continue
            t_done = time.perf_counter()
            device_s = t_done - t_batch
            if gen.gen != self._first_served_gen:
                # first answered batch of a new generation: the
                # swap-to-first-serve edge of the freshness Δ
                self._first_served_gen = gen.gen
                self._first_serve_span(gen)
            self._trace_batch(group, spans, t_batch, t_done, gen, rung)
            queue_waits, totals = [], []
            n_rows = 0
            for req, lo, hi in spans:
                queue_waits.append(t_batch - req.t_submit)
                totals.append(t_done - req.t_submit)
                n_rows += hi - lo
                req.future.set_result(
                    {
                        "pctr": [float(x) for x in p[lo:hi]],
                        "generation": gen.gen,
                        "step": gen.step,
                        "queue_ms": round((t_batch - req.t_submit) * 1e3, 3),
                        "total_ms": round((t_done - req.t_submit) * 1e3, 3),
                    }
                )
            self.metrics.observe_batch(
                len(group), n_rows, queue_waits, device_s, totals,
                batch_size=rung,
            )
            self._autotune(self.metrics.maybe_flush(
                gen.gen, gen.step, freshness_s=_freshness(gen),
            ))
            self._batches_served += 1
            if (
                self._fault_kill_batches
                and self._batches_served >= self._fault_kill_batches
            ):
                # chaos drill: SIGKILL after the Nth answered batch — a
                # replica dying MID-LOAD with responses in flight (its
                # supervised relaunch inherits the env generation-gated,
                # so it survives; testing/faults.hard_kill)
                from xflow_tpu.testing.faults import hard_kill

                hard_kill()

    # ----------------------------------------------------------- autotune
    def _autotune(self, window: Optional[dict]) -> None:
        """Feed one flushed metrics window to the SLO controller and
        apply + publish its decisions. Every decision ships as a
        stamped kind="autotune" record (the audit trail metrics_report
        gates) plus an operational span carrying the same knob move, so
        `request_trace --timeline` overlays the controller's actions on
        the latency spans they caused. No-op when autotune is off or
        the window didn't flush."""
        if window is None or self.autotuner is None:
            return
        t0_wall, t0 = time.time(), time.perf_counter()
        for d in self.autotuner.observe(window):
            if d.knob == "window_ms" and d.new != d.old:
                self.batcher.set_window_s(d.new / 1e3)
            elif d.knob == "rung" and d.new != d.old:
                self.batcher.set_release_rows(int(d.new))
            self.metrics.appender.append({
                "kind": "autotune",
                "knob": d.knob,
                "old": round(d.old, 4),
                "new": round(d.new, 4),
                "reason": d.reason,
                "slo_p99_ms": self.autotuner.slo_ms,
                "total_p99_ms": window["total_p99_ms"],
                "queue_wait_p99_ms": window["queue_wait_p99_ms"],
                "device_p99_ms": window["device_p99_ms"],
                "batch_fill": window["batch_fill"],
            })
            emit_op_span(
                self.metrics.appender, "autotune", t0_wall,
                time.perf_counter() - t0,
                knob=d.knob, old=round(d.old, 4), new=round(d.new, 4),
                reason=d.reason,
            )

    # ------------------------------------------------------------- tracing
    def _first_serve_span(self, gen) -> None:
        """One `serve_first` span per model generation, emitted when its
        FIRST batch answers: carries the publication's ingest trace id
        (parented under the reload swap span), so
        tools/freshness_report.py can close the ingested-row ->
        served-prediction loop at the exact instant predictions from
        the new data became externally visible. Silent (byte-identical
        streams) without a span sink or a published checkpoint."""
        sink = self.runner.span_sink
        pub = getattr(gen, "publication", None)
        if sink is None or not isinstance(pub, dict):
            return
        trace = pub.get("trace")
        if not isinstance(trace, str) or not trace:
            return
        from xflow_tpu.tracing import emit_linked_span

        emit_linked_span(
            sink, "serve_first", time.time(), 0.0,
            trace=trace,
            parent=getattr(gen, "reload_span", None) or pub.get("span") or None,
            step=gen.step, generation=gen.gen,
        )

    def _trace_batch(self, group, spans, t_batch, t_done, gen, rung) -> None:
        """Emit the shared device_batch span + each traced member's
        queue/device spans (the batch-membership link: N request trees
        reference ONE batch span by id). Zero-cost when tracing is off
        or no member request carries a trace."""
        tr = self.tracer
        if not tr.enabled:
            return
        traced = [(req, lo, hi) for req, lo, hi in spans if req.trace]
        if not traced:
            return
        n_rows = sum(hi - lo for _, lo, hi in spans)
        # flush reason: the oldest member aging past the (possibly
        # brownout-shrunk) window means a deadline flush; otherwise the
        # backlog filled the batch (size flush / close drain)
        oldest_wait = t_batch - min(req.t_submit for req, _, _ in spans)
        flush = (
            "window"
            if oldest_wait >= 0.95 * self.batcher.effective_window_s
            else "size"
        )
        bid = new_id()
        batch_rec = {
            "kind": "span",
            "trace": traced[0][0].trace,
            "span": bid,
            "name": "device_batch",
            "t0": round(tr.wall(t_batch), 6),
            "dur_ms": round((t_done - t_batch) * 1e3, 3),
            "requests": len(spans),
            "rows": n_rows,
            # fill against the rung this batch actually shipped at (the
            # single unconfigured rung is max_batch — same value)
            "batch_fill": round(n_rows / max(rung, 1), 4),
            "flush": flush,
            "generation": gen.gen,
        }
        tr.add_shared(batch_rec, [req.trace for req, _, _ in traced])
        for req, lo, hi in traced:
            tr.add(req.trace, {
                "kind": "span", "trace": req.trace, "span": new_id(),
                "parent": req.span, "name": "queue",
                "t0": round(tr.wall(req.t_submit), 6),
                "dur_ms": round((t_batch - req.t_submit) * 1e3, 3),
                "rows": hi - lo,
            })
            tr.add(req.trace, {
                "kind": "span", "trace": req.trace, "span": new_id(),
                "parent": req.span, "name": "device",
                "t0": round(tr.wall(t_batch), 6),
                "dur_ms": round((t_done - t_batch) * 1e3, 3),
                "batch": bid,
            })

    # ----------------------------------------------------------- app logic
    def handle_predict(
        self,
        body: bytes,
        priority: int = 0,
        trace_id: str = "",
        parent_span: str = "",
        force_trace: bool = False,
    ) -> tuple[int, dict]:
        """(http_status, response dict) for one POST /predict body:
        {"rows": ["field:feat field:feat ...", ...]}. `priority` < 0
        (the X-Request-Priority: low header) marks the request
        sheddable under brownout. `trace_id`/`parent_span`/`force_trace`
        carry the X-Trace-Id / X-Parent-Span / X-Trace-Force headers:
        with tracing on, the request's server/parse/queue/device spans
        buffer under the trace and flush on its verdict (head-sampled,
        router-forced, or tail-captured here: error/shed/slow)."""
        tr = self.tracer if (self.tracer.enabled and trace_id) else None
        if tr is None:
            return self._predict_impl(body, priority)
        root = tr.span(trace_id, "server", parent=parent_span or None)
        status, payload = self._predict_impl(
            body, priority, tr=tr, trace_id=trace_id, root=root
        )
        rec = tr.end(root, status=status)
        # tail capture: errors, sheds, and slow requests are exemplars
        # whatever the sampling verdict (docs/OBSERVABILITY.md)
        tr.finish(
            trace_id,
            force=force_trace or status != 200
            or rec["dur_ms"] / 1e3 > tr.slow_s,
        )
        return status, payload

    def _predict_impl(
        self, body: bytes, priority: int = 0, tr=None, trace_id: str = "",
        root=None,
    ) -> tuple[int, dict]:
        try:
            payload = json.loads(body.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            self.metrics.observe_bad_request()
            return 400, {"error": f"body is not JSON: {e}"}
        rows = payload.get("rows") if isinstance(payload, dict) else None
        if not isinstance(rows, list) or not rows:
            self.metrics.observe_bad_request()
            return 400, {"error": 'expected {"rows": [<libffm feature row>, ...]}'}
        t_parse = time.perf_counter()
        try:
            fields_rows, slots_rows = parse_rows(rows, self.cfg.data)
        except BadRequest as e:
            self.metrics.observe_bad_request()
            return 400, {"error": str(e)}
        if tr is not None:
            tr.add(trace_id, {
                "kind": "span", "trace": trace_id, "span": new_id(),
                "parent": root["span"], "name": "parse",
                "t0": round(tr.wall(t_parse), 6),
                "dur_ms": round((time.perf_counter() - t_parse) * 1e3, 3),
                "rows": len(rows),
            })
        try:
            fut = self.batcher.submit(
                fields_rows, slots_rows, priority=priority,
                trace=trace_id if tr is not None else "",
                span=root["span"] if tr is not None else "",
            )
        except RejectedRequest as e:
            if e.shed:
                # brownout shed is ADMISSION telemetry, not a bad
                # request: its own counter, still a retryable 503
                self.metrics.observe_shed()
                return 503, {"error": str(e)}
            self.metrics.observe_bad_request()
            # oversized request is the CLIENT's error; backlog/shutdown
            # is load shedding (the exception carries the class)
            return (400 if e.client_error else 503), {"error": str(e)}
        try:
            return 200, fut.result(timeout=self._timeout_s)
        except FutureTimeout:
            return 503, {"error": f"timed out after {self._timeout_s}s"}
        except Exception as e:  # noqa: BLE001 — a failed batch reports
            # its reason to the client instead of a hung connection
            return 500, {"error": f"{type(e).__name__}: {e}"}

    def health(self) -> dict:
        gen = self.runner.generation
        out = {
            "ok": gen is not None,
            "generation": gen.gen if gen else 0,
            "step": gen.step if gen else -1,
            "queued_rows": self.batcher.queued_rows,
            "brownout": self.batcher.brownout,
            "uptime_s": round(time.perf_counter() - self.t_start, 3),
        }
        fresh = _freshness(gen)
        if fresh is not None:
            # present only for published checkpoints, so unpublished
            # fleets keep the pre-freshness /healthz shape (the router
            # probe and its fleet min/max read this field)
            out["data_freshness_s"] = round(fresh, 3)
        return out

    def stats(self) -> dict:
        from xflow_tpu.telemetry import default_registry

        out = {**self.health(), "registry": default_registry().snapshot()}
        if self.autotuner is not None:
            # live controller state (docs/SERVING.md "Autotuning"):
            # absent entirely when autotune is off, so off-mode /stats
            # responses stay shape-identical to a pre-autotune build
            out["autotune"] = self.autotuner.state()
        return out

    def close(self) -> None:
        """Graceful: stop intake, drain the backlog (every queued
        future resolves), stop the worker, flush metrics."""
        self.batcher.close()
        self._stop.set()
        if self._worker.is_alive():
            self._worker.join(timeout=30.0)
        gen = self.runner.generation
        self.metrics.close(
            gen.gen if gen else -1,
            gen.step if gen else -1,
            freshness_s=_freshness(gen),
        )


def _make_handler(app: ServeApp):
    class Handler(BaseHTTPRequestHandler):
        # serving answers many short requests; HTTP/1.1 keep-alive makes
        # the loadgen's closed loop connection-reuse instead of
        # connect-per-request
        protocol_version = "HTTP/1.1"
        # buffered wfile: headers + body leave in ONE segment (the
        # stdlib default wbufsize=0 writes them separately, and Nagle
        # holds the body until the headers are ACKed — with the peer's
        # delayed ACK that is a ~40 ms stall per response on loopback)
        wbufsize = -1

        def setup(self):
            super().setup()
            try:
                self.connection.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
            except OSError:
                pass  # AF_UNIX transport: no Nagle to disable

        def _reply(self, status: int, payload: dict, trace: str = "") -> None:
            data = json.dumps(payload).encode("utf-8")
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            if trace:
                # the trace-id echo: every response returns the id the
                # request carried (serve_bench asserts the round trip)
                self.send_header(TRACE_HEADER, trace)
            self.end_headers()
            self.wfile.write(data)

        def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler contract
            if self.path != "/predict":
                self._reply(404, {"error": f"no such endpoint {self.path!r}"})
                return
            try:
                n = int(self.headers.get("Content-Length", "0"))
            except ValueError:
                n = 0
            body = self.rfile.read(n) if n > 0 else b""
            # trace identity: a client-sent X-Trace-Id wins; with
            # tracing on, a direct (router-less) client gets one minted
            # here — the id is echoed either way, sampled only when
            # tracing is on
            tid = clean_id(self.headers.get(TRACE_HEADER))
            if not tid and app.tracer.enabled:
                tid = new_id()
            status, payload = app.handle_predict(
                body,
                priority=parse_priority(self.headers.get(PRIORITY_HEADER)),
                trace_id=tid,
                parent_span=clean_id(self.headers.get(PARENT_HEADER)),
                force_trace=self.headers.get(FORCE_HEADER) == "1",
            )
            self._reply(status, payload, trace=tid)

        def do_GET(self):  # noqa: N802
            if self.path == "/healthz":
                h = app.health()
                self._reply(200 if h["ok"] else 503, h)
            elif self.path == "/stats":
                self._reply(200, app.stats())
            else:
                self._reply(404, {"error": f"no such endpoint {self.path!r}"})

        def log_message(self, fmt, *args):  # quiet: telemetry JSONL is
            pass  # the record of traffic, not per-request stderr lines

        def address_string(self):
            # AF_UNIX client addresses are ''/b'' — BaseHTTPRequestHandler
            # indexes client_address[0], which only works for AF_INET
            try:
                return super().address_string()
            except (IndexError, TypeError):
                return "unix"

    return Handler


class _QuietDisconnects:
    """A client dropping its keep-alive connection mid-read is normal
    load-balancer/loadgen behavior, not a server error — suppress the
    default stderr traceback for exactly that; real errors still print."""

    def handle_error(self, request, client_address):
        import sys as _sys

        exc = _sys.exc_info()[1]
        if isinstance(exc, (ConnectionResetError, BrokenPipeError, TimeoutError)):
            return
        super().handle_error(request, client_address)


class _TCPHTTPServer(_QuietDisconnects, ThreadingHTTPServer):
    daemon_threads = True


def make_http_server(app: ServeApp, host: str, port: int) -> ThreadingHTTPServer:
    """TCP server (port 0 = pick free; read .server_address back)."""
    return _TCPHTTPServer((host, port), _make_handler(app))


class _UnixHTTPServer(
    _QuietDisconnects, socketserver.ThreadingMixIn, socketserver.TCPServer
):
    """HTTP over AF_UNIX: same handler, same wire protocol — the
    colocated-client path (the reference's C API embeds in a native
    ranking server; a unix socket skips the TCP stack for it)."""

    address_family = socket.AF_UNIX
    allow_reuse_address = True
    daemon_threads = True

    def server_bind(self):
        # a stale socket file from a dead server would EADDRINUSE
        if os.path.exists(self.server_address):
            os.unlink(self.server_address)
        super().server_bind()

    def get_request(self):
        request, _ = super().get_request()
        # BaseHTTPRequestHandler formats client_address[0]; give it a
        # stable shape for unix peers
        return request, ("unix", 0)


def make_unix_server(app: ServeApp, path: str) -> _UnixHTTPServer:
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    return _UnixHTTPServer(path, _make_handler(app))


def serve_main(cfg: Config, mesh=None, ready_out=None) -> int:
    """The `xflow serve` body: load -> watch -> serve until SIGTERM/
    SIGINT. `ready_out` (a file object; default stdout) gets ONE JSON
    line once the sockets are listening — scripts wait on it and read
    the bound port back (serve.port=0 picks a free one)."""
    import signal
    import sys

    runner = ServeRunner(cfg, mesh=mesh)
    gen = runner.load()  # startup: no checkpoint IS fatal
    app = ServeApp(cfg, runner)
    if runner.compile_recorder is not None:
        # compile records join the serve stream (the predict program
        # compiles lazily on the first batch, after this bind)
        runner.compile_recorder.bind(app.metrics.appender)
    if app.tracer.enabled:
        # hot-reload swaps emit kind="span" records into the same
        # stream (request_trace --timeline overlays them); off when
        # tracing is off so rate-0 streams stay byte-identical
        runner.span_sink = app.metrics.appender
    if cfg.serve.autotune or len(getattr(runner, "rungs", ())) > 1:
        # AOT-compile the whole ladder BEFORE the ready line: the
        # controller must be able to move the rung without the first
        # batch at a new shape paying its compile on the latency path.
        # Unladdered autotune-off servers keep the lazy first-batch
        # compile, byte-identical to the pre-ladder build.
        n = runner.warmup()
        print(f"serve: precompiled {n} ladder rung(s)", file=sys.stderr)
    app.metrics.event("start", generation=gen.gen, step=gen.step)
    try:
        # the fleet's staggered-reload offset (serve/fleet.py exports
        # replica k's share; solo servers have no stagger)
        stagger_s = float(os.environ.get("XFLOW_RELOAD_STAGGER_S", 0) or 0)
    except ValueError:
        stagger_s = 0.0
    watcher = CheckpointWatcher(
        runner,
        poll_s=cfg.serve.reload_poll_s,
        on_reload=lambda g: app.metrics.event(
            "reload", generation=g.gen, step=g.step
        ),
        on_failed=lambda: app.metrics.event("reload_failed"),
        stagger_s=stagger_s,
    )
    app.start()
    watcher.start()

    servers = []
    threads = []
    if cfg.serve.port >= 0:
        http = make_http_server(app, cfg.serve.host, cfg.serve.port)
        servers.append(http)
    if cfg.serve.unix_socket:
        servers.append(make_unix_server(app, cfg.serve.unix_socket))
    if not servers:
        print("serve: nothing to listen on (serve.port=-1 and no "
              "serve.unix_socket)", file=sys.stderr)
        return 2
    for srv in servers:
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        threads.append(t)

    ready = {
        "serving": True,
        "step": gen.step,
        "generation": gen.gen,
        "pid": os.getpid(),
    }
    if cfg.serve.port >= 0:
        ready["host"], ready["port"] = servers[0].server_address[:2]
    if cfg.serve.unix_socket:
        ready["unix_socket"] = cfg.serve.unix_socket
    out = ready_out or sys.stdout
    print(json.dumps(ready), file=out, flush=True)

    stop = threading.Event()
    prev = {}

    def on_signal(signum, frame):
        stop.set()
        for s, h in prev.items():
            signal.signal(s, h)  # second signal kills normally

    for s in (signal.SIGTERM, signal.SIGINT):
        prev[s] = signal.signal(s, on_signal)
    try:
        while not stop.wait(0.2):
            pass
    finally:
        print("serve: shutting down (draining queued requests)", file=sys.stderr)
        for srv in servers:
            srv.shutdown()
        watcher.close()
        app.close()
        for srv in servers:
            srv.server_close()
        if cfg.serve.unix_socket and os.path.exists(cfg.serve.unix_socket):
            try:
                os.unlink(cfg.serve.unix_socket)
            except OSError:
                pass
    return 0
