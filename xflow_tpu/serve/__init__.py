"""Online serving: sharded inference over committed checkpoints.

`xflow_tpu serve` (launch/cli.py cmd_serve) loads any COMMITTED
checkpoint — reshard-on-load places the tables onto whatever devices
serving has — and answers pCTR queries over HTTP / unix socket with
request microbatching (serve/coalescer.py) and hot model reload
(serve/runner.py CheckpointWatcher). docs/SERVING.md has the
architecture and the knob reference.
"""

from xflow_tpu.serve.coalescer import MicroBatcher, RejectedRequest, assemble_batch
from xflow_tpu.serve.runner import CheckpointWatcher, ServeRunner, parse_rows

__all__ = [
    "MicroBatcher",
    "RejectedRequest",
    "assemble_batch",
    "CheckpointWatcher",
    "ServeRunner",
    "parse_rows",
]
