"""Serving telemetry: kind="serve" JSONL windows + reload events.

Plugs into the same registry/appender plumbing training uses
(xflow_tpu/telemetry.py, xflow_tpu/jsonl.py): every record is stamped
ts/rank/run_id/gen/world by the shared appender and kind="serve" keys
the stream, so one run dir can hold training metrics, heartbeats, AND
serving windows and tools/metrics_report.py tells them apart.

Window records (one per `every_s`, only when traffic flowed) carry the
serving SLO view: QPS, rows/s, batch-fill ratio (how well the
coalescer amortizes the device), and the latency decomposition —
queue-wait (coalescing delay), device (predict step), total
(submit -> response ready) p50/p99. `generation`/`step` carry the
newest model generation this sink has recorded at flush time — a
monotone high-water mark shared with the event path, so a window
flushed right after a reload event can never stamp the pre-swap
pair (the stream stays monotone in file order even though the
watcher and metrics threads race). Event records ({"event": "reload"|
"reload_failed"|"start"|"final"}) mark the hot-reload timeline.
docs/OBSERVABILITY.md documents the schema; metrics_report --check
gates it (all-or-none keys, monotone generation).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from xflow_tpu.jsonl import JsonlAppender
from xflow_tpu.telemetry import Registry, default_registry

# the key set every serve window record carries (metrics_report --check
# enforces all-or-none via its SERVE_KEYS copy of this tuple; keep
# docs/OBSERVABILITY.md in sync)
SERVE_WINDOW_KEYS = (
    "requests",
    "rows",
    "qps",
    "rows_per_s",
    "batches",
    "batch_fill",
    "queue_wait_p50_ms",
    "queue_wait_p99_ms",
    "device_p50_ms",
    "device_p99_ms",
    "total_p50_ms",
    "total_p99_ms",
    "window_s",
    "bad_requests",
    "shed_requests",
    "generation",
    "step",
)
# optional window key (the OPTIONAL_SERVE_KEYS convention in
# metrics_report): present only while the served generation carries a
# publication sidecar (train.publish_every) — seconds between the
# model's newest ingested row and the flush (docs/SERVING.md
# "Freshness"). Absent = not measurable, never a fake 0.
SERVE_FRESHNESS_KEY = "data_freshness_s"


class ServeMetrics:
    """Thread-safe window aggregator -> JSONL sink. `observe_batch`
    runs on the device-worker thread, `observe_bad_request` on HTTP
    handler threads, `event` on the watcher thread."""

    def __init__(
        self,
        path: str = "",
        every_s: float = 5.0,
        batch_size: int = 1,
        registry: Optional[Registry] = None,
        max_bytes: int = 0,
    ):
        # lazy rank/run_id stamp; max_bytes (serve.metrics_max_bytes)
        # rolls long-running fleets' streams instead of growing forever
        self._app = JsonlAppender(path, stamp=None, max_bytes=max_bytes)
        self._kind = {"kind": "serve"}
        self._every = max(float(every_s), 0.05)
        self._batch_size = max(int(batch_size), 1)
        self._reg = registry or default_registry()
        self._lock = threading.Lock()
        self._win_start = time.perf_counter()
        # monotone high-water mark of (generation, step) across EVERY
        # record this sink emitted: the reload event (watcher thread)
        # and the window flush (metrics thread) race on the appender,
        # and a window computed against a pre-swap snapshot must not
        # land AFTER the reload event stamped with the pre-swap pair —
        # metrics_report --check reads the stream in file order and
        # gates generation/step monotonicity per restart generation
        self._seen_gen = -1
        self._seen_step = -1
        self._reset_window_locked()

    @property
    def appender(self) -> JsonlAppender:
        """The underlying stamped sink — serve_main binds the compile
        recorder to it so kind="compile" records join this stream."""
        return self._app

    def _reset_window_locked(self) -> None:
        self._requests = 0
        self._rows = 0
        self._batches = 0
        self._capacity = 0  # sum of per-batch padded shapes (ladder rungs)
        self._bad = 0
        self._shed = 0
        self._queue_waits: list = []
        self._device: list = []
        self._totals: list = []

    # ------------------------------------------------------------ observing
    def observe_batch(
        self,
        n_requests: int,
        n_rows: int,
        queue_waits_s: list,
        device_s: float,
        totals_s: list,
        batch_size: Optional[int] = None,
    ) -> None:
        """`batch_size` is the PADDED shape this batch shipped at — the
        ladder rung (serve/autotune.py). None (the pre-ladder callers)
        falls back to the constructor's fixed batch size, so batch_fill
        keeps meaning rows/padded-capacity either way."""
        with self._lock:
            self._requests += n_requests
            self._rows += n_rows
            self._batches += 1
            self._capacity += int(batch_size) if batch_size else self._batch_size
            self._queue_waits.extend(queue_waits_s)
            self._device.append(device_s)
            self._totals.extend(totals_s)
        self._reg.counter("serve.requests").inc(n_requests)
        self._reg.counter("serve.rows").inc(n_rows)
        self._reg.counter("serve.batches").inc()

    def observe_bad_request(self) -> None:
        with self._lock:
            self._bad += 1
        self._reg.counter("serve.bad_requests").inc()

    def observe_shed(self) -> None:
        """A brownout priority shed (admission control) — counted apart
        from bad_requests: a shed is the SERVER's choice under load, a
        retry-later signal, not a malformed or cliff-rejected request."""
        with self._lock:
            self._shed += 1
        self._reg.counter("serve.shed_requests").inc()

    def _advance_seen_locked(self, generation, step) -> tuple:
        """Fold (generation, step) into the high-water mark and return
        the folded pair. The pair moves together: a newer model
        generation carries its own step; within one generation the
        runner never regresses the step."""
        if generation is not None and int(generation) > self._seen_gen:
            self._seen_gen = int(generation)
            self._seen_step = int(step) if step is not None else self._seen_step
        elif step is not None and int(generation or -1) == self._seen_gen:
            self._seen_step = max(self._seen_step, int(step))
        return self._seen_gen, self._seen_step

    def event(self, name: str, **extra) -> None:
        """Append an event record immediately (reload timeline). Held
        under the window lock so the high-water fold and the append are
        one atomic step relative to `maybe_flush`."""
        with self._lock:
            self._advance_seen_locked(extra.get("generation"), extra.get("step"))
            self._app.append({**self._kind, "event": name, **extra})

    # ------------------------------------------------------------- flushing
    def maybe_flush(
        self,
        generation: int,
        step: int,
        force: bool = False,
        freshness_s: Optional[float] = None,
    ) -> Optional[dict]:
        """Emit a window record when the window elapsed (or `force`) and
        traffic flowed; returns the record (tests) or None.
        `freshness_s` (Generation.freshness_s) adds the optional
        data_freshness_s key — None (unpublished checkpoint) leaves the
        record byte-identical to a pre-freshness build."""
        now = time.perf_counter()
        with self._lock:
            elapsed = now - self._win_start
            if not force and elapsed < self._every:
                return None
            if self._batches == 0 and self._bad == 0 and self._shed == 0:
                self._win_start = now  # idle window: emit nothing
                return None
            pct = lambda xs, q: (
                round(float(np.percentile(np.asarray(xs) * 1e3, q)), 3)
                if xs
                else None
            )
            rec = {
                **self._kind,
                "requests": self._requests,
                "rows": self._rows,
                "qps": round(self._requests / max(elapsed, 1e-9), 2),
                "rows_per_s": round(self._rows / max(elapsed, 1e-9), 1),
                "batches": self._batches,
                "batch_fill": round(self._rows / max(self._capacity, 1), 4),
                "queue_wait_p50_ms": pct(self._queue_waits, 50),
                "queue_wait_p99_ms": pct(self._queue_waits, 99),
                "device_p50_ms": pct(self._device, 50),
                "device_p99_ms": pct(self._device, 99),
                "total_p50_ms": pct(self._totals, 50),
                "total_p99_ms": pct(self._totals, 99),
                "window_s": round(elapsed, 3),
                "bad_requests": self._bad,
                "shed_requests": self._shed,
            }
            # stamp the high-water (generation, step): the caller's pair
            # is a snapshot that may predate a reload event already in
            # the file; the append stays under the lock so no fresher
            # event can slip in between the fold and the write
            g, s = self._advance_seen_locked(generation, step)
            rec["generation"], rec["step"] = g, s
            if freshness_s is not None:
                rec[SERVE_FRESHNESS_KEY] = round(max(float(freshness_s), 0.0), 3)
            self._reset_window_locked()
            self._win_start = now
            self._app.append(rec)
        self._reg.gauge("serve.qps").set(rec["qps"])
        if rec["batches"]:
            self._reg.gauge("serve.batch_fill").set(rec["batch_fill"])
        if freshness_s is not None:
            self._reg.gauge("serve.data_freshness_s").set(
                rec[SERVE_FRESHNESS_KEY]
            )
        return rec

    def close(
        self,
        generation: int = -1,
        step: int = -1,
        freshness_s: Optional[float] = None,
    ) -> None:
        self.maybe_flush(generation, step, force=True, freshness_s=freshness_s)
        self._app.append({**self._kind, "event": "final"})
        self._app.close()
