"""Fault injectors for the resilience subsystem (docs/ROBUSTNESS.md).

One shared library drives every recovery path end-to-end — the tier-1
fault-injection tests (tests/test_fault_injection.py) and the operator
CLI (tools/corrupt_ckpt.py) call the SAME functions, so what the tests
prove recoverable is exactly what an operator can rehearse against a
real checkpoint dir:

- `poison_nan_batches`: wrap a Trainer so chosen steps' labels become
  NaN — the non-finite guard's trigger (`train.nonfinite_guard`).
- `truncate_file` / `bitflip_file`: byte-level corruption primitives.
- `corrupt_npz_checkpoint` / `corrupt_orbax_checkpoint`: apply them to
  the newest (or a chosen) checkpoint — the self-healing restore's
  trigger (`checkpoint.restore_any`).
- `write_malformed_libffm`: shards mixing good rows with junk labels,
  feature-less lines, separators-only lines, and a truncated final
  line — the bad-record quarantine's trigger (`data.max_bad_rows`) and
  the counter/parser parity tests' input.
- `kill_step_from_env` / `hard_kill`: env-gated SIGKILL at step K
  (generation-gated so a supervised relaunch survives) and
  `abort_after_step`: the in-process crash analog — the elastic
  recovery layer's triggers (supervised auto-restart + exact data
  resume, docs/ROBUSTNESS.md "Elastic recovery").
- `serve_faults_from_env`: the serving-fleet chaos injectors — a
  per-batch delay (slow replica: circuit-breaker/hedging drills) and a
  kill-after-N-batches SIGKILL (replica dying mid-load; generation-
  gated so the supervised relaunch rejoins) — tools/smoke_serve_fleet.sh
  drives both through `xflow serve-fleet` (docs/SERVING.md).

The reference has no analog: it neither checkpoints nor validates input
(SURVEY.md §5 A3), so every one of these faults is either fatal or
silent there.
"""

from __future__ import annotations

import errno
import os
import random
import time
from typing import Iterable, Optional

import numpy as np


# ------------------------------------------------------------- byte faults
def truncate_file(path: str, keep_frac: float = 0.5,
                  keep_bytes: Optional[int] = None) -> int:
    """Truncate `path` to `keep_bytes` (or keep_frac of its size).
    Returns the new size. Emulates a crashed/partial write."""
    size = os.path.getsize(path)
    keep = keep_bytes if keep_bytes is not None else int(size * keep_frac)
    keep = max(0, min(size, keep))
    with open(path, "rb+") as f:
        f.truncate(keep)
    return keep


def bitflip_file(path: str, offset: Optional[int] = None, count: int = 8,
                 seed: int = 0) -> list[int]:
    """Flip one bit in each of `count` bytes (random offsets from `seed`
    unless `offset` pins the first). Returns the offsets touched.
    Emulates silent media/transfer corruption."""
    size = os.path.getsize(path)
    if size == 0:
        return []
    rng = random.Random(seed)
    offsets = sorted(
        {offset if offset is not None and i == 0 else rng.randrange(size)
         for i in range(count)}
    )
    with open(path, "rb+") as f:
        for off in offsets:
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ (1 << rng.randrange(8))]))
    return offsets


def bitflip_npz_array(path: str, member: Optional[str] = None, count: int = 8,
                      seed: int = 0, offset: Optional[int] = None) -> list[int]:
    """Flip bits inside ONE array member's payload of an .npz and
    REWRITE the container with fresh zip CRCs — SILENT corruption by
    construction: a raw `bitflip_file` on an npz trips the zip layer's
    own CRC32 on read (the loud failure mode `restore_any` already
    heals), while this flip survives every container-level check and is
    caught only by the per-array digests meta.json records at save
    (checkpoint v3, `verify_digest`). The .npy header is skipped too —
    a damaged header fails loudly at parse, which is not the drill.

    `member` defaults to the largest array (the table payload).
    `offset` pins the first flipped byte, RELATIVE to the array payload
    (offset 0 = the first data byte after the header); out-of-payload
    offsets raise ValueError rather than silently invalidating the
    drill. Returns the flipped offsets within the member's bytes."""
    import struct
    import zipfile

    with zipfile.ZipFile(path) as z:
        names = z.namelist()
        target = member or max(names, key=lambda n: z.getinfo(n).file_size)
        blobs = {n: z.read(n) for n in names}
    data = bytearray(blobs[target])
    # .npy layout: \x93NUMPY, major, minor, header-len (2 bytes v1.x /
    # 4 bytes v2.x), header, then raw array bytes — flip only past the
    # header so dtype/shape parse fine and the VALUES are what changed
    if len(data) < 12 or data[:6] != b"\x93NUMPY":
        raise ValueError(f"{target!r} in {path!r} is not an .npy member")
    if data[6] >= 2:
        start = 12 + struct.unpack("<I", data[8:12])[0]
    else:
        start = 10 + struct.unpack("<H", data[8:10])[0]
    if start >= len(data):
        raise ValueError(f"{target!r} has no array payload to corrupt")
    first = None
    if offset is not None:
        first = start + int(offset)
        if not start <= first < len(data):
            raise ValueError(
                f"offset {offset} is outside {target!r}'s array payload "
                f"(0..{len(data) - start - 1})"
            )
    rng = random.Random(seed)
    offsets = sorted(
        {first if first is not None and i == 0 else rng.randrange(start, len(data))
         for i in range(count)}
    )
    for off in offsets:
        data[off] ^= 1 << rng.randrange(8)
    blobs[target] = bytes(data)
    # rewrite uncompressed (np.savez's own layout): the zip member CRCs
    # are recomputed over the CORRUPTED bytes, so the container stays
    # self-consistent and only the digest layer can tell
    with zipfile.ZipFile(path, "w", zipfile.ZIP_STORED) as z:
        for n in names:
            z.writestr(n, blobs[n])
    return offsets


# ------------------------------------------------------ checkpoint corruption
def _apply(path: str, mode: str, **kw) -> str:
    if mode == "truncate":
        truncate_file(path, **{k: v for k, v in kw.items()
                               if k in ("keep_frac", "keep_bytes")})
    elif mode == "bitflip":
        bitflip_file(path, **{k: v for k, v in kw.items()
                              if k in ("offset", "count", "seed")})
    else:
        raise ValueError(f"mode={mode!r}: expected truncate|bitflip")
    return path


def corrupt_npz_checkpoint(ckpt_dir: str, step: Optional[int] = None,
                           mode: str = "truncate", target: str = "state",
                           **kw) -> str:
    """Corrupt a file of the newest (or given) COMMITTED checkpoint.
    The commit marker is left intact — the point is a checkpoint that
    LOOKS valid and fails only when read.

    target="state" (default): `state.npz` — the case restore_any heals
    by walking back to the previous committed step. mode="bitflip"
    there flips bytes INSIDE an array payload and rewrites the
    container (`bitflip_npz_array`): the zip CRCs stay self-consistent,
    so only the v3 per-array digests catch it — the silent-corruption
    drill. (mode="truncate", and raw flips via the CLI's --file, stay
    the loud container-level failure modes.)
    target="data_state": `data_state.json` (elastic recovery) — the
    case read_data_state DOWNGRADES: the model still restores, the run
    resumes with a fresh stream, and the downgrade is logged. Operators
    drill both through tools/corrupt_ckpt.py."""
    from xflow_tpu.train.checkpoint import committed_steps, data_state_path

    if step is None:
        steps = committed_steps(ckpt_dir)
        if not steps:
            raise FileNotFoundError(f"no committed checkpoint under {ckpt_dir!r}")
        step = steps[0]
    if target == "data_state":
        victim = data_state_path(ckpt_dir, step, fmt="npz")
        if not os.path.exists(victim):
            raise FileNotFoundError(
                f"checkpoint step {step} has no data_state (pre-v2 "
                f"checkpoint?) under {ckpt_dir!r}"
            )
    elif target == "state":
        victim = os.path.join(ckpt_dir, f"step_{step}", "state.npz")
        if mode == "bitflip":
            bitflip_npz_array(
                victim,
                **{k: v for k, v in kw.items()
                   if k in ("member", "offset", "count", "seed")},
            )
            return victim
    else:
        raise ValueError(f"target={target!r}: expected state|data_state")
    return _apply(victim, mode, **kw)


def corrupt_orbax_checkpoint(ckpt_dir: str, step: Optional[int] = None,
                             mode: str = "truncate",
                             target: str = "manifest", **kw) -> str:
    """Corrupt a file inside the newest (or given) orbax checkpoint dir.

    target="manifest" (default): the top-level OCDBT manifest — the torn
    partial-upload scenario; its loss makes restore fail LOUDLY
    (DATA_LOSS), which is what restore_any's walk-back heals.
    target="largest": the biggest data file (the table shards). CAVEAT,
    measured on this tensorstore: byte corruption THERE restores without
    error and yields wrong values — OCDBT data reads are not
    checksum-verified, unlike npz (zip CRC32 catches every flip). Use
    npz where end-to-end integrity matters (docs/ROBUSTNESS.md)."""
    from xflow_tpu.train.checkpoint import orbax_steps

    if step is None:
        steps = orbax_steps(ckpt_dir)
        if not steps:
            raise FileNotFoundError(f"no orbax checkpoint under {ckpt_dir!r}")
        step = steps[0]
    root = os.path.join(ckpt_dir, f"orbax_step_{step}")
    if target == "data_state":
        from xflow_tpu.train.checkpoint import data_state_path

        victim = data_state_path(ckpt_dir, step, fmt="orbax")
        if not os.path.exists(victim):
            raise FileNotFoundError(
                f"orbax step {step} has no data_state sibling under "
                f"{ckpt_dir!r}"
            )
        return _apply(victim, mode, **kw)
    if target == "manifest":
        victim = os.path.join(root, "manifest.ocdbt")
        if not os.path.exists(victim):
            raise FileNotFoundError(f"no OCDBT manifest under {root!r}")
    elif target == "largest":
        victim, largest_size = None, -1
        for dirpath, _, files in os.walk(root):
            for name in files:
                p = os.path.join(dirpath, name)
                s = os.path.getsize(p)
                if s > largest_size:
                    victim, largest_size = p, s
        if victim is None:
            raise FileNotFoundError(f"no files under {root!r}")
    else:
        raise ValueError(
            f"target={target!r}: expected manifest|largest|data_state"
        )
    return _apply(victim, mode, **kw)


# --------------------------------------------------------------- disk faults
def ckpt_write_fault(tier: str):
    """Disk-fault injector for checkpoint WRITES — the async-tiered
    durability drills' trigger (docs/ROBUSTNESS.md "Async tiered
    checkpointing"). Returns a callback `fault(tmp_path)` the writer
    invokes on each staged temp file just before its commit rename, or
    None when no fault is armed — resolved ONCE per save per tier, so
    the ENOSPC byte budget is per-save, not cumulative across a run.

    Env contract (tools/smoke_durable.sh and tests/test_durable_ckpt.py
    export these):
    - XFLOW_FAULT_CKPT_ENOSPC_BYTES: once the save's cumulative staged
      bytes pass this budget, raise OSError(ENOSPC) — a volume filling
      up mid-write. The trainer's async writer latches degraded mode
      and falls back to replica-only saves.
    - XFLOW_FAULT_CKPT_SLOW_S_PER_MB: sleep size/1e6 * this per staged
      file — a slow disk. Widens the in-flight window so the
      kill-mid-async-save and skip-on-busy drills land deterministically.
    - XFLOW_FAULT_CKPT_TIER: restrict to "primary" or "replica"
      (default: both tiers).

    Injection rides the npz temp+replace path and the replica mirror's
    per-file copy; the orbax main step dir writes through orbax's own
    machinery and is NOT injected (its sidecars are).
    """
    target = os.environ.get("XFLOW_FAULT_CKPT_TIER")
    if target is not None and target != tier:
        return None

    def _num(name: str, cast, default):
        try:
            return cast(os.environ.get(name, default) or default)
        except ValueError:
            return cast(default)

    enospc = _num("XFLOW_FAULT_CKPT_ENOSPC_BYTES", int, 0)
    slow = _num("XFLOW_FAULT_CKPT_SLOW_S_PER_MB", float, 0.0)
    if enospc <= 0 and slow <= 0:
        return None
    written = {"bytes": 0}

    def fault(tmp_path: str) -> None:
        size = os.path.getsize(tmp_path)
        if slow > 0:
            time.sleep(size / 1e6 * slow)
        written["bytes"] += size
        if 0 < enospc < written["bytes"]:
            raise OSError(
                errno.ENOSPC,
                "injected ENOSPC (XFLOW_FAULT_CKPT_ENOSPC_BYTES)",
                tmp_path,
            )

    return fault


# -------------------------------------------------------------- kill faults
def kill_step_from_env(rank: int) -> int:
    """1-based step at which this rank hard-kills itself (0 = off) — the
    elastic-recovery drill injector, resolved ONCE at fit() start like
    the pacing faults (zero per-step cost when unset).

    Env contract (the launch-local auto-restart drill exports these):
    - XFLOW_FAULT_KILL_STEP: SIGKILL this process the moment that
      1-based step completes (after its heartbeat/checkpoint cadence
      ran, so a kill on a checkpoint boundary leaves that step
      committed) — a preemption without grace.
    - XFLOW_FAULT_KILL_RANK: restrict the kill to one rank (default:
      all ranks).
    - XFLOW_FAULT_KILL_GEN (default 0): only kill in this restart
      generation — the supervised relaunch (which inherits the env)
      must survive, not die at step K forever.
    """
    try:
        step = int(os.environ.get("XFLOW_FAULT_KILL_STEP", 0) or 0)
    except ValueError:
        return 0
    if step <= 0:
        return 0
    r = os.environ.get("XFLOW_FAULT_KILL_RANK")
    if r is not None:
        try:
            if int(r) != rank:
                return 0
        except ValueError:
            return 0
    from xflow_tpu.telemetry import resolve_restart_gen

    try:
        want_gen = int(os.environ.get("XFLOW_FAULT_KILL_GEN", 0) or 0)
    except ValueError:
        want_gen = 0
    return step if resolve_restart_gen() == want_gen else 0


def hard_kill() -> None:
    """SIGKILL this process — no atexit, no finally blocks, no flushes
    beyond what already hit the disk: the closest userspace emulation of
    a preempted/OOM-killed host."""
    import signal

    try:
        os.kill(os.getpid(), signal.SIGKILL)
    except (OSError, AttributeError):
        pass
    os._exit(137)  # unreachable on POSIX; belt for exotic platforms


def abort_after_step(trainer, step: int) -> None:
    """Make the trainer's TRAINING stream raise RuntimeError right after
    the 1-based global step `step`'s batch is consumed — the in-process
    analog of a mid-run crash (the subprocess drills use
    kill_step_from_env instead). Checkpoints committed up to the abort
    survive, so a resume exercises the exact-stream data_state path;
    eval streams pass through untouched (same seam and counting rule as
    poison_nan_batches)."""
    orig = trainer._coordinated_batches
    counter = [0]

    def wrapped(path, *args, **kwargs):
        training = kwargs.get("enforce_bad_rows", True)
        for batch, arrays in orig(path, *args, **kwargs):
            yield batch, arrays
            if training:
                counter[0] += 1
                if counter[0] >= step:
                    raise RuntimeError(
                        f"injected abort after step {counter[0]} "
                        "(testing/faults.abort_after_step)"
                    )

    trainer._coordinated_batches = wrapped


# ------------------------------------------------------------- serve faults
def serve_faults_from_env() -> tuple[float, int]:
    """(per_batch_delay_s, kill_after_batches) for THIS serve process —
    the serving-fleet chaos injectors, resolved ONCE at ServeApp
    construction like the fit-loop faults (zero per-batch cost unset).

    Env contract (tools/smoke_serve_fleet.sh exports these):
    - XFLOW_FAULT_SERVE_DELAY_S: sleep this long before EVERY device
      batch — a persistently slow replica (the router's circuit breaker
      and hedging drills, docs/SERVING.md failure matrix).
    - XFLOW_FAULT_SERVE_KILL_BATCHES: SIGKILL the process right after
      the Nth answered batch (responses already in flight) — a replica
      dying MID-LOAD, deterministic where a timed external kill races
      the bench.
    - XFLOW_FAULT_SERVE_REPLICA: restrict either fault to one fleet
      replica index (default: all; matched against XFLOW_REPLICA via
      telemetry.resolve_replica).
    - XFLOW_FAULT_SERVE_KILL_GEN (default 0): only kill in this restart
      generation — the supervised relaunch (which inherits the env)
      must survive and REJOIN, not re-die forever (same contract as
      XFLOW_FAULT_KILL_GEN).
    """
    from xflow_tpu.telemetry import resolve_replica, resolve_restart_gen

    def _num(name: str, cast, default):
        try:
            return cast(os.environ.get(name, default) or default)
        except ValueError:
            return cast(default)

    target = os.environ.get("XFLOW_FAULT_SERVE_REPLICA")
    if target is not None:
        try:
            if int(target) != resolve_replica():
                return 0.0, 0
        except (ValueError, TypeError):
            return 0.0, 0
    delay = _num("XFLOW_FAULT_SERVE_DELAY_S", float, 0.0)
    kill = _num("XFLOW_FAULT_SERVE_KILL_BATCHES", int, 0)
    if kill > 0 and resolve_restart_gen() != _num(
        "XFLOW_FAULT_SERVE_KILL_GEN", int, 0
    ):
        kill = 0
    return max(delay, 0.0), max(kill, 0)


# -------------------------------------------------------------- sync faults
def sync_faults_from_env() -> tuple[int, float]:
    """(kill_round, sync_delay_s) for THIS slice's sync tier — the
    multi-slice chaos injectors (parallel/multislice.SliceSyncer
    resolves them ONCE at construction, zero per-round cost unset).

    Env contract (tools/smoke_multislice.sh exports these):
    - XFLOW_FAULT_SLICE_KILL_ROUND: SIGKILL this slice the moment it
      ENTERS that 1-based sync round, before publishing its delta — the
      slice-loss drill: survivors must drop it from the sync group and
      continue degraded, and its supervised relaunch must catch up from
      the freshest published snapshot.
    - XFLOW_FAULT_SYNC_DELAY_S: sleep this long inside EVERY sync round
      — a persistently straggling slice (the staleness-bound /
      proceed-on-stale drill; peers see its lag grow past K).
    - XFLOW_FAULT_SLICE: restrict either fault to one slice index
      (default: all; matched against XFLOW_SLICE via
      telemetry.resolve_slice). XFLOW_FAULT_SLICE_KILL_SLICE /
      XFLOW_FAULT_SYNC_DELAY_SLICE override it per injector — the
      smoke drill kills slice 1 while pacing slice 0 as a straggler so
      the survivor's sync trail deterministically records the
      leave/degraded/rejoin sequence.
    - XFLOW_FAULT_SLICE_KILL_GEN (default 0): only kill in this restart
      generation — the relaunched slice (which inherits the env) must
      survive and REJOIN, not re-die at round R forever (same contract
      as XFLOW_FAULT_KILL_GEN).
    """
    from xflow_tpu.telemetry import resolve_restart_gen, resolve_slice

    def _num(name: str, cast, default):
        try:
            return cast(os.environ.get(name, default) or default)
        except ValueError:
            return cast(default)

    def _targeted(var: str) -> bool:
        """True when the injector guarded by `var` aims at THIS slice
        (unset target = every slice; unparseable = no slice)."""
        target = os.environ.get(var, os.environ.get("XFLOW_FAULT_SLICE"))
        if target is None:
            return True
        try:
            return int(target) == resolve_slice()
        except (ValueError, TypeError):
            return False

    kill = (
        _num("XFLOW_FAULT_SLICE_KILL_ROUND", int, 0)
        if _targeted("XFLOW_FAULT_SLICE_KILL_SLICE") else 0
    )
    # the straggler can aim at a DIFFERENT slice than the kill (the
    # smoke drill paces the survivor while killing its peer)
    delay = (
        _num("XFLOW_FAULT_SYNC_DELAY_S", float, 0.0)
        if _targeted("XFLOW_FAULT_SYNC_DELAY_SLICE") else 0.0
    )
    if kill > 0 and resolve_restart_gen() != _num(
        "XFLOW_FAULT_SLICE_KILL_GEN", int, 0
    ):
        kill = 0
    return max(kill, 0), max(delay, 0.0)


# ------------------------------------------------------------ pacing faults
def fit_delays_from_env(rank: int) -> tuple[float, int, float]:
    """(per_step_sleep_s, stall_step, stall_s) for this rank — the
    straggler/hang drill injector the fit loop resolves ONCE at start
    (zero per-step cost when unset).

    Env contract (the launch-local watchdog drill exports these):
    - XFLOW_FAULT_STEP_DELAY_S: sleep this long before EVERY step — a
      persistently slow host.
    - XFLOW_FAULT_STALL_S (+ XFLOW_FAULT_STALL_STEP, default 1): sleep
      once, at that 1-based step — a rank that stops progressing while
      its peers run ahead, the heartbeat watchdog's straggler signature.
    - XFLOW_FAULT_DELAY_RANK: restrict either fault to one rank
      (default: all ranks).
    """
    r = os.environ.get("XFLOW_FAULT_DELAY_RANK")
    if r is not None:
        try:
            if int(r) != rank:
                return 0.0, 0, 0.0
        except ValueError:
            return 0.0, 0, 0.0
    delay = float(os.environ.get("XFLOW_FAULT_STEP_DELAY_S", 0) or 0)
    stall = float(os.environ.get("XFLOW_FAULT_STALL_S", 0) or 0)
    stall_step = int(os.environ.get("XFLOW_FAULT_STALL_STEP", 1) or 1)
    return delay, stall_step, stall


# ------------------------------------------------------------- data faults
def poison_nan_batches(trainer, steps: Iterable[int],
                       value: float = float("nan")) -> None:
    """Make the trainer's batch stream deliver `value` as every label of
    the 1-based global step indices in `steps` (counted across epochs).

    Injection happens at the (batch, arrays) seam the fit loop consumes
    — after parsing, before device transfer — because libffm labels
    cannot be non-finite by construction (label = 1 iff strtod(tok) >
    1e-7), so a NaN batch models an upstream feature-store bug, exactly
    the failure the non-finite guard exists for."""
    bad = set(int(s) for s in steps)
    orig = trainer._coordinated_batches
    counter = [0]

    def wrapped(path, *args, **kwargs):
        # only TRAINING streams advance the step counter: eval/predict
        # passes announce themselves with enforce_bad_rows=False, and
        # counting their batches would drift the poisoned indices off
        # the fit loop's steps whenever train.eval_every interleaves
        # eval passes between epochs
        training = kwargs.get("enforce_bad_rows", True)
        for batch, arrays in orig(path, *args, **kwargs):
            if training:
                counter[0] += 1
                if counter[0] in bad:
                    arrays = dict(arrays)
                    arrays["labels"] = np.full_like(
                        np.asarray(arrays["labels"]), value
                    )
            yield batch, arrays

    trainer._coordinated_batches = wrapped


def write_malformed_libffm(path: str, n_good: int = 40, n_bad: int = 6,
                           n_junk_label: int = 4, n_nonrows: int = 5,
                           seed: int = 0, truncated_tail: bool = False) -> dict:
    """Write a libffm shard mixing good rows with malformed content.

    Composition (shuffled, seeded):
    - `n_good` well-formed rows (`label\\tf:id:1 ...`);
    - `n_bad` BAD rows: labeled lines whose every feature token is
      malformed (no ':'), so they parse to zero features — counted
      rows, quarantine fodder;
    - `n_junk_label` rows with junk labels but valid features (strtod
      yields 0.0 → label 0; the row itself is fine);
    - `n_nonrows` lines that are NOT rows for either parser: empty,
      whitespace-only, and label-only lines without a separator;
    - `truncated_tail`: ends the file mid-token without a newline (a
      torn write); the partial line still contains a separator, so both
      the counters and the parsers must agree on treating it as a row.

    Returns {"rows": ..., "bad": ..., "lines": ...} where `rows` is the
    count BOTH `count_rows` and `native_count_rows` must report and both
    parsers must yield, and `bad` the zero-feature subset.
    """
    rng = random.Random(seed)
    lines = []
    for i in range(n_good):
        toks = " ".join(
            f"{f}:{rng.randrange(1000)}:1" for f in range(rng.randrange(1, 5))
        )
        lines.append((f"{rng.randrange(2)}\t{toks}", "good"))
    for i in range(n_bad):
        junk = " ".join(rng.choice(["garbage", "??", "novalue", "a_b"])
                        for _ in range(rng.randrange(1, 3)))
        lines.append((f"{rng.randrange(2)}\t{junk}", "bad"))
    for i in range(n_junk_label):
        lines.append((f"abc{i}\t0:{rng.randrange(1000)}:1", "junk_label"))
    # non-rows for BOTH parsers: empty, whitespace-only (incl. a lone
    # tab, which strips to empty), and label-only lines with no separator
    nonrows = ["", "   ", "\t", "1", "justalabel"][:n_nonrows]
    lines.extend((l, "nonrow") for l in nonrows)
    rng.shuffle(lines)
    tail = None
    if truncated_tail:
        # a separator is present, the final token is torn mid-way
        tail = "1\t3:12345"
    rows = sum(1 for _, kind in lines if kind != "nonrow")
    bad = sum(1 for _, kind in lines if kind == "bad")
    with open(path, "w") as f:
        for text, _ in lines:
            f.write(text + "\n")
        if tail is not None:
            f.write(tail)  # no trailing newline
    if tail is not None:
        rows += 1
    return {"rows": rows, "bad": bad, "lines": len(lines) + (1 if tail else 0)}
