"""Test-support utilities shipped with the package (not tests themselves):
fault injectors (testing/faults.py) shared by the tier-1 fault-injection
suite and operator tooling (tools/corrupt_ckpt.py)."""
