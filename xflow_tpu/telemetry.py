"""Telemetry: the counters/gauges/timers registry, step-time
decomposition, and programmatic profiler trace windows.

The reference prints only loss/AUC lines to stdout
(`/root/reference/src/model/lr/lr.cc` train loop), which is unusable for
diagnosing a TPU trainer: async dispatch deliberately hides where the
time goes (data-wait? host dispatch? device step?), and the stdout
stream carries no rank identity, no timestamps, and nothing a tool can
aggregate. This module is the first-class instrumentation layer:

- `Registry` / `Counter` / `Gauge` / `Timer`: process-wide named
  metrics. The data pipeline and the quarantine path report through the
  default registry (data/pipeline.py, data/libffm.py); the trainer
  snapshots it into every metrics-JSONL window record.
- `StepTimer`: decomposes each train step into data-wait (iterator
  next), host dispatch (plan resolve + transfer + async dispatch), and
  device time — the device side measured ONE STEP BEHIND via a
  block-until-ready on the *previous* step's metrics right after the
  current step's dispatch, the same hide-under-device-time trick the
  non-finite guard's flag read uses (train/trainer.py check_pending),
  so the instrumentation adds no sync bubble to the pipeline.
- `TraceWindow`: a programmatic xprof trace window
  (`train.trace_start_step` / `train.trace_num_steps`) captured mid-run
  after compilation settles, replacing the blunt whole-run
  start/stop-trace (which buried the steady state under compile noise).

Timing convention (docs/OBSERVABILITY.md): durations come from
`time.perf_counter()` (monotonic — wall-clock `time.time()` jumps under
NTP slew); the `ts` field every JSONL record carries (xflow_tpu/jsonl.py)
is wall-clock, for cross-stream/cross-host log correlation only.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from collections import deque
from typing import Iterable, Iterator, Optional

import numpy as np

_RUN_ID: Optional[str] = None


def new_run_id() -> str:
    """A fresh launch-scoped id honoring an operator-exported
    XFLOW_RUN_ID — the one place the env-var name and id format live
    (launchers mint one per launch and export it to every rank)."""
    return os.environ.get("XFLOW_RUN_ID") or uuid.uuid4().hex[:12]


def resolve_run_id() -> str:
    """One id per training run, identical on every rank: XFLOW_RUN_ID
    when a launcher exported it (launch/local.py, launch/dist.py),
    else one random id minted per process — cached so every sink in the
    process (metrics stream, quarantine stream) stamps the SAME id and
    the streams stay joinable."""
    global _RUN_ID
    rid = os.environ.get("XFLOW_RUN_ID")
    if rid:
        return rid
    if _RUN_ID is None:
        _RUN_ID = new_run_id()
    return _RUN_ID


def resolve_restart_gen() -> int:
    """This process's restart generation: 0 on a first launch, k after
    the k-th supervised auto-restart (launch/supervise.py exports
    XFLOW_RESTART_GEN to every rank). Stamped as `gen` into every JSONL
    record (jsonl.JsonlAppender) so one run's multi-generation streams
    segment cleanly — step counts restart from 0 inside each generation,
    and metrics_report.py keys its per-stream gates on (run_id, rank,
    kind, gen)."""
    try:
        return int(os.environ.get("XFLOW_RESTART_GEN", "0") or 0)
    except ValueError:
        return 0


def resolve_rank() -> int:
    """This process's rank for record stamping. The launcher env
    (XFLOW_PROCESS_ID) is authoritative and avoids touching jax from
    sinks that open before distributed init; fall back to
    jax.process_index() (0 single-process) once jax is importable."""
    env = os.environ.get("XFLOW_PROCESS_ID")
    if env is not None:
        try:
            return int(env)
        except ValueError:
            pass
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


def resolve_world_size() -> int:
    """The launch world size for record stamping (`world` in every
    JSONL record). Under degraded-mode supervision (--allow-shrink,
    docs/ROBUSTNESS.md) a relaunch after a lost host runs with FEWER
    ranks under the same run_id — the per-generation world stamp is how
    report tools tell a shrunk-away rank (`retired@genK`) from a dead
    one. The launcher env (XFLOW_NUM_PROCESSES) is authoritative, same
    pattern as resolve_rank; falls back to jax.process_count()."""
    env = os.environ.get("XFLOW_NUM_PROCESSES")
    if env:
        try:
            n = int(env)
            if n > 0:
                return n
        except ValueError:
            pass
    try:
        import jax

        return int(jax.process_count())
    except Exception:
        return 1


# ------------------------------------------------------------------ registry


class Counter:
    """Monotonically increasing count. Thread-safe (the prefetch worker
    increments data counters while the fit loop snapshots)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        if n < 0:
            raise ValueError(f"Counter.inc({n}): counters are monotone, use a Gauge")
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Timer:
    """Duration accumulator with window percentiles.

    `observe(seconds)` (or the `timing()` context manager) feeds both
    the run totals (count / total_s — monotone, snapshot-friendly) and
    the current window, which `percentile(q)` reads and
    `window_reset()` clears — the StepTimer and the trainer's log
    window share this reset cadence. The window is a bounded deque
    (newest WINDOW_CAP observations) so a consumer that never resets —
    a run with train.log_every=0 — cannot grow host memory for the
    life of a pod-scale job."""

    WINDOW_CAP = 8192

    __slots__ = ("_lock", "count", "total_s", "_window")

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.total_s = 0.0
        self._window: deque = deque(maxlen=self.WINDOW_CAP)

    def observe(self, seconds: float) -> None:
        with self._lock:
            self.count += 1
            self.total_s += float(seconds)
            self._window.append(float(seconds))

    def timing(self):
        timer = self

        class _Ctx:
            def __enter__(self):
                self._t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                timer.observe(time.perf_counter() - self._t0)
                return False

        return _Ctx()

    def percentile(self, q: float) -> float:
        """q-th percentile (0..100) over the CURRENT window; NaN when
        the window is empty."""
        with self._lock:
            if not self._window:
                return float("nan")
            return float(np.percentile(np.asarray(self._window), q))

    def window_reset(self) -> list:
        """Return and clear the current window's observations."""
        with self._lock:
            out = list(self._window)
            self._window.clear()
            return out


class Registry:
    """Create-or-get named metrics. One flat namespace; a name is
    permanently one kind (asking for a counter where a gauge lives is a
    bug, reported loudly)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict = {}

    def _get(self, name: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls()
            elif not isinstance(m, cls):
                raise TypeError(
                    f"telemetry metric {name!r} is a {type(m).__name__}, "
                    f"not a {cls.__name__}"
                )
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def timer(self, name: str) -> Timer:
        return self._get(name, Timer)

    def snapshot(self) -> dict:
        """Flat {name: value} of every metric — counters/gauges by
        value, timers as `<name>.count` / `<name>.total_s`. Values are
        run totals (monotone for counters/timers), so consumers join
        across windows by diffing."""
        with self._lock:
            items = list(self._metrics.items())
        out: dict = {}
        for name, m in items:
            if isinstance(m, Timer):
                out[f"{name}.count"] = m.count
                out[f"{name}.total_s"] = round(m.total_s, 6)
            else:  # Counter / Gauge
                out[name] = m.value
        return out

    def reset(self) -> None:
        """Drop every metric (tests; a fresh fit() keeps run totals)."""
        with self._lock:
            self._metrics.clear()


_DEFAULT = Registry()


def default_registry() -> Registry:
    """The process-wide registry the pipeline/quarantine counters and
    the trainer's window snapshots share."""
    return _DEFAULT


# ----------------------------------------------------------------- StepTimer


def _block(tree) -> None:
    """block_until_ready on a pytree of (possibly jax) values; host
    numpy passes through untouched so StepTimer is testable without a
    device."""
    try:
        import jax

        jax.block_until_ready(tree)
    except ImportError:
        pass


class StepTimer:
    """One-step-behind step-time decomposition.

    Per step i the fit loop calls:

      for batch in st.batches(iterator):   # data-wait = time inside next()
          ... resolve/shard/dispatch ...   # host dispatch
          st.dispatched(metrics_i, rows)   # blocks on step i-1's metrics

    `dispatched` records step i's host-side timings, then finishes step
    i-1 by blocking on its (async) metrics — the block overlaps step i's
    device execution, so no sync bubble is added; the cost model is the
    non-finite guard's (train/trainer.py check_pending). Consequently a
    step's record lands one call later, and the LAST step needs
    `flush()` after the loop.

    Per finished step:
      - data_wait_s: time spent inside the iterator's next()
      - dispatch_s:  fetch end -> dispatch return (plan resolve, host
        transfer, async dispatch)
      - device_s:    dispatch return -> metrics ready. When the device
        is the bottleneck this is the device step time; when the host
        is, the block returns immediately and it degrades to the
        pipeline interval — an upper bound, never an undercount.
      - step_s:      completion-to-completion interval. These telescope,
        so their sum over a run equals the elapsed wall time (the
        decomposition tests' invariant).
    """

    def __init__(self, registry: Optional[Registry] = None):
        self._reg = registry or default_registry()
        self._pending = None  # (metrics, rows, wait_s, dispatch_s, dispatch_end)
        self._last_ready: Optional[float] = None
        self._last_wait = 0.0
        self._wait_end: Optional[float] = None
        self._win_rows = 0
        self._win: dict = {"step": [], "wait": [], "dispatch": [], "device": []}
        self._win_start = time.perf_counter()
        self.steps = 0
        self.rows = 0

    def batches(self, iterable: Iterable) -> Iterator:
        """Wrap the batch iterator so time spent INSIDE next() — and
        only that — is the step's data-wait. Abandonment (an early
        break / exception in the consuming loop) closes the wrapped
        iterator promptly, preserving the prefetch worker's
        close-cascade contract (data/pipeline.py prefetch)."""
        it = iter(iterable)
        try:
            while True:
                t0 = time.perf_counter()
                try:
                    item = next(it)
                except StopIteration:
                    return
                self._wait_end = time.perf_counter()
                self._last_wait = self._wait_end - t0
                yield item
        finally:
            close = getattr(it, "close", None)
            if close is not None:
                close()

    def dispatched(self, metrics, rows: int) -> None:
        """Call right after the step's async dispatch returns. Finishes
        the PREVIOUS step (block-until-ready overlapping this step's
        device execution) and stages this one."""
        now = time.perf_counter()
        wait_end = self._wait_end if self._wait_end is not None else now
        cur = (metrics, int(rows), self._last_wait, now - wait_end, now)
        self._finish_pending()
        self._pending = cur

    def flush(self) -> None:
        """Finish the final in-flight step (its metrics have no
        successor to hide behind — the one sync this class adds, at
        end of data)."""
        self._finish_pending()

    def _finish_pending(self) -> None:
        if self._pending is None:
            return
        metrics, rows, wait_s, dispatch_s, dispatch_end = self._pending
        self._pending = None
        _block(metrics)
        t_ready = time.perf_counter()
        device_s = t_ready - dispatch_end
        # first step: anchor on its own fetch start so intervals telescope
        base = (
            self._last_ready
            if self._last_ready is not None
            else dispatch_end - dispatch_s - wait_s
        )
        self._last_ready = t_ready
        self.steps += 1
        self.rows += rows
        self._win_rows += rows
        w = self._win
        w["step"].append(t_ready - base)
        w["wait"].append(wait_s)
        w["dispatch"].append(dispatch_s)
        w["device"].append(device_s)
        self._reg.timer("step.time").observe(t_ready - base)
        self._reg.timer("step.data_wait").observe(wait_s)

    def window_record(self) -> dict:
        """Stats over the steps finished since the last call, then reset
        the window. Empty dict when no step has finished yet (the very
        first log tick under log_every=1 — timing runs one behind)."""
        w = self._win
        n = len(w["step"])
        if n == 0:
            return {}
        now = time.perf_counter()
        elapsed = max(now - self._win_start, 1e-9)
        step_ms = np.asarray(w["step"]) * 1e3
        rec = {
            "steps_per_s": round(n / elapsed, 3),
            "rows_per_s": round(self._win_rows / elapsed, 1),
            "step_time_p50_ms": round(float(np.percentile(step_ms, 50)), 3),
            "step_time_p99_ms": round(float(np.percentile(step_ms, 99)), 3),
            "data_wait_ms": round(float(np.mean(w["wait"])) * 1e3, 3),
            "dispatch_ms": round(float(np.mean(w["dispatch"])) * 1e3, 3),
            "device_ms": round(float(np.mean(w["device"])) * 1e3, 3),
        }
        self._win = {"step": [], "wait": [], "dispatch": [], "device": []}
        self._win_rows = 0
        self._win_start = now
        # shared cadence: the registry timers' percentile windows clear
        # with the log window (their run totals are monotone and survive)
        self._reg.timer("step.time").window_reset()
        self._reg.timer("step.data_wait").window_reset()
        return rec


# ------------------------------------------------------------- HealthMonitor


def estimate_collision_rate(distinct_slots: int, num_slots: int) -> float:
    """Live collision-rate estimate from slot saturation.

    The offline tool (xflow_tpu/tools/collisions.py) computes the exact
    rate from distinct feature tokens, which the trainer never sees
    (the parser hands it post-fold slots). But under uniform hashing the
    expected distinct-slot count for n distinct keys is
    d = S·(1 − (1 − 1/S)^n); inverting gives n̂ = ln(1 − d/S)/ln(1 − 1/S)
    and the estimated rate 1 − d/n̂ — the same birthday math, driven by
    what the trainer CAN observe. Exact at d→0, conservative near
    saturation (d→S ⇒ rate→1)."""
    S, d = int(num_slots), int(distinct_slots)
    if d <= 0 or S <= 1:
        return 0.0
    if d >= S:
        return 1.0
    import math

    n_hat = math.log1p(-d / S) / math.log1p(-1.0 / S)
    return max(0.0, 1.0 - d / n_hat)


class HealthMonitor:
    """Host side of the model-health pipeline (train.health_metrics).

    The step builders fuse grad/update/param norms into each step's
    metrics dict (train/step.py health_norms); this class consumes them
    ONE STEP BEHIND — `collect()` runs right after `StepTimer.dispatched`
    has block_until_ready'd the previous step's metrics, so every read
    here is a ready-buffer host copy, never a sync — and maintains what
    only the host can: the loss EMA, the touched-slot bitmap behind the
    occupancy/collision gauges, and the per-window values the trainer
    folds into its metrics-JSONL records.

    Thread-safety: `observe_batch` runs on the prefetch/plan thread
    (trainer._with_arrays) while `collect`/`window_record` run on the
    fit loop — the bitmap and window state are lock-protected.
    """

    KEYS = ("grad_norm", "update_norm", "param_norm")

    def __init__(
        self,
        mode: str = "off",
        ema_decay: float = 0.99,
        registry: Optional[Registry] = None,
        num_slots: int = 0,
    ):
        if mode not in ("off", "norms", "full"):
            raise ValueError(f"health mode {mode!r}: expected off|norms|full")
        self.enabled = mode != "off"
        self.mode = mode
        self._decay = float(ema_decay)
        self._reg = registry or default_registry()
        self._lock = threading.Lock()
        self.loss_ema = float("nan")
        self._pending = None  # a step's metrics awaiting the one-behind read
        self._last: dict = {}  # last observed health floats
        self._win_grad_max = float("nan")
        self._seen = (
            np.zeros(int(num_slots), dtype=bool)
            if self.enabled and num_slots > 0
            else None
        )
        self._num_slots = int(num_slots)

    # ------------------------------------------------- step-metrics side
    def staged(self, metrics) -> None:
        """Stage a just-dispatched step's (async) metrics for the next
        collect — mirrors the trainer's pending_ok bookkeeping."""
        if self.enabled:
            self._pending = metrics

    def collect(self) -> None:
        """Finish the PREVIOUS step: read its (ready) health scalars and
        loss, fold the EMA, refresh the gauges. Call right after
        StepTimer.dispatched — the block there made these reads free."""
        if self._pending is None:
            return
        m = self._pending
        self._pending = None
        loss = float(m["loss"]) if "loss" in m else float("nan")
        if loss == loss and abs(loss) != float("inf"):
            self.loss_ema = (
                loss
                if self.loss_ema != self.loss_ema
                else self._decay * self.loss_ema + (1.0 - self._decay) * loss
            )
            self._reg.gauge("health.loss_ema").set(self.loss_ema)
        vals = {}
        for key in self.KEYS:
            if key in m:
                vals[key] = float(m[key])
                self._reg.gauge(f"health.{key}").set(vals[key])
        if self.mode == "full":
            for key in m:
                if isinstance(key, str) and "." in key and key.split(".")[0] in (
                    "grad_norm", "update_norm", "param_norm",
                ):
                    vals[key] = float(m[key])
        with self._lock:
            if vals:
                self._last = vals
                g = vals.get("grad_norm")
                if g is not None and (
                    self._win_grad_max != self._win_grad_max or g > self._win_grad_max
                ):
                    self._win_grad_max = g

    def flush(self) -> None:
        """End-of-data: the final step's metrics were just blocked on by
        StepTimer.flush(), so this collect is still sync-free."""
        self.collect()

    # --------------------------------------------------- occupancy side
    def observe_batch(self, slots, mask) -> None:
        """Mark a training batch's masked slots as touched (called from
        the plan/prefetch thread so the bitmap write overlaps device
        compute). Drives the occupancy + collision-estimate gauges."""
        if self._seen is None:
            return
        idx = np.asarray(slots)[np.asarray(mask) > 0]
        with self._lock:
            self._seen[idx] = True

    # ------------------------------------------------------- windowing
    def window_record(self) -> dict:
        """The health fields for one metrics-JSONL window record: last
        norm values + the window's grad-norm max, the loss EMA, and the
        occupancy/collision gauges. Empty dict when nothing was
        collected yet (step 1 under log_every=1 — the health read runs
        one behind, like the StepTimer)."""
        if not self.enabled:
            return {}
        with self._lock:
            if not self._last and self.loss_ema != self.loss_ema:
                return {}
            fin = lambda v: round(v, 6) if v == v and abs(v) != float("inf") else None
            rec = {
                "grad_norm": fin(self._last.get("grad_norm", float("nan"))),
                "grad_norm_max": fin(self._win_grad_max),
                "update_norm": fin(self._last.get("update_norm", float("nan"))),
                "param_norm": fin(self._last.get("param_norm", float("nan"))),
                "loss_ema": fin(self.loss_ema),
            }
            if self.mode == "full":
                tables: dict = {}
                for key, v in self._last.items():
                    if "." in key:
                        kind, tname = key.split(".", 1)
                        tables.setdefault(tname, {})[kind] = fin(v)
                if tables:
                    rec["health_tables"] = tables
            self._win_grad_max = float("nan")
            if self._seen is not None:
                touched = int(np.count_nonzero(self._seen))
                occ = touched / self._num_slots
                est = estimate_collision_rate(touched, self._num_slots)
                rec["slots_touched"] = touched
                rec["table_occupancy"] = round(occ, 6)
                rec["est_collision_rate"] = round(est, 6)
                self._reg.gauge("health.slots_touched").set(touched)
                self._reg.gauge("health.table_occupancy").set(occ)
                self._reg.gauge("health.est_collision_rate").set(est)
        return rec


# ----------------------------------------------------------- liveness hooks


def install_stack_dump_handler():
    """Register faulthandler on SIGUSR1 so an operator can get all-thread
    stack dumps from a live (or wedged) trainer with plain `kill -USR1`
    — the standard "why is this rank stuck" tool. Returns a restore
    callable; a no-op off the main thread (signal handlers can only be
    installed there; non-main callers keep training, just without the
    hook) and on platforms without SIGUSR1."""
    try:
        import faulthandler
        import signal

        if threading.current_thread() is not threading.main_thread():
            return lambda: None
        sig = getattr(signal, "SIGUSR1", None)
        if sig is None:
            return lambda: None
        faulthandler.register(sig, all_threads=True)
        return lambda: faulthandler.unregister(sig)
    except Exception:
        return lambda: None


class HangWatchdog:
    """No-progress watchdog (train.hang_timeout_s): a daemon thread that
    dumps ALL thread stacks to stderr (faulthandler) when `tick()` has
    not been called for `timeout_s` — one dump per stall, re-armed by
    the next tick, so a recovered pipeline can trip it again later.
    A hang in an SPMD trainer usually means a peer died mid-collective
    (docs/ROBUSTNESS.md); the dump shows exactly which collective."""

    def __init__(self, timeout_s: float, out=None):
        self._timeout = float(timeout_s)
        self._out = out  # test seam; defaults to sys.stderr at dump time
        self._last = time.perf_counter()
        self._dumped = False
        self._stop = threading.Event()
        self._thread = None
        self.dumps = 0
        if self._timeout > 0:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="xflow-hang-watchdog"
            )
            self._thread.start()

    def tick(self) -> None:
        self._last = time.perf_counter()
        self._dumped = False

    def _run(self) -> None:
        import faulthandler
        import sys as _sys

        poll = min(max(self._timeout / 4.0, 0.05), 5.0)
        while not self._stop.wait(poll):
            idle = time.perf_counter() - self._last
            if idle > self._timeout and not self._dumped:
                self._dumped = True
                self.dumps += 1
                out = self._out or _sys.stderr
                print(
                    f"xflow: hang watchdog: no step progress for "
                    f"{idle:.1f}s (> train.hang_timeout_s="
                    f"{self._timeout}); dumping all thread stacks",
                    file=out,
                )
                try:
                    faulthandler.dump_traceback(file=out, all_threads=True)
                except Exception:
                    pass

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


# --------------------------------------------------------------- TraceWindow


class TraceWindow:
    """Programmatic xprof trace window.

    `train.trace_start_step >= 1` (with `train.profile_dir` set) starts
    the trace just before that step's dispatch — after compilation has
    settled, so the window shows the steady state instead of burying it
    under compile noise — and stops it once `train.trace_num_steps`
    steps have dispatched. `trace_start_step = 0` keeps the legacy
    whole-run trace. `close()` (the fit loop's finally) stops a trace
    still running when the data ends inside the window.

    `profiler` is a test seam; the default is `jax.profiler`.
    """

    def __init__(
        self,
        profile_dir: str,
        start_step: int = 0,
        num_steps: int = 0,
        profiler=None,
    ):
        self._dir = profile_dir
        self._start = max(int(start_step), 0)
        self._num = max(int(num_steps), 1)
        self._running = False
        self._done = not profile_dir
        self._prof = profiler

    def _profiler(self):
        if self._prof is None:
            import jax

            self._prof = jax.profiler
        return self._prof

    def maybe_start_run(self) -> None:
        """Pre-loop hook: whole-run mode (start_step=0) starts here."""
        if not self._done and not self._running and self._start == 0:
            self._profiler().start_trace(self._dir)
            self._running = True

    def before_step(self, step: int) -> None:
        """Window mode: called with the 1-based step about to dispatch."""
        if self._done or self._start == 0:
            return
        if not self._running and step == self._start:
            self._profiler().start_trace(self._dir)
            self._running = True
        elif self._running and step >= self._start + self._num:
            self._stop()

    def _stop(self) -> None:
        if self._running:
            self._profiler().stop_trace()
            self._running = False
        self._done = True

    def close(self) -> None:
        """Stop a still-running trace (end of data / abnormal exit)."""
        if self._running:
            self._profiler().stop_trace()
            self._running = False
        self._done = True
