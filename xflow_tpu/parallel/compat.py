"""jax API compatibility shims for the parallel engines.

The engines are written against the current stable surface
(`jax.shard_map`, `check_vma=`); older runtimes (jax <= 0.4.x, which
some CI images pin) expose the same primitive as
`jax.experimental.shard_map.shard_map` with the flag spelled
`check_rep=`. One shim keeps every call site on the modern spelling.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """`jax.shard_map` with fallback to the pre-0.5 experimental API
    (`check_vma` maps onto the old `check_rep`)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )
