"""Emulated multi-slice runtime: bounded-staleness table sync across
slice subprocesses — the DCN tier of the two-tier topology the ROADMAP
names (synchronous SPMD inside a slice over ICI, asynchronous
parameter-server semantics ACROSS slices over DCN).

The reference system's defining robustness property was asynchrony:
ps-lite workers push/pull the shared tables and never block on each
other (PAPER.md: KVWorker ``Wait(Push/Pull)``), so a slow or dead
worker degrades throughput instead of halting the job. Our GSPMD
engine is the opposite — fully synchronous — and this module restores
the asynchronous tier WITHOUT touching the jit programs: each slice is
one independent ``xflow train`` subprocess (own mesh, own data shards,
own checkpoints — the launch-local pattern minus the coordinator), and
a host-level `SliceSyncer` exchanges ADDITIVE table deltas through a
shared directory between K-step scan blocks. Engine-agnostic by
construction: the syncer sees only the host-side TrainState pytree.

Delta model (local-SGD style): every slice keeps ``base`` — its state
at the last sync. At a sync boundary it publishes
``delta_i = local - base``, applies every peer delta it has not yet
applied (in (round, slice) order, each exactly once), and rebases.
Since every slice starts from the same seeded init, all slices
converge to ``init + sum(all deltas)`` once caught up — regardless of
HOW stale each exchange ran. The one structural guarantee: when no
peer delta applies (single slice, or async with nothing landed), the
live state passes through UNTOUCHED — no base + (local - base) float
round-trip — so K=0 single-slice runs are bitwise-identical to a plain
run (tests/test_multislice.py).

Failure semantics (parameter-server, throughout):
- every staleness wait is bounded by ``sync.timeout_s`` with
  ``sync.retries`` backoff-spaced re-checks (supervise.backoff_delay —
  the rendezvous-hardening curve); a vanished peer costs a bounded
  wait, never a hang;
- a slice that misses its bound triggers the ``sync.on_stale`` policy
  (wait vs. proceed-on-stale), counted in the ``kind="sync"`` record;
- a slice that DIES (watchdog dead verdict or process exit) is dropped
  from ``membership.json`` by the launcher, and survivors stop waiting
  on it — degraded continue;
- a relaunched slice resumes its OWN checkpoint (exact data_state
  accounting — zero lost examples) and catches up by adopting the
  freshest published full-state snapshot at syncer attach (the
  reshard-on-load restore idiom: host arrays placed onto the live
  sharding).

Every sync emits a stamped ``kind="sync"`` JSONL record plus a
``kind="span"`` timing span (tracing.emit_op_span), so
``metrics_report --check`` gates the schema and ``--health`` can name
the most-stale slice (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import threading
import time
from typing import TYPE_CHECKING, Optional

import numpy as np

if TYPE_CHECKING:  # config type only — no runtime import cost
    from xflow_tpu.config import SyncConfig

MEMBERSHIP_FILE = "membership.json"
_DELTA_RE = re.compile(r"^delta_s(\d+)_r(\d+)\.ok$")
_SNAP_RE = re.compile(r"^snap_s(\d+)_r(\d+)\.ok$")
# staleness-wait poll cadence: the deltas land via os.replace, so a
# tight poll costs one readdir — cheap against a K-step train block
_POLL_S = 0.05


# ----------------------------------------------------------- membership
def write_membership(sync_dir: str, live, run_id: str = "",
                     note: str = "") -> None:
    """Atomically publish the live slice set (launcher-owned: the
    watchdog dead verdict and the per-slice supervision loop are the
    only writers; every SliceSyncer re-reads it on each wait poll so a
    dead slice stops being waited on mid-exchange)."""
    from xflow_tpu.train.checkpoint import _write_atomic

    payload = {
        "live": sorted(int(s) for s in live),
        "run_id": run_id,
        "note": note,
        "ts": round(time.time(), 6),
    }

    def write_json(p):
        with open(p, "w") as f:
            json.dump(payload, f)

    _write_atomic(os.path.join(sync_dir, MEMBERSHIP_FILE), write_json)


def read_membership(sync_dir: str, num_slices: int) -> set:
    """The live slice set, defensively: a missing/corrupt membership
    file (first sync racing the launcher's initial write) means
    everyone is live — the syncer's timeouts bound the cost of a wrong
    optimistic answer, while a wrong 'dead' answer would silently drop
    a healthy slice's deltas."""
    path = os.path.join(sync_dir, MEMBERSHIP_FILE)
    try:
        with open(path) as f:
            data = json.load(f)
        live = {int(s) for s in data["live"]}
    except (OSError, ValueError, TypeError, KeyError):
        return set(range(num_slices))
    return {s for s in live if 0 <= s < num_slices} or set(range(num_slices))


# ------------------------------------------------------------ the syncer
class SliceSyncer:
    """The per-slice half of the sync tier: publish my delta, gather my
    peers' (subject to the staleness bound), apply, rebase.

    Pure against I/O other than the sync dir: the caller (the trainer's
    fit-loop hook) owns record emission and spans; `sync` returns the
    new state plus the ready-to-append ``kind="sync"`` record body.
    Rounds are 1-based; ``_applied[p]`` is the last round of peer ``p``
    folded into my state (0 = none yet)."""

    def __init__(self, sync_cfg: "SyncConfig", slice_id: int,
                 num_slices: int, clock=time.monotonic, sleep=time.sleep):
        mode = str(sync_cfg.mode)
        if mode not in ("sync", "bounded", "async"):
            raise ValueError(
                f"sync.mode={mode!r}: expected sync|bounded|async "
                "(off never constructs a syncer)"
            )
        if not sync_cfg.dir:
            raise ValueError(
                "sync.dir is empty: the sync tier needs a shared "
                "directory (launch-multislice wires <run_dir>/sync)"
            )
        self.cfg = sync_cfg
        self.mode = mode
        # mode=sync is the K=0 lockstep; bounded honors staleness_k
        self.k = 0 if mode == "sync" else max(int(sync_cfg.staleness_k), 0)
        self.slice_id = int(slice_id)
        self.num_slices = max(int(num_slices), 1)
        self.dir = sync_cfg.dir
        self.round = 0
        self._base: Optional[dict] = None
        self._applied = {
            p: 0 for p in range(self.num_slices) if p != self.slice_id
        }
        self._last_live = set(range(self.num_slices))
        self._adopted = False
        self._clock = clock
        self._sleep = sleep
        # chaos injectors, resolved once (testing/faults.py)
        from xflow_tpu.testing.faults import sync_faults_from_env

        self._kill_round, self._delay_s = sync_faults_from_env()
        os.makedirs(self.dir, exist_ok=True)

    # ------------------------------------------------- state <-> host
    def _flatten(self, state) -> dict:
        """Host-side flat view of the SYNCABLE leaves — tables plus
        optimizer state (FTRL z/n are additive accumulators, so the
        delta model covers them), NEVER the step counter: each slice's
        step/data position is its own (exact example accounting)."""
        from xflow_tpu.train.checkpoint import _flatten

        flat = _flatten(state)
        flat.pop("step", None)
        return flat

    def _rebuild(self, state, flat: dict):
        """Place the merged host arrays back onto the live state's
        shardings (the reshard-on-load idiom, train/checkpoint.restore:
        device_put against each leaf's own sharding handles any
        in-slice mesh layout)."""
        import jax

        tables = {}
        for name, t in state.tables.items():
            arr = np.asarray(flat[f"tables/{name}"], dtype=t.dtype)
            tables[name] = jax.device_put(arr, t.sharding)
        opt_state = {}
        for name, st in state.opt_state.items():
            opt_state[name] = {}
            for k, v in st.items():
                arr = np.asarray(flat[f"opt/{name}/{k}"], dtype=v.dtype)
                opt_state[name][k] = jax.device_put(arr, v.sharding)
        return state._replace(tables=tables, opt_state=opt_state)

    def attach(self, state):
        """Fix the delta base = the state entering the fit loop. MUST
        run before the first `sync` (the trainer calls it at fit start,
        after any checkpoint restore and snapshot adoption)."""
        self._base = self._flatten(state)
        latest = self._scan(_DELTA_RE)
        # a relaunched slice must continue its round numbering past its
        # previous generation's published files (peers' _applied
        # bookkeeping survives in their processes; re-publishing an old
        # round would collide with a committed file)
        self.round = max(self.round, latest.get(self.slice_id, 0))
        from xflow_tpu.telemetry import resolve_restart_gen

        if resolve_restart_gen() > 0 and not self._adopted:
            # rejoin WITHOUT a snapshot to adopt (death before the
            # first snapshot round): the restored checkpoint already
            # folded in some unknown prefix of every peer's deltas, so
            # re-applying from round 1 would double-count. Fast-forward
            # the bookkeeping past everything already published —
            # peer work from the dead window is skipped, never applied
            # twice (monotone, bounded-staleness-honest; the snapshot
            # path is the lossless catch-up).
            for p in self._applied:
                self._applied[p] = max(self._applied[p], latest.get(p, 0))

    # ------------------------------------------------------ dir scans
    def _scan(self, rx: re.Pattern) -> dict:
        """{slice: newest committed round} for one marker family —
        commit markers only (the .npz lands first via temp+rename, the
        .ok marker witnesses the ordering, same protocol as COMMITTED)."""
        latest: dict = {}
        try:
            names = os.listdir(self.dir)
        except OSError:
            return latest
        for name in names:
            m = rx.match(name)
            if m:
                s, r = int(m.group(1)), int(m.group(2))
                if r > latest.get(s, 0):
                    latest[s] = r
        return latest

    def _live(self) -> set:
        return read_membership(self.dir, self.num_slices)

    def _delta_path(self, s: int, r: int) -> str:
        return os.path.join(self.dir, f"delta_s{s}_r{r}.npz")

    def _snap_path(self, s: int, r: int) -> str:
        return os.path.join(self.dir, f"snap_s{s}_r{r}.npz")

    def _publish(self, kind: str, path: str, marker: str, arrays: dict,
                 extra: Optional[dict] = None) -> int:
        """Atomic npz + JSON commit marker; returns the payload bytes."""
        from xflow_tpu.train.checkpoint import _write_atomic

        def write_npz(p):
            with open(p, "wb") as f:
                np.savez(f, **arrays)

        _write_atomic(path, write_npz)
        size = os.path.getsize(path)
        meta = {
            "kind": kind,
            "slice": self.slice_id,
            "bytes": size,
            "ts": round(time.time(), 6),
            **(extra or {}),
        }

        def write_marker(p):
            with open(p, "w") as f:
                json.dump(meta, f)

        _write_atomic(marker, write_marker)
        return size

    # ------------------------------------------------ snapshot catch-up
    def adopt_latest_snapshot(self, state):
        """Rejoin catch-up: overwrite the syncable leaves with the
        freshest published snapshot (highest round; ties to the lowest
        slice), KEEPING my own step counter and data position — the
        checkpoint restore already placed those, and they are what the
        zero-lost-examples accounting audits. Returns
        (state, (round, source_slice) | None). Peer bookkeeping jumps
        to the snapshot round: deltas the snapshot already folded in
        must not double-apply (older rounds are skipped; missing files
        in the gap are tolerated — at-least-once, bounded-staleness
        semantics, not exact replay)."""
        snaps = self._scan(_SNAP_RE)
        if not snaps:
            return state, None
        r = max(snaps.values())
        src = min(s for s, rr in snaps.items() if rr == r)
        try:
            with np.load(self._snap_path(src, r)) as z:
                flat = {k: z[k] for k in z.files if k != "step"}
        except (OSError, ValueError) as e:
            print(
                f"# multislice: snapshot s{src} r{r} unreadable "
                f"({type(e).__name__}: {e}); rejoining without catch-up",
                file=sys.stderr,
            )
            return state, None
        state = self._rebuild(state, flat)
        self._base = flat
        for p in self._applied:
            self._applied[p] = max(self._applied[p], r)
        self.round = max(self.round, r)
        self._adopted = True
        return state, (r, src)

    # ------------------------------------------------------- the round
    def _wait_for_bound(self, want: int, peers_of) -> tuple:
        """Block until every live peer has published round >= want, the
        membership has shrunk past the laggard, or the timeout+retry
        budget is spent. Returns (satisfied, timeouts, live_set).
        Every path is bounded: worst case timeout_s * (retries + 1)
        plus the backoff sleeps."""
        from xflow_tpu.launch.supervise import backoff_delay

        timeouts = 0
        retries = max(int(self.cfg.retries), 0)
        timeout_s = max(float(self.cfg.timeout_s), 0.0)
        for attempt in range(retries + 1):
            deadline = self._clock() + timeout_s
            while True:
                live = self._live()
                latest = self._scan(_DELTA_RE)
                if all(latest.get(p, 0) >= want for p in peers_of(live)):
                    return True, timeouts, live
                if self._clock() >= deadline:
                    break
                self._sleep(_POLL_S)
            timeouts += 1
            if attempt < retries:
                self._sleep(
                    backoff_delay(attempt, float(self.cfg.backoff_s))
                )
        return False, timeouts, self._live()

    def sync(self, state) -> tuple:
        """One sync round: publish my delta, gather peers under the
        staleness policy, apply in (round, slice) order, rebase.
        Returns (new_state, record) — `record` is the ``kind="sync"``
        body the trainer appends (docs/OBSERVABILITY.md schema)."""
        t0 = time.perf_counter()
        self.round += 1
        r = self.round
        if self._kill_round and r == self._kill_round:
            # the slice-loss drill: die ENTERING the round, before the
            # delta publishes — peers must time out, drop us via the
            # launcher's membership update, and continue degraded
            from xflow_tpu.testing.faults import hard_kill

            hard_kill()
        if self._delay_s:
            self._sleep(self._delay_s)  # the straggler drill
        if self._base is None:
            raise RuntimeError("SliceSyncer.sync before attach()")
        local = self._flatten(state)
        delta = {k: local[k] - self._base[k] for k in local}
        bytes_out = self._publish(
            "delta",
            self._delta_path(self.slice_id, r),
            os.path.join(self.dir, f"delta_s{self.slice_id}_r{r}.ok"),
            delta,
            extra={"round": r},
        )
        del delta

        def peers_of(live):
            return [
                p for p in sorted(live)
                if p != self.slice_id and p in self._applied
            ]

        timeouts = 0
        if self.mode != "async":
            want = r - self.k
            latest = self._scan(_DELTA_RE)
            satisfied = all(
                latest.get(p, 0) >= want for p in peers_of(self._live())
            )
            if not satisfied and want > 0 and not (
                self.mode == "bounded" and str(self.cfg.on_stale) == "proceed"
            ):
                # on_stale=proceed checks once and continues on stale
                # state (counted below); everyone else runs the bounded
                # wait
                _, timeouts, _ = self._wait_for_bound(want, peers_of)
        # apply every not-yet-applied peer round up to MY round (peer
        # rounds from my future wait until I get there: deterministic
        # at K=0, and exactly the staleness window otherwise). ALL
        # peers, live or not: a dead slice's committed deltas are
        # trained examples — dropping them would lose its work, and the
        # zero-lost-examples accounting audits exactly that.
        latest = self._scan(_DELTA_RE)
        merged: Optional[dict] = None
        bytes_in = 0
        applied = 0
        for p in sorted(self._applied):
            top = min(latest.get(p, 0), r)
            for rr in range(self._applied[p] + 1, top + 1):
                path = self._delta_path(p, rr)
                marker = os.path.join(self.dir, f"delta_s{p}_r{rr}.ok")
                if not os.path.exists(marker):
                    continue  # gap from a crashed generation: tolerated
                try:
                    with np.load(path) as z:
                        if merged is None:
                            merged = {k: local[k].copy() for k in local}
                        for k in merged:
                            merged[k] += z[k]
                except (OSError, ValueError, KeyError) as e:
                    print(
                        f"# multislice: delta s{p} r{rr} unreadable "
                        f"({type(e).__name__}: {e}); skipped",
                        file=sys.stderr,
                    )
                    continue
                bytes_in += os.path.getsize(path)
                applied += 1
            self._applied[p] = max(self._applied[p], top)
        if merged is not None:
            state = self._rebuild(state, merged)
            self._base = merged
        else:
            # structural passthrough: the bitwise-K=0 guarantee
            self._base = local
        # staleness accounting against the LIVE set only (a dead slice
        # is the launcher's problem, not a lag statistic)
        live = self._live()
        lags = {
            str(p): r - self._applied[p] for p in peers_of(live)
        }
        lag_max = max(lags.values(), default=0)
        stale = sum(1 for v in lags.values() if v > self.k)
        joined = sorted(live - self._last_live)
        left = sorted(self._last_live - live)
        self._last_live = live
        if self.cfg.snapshot_every > 0 and r % int(self.cfg.snapshot_every) == 0:
            snap = dict(self._base)
            snap["step"] = np.asarray(state.step)
            self._publish(
                "snapshot",
                self._snap_path(self.slice_id, r),
                os.path.join(self.dir, f"snap_s{self.slice_id}_r{r}.ok"),
                snap,
                extra={"round": r, "step": int(state.step)},
            )
        record = {
            "kind": "sync",
            "round": r,
            "k": self.k,
            "mode": self.mode,
            "live": sorted(live),
            "joined": joined,
            "left": left,
            "bytes_out": int(bytes_out),
            "bytes_in": int(bytes_in),
            "applied": int(applied),
            "stale": int(stale),
            "timeouts": int(timeouts),
            "lag_max": int(lag_max),
            "lags": lags,
            "dur_ms": round((time.perf_counter() - t0) * 1e3, 3),
        }
        return state, record


# ----------------------------------------------------------- the launcher
def slice_forward_args(forward_args: list, j: int) -> list:
    """Per-slice argv: the literal ``{slice}`` placeholder substitutes
    to the slice index, so one command line gives every slice its own
    data shards and checkpoint dir (e.g.
    ``--train data/s{slice} --checkpoint-dir run/ckpt_slice{slice}``)."""
    return [a.replace("{slice}", str(j)) for a in forward_args]


def _spawn_slice(j: int, num_slices: int, forward_args: list, run_dir: str,
                 sync_dir: str, run_id: str, gen: int) -> subprocess.Popen:
    """One slice subprocess: an independent single-process
    ``xflow train`` (no coordinator — each slice is its own world; the
    DCN tier is the filesystem, not collectives). XFLOW_PROCESS_ID
    doubles as the rank stamp so the shared watchdog and
    metrics_report see slice j as rank j."""
    from xflow_tpu.launch.local import rank_metrics_args

    env = dict(os.environ)
    env.pop("XFLOW_COORDINATOR", None)
    env.pop("XFLOW_NUM_PROCESSES", None)
    env.update(
        XFLOW_SLICE=str(j),
        XFLOW_NUM_SLICES=str(num_slices),
        XFLOW_PROCESS_ID=str(j),
        XFLOW_RUN_ID=run_id,
        XFLOW_RESTART_GEN=str(gen),
        # CPU devices by default, same reasoning as launch-local: every
        # slice landing on one ambient accelerator would serialize them
        JAX_PLATFORMS=env.get("XFLOW_LAUNCH_PLATFORM", "cpu"),
    )
    cmd = [
        sys.executable, "-m", "xflow_tpu", "train",
        *slice_forward_args(forward_args, j),
        *rank_metrics_args(run_dir, j),
        "--set", f"sync.dir={sync_dir}",
    ]
    return subprocess.Popen(cmd, env=env)


def launch_multislice(
    num_slices: int,
    forward_args: list,
    run_dir: str,
    straggler_factor: float = 0.0,
    dead_after_s: float = 0.0,
    watchdog_poll_s: float = 0.0,
    max_restarts: int = 0,
    restart_backoff: float = 1.0,
    min_uptime_s: float = 0.0,
) -> int:
    """Run N slices under PER-SLICE supervision. The structural
    difference from launch-local: slices share no collectives, so a
    dead slice must NOT tear the job down (no fail-fast) — its
    supervision loop relaunches it alone (with ``train.resume=true``,
    restoring its own checkpoint for exact data accounting) while the
    survivors keep training degraded. The launcher owns
    ``membership.json``: a slice leaves the live set on process exit or
    a watchdog dead verdict (PR 5's DeadHostTracker bookkeeping — a
    wedged slice that never exits is killed so its supervisor can act)
    and rejoins when its relaunch spawns. Returns 0 iff every slice's
    supervision ended clean."""
    from xflow_tpu.launch.local import resolve_launch_run_id
    from xflow_tpu.launch.supervise import (
        DeadHostTracker,
        resume_forward_args,
        supervise,
        terminate_procs,
    )
    from xflow_tpu.launch.watchdog import RunWatchdog

    if forward_args and forward_args[0] == "--":
        forward_args = forward_args[1:]
    if num_slices < 1:
        print("launch-multislice: --slices must be >= 1", file=sys.stderr)
        return 2
    if not run_dir:
        print(
            "launch-multislice: --run-dir is required (the sync tier "
            "lives in <run-dir>/sync)",
            file=sys.stderr,
        )
        return 2
    os.makedirs(run_dir, exist_ok=True)
    sync_dir = os.path.join(run_dir, "sync")
    os.makedirs(sync_dir, exist_ok=True)
    run_id = resolve_launch_run_id()
    live = set(range(num_slices))
    lock = threading.Lock()
    write_membership(sync_dir, live, run_id=run_id, note="launch")
    procs: dict = {}
    # slices are always shrinkable (no collectives to wedge peers), so
    # the tracker runs in allow-shrink mode unconditionally
    tracker = DeadHostTracker(allow_shrink=True)

    def set_live(j: int, alive: bool, note: str) -> None:
        with lock:
            changed = (j in live) != alive
            if alive:
                live.add(j)
            else:
                live.discard(j)
            if changed:
                write_membership(sync_dir, live, run_id=run_id, note=note)
        if changed:
            print(
                f"launch-multislice: slice {j} "
                f"{'rejoined' if alive else 'left'} the sync group "
                f"({note}); live = {sorted(live)}",
                file=sys.stderr,
            )

    def on_dead(row: dict) -> None:
        # the wedged-slice path: a dead/missing verdict drops the slice
        # from the sync group and KILLS its process, so the per-slice
        # supervisor (below) observes the exit and relaunches it —
        # verdict-to-recovery without any cross-slice teardown
        j = row.get("rank")
        if not isinstance(j, int) or not 0 <= j < num_slices:
            return
        tracker.record(("slice", j))
        set_live(j, False, "watchdog-dead")
        p = procs.get(j)
        if p is not None and p.poll() is None:
            p.kill()

    watchdog = RunWatchdog(
        run_dir,
        num_ranks=num_slices,
        straggler_factor=straggler_factor,
        dead_after_s=dead_after_s,
        poll_s=watchdog_poll_s,
        run_id=run_id,
        on_dead=on_dead,
        gen=0,
    )
    watchdog.start()
    results: dict = {}

    def slice_main(j: int) -> None:
        def attempt(gen: int) -> int:
            args = (
                forward_args if gen == 0 else resume_forward_args(forward_args)
            )
            if gen > 0:
                set_live(j, True, f"relaunch gen {gen}")
            p = _spawn_slice(
                j, num_slices, args, run_dir, sync_dir, run_id, gen
            )
            procs[j] = p
            rc = p.wait()
            if rc != 0:
                tracker.record(("slice", j))
                set_live(j, False, f"exit rc={rc}")
            else:
                # a finished slice publishes no further rounds — leave
                # the group so still-training peers stop waiting on it
                # (their staleness waits re-read membership each poll)
                set_live(j, False, "finished")
            return rc

        results[j] = supervise(
            attempt,
            max_restarts=max_restarts,
            restart_backoff=restart_backoff,
            min_uptime_s=min_uptime_s,
            label=f"launch-multislice[slice{j}]",
        )

    threads = [
        threading.Thread(target=slice_main, args=(j,), name=f"xflow-slice{j}")
        for j in range(num_slices)
    ]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    except KeyboardInterrupt:
        terminate_procs([p for p in procs.values() if p is not None])
        raise
    finally:
        watchdog.stop()
    lost = len(tracker.lost)
    if lost:
        print(
            f"launch-multislice: {lost} slice-loss event(s) recorded "
            f"this run (see {os.path.join(run_dir, 'watchdog.jsonl')} "
            "and the kind=sync membership trail)",
            file=sys.stderr,
        )
    return next((rc for rc in results.values() if rc), 0)
