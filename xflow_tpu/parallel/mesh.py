"""Device mesh and sharding specs.

The reference's process topology — N async workers × M key-range-sharded
servers (ps-lite; SURVEY.md §1 "Parallelism topology") — maps onto a
2-D ``('data', 'table')`` mesh:

- the ``data`` axis is the worker tier: the batch is split across it
  (synchronous data parallelism instead of hogwild async);
- the ``table`` axis is the server tier: parameter/optimizer tables are
  sharded on the feature-slot axis.

Tables are sharded over *both* axes (``P(('data','table'))``) so every
chip holds 1/(D·T) of each table — the 1B-feature FTRL state of the
north-star config only fits HBM fully sharded (SURVEY.md §7 hard part
d). GSPMD then lowers the step's gather/scatter into the ICI
collectives that replace ps-lite's ZMQ Push/Pull RPC.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from xflow_tpu.config import Config

DATA_AXIS = "data"
TABLE_AXIS = "table"


def make_mesh(cfg: Config, devices=None) -> Mesh:
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = devices.size
    d, t = cfg.mesh.data, cfg.mesh.table
    if d == -1 and t == -1:
        d, t = n, 1
    elif d == -1:
        d = n // t
    elif t == -1:
        t = n // d
    if d * t != n:
        raise ValueError(f"mesh {d}x{t} != {n} devices")
    return Mesh(devices.reshape(d, t), (DATA_AXIS, TABLE_AXIS))


def table_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Slot axis fully sharded over the whole mesh; trailing dims replicated."""
    spec = ((DATA_AXIS, TABLE_AXIS),) + (None,) * (ndim - 1)
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> dict:
    """Batch arrays split on the leading (row) axis over the data axis.

    The sorted-plan entries ([D, Np_l] stacked per-data-shard plans,
    parallel/sorted_sharded.py) shard their leading axis the same way.
    """
    row2d = NamedSharding(mesh, P(DATA_AXIS, None))
    row1d = NamedSharding(mesh, P(DATA_AXIS))
    # fullshard buffers [D_src, T, D_dst, cap]: source shard on 'data',
    # destination column on 'table' (parallel/sorted_fullshard.py)
    fs4 = NamedSharding(mesh, P(DATA_AXIS, TABLE_AXIS, None, None))
    return {
        "slots": row2d,
        "fields": row2d,
        "mask": row2d,
        "labels": row1d,
        "row_mask": row1d,
        "sorted_slots": row2d,
        "sorted_row": row2d,
        "sorted_mask": row2d,
        "sorted_fields": row2d,
        "win_off": row2d,
        "fs_slots": fs4,
        "fs_row": fs4,
        "fs_mask": fs4,
        "fs_off": fs4,
        "fs_fields": fs4,
        # host-dedup arrays (data.dedup): the unique set is global to the
        # batch (replicated); the inverse indexes per row
        "unique_slots": NamedSharding(mesh, P()),
        "inverse": row2d,
    }


def state_shardings(state, mesh: Mesh):
    """A pytree of NamedShardings matching a TrainState."""

    def spec(leaf):
        if getattr(leaf, "ndim", 0) >= 1:
            n = getattr(leaf, "shape", (0,))[0]
            if n % mesh.size != 0:
                # fail here with a framework message instead of deep
                # inside XLA partitioning
                raise ValueError(
                    f"table slot count {n} is not divisible by the mesh size "
                    f"{mesh.size} ({dict(mesh.shape)}); pick data.log2_slots "
                    "so 2^log2_slots is a multiple of data*table"
                )
            return table_sharding(mesh, leaf.ndim)
        return replicated(mesh)

    return jax.tree.map(spec, state)
