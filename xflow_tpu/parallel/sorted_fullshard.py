"""Fully-sharded sorted-window training: the pod-scale fast path.

The table AND its optimizer state shard over the WHOLE mesh —
``P(('data','table'), None)``, each device owning ``S/(D*T)`` slots =
``wpo`` whole windows — with NO replication anywhere (the 1B-feature /
12 GB-FTRL-state north-star regime only fits HBM this way; SURVEY.md §7
hard part d). This is the direct analog of ps-lite sharding the uint64
key space across *all* servers with no replication (SURVEY.md §2 C13),
where `parallel/sorted_sharded.py` replicates the table across 'data'
(D× memory) to save collectives.

Data flow per step, device (d, t), owner block o = d*T + t:

1. HOST: each data shard's occurrences are slot-sorted once
   (`plan_sorted_batch`, the same plan the single-chip engine uses) and
   then sliced at owner-block boundaries — a block's occurrences are one
   CONTIGUOUS span of the sorted stream — into fixed-capacity buffers
   ``[T, D_dst, cap]`` (`fullshard_buffers`). Pads carry the block's
   last local slot with mask 0, the same convention as plan pads.
2. ONE `all_to_all` over 'data' delivers to device (d, t) the D buffers
   (one per source shard) targeting ITS block — occurrence-scale
   traffic (~12 B/occurrence · slack), the synchronous analog of every
   worker Pulling from the server that owns each key
   (`lr_worker.cc:170`), batched into one collective.
3. The Pallas sorted-window kernels run on the local ``[S/(D*T), K]``
   table shard over the concatenated buffer stream
   (`table_gather_sorted_multi`: WINDOW-MAJOR in both directions —
   each grid step owns one table window and walks every source
   buffer's span, so the shard crosses HBM→VMEM once per call; the
   VJP accumulates all buffers into one block write per local window).
4. Per-row partial sums for ALL source shards are reduced to their row
   owners by ONE `psum_scatter` over 'data' + ONE `psum` over 'table'
   (~B·ch·4 B each) — aggregated rows cross the wire, never table rows.
5. Backward: the transpose all-gathers the small [R, ch] row cotangent
   over 'data'; the table gradient is a SHARD-LOCAL scatter — no
   table-scale collective exists in either direction.

Load imbalance, stated plainly: hashing spreads slots near-uniformly
across owner blocks, but a hot feature's occurrences all land in one
block (ps-lite has the identical imbalance: one server owns the hot
key). `data.fullshard_slack` sizes the buffers; overflow fails loudly
at plan time with the slack to raise. Host-side dedup shrinks exactly
this traffic on skewed data (docs/PERF.md lever 4).

Supports fused FM, MVM, and FFM (sorted-engine models; FFM rides the
MVM segment mode's machinery with its own channel contract —
models/ffm.py). LR stays on the GSPMD row-major path: its 1-D table
gather is already bandwidth-efficient (2.2× the per-chip target,
BENCH_r02) and needs no windowed engine.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from xflow_tpu.config import Config
from xflow_tpu.metrics import binary_logloss_from_logits
from xflow_tpu.ops.sorted_table import (
    CHUNK,
    WINDOW,
    SortedPlan,
    map_host_parallel,
    plan_sorted_batch,
    row_sums_sorted,
    table_gather_sorted_multi,
)
from xflow_tpu.parallel.compat import shard_map
from xflow_tpu.parallel.mesh import DATA_AXIS, TABLE_AXIS
from xflow_tpu.train.state import TrainState
from xflow_tpu.train.step import guard_nonfinite, health_norms, metrics_keys

FS_KEYS = ("fs_slots", "fs_row", "fs_mask", "fs_off")


class FullshardOverflowError(ValueError):
    """An owner block's occurrences exceed the buffer capacity (data more
    skewed than data.fullshard_slack allows). Distinct from other config
    errors so the trainer can fall back to the GSPMD row-major step for
    the offending batch. Single-process falls back locally; multi-process
    coordinates the fallback rank-symmetrically — every rank contributes
    its overflow flag to one per-batch allgather and ALL ranks run the
    row-major step when any overflowed
    (trainer._resolve_fullshard_overflow), so the collective programs
    never desync."""


def _dims(cfg: Config, mesh: Mesh):
    d, t = mesh.shape[DATA_AXIS], mesh.shape[TABLE_AXIS]
    p = jax.process_count()
    return d, t, p


def validate_sorted_fullshard(cfg: Config, mesh: Mesh) -> None:
    """Reject configs the fully-sharded engine cannot run, with the
    specific reason (mirrors validate_sorted_sharded)."""
    d, t, p = _dims(cfg, mesh)
    S = cfg.num_slots
    if S % (d * t * WINDOW) != 0:
        raise ValueError(
            f"fullshard layout needs num_slots (2^{cfg.data.log2_slots}) "
            f"divisible by data*table*WINDOW = {d}*{t}*{WINDOW} (each device "
            "owns whole windows)"
        )
    if cfg.model.name == "fm":
        if not cfg.model.fm_fused:
            raise ValueError("fullshard FM needs model.fm_fused=true (one table)")
    elif cfg.model.name not in ("mvm", "ffm"):
        raise ValueError(
            "fullshard layout supports fused FM, MVM, and FFM (LR keeps the "
            f"GSPMD row-major path); got model={cfg.model.name}"
        )
    if d % p != 0:
        raise ValueError(
            f"fullshard layout needs the data axis ({d}) divisible by the "
            f"process count ({p}): each process plans its rows into d/P shards"
        )
    if cfg.data.batch_size % (d // p) != 0:
        raise ValueError(
            f"per-process batch_size {cfg.data.batch_size} not divisible by "
            f"the local data-shard count {d // p}"
        )
    if cfg.data.sorted_sub_batches not in (0, d // p):
        raise ValueError(
            f"data.sorted_sub_batches={cfg.data.sorted_sub_batches} conflicts "
            f"with the fullshard plan count (= {d // p} per process); leave it 0"
        )
    if cfg.data.fullshard_slack < 1.0:
        raise ValueError(
            f"data.fullshard_slack={cfg.data.fullshard_slack} < 1 cannot hold "
            "even perfectly uniform occupancy"
        )


def fullshard_capacity(cfg: Config, mesh: Mesh) -> int:
    """Per-(source shard, owner block) buffer capacity: a CHUNK multiple
    covering `slack`× the uniform-hash expectation, plus one spare CHUNK
    for the plan pads that ride in the stream's last block."""
    d, t, p = _dims(cfg, mesh)
    rows = cfg.data.batch_size // (d // p)
    expect = rows * cfg.data.max_nnz / (d * t)  # real occurrences only:
    # plan pads are NOT copied into the buffers (fullshard_buffers clamps
    # spans to n_real; each buffer carries its own pads past its span)
    cap = int(np.ceil(cfg.data.fullshard_slack * expect / CHUNK)) * CHUNK
    return max(cap, CHUNK) + CHUNK


def fullshard_buffers(
    plan: SortedPlan,
    D: int,
    T: int,
    cap: int,
    s_local: int,
    slack: float,
    with_fields: bool = False,
    *,
    n_real: int,
) -> dict:
    """Slice ONE shard's flat sorted plan at owner-block boundaries into
    per-destination buffers.

    Returns ``fs_slots/fs_row/fs_mask`` ``[T, D, cap]`` (+ ``fs_fields``)
    and ``fs_off`` ``[T, D, wpo+1]``: buffer-local window offsets with
    the last entry extended to `cap`, so the block's last window owns the
    pads (pad slot = s_local-1, mask 0 — the plan-pad convention).
    """
    win_off = plan.win_off
    n_win = win_off.shape[0] - 1
    wpo = n_win // (D * T)
    # plan pads (slot num_slots-1, up to 2 CHUNKs of them) would all land
    # in the LAST owner block and can overflow its buffer; clamp every
    # span to `n_real` (the caller's real occurrence count — REQUIRED, so
    # no caller accidentally counts pads against capacity). Stable sorting
    # puts pads after the real occurrences of the last slot, so clamping
    # drops only pads; each buffer pads ITSELF past its span (mask 0,
    # slot s_local-1).
    slots = np.full((T, D, cap), s_local - 1, np.int32)
    row = np.zeros((T, D, cap), np.int32)
    mask = np.zeros((T, D, cap), np.float32)
    fields = np.zeros((T, D, cap), np.int32) if with_fields else None
    off = np.empty((T, D, wpo + 1), np.int32)
    for t in range(T):
        for d in range(D):
            o = d * T + t
            lo = min(int(win_off[o * wpo]), n_real)
            hi = min(int(win_off[(o + 1) * wpo]), n_real)
            L = hi - lo
            if L > cap:
                raise FullshardOverflowError(
                    f"owner block {o} holds {L} occurrences > buffer capacity "
                    f"{cap}: the hash distribution is more skewed than "
                    f"data.fullshard_slack={slack} allows — raise it (a hot "
                    "feature's occurrences all land in one block)"
                )
            slots[t, d, :L] = plan.sorted_slots[lo:hi] - o * s_local
            row[t, d, :L] = plan.sorted_row[lo:hi]
            mask[t, d, :L] = plan.sorted_mask[lo:hi]
            if with_fields:
                fields[t, d, :L] = plan.sorted_fields[lo:hi]
            off[t, d, :wpo] = (
                np.minimum(win_off[o * wpo : (o + 1) * wpo], n_real) - lo
            )
            off[t, d, wpo] = cap
    out = {"fs_slots": slots, "fs_row": row, "fs_mask": mask, "fs_off": off}
    if with_fields:
        out["fs_fields"] = fields
    return out


def plan_fullshard_batch(
    slots: np.ndarray,
    mask: np.ndarray,
    cfg: Config,
    mesh: Mesh,
    fields: Optional[np.ndarray] = None,
) -> dict:
    """This process's [B, F] batch -> stacked fullshard buffers
    [D_local, T, D, cap] (+ fs_off [D_local, T, D, wpo+1]).

    Each local data shard is planned (slot-sorted) and sliced
    independently; the C planner releases the GIL, so shards parallelize
    across host cores like plan_sorted_stacked's sub-batches.
    """
    from xflow_tpu.ops.sorted_table import _native_planner, _plan_pool

    d, t, p = _dims(cfg, mesh)
    d_local = d // p
    B = slots.shape[0]
    if B != cfg.data.batch_size or slots.shape[1] != cfg.data.max_nnz:
        # capacity is sized from the config; a mismatched batch would
        # validate against the wrong buffer budget
        raise ValueError(
            f"batch shape {slots.shape} != configured "
            f"(batch_size={cfg.data.batch_size}, max_nnz={cfg.data.max_nnz})"
        )
    rows = B // d_local
    cap = fullshard_capacity(cfg, mesh)
    s_local = cfg.num_slots // (d * t)
    with_fields = fields is not None

    def one(i):
        sl = slice(i * rows, (i + 1) * rows)
        plan = plan_sorted_batch(
            slots[sl], mask[sl], cfg.num_slots,
            fields=fields[sl] if with_fields else None,
        )
        return fullshard_buffers(
            plan, d, t, cap, s_local, cfg.data.fullshard_slack, with_fields,
            n_real=rows * slots.shape[1],
        )

    bufs = map_host_parallel(one, d_local)
    return {k: np.stack([b[k] for b in bufs]) for k in bufs[0]}


def fullshard_batch_sharding(mesh: Mesh, with_fields: bool = False) -> dict:
    """Subset of the canonical batch_sharding dict (parallel/mesh.py) so
    the placement and jit in_shardings contracts stay in lockstep."""
    from xflow_tpu.parallel.mesh import batch_sharding

    full = batch_sharding(mesh)
    keys = FS_KEYS + (("fs_fields",) if with_fields else ()) + (
        "labels", "row_mask",
    )
    return {k: full[k] for k in keys}


def _local_logits(mode, tbl_local, fs_slots, fs_row, fs_mask, fs_off, fs_fields,
                  R, cfg, D, K, nf, bf16, plus):
    """Device (d, t) forward body, shared by the train and eval steps.

    tbl_local [S/(D*T)/pack, pack*K]; fs_* are MY source shard's buffers
    for column t, [D_dst, cap]; returns logits [R] for MY data
    coordinate's rows. Storage may be packed
    (ops/sorted_table.pack_table) — detected from the shard's shape,
    slot indices stay logical.

    Steps (the numbers refer to the module docstring's data flow):
    2. exchange: my buffer for dest d' -> device (d', t); receive every
       source's buffer for MY block — ONE all_to_all over 'data'.
    3. local windowed gather (+ shard-local scatter in the VJP).
    4. per-row aggregates return to their row owners: psum_scatter over
       'data' + psum over 'table' (owner_reduce).
    """
    from xflow_tpu.ops.sorted_table import pack_of, wire_mask, wire_rows

    def a2a(x):
        return jax.lax.all_to_all(x, DATA_AXIS, 0, 0, tiled=True)

    r_slots = a2a(fs_slots)  # [D_src, cap]
    # compacted wire dtypes (compact_plan_wire) ride through the
    # all_to_all — less ICI traffic too — and upcast after
    r_row = wire_rows(a2a(fs_row))
    r_mask = wire_mask(a2a(fs_mask))
    r_off = a2a(fs_off)  # [D_src, wpo+1]
    slots_flat = r_slots.reshape(-1)
    mask_flat = jax.lax.stop_gradient(r_mask.reshape(-1))

    occ_t = table_gather_sorted_multi(
        tbl_local, slots_flat, r_off, bf16, pack_of(tbl_local, K)
    )
    occm_t = occ_t[:K] * mask_flat[None, :]

    # rows arrive shard-local [0, R); globalize by source index so one
    # segment space covers all D source shards' rows
    grow = (r_row + jnp.arange(D, dtype=jnp.int32)[:, None] * R).reshape(-1)

    def owner_reduce(partials):
        mine = jax.lax.psum_scatter(
            partials, DATA_AXIS, scatter_dimension=0, tiled=True
        )  # [1, R(*nf), ch]
        return jax.lax.psum(mine, TABLE_AXIS)[0]

    if mode == "ffm":
        from xflow_tpu.models.ffm import make_ffm_row_op
        from xflow_tpu.ops.sorted_table import segment_sum_channels

        k_lat = cfg.model.v_dim
        fields_flat = wire_rows(a2a(fs_fields)).reshape(-1)
        # FFM channel contract + exact-at-zeros hand VJP
        # (models/ffm.py make_ffm_row_op): one segment-sum into the
        # per-(row, field) space, owner_reduce row return like the
        # segment MVM mode; the bwd all-gathers the [R, nf·(K+1)]
        # row aggregates over 'data' — the same traffic class as
        # the plain path's d_sums transpose
        op = make_ffm_row_op(
            lambda data, seg: owner_reduce(
                segment_sum_channels(data, seg, D * R * nf).reshape(
                    D, R * nf, K + 1
                )
            ).reshape(R, nf, K + 1),
            lambda arr: jax.lax.all_gather(arr, DATA_AXIS, tiled=True),
            nf, k_lat,
            # the shard_map transpose hands each 'table' copy dl/T
            # (make_ffm_row_op docstring) — restore before use
            restore_dl=lambda dl: jax.lax.psum(dl, TABLE_AXIS),
        )
        return op(occ_t, mask_flat, fields_flat, grow)
    if mode == "mvm_segment":
        from xflow_tpu.ops.sorted_table import segment_sum_channels

        r_fields = wire_rows(a2a(fs_fields))
        seg = grow * nf + r_fields.reshape(-1)
        # mask rides as an extra channel: its segment-sum is the
        # per-(row, field) occurrence count => `present` (models/mvm.py)
        stacked = jnp.concatenate([occm_t, mask_flat[None, :]], axis=0)
        sums_t = segment_sum_channels(stacked, seg, D * R * nf)  # [D*R*nf, k+1]
        sums = owner_reduce(sums_t.reshape(D, R * nf, K + 1))
        sums = sums.reshape(R, nf, K + 1)
        s, present = sums[..., :K], sums[..., K] > 0
        factors = jnp.where(present[..., None], s + plus, 1.0)
        return jnp.prod(factors, axis=1).sum(axis=-1)
    if mode == "mvm_product":
        from xflow_tpu.models.mvm import make_row_products

        # log-space product channels are ADDITIVE over shards (sums
        # of ln|v| / negative and zero counts), so the cross-shard
        # reduction is the same rowsum + psum_scatter + psum as FM's;
        # the op's bwd all-gathers the small [R, 4k] row aggregates
        # over 'data' — the same traffic class as FM's backward
        op = make_row_products(
            lambda stacked, rows_: owner_reduce(
                row_sums_sorted(stacked, rows_, D * R).reshape(D, R, -1)
            ),
            lambda arr: jax.lax.all_gather(arr, DATA_AXIS, tiled=True),
            K,
            # the shard_map transpose hands each 'table' copy dP/T
            # (make_row_products docstring) — restore before use.
            # Without this the product path's updates diverged from
            # single-device at every T>1 (round-4 ADVICE finding,
            # measured in round 5)
            restore_dP=lambda dP: jax.lax.psum(dP, TABLE_AXIS),
        )
        return op(occ_t[:K] + plus, mask_flat, grow).sum(axis=1)
    from xflow_tpu.models.fm import fm_logits_from_sums, stack_channels

    stacked = stack_channels(occm_t, K)  # [ch, N]
    rs = row_sums_sorted(stacked, grow, D * R)  # [D*R, ch]
    sums = owner_reduce(rs.reshape(D, R, -1))
    return fm_logits_from_sums(sums, K, cfg)


def _mode_statics(cfg: Config, mesh: Mesh):
    """(D, tname, K, nf, bf16, plus) shared by the train and eval
    builders — the ONE place the logical row width lives:
    MVM [k], FM [1+k], FFM [1+nf·k]."""
    D, _, _ = _dims(cfg, mesh)
    mvm = cfg.model.name == "mvm"
    ffm = cfg.model.name == "ffm"
    nf = cfg.model.num_fields
    K = cfg.model.v_dim if mvm else (
        1 + nf * cfg.model.v_dim if ffm else 1 + cfg.model.v_dim
    )
    return (
        D, "v" if mvm else "wv", K, nf, cfg.data.sorted_bf16,
        1.0 if cfg.model.mvm_plus_one else 0.0,
    )


def _batch_mode(cfg: Config, batch: dict) -> str:
    if cfg.model.name == "mvm":
        return "mvm_segment" if "fs_fields" in batch else "mvm_product"
    return "ffm" if cfg.model.name == "ffm" else "fm"


def make_fullshard_eval_step(cfg: Config, mesh: Mesh, recorder=None) -> Callable:
    """Forward-only fullshard step: eval consumes the SAME host plan the
    train step does (fs_* buffers, one all_to_all + owner_reduce)
    instead of shipping the dead row-major [B, F] arrays (~24 MB/batch
    at bench shapes — round-3 weak #5). Returns reference-clamped pctrs
    [B] sharded over 'data'."""
    from xflow_tpu.metrics import reference_pctr

    validate_sorted_fullshard(cfg, mesh)
    D, tname, K, nf, bf16, plus = _mode_statics(cfg, mesh)
    fs_spec = P(DATA_AXIS, TABLE_AXIS, None, None)
    jitted: dict = {}

    def build(mode: str):
        with_fields = mode in ("mvm_segment", "ffm")

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(
                P((DATA_AXIS, TABLE_AXIS), None),
                fs_spec, fs_spec, fs_spec, fs_spec, fs_spec,
                P(DATA_AXIS, None),  # labels (row count only)
            ),
            out_specs=P(DATA_AXIS, None),
            check_vma=False,
        )
        def sharded_pctr(tbl, fss, fsr, fsm, fso, fsf, labels):
            sq = lambda x: x[0, 0]
            logits = _local_logits(
                mode, tbl, sq(fss), sq(fsr), sq(fsm), sq(fso), sq(fsf),
                labels.shape[1], cfg, D, K, nf, bf16, plus,
            )
            return reference_pctr(logits)[None, :]

        def eval_step(tables, batch: dict):
            fsf = batch["fs_fields"] if with_fields else batch["fs_slots"]
            return sharded_pctr(
                tables[tname],
                batch["fs_slots"], batch["fs_row"], batch["fs_mask"],
                batch["fs_off"], fsf,
                batch["labels"].reshape(D, -1),
            ).reshape(-1)

        keys = FS_KEYS + (("fs_fields",) if with_fields else ()) + ("labels",)
        return eval_step, keys

    def call(tables, batch: dict):
        mode = _batch_mode(cfg, batch)
        if mode not in jitted:
            step, keys = build(mode)
            fn = jax.jit(step)
            if recorder is not None:
                fn = recorder.wrap(f"predict.fullshard.{mode}", fn)
            jitted[mode] = (fn, keys)
        fn, keys = jitted[mode]
        return fn(tables, {k: batch[k] for k in keys})

    return call


def make_fullshard_train_step(
    optimizer, cfg: Config, mesh: Mesh, recorder=None
) -> Callable:
    """FM/MVM train step with everything sharded over ('data','table').

    MVM runs in one of two row-side modes, chosen PER BATCH by the
    planner (trainer._mvm_wants_fields): "mvm_product" (no fs_fields —
    exclusive fields verified on the host; the row side is the same
    [R, ~24] row-sum + psum_scatter as FM, models/mvm.py) or
    "mvm_segment" (general multi-valued fields through the [R·nf]
    segment space). Each mode is its own jitted program; multi-process
    runs pin one mode for the whole run (resolve_mvm_product) so the
    ranks' collective sequences always agree.
    """
    validate_sorted_fullshard(cfg, mesh)
    D, tname, K, nf, bf16, plus = _mode_statics(cfg, mesh)

    def local_logits(mode, tbl_local, fs_slots, fs_row, fs_mask, fs_off,
                     fs_fields, R):
        return _local_logits(
            mode, tbl_local, fs_slots, fs_row, fs_mask, fs_off, fs_fields,
            R, cfg, D, K, nf, bf16, plus,
        )

    def local_loss(mode, tbl_local, fs_slots, fs_row, fs_mask, fs_off, fs_fields,
                   labels, row_mask):
        """Device (d, t) body: the shared forward (`_local_logits`) plus
        the loss reduction."""
        # "gather" holds the forward: shard-local windowed gather, the
        # occurrence all_to_all, and the row-aggregate return collectives
        with jax.named_scope("gather"):
            logits = local_logits(
                mode, tbl_local, fs_slots, fs_row, fs_mask, fs_off, fs_fields,
                labels.shape[0],
            )
        with jax.named_scope("loss"):
            per_row = binary_logloss_from_logits(logits, labels)
            loss_sum = jax.lax.psum((per_row * row_mask).sum(), DATA_AXIS)
            rows_n = jax.lax.psum(row_mask.sum(), DATA_AXIS)
            return loss_sum / jnp.maximum(rows_n, 1.0), rows_n

    fs_spec = P(DATA_AXIS, TABLE_AXIS, None, None)

    def build(mode: str):
        """One jitted step per row-side mode (its own collective program)."""
        with_fields = mode in ("mvm_segment", "ffm")

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(
                P((DATA_AXIS, TABLE_AXIS), None),  # table shard [S/(D*T), K]
                fs_spec, fs_spec, fs_spec, fs_spec, fs_spec,  # fs_* [1,1,D,cap]
                P(DATA_AXIS, None),  # labels [1, R]
                P(DATA_AXIS, None),  # row_mask
            ),
            out_specs=(P(), P()),
            check_vma=False,
        )
        def sharded_loss(tbl, fss, fsr, fsm, fso, fsf, labels, rm):
            sq = lambda x: x[0, 0]
            return local_loss(
                mode, tbl, sq(fss), sq(fsr), sq(fsm), sq(fso), sq(fsf),
                labels[0], rm[0],
            )

        def loss_for_grad(tbl, batch):
            # fs_fields only exists on the segment path; others pass
            # fs_slots as an unused same-shaped dummy
            fsf = batch["fs_fields"] if with_fields else batch["fs_slots"]
            return sharded_loss(
                tbl,
                batch["fs_slots"], batch["fs_row"], batch["fs_mask"],
                batch["fs_off"], fsf,
                batch["labels"].reshape(D, -1),
                batch["row_mask"].reshape(D, -1),
            )

        def train_step(state: TrainState, batch: dict):
            # "grad" covers forward+backward: the scatter (gather's
            # transpose, staying on the owning device) lands here
            with jax.named_scope("grad"):
                (loss, rows), grads = jax.value_and_grad(
                    loss_for_grad, has_aux=True
                )(state.tables[tname], batch)
            with jax.named_scope("optimizer"):
                new_tables, new_opt = optimizer.apply(
                    {tname: state.tables[tname]}, state.opt_state, {tname: grads}, cfg
                )
            metrics = {"loss": loss, "rows": rows}
            # health norms ride the same replicated-scalar contract as
            # the guard flag (shared helper, train/step.py): sharded
            # reductions + one psum, identical values on every rank
            metrics.update(
                health_norms(
                    cfg, state.tables, new_tables, grads={tname: grads}
                )
            )
            # non-finite guard: update_ok computed from replicated loss +
            # the sharded updated leaves (the isfinite reduction GSPMDs to
            # shard-local alls + one psum) — every rank/device sees the
            # same flag, so the jnp.where discard stays rank-symmetric
            return guard_nonfinite(
                cfg, state, TrainState(new_tables, new_opt, state.step + 1),
                metrics,
            )

        return train_step, fullshard_batch_sharding(mesh, with_fields=with_fields)

    from xflow_tpu.parallel.mesh import state_shardings

    rep = NamedSharding(mesh, P())
    jitted: dict = {}

    def call(state: TrainState, batch: dict):
        mode = _batch_mode(cfg, batch)
        if mode not in jitted:
            step, bsh = build(mode)
            ssh = state_shardings(state, mesh)
            fn = jax.jit(
                step,
                in_shardings=(ssh, bsh),
                out_shardings=(ssh, {k: rep for k in metrics_keys(cfg)}),
                donate_argnums=(0,),
            )
            if recorder is not None:
                fn = recorder.wrap(f"train_step.fullshard.{mode}", fn)
            jitted[mode] = (fn, bsh)
        fn, bsh = jitted[mode]
        return fn(state, {k: batch[k] for k in bsh})

    return call
