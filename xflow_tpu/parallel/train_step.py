"""Sharded train/eval steps.

The single-device step (train/step.py) IS the multi-device step: the
program is written once over logical arrays, shardings are attached to
the inputs, and GSPMD partitions the computation — the table gather
(Pull) and its scatter-add transpose (Push) lower to cross-chip
collectives over ICI/DCN, and the loss/metric reductions to psums.
This is the design center of the rebuild (SURVEY.md §2 C13): where the
reference hand-routes sparse KV RPC over ZeroMQ, here the compiler
emits the communication from sharding annotations.

Explicit in/out shardings are passed to `jax.jit` so the step never
silently falls back to replicated tables, and the donated input state
buffer is reused for the output (in-place HBM update, like the server's
in-place hash-map mutation — but functional).
"""

from __future__ import annotations

from typing import Callable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from xflow_tpu.config import Config
from xflow_tpu.models.base import Model
from xflow_tpu.optim.base import Optimizer
from xflow_tpu.parallel.mesh import batch_sharding, replicated, state_shardings
from xflow_tpu.train.state import TrainState
from xflow_tpu.train.step import make_train_step, make_eval_step, metrics_keys


def shard_state(state: TrainState, mesh: Mesh) -> TrainState:
    """Place an (unsharded) TrainState onto the mesh's table sharding."""
    shardings = state_shardings(state, mesh)
    return jax.tree.map(lambda x, s: jax.device_put(x, s), state, shardings)


def make_sharded_train_step(
    model: Model, optimizer: Optimizer, cfg: Config, mesh: Mesh, recorder=None
) -> Callable:
    step = make_train_step(model, optimizer, cfg, jit=False, allow_fused=False)
    # state shardings depend only on pytree structure; build from a spec of
    # the real state at first call via jit's lazy specialization
    bsh = batch_sharding(mesh)

    def sharded(state: TrainState, batch: dict):
        # inner gather/grad/optimizer scopes come from make_train_step;
        # this outer scope brackets the whole GSPMD step (incl. the
        # compiler-inserted collectives) in an xprof trace
        with jax.named_scope("train_step"):
            return step(state, batch)

    # the non-finite guard's update_ok flag rides in the metrics dict
    # (train/step.py metrics_keys), replicated like loss/rows
    out_metrics_sh = {k: replicated(mesh) for k in metrics_keys(cfg)}

    def wrap(state: TrainState, batch: dict):
        ssh = state_shardings(state, mesh)
        return jax.jit(
            sharded,
            # subset to the batch's actual keys: jit in_shardings must
            # match the pytree exactly, and batch_sharding carries entries
            # for optional arrays (sorted plans) too
            in_shardings=(ssh, {k: bsh[k] for k in batch}),
            out_shardings=(ssh, out_metrics_sh),
            donate_argnums=(0,),
        )

    # cache the jitted fn per batch-key set (state structure is fixed);
    # the compile recorder (one shared program name — signatures tell
    # the key sets apart) gives each set its kind="compile" record
    cache = {}

    def call(state: TrainState, batch: dict):
        key = frozenset(batch)
        if key not in cache:
            jitted = wrap(state, batch)
            cache[key] = (
                recorder.wrap("train_step.gspmd", jitted)
                if recorder is not None
                else jitted
            )
        return cache[key](state, batch)

    return call


def make_sharded_eval_step(
    model: Model, cfg: Config, mesh: Mesh, recorder=None
) -> Callable:
    ev = make_eval_step(model, cfg, jit=False)
    bsh = batch_sharding(mesh)
    cache = {}

    def call(tables, batch):
        # accept the tables AS SHARDED (jit with explicit in_shardings
        # rejects mismatches instead of resharding): the GSPMD eval
        # forward partitions fine under either the default
        # P(('data','table')) layout or the sorted engine's
        # P('table', None). The live shardings are part of the cache key:
        # a restore/device_put that reshards the tables mid-lifetime gets
        # a fresh jit instead of an in_shardings mismatch error (advisor r2).
        tsh = jax.tree.map(
            lambda x: x.sharding if hasattr(x, "sharding") else replicated(mesh),
            tables,
        )
        key = (frozenset(batch), tuple(jax.tree.leaves(tsh)))
        if key not in cache:
            jitted = jax.jit(
                ev,
                in_shardings=(tsh, {k: bsh[k] for k in batch}),
                out_shardings=NamedSharding(mesh, P("data")),
            )
            cache[key] = (
                recorder.wrap("predict.gspmd", jitted)
                if recorder is not None
                else jitted
            )
        return cache[key](tables, batch)

    return call
