"""Multi-host initialization.

The reference's cluster bring-up is env-driven role dispatch: a ZMQ
rendezvous at the scheduler (`DMLC_PS_ROOT_URI/PORT`,
`scripts/local.sh:8-19`) sorts processes into scheduler/server/worker.
Here every process is an identical SPMD rank: `jax.distributed.initialize`
replaces the scheduler rendezvous (coordinator address), and the
server/worker split collapses into the mesh axes (SURVEY.md §2 C13).

Environment variables (the launcher sets these; compatible names kept
close to the reference's so migration is mechanical):

- ``XFLOW_COORDINATOR`` — ``host:port`` of rank 0 (reference:
  ``DMLC_PS_ROOT_URI``/``DMLC_PS_ROOT_PORT``)
- ``XFLOW_NUM_PROCESSES`` — world size (reference: ``DMLC_NUM_WORKER``)
- ``XFLOW_PROCESS_ID`` — this rank
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def maybe_initialize(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> int:
    """Initialize jax.distributed if configured; returns this process's rank."""
    coordinator = coordinator or os.environ.get("XFLOW_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("XFLOW_NUM_PROCESSES", "0") or 0)
    if process_id is None:
        pid_env = os.environ.get("XFLOW_PROCESS_ID")
        process_id = int(pid_env) if pid_env is not None else None
    auto = os.environ.get("XFLOW_AUTO_DIST", "").lower()
    if not coordinator and auto not in ("", "0", "false", "no", "off"):
        # TPU pod slices (and other managed clusters) publish their own
        # topology: a no-arg initialize reads it from the runtime
        # metadata, so a pod launch needs no XFLOW_* contract at all —
        # export XFLOW_AUTO_DIST=1 on every worker (docs/DISTRIBUTED.md)
        jax.distributed.initialize()
        return jax.process_index()
    if coordinator and num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
        # Loud world-formation check: if the backend ignored the distributed
        # config (e.g. every process initialized on the same ambient
        # accelerator), each process would silently run as its own rank 0
        # and train shard 0 N times. Fail instead.
        if jax.process_count() != num_processes:
            raise RuntimeError(
                f"distributed world failed to form: jax.process_count()="
                f"{jax.process_count()} != num_processes={num_processes} "
                f"(platform={jax.default_backend()!r}; on a single-accelerator "
                "host launch with JAX_PLATFORMS=cpu)"
            )
        return jax.process_index()
    return 0
