"""Multi-host initialization.

The reference's cluster bring-up is env-driven role dispatch: a ZMQ
rendezvous at the scheduler (`DMLC_PS_ROOT_URI/PORT`,
`scripts/local.sh:8-19`) sorts processes into scheduler/server/worker.
Here every process is an identical SPMD rank: `jax.distributed.initialize`
replaces the scheduler rendezvous (coordinator address), and the
server/worker split collapses into the mesh axes (SURVEY.md §2 C13).

Environment variables (the launcher sets these; compatible names kept
close to the reference's so migration is mechanical):

- ``XFLOW_COORDINATOR`` — ``host:port`` of rank 0 (reference:
  ``DMLC_PS_ROOT_URI``/``DMLC_PS_ROOT_PORT``)
- ``XFLOW_NUM_PROCESSES`` — world size (reference: ``DMLC_NUM_WORKER``)
- ``XFLOW_PROCESS_ID`` — this rank

Rendezvous hardening (elastic recovery, docs/ROBUSTNESS.md): a
supervised auto-restart (launch/supervise.py) relaunches every rank of
a job, and a restarted rank reaching the rendezvous BEFORE rank 0's
coordinator is listening would fail the whole attempt on what is only
a startup race. `jax.distributed.initialize` is therefore wrapped in
bounded retry with exponential backoff + jitter:

- ``XFLOW_RENDEZVOUS_RETRIES`` (default 3) — retries after the first
  failure; 0 restores the old fail-on-first-error behavior,
- ``XFLOW_RENDEZVOUS_BACKOFF_S`` (default 1.0) — backoff base; the
  delay doubles per attempt (capped at 30 s) with [0.5, 1.0]× jitter
  so N restarted ranks don't re-stampede the coordinator in lockstep.

Between attempts the half-initialized runtime is shut down
(`jax.distributed.shutdown`), so a retry starts from a clean slate.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def _rendezvous_retry_env() -> tuple[int, float]:
    """(retries, backoff_base_s) from the env, defensively parsed — a
    junk value must degrade to the default, not kill the launch."""
    try:
        retries = int(os.environ.get("XFLOW_RENDEZVOUS_RETRIES", "3") or 3)
    except ValueError:
        retries = 3
    try:
        base = float(os.environ.get("XFLOW_RENDEZVOUS_BACKOFF_S", "1.0") or 1.0)
    except ValueError:
        base = 1.0
    return max(retries, 0), max(base, 0.0)


def _initialize_with_retry(**kwargs) -> None:
    """`jax.distributed.initialize` under bounded backoff+jitter retry
    (launch/supervise.retry_call — the same primitive the supervision
    loop uses), shutting the runtime down between attempts."""
    from xflow_tpu.launch.supervise import retry_call

    retries, base = _rendezvous_retry_env()

    def cleanup():
        jax.distributed.shutdown()

    retry_call(
        lambda: jax.distributed.initialize(**kwargs),
        what="rendezvous",
        retries=retries,
        base_s=base,
        cap_s=30.0,
        cleanup=cleanup,
    )


def maybe_initialize(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> int:
    """Initialize jax.distributed if configured; returns this process's rank."""
    coordinator = coordinator or os.environ.get("XFLOW_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("XFLOW_NUM_PROCESSES", "0") or 0)
    if process_id is None:
        pid_env = os.environ.get("XFLOW_PROCESS_ID")
        process_id = int(pid_env) if pid_env is not None else None
    auto = os.environ.get("XFLOW_AUTO_DIST", "").lower()
    if not coordinator and auto not in ("", "0", "false", "no", "off"):
        # TPU pod slices (and other managed clusters) publish their own
        # topology: a no-arg initialize reads it from the runtime
        # metadata, so a pod launch needs no XFLOW_* contract at all —
        # export XFLOW_AUTO_DIST=1 on every worker (docs/DISTRIBUTED.md)
        _initialize_with_retry()
        return jax.process_index()
    if coordinator and num_processes > 1:
        _initialize_with_retry(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
        # Loud world-formation check: if the backend ignored the distributed
        # config (e.g. every process initialized on the same ambient
        # accelerator), each process would silently run as its own rank 0
        # and train shard 0 N times. Fail instead.
        if jax.process_count() != num_processes:
            raise RuntimeError(
                f"distributed world failed to form: jax.process_count()="
                f"{jax.process_count()} != num_processes={num_processes} "
                f"(platform={jax.default_backend()!r}; on a single-accelerator "
                "host launch with JAX_PLATFORMS=cpu)"
            )
        return jax.process_index()
    return 0
