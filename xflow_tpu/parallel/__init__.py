from xflow_tpu.parallel.mesh import make_mesh, table_sharding, batch_sharding
from xflow_tpu.parallel.train_step import (
    make_sharded_train_step,
    make_sharded_eval_step,
    shard_state,
)

__all__ = [
    "make_mesh",
    "table_sharding",
    "batch_sharding",
    "make_sharded_train_step",
    "make_sharded_eval_step",
    "shard_state",
]
