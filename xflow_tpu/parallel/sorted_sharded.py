"""Sharded sorted-window FM training: the pod-scale path for the Pallas
table engine (ops/sorted_table.py).

Layout (vs the GSPMD row-major path, parallel/train_step.py, which
shards tables over BOTH mesh axes and lets the compiler route the
gather/scatter collectives):

- the fused FM table (and its FTRL state) is sharded on the slot axis
  over the **'table' axis only** — `P('table', None)` — and replicated
  across 'data'. Each device owns `S/T` slots = `n_win/T` whole windows.
- each 'data' shard plans ITS rows' occurrences over the FULL table
  (host side, `plan_sorted_stacked` with `num_sub = D`), so a device's
  occurrences for its windows are one contiguous span of the
  slot-sorted stream: the Pallas kernels run *unmodified* on the local
  table shard with a sliced `win_off` and rebased slots.
- forward cross-device traffic is ONE `psum` of the per-row partial
  sums `[B/D, ch]` over the 'table' axis (~tens of KB at k=10) — the
  analog of the reference workers pulling from every server
  (`lr_worker.cc:170`), but aggregated rows cross the wire instead of
  per-key values.
- backward needs NO extra collective on the 'table' axis (each shard
  scatters only its own windows); shard_map's transpose inserts the
  gradient `psum` over 'data' (the table is replicated there) — the
  classic data-parallel allreduce, ~(S/T)·(1+k)·4 B per step.

Trade-off, stated plainly: replicating the table across the 'data' axis
costs D× table memory. For the 1B-feature / 12 GB-state regime, use the
fully-sharded GSPMD path; this path is the throughput engine for tables
that fit per-host HBM (e.g. 2^26 slots × 11 × 3 arrays ≈ 8.8 GB split
over T=4 ⇒ 2.2 GB/device).

Reference analog: N workers × M servers (SURVEY.md §1) with D data
shards × T table shards; `Wait(Pull)`/`Wait(Push)` become the one psum
and the transpose-inserted gradient allreduce.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from xflow_tpu.config import Config
from xflow_tpu.metrics import binary_logloss_from_logits
from xflow_tpu.ops.sorted_table import (
    WINDOW,
    row_sums_sorted,
    table_gather_sorted,
)
from xflow_tpu.parallel.compat import shard_map
from xflow_tpu.parallel.mesh import DATA_AXIS, TABLE_AXIS
from xflow_tpu.train.state import TrainState
from xflow_tpu.train.step import guard_nonfinite, health_norms, metrics_keys


def validate_sorted_sharded(cfg: Config, mesh: Mesh) -> None:
    """Reject configs the sharded sorted engine cannot run, with the
    specific reason. Multi-process: each of P processes plans its OWN
    (per-process) batch into d/P sub-plans, so the divisibility
    requirements are per-process."""
    d, t = mesh.shape[DATA_AXIS], mesh.shape[TABLE_AXIS]
    p = jax.process_count()
    S = cfg.num_slots
    if S % (t * WINDOW) != 0:
        raise ValueError(
            f"sorted sharded layout needs num_slots (2^{cfg.data.log2_slots}) "
            f"divisible by table_axis*WINDOW = {t}*{WINDOW}"
        )
    if d % p != 0:
        raise ValueError(
            f"sorted sharded layout needs the data axis ({d}) divisible by "
            f"the process count ({p}): each process plans its rows into d/P "
            "sub-plans"
        )
    if cfg.data.batch_size % (d // p) != 0:
        raise ValueError(
            f"per-process batch_size {cfg.data.batch_size} not divisible by "
            f"the local data-shard count {d // p} (data axis {d} / {p} "
            "process(es))"
        )
    if not (cfg.model.name == "fm" and cfg.model.fm_fused):
        raise ValueError("sorted sharded layout supports fused FM only")
    if cfg.data.sorted_sub_batches not in (0, d // p):
        # the per-process plan count IS d/P here; silently overriding a
        # user's explicit single-device tuning value would benchmark a
        # different configuration than they asked for
        raise ValueError(
            f"data.sorted_sub_batches={cfg.data.sorted_sub_batches} conflicts "
            f"with the mesh sorted path (per-process plan count = {d // p}); "
            "leave it 0"
        )


def sorted_batch_sharding(mesh: Mesh) -> dict:
    """Shardings for the stacked per-data-shard plan arrays [D, Np_l] —
    subset of the canonical dict so the two stay in lockstep."""
    from xflow_tpu.parallel.mesh import batch_sharding

    full = batch_sharding(mesh)
    return {k: full[k] for k in ("sorted_slots", "sorted_row", "sorted_mask", "win_off")}


def make_sorted_sharded_train_step(
    optimizer, cfg: Config, mesh: Mesh, recorder=None
) -> Callable:
    """FM train step over ('data','table'): Pallas sorted kernels on the
    local table shard, one row-sum psum, shard_map-transposed grad psum.
    `recorder` routes the jit through the compile-accounting seam
    (telemetry.CompileRecorder, program "train_step.replicated").
    """
    validate_sorted_sharded(cfg, mesh)
    S = cfg.num_slots
    T = mesh.shape[TABLE_AXIS]
    S_local = S // T
    wpt = (S // WINDOW) // T  # windows per table shard

    def local_loss(wv_local, sorted_slots, sorted_row, sorted_mask, win_off,
                   labels, row_mask):
        """Per-device body. wv_local [S/T/pack, pack*K]; occurrence
        arrays are this data shard's full plan [Np_l]; labels/row_mask
        [B/D]. Storage may be packed (pack_table) — detected from the
        shard shape; slot indices stay logical."""
        from xflow_tpu.ops.sorted_table import pack_of, wire_mask, wire_rows

        sorted_row = wire_rows(sorted_row)
        sorted_mask = wire_mask(sorted_mask)
        K = 1 + cfg.model.v_dim
        t_idx = jax.lax.axis_index(TABLE_AXIS)
        # this shard's windows: global win_off sliced to [t*wpt, (t+1)*wpt]
        off_local = jax.lax.dynamic_slice(win_off, (t_idx * wpt,), (wpt + 1,))
        # rebase global slots to the local shard's window space; positions
        # outside this shard's span get out-of-range values the kernels
        # never touch (their chunk ranges come from off_local) and the
        # in-span mask removes from compute
        slots_local = sorted_slots - t_idx * S_local
        with jax.named_scope("gather"):
            occ_t = table_gather_sorted(
                wv_local, slots_local, off_local, cfg.data.sorted_bf16,
                pack_of(wv_local, K),
            )  # [K8, Np_l]
        pos = jnp.arange(sorted_slots.shape[0], dtype=jnp.int32)
        in_span = (pos >= off_local[0]) & (pos < off_local[-1])
        # where() (not multiply) so untouched positions — which may hold
        # uninitialized/garbage values — cannot poison the sums as NaN*0
        occm_t = jnp.where(in_span[None, :], occ_t[:K], 0.0) * sorted_mask[None, :]
        from xflow_tpu.models.fm import stack_channels

        with jax.named_scope("loss"):
            stacked = stack_channels(occm_t, K)
            partial_sums = row_sums_sorted(stacked, sorted_row, labels.shape[0])
            sums = jax.lax.psum(partial_sums, TABLE_AXIS)  # the ONE fwd collective
            from xflow_tpu.models.fm import fm_logits_from_sums

            logits = fm_logits_from_sums(sums, K, cfg)
            per_row = binary_logloss_from_logits(logits, labels)
            loss_sum = jax.lax.psum((per_row * row_mask).sum(), DATA_AXIS)
            rows = jax.lax.psum(row_mask.sum(), DATA_AXIS)
            return loss_sum / jnp.maximum(rows, 1.0), rows

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P(TABLE_AXIS, None),  # wv shard
            P(DATA_AXIS, None),  # sorted_slots [D, Np_l]
            P(DATA_AXIS, None),  # sorted_row
            P(DATA_AXIS, None),  # sorted_mask
            P(DATA_AXIS, None),  # win_off [D, n_win+1]
            P(DATA_AXIS, None),  # labels [D, B/D]
            P(DATA_AXIS, None),  # row_mask
        ),
        out_specs=(P(), P()),
        check_vma=False,
    )
    def sharded_loss(wv, ss, sr, sm, wo, labels, rm):
        return local_loss(wv, ss[0], sr[0], sm[0], wo[0], labels[0], rm[0])

    def loss_for_grad(wv, batch):
        loss, rows = sharded_loss(
            wv,
            batch["sorted_slots"],
            batch["sorted_row"],
            batch["sorted_mask"],
            batch["win_off"],
            batch["labels"].reshape(mesh.shape[DATA_AXIS], -1),
            batch["row_mask"].reshape(mesh.shape[DATA_AXIS], -1),
        )
        return loss, rows

    def train_step(state: TrainState, batch: dict):
        # "grad" covers forward+backward: the windowed scatter (the
        # gather's transpose) and the 'data'-axis gradient psum land here
        with jax.named_scope("grad"):
            (loss, rows), grads = jax.value_and_grad(loss_for_grad, has_aux=True)(
                state.tables["wv"], batch
            )
        with jax.named_scope("optimizer"):
            new_tables, new_opt = optimizer.apply(
                {"wv": state.tables["wv"]},
                state.opt_state,
                {"wv": grads},
                cfg,
            )
        metrics = {"loss": loss, "rows": rows}
        # health norms + non-finite guard: the shared helpers every
        # engine uses (train/step.py) — reductions over the sharded
        # leaves lower to shard-local sums + one psum, outputs replicated
        metrics.update(
            health_norms(cfg, state.tables, new_tables, grads={"wv": grads})
        )
        return guard_nonfinite(
            cfg, state, TrainState(new_tables, new_opt, state.step + 1), metrics
        )

    table_sh = NamedSharding(mesh, P(TABLE_AXIS, None))
    opt_sh = {"wv": {"n": table_sh, "z": table_sh}}
    state_sh = TrainState(
        {"wv": table_sh}, opt_sh, NamedSharding(mesh, P())
    )
    bsh = {
        **sorted_batch_sharding(mesh),
        "labels": NamedSharding(mesh, P(DATA_AXIS)),
        "row_mask": NamedSharding(mesh, P(DATA_AXIS)),
    }
    rep = NamedSharding(mesh, P())
    jitted = jax.jit(
        train_step,
        in_shardings=(state_sh, bsh),
        out_shardings=(state_sh, {k: rep for k in metrics_keys(cfg)}),
        donate_argnums=(0,),
    )
    if recorder is not None:
        jitted = recorder.wrap("train_step.replicated", jitted)

    def call(state: TrainState, batch: dict):
        # tolerate a batch dict carrying extra keys (slots/fields/mask for
        # the eval path): jit in_shardings must match the pytree exactly
        return jitted(state, {k: batch[k] for k in bsh})

    return call


def shard_sorted_state(state: TrainState, mesh: Mesh) -> TrainState:
    """Place state onto the table-axis-only sharding this path uses."""
    table_sh = NamedSharding(mesh, P(TABLE_AXIS, None))

    def put(x):
        if getattr(x, "ndim", 0) >= 1:
            return jax.device_put(x, table_sh)
        return jax.device_put(x, NamedSharding(mesh, P()))

    return jax.tree.map(put, state)
