"""Evaluation metrics: pCTR, AUC, logloss.

Reference: `/root/reference/src/base/base.h`.

- `reference_pctr` keeps the reference sigmoid's clamping behavior
  (`base.h:54-63`: x < −30 → 1e-6, x > 30 → 1.0) so dumped predictions
  are comparable.
- `auc_logloss` is the reference's rank-sum AUC (`base.h:84-110`: sort
  by pctr desc, accumulate true-positive count at each negative,
  normalize by tp·fp). Two reference accidents fixed (SURVEY.md §7):
  logloss uses natural log, not `std::log2` (`base.h:97`), and the
  accumulator is not carried across calls (`base.h:113` never resets).
- `BucketAUC` is a streaming alternative: histogram positives/negatives
  by score bucket on the host as scores come off the device; counts are
  summable across batches and hosts (one allgather per eval pass) so
  giant eval sets never need a global sort.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


def reference_pctr(logits: jnp.ndarray) -> jnp.ndarray:
    """σ with the reference's clamps (`base.h:54-63`)."""
    p = jax.nn.sigmoid(logits)
    p = jnp.where(logits < -30.0, 1e-6, p)
    p = jnp.where(logits > 30.0, 1.0, p)
    return p


def auc_logloss(pctrs: np.ndarray, labels: np.ndarray, log2: bool = False) -> tuple[float, float]:
    """Rank-sum AUC + mean logloss on host. Returns (auc, logloss).

    Sign convention (reference parity, kept deliberately): the returned
    "logloss" is the mean log-LIKELIHOOD — a NEGATIVE number — exactly
    as the reference accumulates `label*log(p)+(1-label)*log(1-p)`
    without negating (`base.h:94-97`). Conventional logloss is its
    negation; downstream prints/logs keep the reference's sign so
    numbers are directly comparable against reference output. We fixed
    the reference's log₂ accident (natural log here; `log2=True`
    restores it) but not its sign, which is a convention rather than a
    bug. Documented in docs/PARITY.md (C8).

    AUC is NaN when one class is absent (the reference prints only tp_n
    then, `base.h:102-103`).
    """
    pctrs = np.asarray(pctrs, dtype=np.float64)
    labels = np.asarray(labels, dtype=np.float64)
    order = np.argsort(-pctrs, kind="stable")
    sorted_labels = labels[order]
    tp = np.cumsum(sorted_labels)
    area = float((tp * (1.0 - sorted_labels)).sum())
    tp_n = float(sorted_labels.sum())
    fp_n = float(len(labels) - tp_n)
    auc = area / (tp_n * fp_n) if tp_n > 0 and fp_n > 0 else float("nan")
    eps = 1e-15
    p = np.clip(pctrs, eps, 1.0 - eps)
    ll = labels * np.log(p) + (1.0 - labels) * np.log(1.0 - p)
    if log2:
        ll = ll / np.log(2.0)
    return auc, float(ll.mean())


class BucketAUC(NamedTuple):
    """Streaming AUC state: per-bucket positive/negative counts.

    HOST-side accumulation in float64 (np.bincount): eval scores come
    off the device per batch anyway (for the pred dump and logloss), and
    float64 counts stay exact past 2^24 rows where a float32 device
    histogram would saturate. Counts are plain sums, so cross-batch and
    cross-host merging is addition (trainer._evaluate_bucketed allgathers
    and sums them once per eval pass)."""

    pos: np.ndarray  # [num_buckets]
    neg: np.ndarray  # [num_buckets]

    @staticmethod
    def init(num_buckets: int = 8192) -> "BucketAUC":
        z = np.zeros((num_buckets,), dtype=np.float64)
        return BucketAUC(pos=z, neg=z)

    def update(self, pctrs, labels, weights=None) -> "BucketAUC":
        nb = self.pos.shape[0]
        p = np.asarray(pctrs, np.float64)
        y = np.asarray(labels, np.float64)
        w = np.ones_like(p) if weights is None else np.asarray(weights, np.float64)
        idx = np.clip((p * nb).astype(np.int64), 0, nb - 1)
        pos = self.pos + np.bincount(idx, weights=y * w, minlength=nb)
        neg = self.neg + np.bincount(idx, weights=(1.0 - y) * w, minlength=nb)
        return BucketAUC(pos=pos, neg=neg)

    def decay(self, factor: float) -> "BucketAUC":
        """Multiply both histograms by `factor` — the time-decayed
        sliding-window step (train.eval_window_decay): counts are plain
        sums, so an exponential decay before each fold turns the
        lifetime accumulator into a recency-weighted window with an
        effective length of ~1/(1-factor) eval passes. factor 0 resets
        (per-pass-fresh); factor 1 is the undecayed lifetime sum."""
        f = float(factor)
        return BucketAUC(pos=self.pos * f, neg=self.neg * f)

    def compute(self) -> float:
        """AUC from bucket counts (ties within a bucket count 1/2)."""
        pos, neg = np.asarray(self.pos, np.float64), np.asarray(self.neg, np.float64)
        tp_n, fp_n = pos.sum(), neg.sum()
        if tp_n == 0 or fp_n == 0:
            return float("nan")
        pos_below = np.concatenate([[0.0], np.cumsum(pos)[:-1]])
        area = (neg * (tp_n - pos_below - pos) + neg * pos * 0.5).sum()
        # area counts (pos ranked above neg) pairs: positives in strictly
        # higher buckets + half the same-bucket ties.
        return float(area / (tp_n * fp_n))


def binary_logloss_from_logits(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Numerically stable per-row BCE in nats: softplus(x) − y·x."""
    return jax.nn.softplus(logits) - labels * logits
