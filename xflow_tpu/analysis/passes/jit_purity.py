"""XF101 jit-purity: host-side effects inside traced code.

A function traced by `jax.jit`/`pjit`/`shard_map`/`jax.grad`/a
`lax.scan`/`while_loop`/`cond` body executes ONCE at trace time; a
`time.perf_counter()`, `random.random()`, `print`, file write, or
global mutation inside it runs at compile time and then never again —
the classic silent bug where a "timer" measures tracing, an RNG draw
freezes into the compiled program, and a log line prints once per
compile instead of once per step. PR 2 moved every duration in this
repo to host-side `time.perf_counter` *outside* the step exactly
because of this; this pass enforces it mechanically.

Detection: functions are "jit-reachable" when they are (a) decorated
with a jit-family transform, (b) passed to a jit-family call
(`jax.jit(f)`, `shard_map(f, ...)`, `lax.scan(f, ...)`, ...), or
(c) called (by name, transitively, within the module) from a
jit-reachable function. Calls to the banned host APIs — and `global`
mutations — inside jit-reachable code are findings. `jax.debug.print`
/ `jax.debug.callback` / `jax.random.*` are the sanctioned escape
hatches and never flagged; functions only *referenced* as
`pure_callback`/`io_callback` targets are host code, not jit roots.
"""

from __future__ import annotations

import ast

from xflow_tpu.analysis import astutil
from xflow_tpu.analysis.core import Finding, Project, register_pass

RULE = "XF101"

# callables whose function-valued arguments get traced
JIT_WRAPPERS = {
    "jax.jit", "jit", "pjit", "jax.pjit",
    "shard_map", "jax.shard_map", "jax.experimental.shard_map.shard_map",
    "jax.grad", "jax.value_and_grad", "jax.vmap", "jax.pmap",
    "jax.checkpoint", "jax.remat", "jax.lax.map",
    "jax.lax.scan", "lax.scan",
    "jax.lax.while_loop", "lax.while_loop",
    "jax.lax.fori_loop", "lax.fori_loop",
    "jax.lax.cond", "lax.cond", "jax.lax.switch", "lax.switch",
}

# host-effect calls banned inside traced code: {dotted name: why}
BANNED_CALLS = {
    "time.time": "wall clock freezes at trace time",
    "time.perf_counter": "host timer freezes at trace time (PR 2 rule: "
                         "time steps from the host, outside the program)",
    "time.monotonic": "host timer freezes at trace time",
    "time.process_time": "host timer freezes at trace time",
    "time.sleep": "host sleep runs at trace time only",
    "datetime.now": "wall clock freezes at trace time",
    "datetime.utcnow": "wall clock freezes at trace time",
    "datetime.datetime.now": "wall clock freezes at trace time",
    "datetime.datetime.utcnow": "wall clock freezes at trace time",
    "print": "prints once per COMPILE, not per step (use jax.debug.print)",
    "input": "host IO inside traced code",
    "open": "host IO runs at trace time only",
    "uuid.uuid4": "host RNG freezes at trace time",
    "os.urandom": "host RNG freezes at trace time",
}
# whole host-RNG namespaces (any attribute under them)
BANNED_PREFIXES = {
    "random.": "host RNG freezes into the compiled program "
               "(use jax.random with an explicit key)",
    "np.random.": "numpy RNG freezes into the compiled program "
                  "(use jax.random with an explicit key)",
    "numpy.random.": "numpy RNG freezes into the compiled program "
                     "(use jax.random with an explicit key)",
}
# sanctioned escapes — never flagged even though they look like IO
ALLOWED = {"jax.debug.print", "jax.debug.callback", "jax.debug.breakpoint"}
# function-reference args to these run on the HOST (not jit roots)
HOST_CALLBACK_WRAPPERS = {
    "jax.pure_callback", "jax.experimental.io_callback", "io_callback",
    "jax.debug.callback",
}


def _is_jit_decorator(dec: ast.AST, aliases: dict) -> bool:
    name = astutil.canonical(astutil.dotted(dec), aliases)
    if name in JIT_WRAPPERS:
        return True
    if isinstance(dec, ast.Call):
        cn = astutil.canonical(astutil.call_name(dec), aliases)
        if cn in JIT_WRAPPERS:
            return True
        # functools.partial(jax.jit, ...) as a decorator factory
        if cn in ("functools.partial", "partial") and dec.args:
            return astutil.canonical(
                astutil.dotted(dec.args[0]), aliases) in JIT_WRAPPERS
    return False


def _jit_roots(tree: ast.AST, defs: list, aliases: dict) -> tuple:
    """(root qualnames, lambda nodes traced directly)."""
    by_name = astutil.defs_by_name(defs)
    roots: set = set()
    lambdas: list = []
    for qn, node, _cls in defs:
        if any(_is_jit_decorator(d, aliases) for d in node.decorator_list):
            roots.add(qn)
    for caller_qn, node in astutil.scope_sites(tree, defs):
        if not isinstance(node, ast.Call):
            continue
        cn = astutil.canonical(astutil.call_name(node), aliases)
        if cn in HOST_CALLBACK_WRAPPERS:
            continue
        if cn not in JIT_WRAPPERS:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Name):
                roots.update(astutil.resolve_scoped(arg.id, caller_qn,
                                                    by_name))
            elif isinstance(arg, ast.Lambda):
                lambdas.append(arg)
            elif isinstance(arg, ast.Attribute):
                # self.step / cls.step — match by trailing attribute
                roots.update(astutil.resolve_scoped(arg.attr, caller_qn,
                                                    by_name))
    return roots, lambdas


def _scan_body(body_owner: ast.AST, relpath: str, where: str,
               aliases: dict) -> list:
    out = []
    nodes = astutil.walk_scope(body_owner)
    for sub in nodes:
        if isinstance(sub, ast.Global):
            out.append(Finding(
                rule=RULE, path=relpath, line=sub.lineno,
                message=f"global mutation inside jit-traced code ({where})",
                hint="thread state through the function as an argument "
                     "and return the new value",
            ))
            continue
        if not isinstance(sub, ast.Call):
            continue
        cn = astutil.canonical(astutil.call_name(sub), aliases)
        if cn is None or cn in ALLOWED:
            continue
        why = BANNED_CALLS.get(cn)
        if why is None:
            for pfx, pwhy in BANNED_PREFIXES.items():
                if cn.startswith(pfx):
                    why = pwhy
                    break
        if why is None:
            continue
        out.append(Finding(
            rule=RULE, path=relpath, line=sub.lineno,
            message=f"host-side call `{cn}` inside jit-traced code "
                    f"({where}): {why}",
            hint="hoist the call out of the traced function; for debug "
                 "output use jax.debug.print",
        ))
    return out


@register_pass("jit-purity", (RULE,))
def run(project: Project) -> list:
    findings = []
    for mod in project.modules:
        if mod.tree is None:
            continue
        defs = astutil.func_defs(mod.tree)
        aliases = astutil.import_aliases(mod.tree)
        roots, lambdas = _jit_roots(mod.tree, defs, aliases)
        if not roots and not lambdas:
            continue
        graph = astutil.local_call_graph(defs)
        reach = astutil.reachable(roots, graph)
        by_qn = {qn: node for qn, node, _cls in defs}
        for qn in sorted(reach):
            node = by_qn.get(qn)
            if node is None:
                continue
            where = qn if qn in roots else f"{qn}, reached from a jit root"
            findings.extend(_scan_body(node, mod.relpath, where, aliases))
        for lam in lambdas:
            findings.extend(
                _scan_body(lam, mod.relpath, "lambda traced in place",
                           aliases))
    return findings
