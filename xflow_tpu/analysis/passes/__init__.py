"""xflowlint passes. Importing this package registers every pass with
core.PASS_REGISTRY (the driver imports it lazily so a partial install
never breaks `import xflow_tpu.analysis`)."""

from xflow_tpu.analysis.passes import (  # noqa: F401
    config_keys,
    hostsync,
    ir_rules,
    jit_purity,
    lockset,
    recompile,
    schema_drift,
    sharding_contract,
    shell,
)
