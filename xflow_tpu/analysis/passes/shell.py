"""XF601 shell strict mode: smoke scripts must fail loudly.

The smoke scripts are CI gates: a script without `set -euo pipefail`
can drop a failing pipeline stage (`cmd | tee log` swallows cmd's
exit), read an unset variable as empty (`rm -rf "$WORK/"` with WORK
unset), or keep running past a failed step and green-light a broken
tree. Built while wiring the config cross-check's script scanner
(ISSUE 10); the unquoted-variable sweep is manual — bash quoting is
not statically decidable without a real parser.

- XF601 shell-strict-mode: the script does not establish
  `set -euo pipefail` (in one line, or split across `set -e`/`set -u`/
  `set -o pipefail`) before its first non-comment command.
"""

from __future__ import annotations

import re

from xflow_tpu.analysis.core import Finding, Project, register_pass

RULE = "XF601"

PIPEFAIL_RE = re.compile(r"-[a-zA-Z]*o\s+pipefail")


def _flags(script) -> tuple:
    """(has_e, has_u, has_pipefail, first_set_line). Handles combined
    clusters (`set -euo pipefail` == `-e` + `-u` + `-o pipefail`) and
    ORDER: only `set` lines seen before the first other command count —
    strict mode established after fallible commands protects nothing
    (the rule's own message says 'before its first non-comment
    command')."""
    e = u = pf = False
    first = None
    for i, line in enumerate(script.lines, 1):
        stripped = line.strip()
        if not stripped or stripped.startswith("#"):
            continue
        if not (stripped == "set" or stripped.startswith("set ")):
            break  # first real command: later `set` lines are too late
        if first is None:
            first = i
        body = stripped[3:]
        if PIPEFAIL_RE.search(body):
            pf = True
        for m in re.finditer(r"(?<!\S)-([a-zA-Z]+)", body):
            e = e or "e" in m.group(1)
            u = u or "u" in m.group(1)
    return e, u, pf, first


@register_pass("shell-strict-mode", (RULE,))
def run(project: Project) -> list:
    findings = []
    for script in project.shell_scripts:
        e, u, pf, first = _flags(script)
        missing = [flag for ok, flag in
                   ((e, "-e"), (u, "-u"), (pf, "-o pipefail")) if not ok]
        if missing:
            findings.append(Finding(
                rule=RULE, path=script.relpath, line=first or 1,
                message="script does not establish `set -euo pipefail` "
                        f"(missing: {', '.join(missing)})",
                hint="CI smoke scripts must die on the first failed "
                     "command, unset variable, or failed pipeline stage",
            ))
    return findings
