"""XF2xx recompile hazards: patterns that silently thrash the jit cache.

PR 7's CompileRecorder turned "each (program, signature) compiles
exactly once per run" into a runtime `--check` gate; these rules catch
the same class of bug before the code ever runs:

- XF201 jit-in-loop: a `jax.jit(...)` (or immediately-invoked
  `jax.jit(f)(x)`) inside a for/while body builds a FRESH callable —
  and with it a fresh trace + compile — on every iteration. The cache
  keys on the function object; a new object never hits.
- XF202 varying-static-argument: a callable jitted with
  `static_argnums`/`static_argnames` recompiles once per DISTINCT
  value of each static argument. Passing a loop induction variable, or
  different literals across call sites, in a static slot is a
  compile-per-step bug. Loop-variable detection rides the
  flow-sensitive dataflow engine (analysis/dataflow.py): a value is
  flagged only when it still varies with a loop ENCLOSING the call
  site — a loop variable read after its loop (one value per outer
  execution), or a name rebound to a constant inside the loop, no
  longer false-fires, and a value copied OFF the induction variable
  (`n = k; g(1.0, n)`) is now caught. This removed the pass's old
  scope-locality precision caveats.
- XF203 unhashable-static-argument: a list/dict/set literal in a
  static slot raises (static args are cache keys and must hash) — at
  call time, far from the jit site that declared it static.
- XF204 unrecorded-jit: in the engine/serve modules, every jit must
  route through `telemetry.CompileRecorder.wrap` so the exactly-once
  contract stays observable (docs/OBSERVABILITY.md "Compile
  accounting"). A bare `jax.jit` there compiles invisibly — the
  metrics stream cannot prove it didn't recompile.
"""

from __future__ import annotations

import ast
from xflow_tpu.analysis import astutil, dataflow
from xflow_tpu.analysis.core import Finding, Project, register_pass

RULES = ("XF201", "XF202", "XF203", "XF204")

JIT_CALLS = {"jax.jit", "jit", "pjit", "jax.pjit"}

# modules where PR 7's recorder contract applies: every jitted program
# must be wrapped so compile accounting sees it
RECORDER_SCOPED = (
    "xflow_tpu/train/step.py",
    "xflow_tpu/parallel/train_step.py",
    "xflow_tpu/parallel/sorted_sharded.py",
    "xflow_tpu/parallel/sorted_fullshard.py",
    "xflow_tpu/models/predict.py",
    "xflow_tpu/serve/",
)


def _static_spec(call: ast.Call) -> tuple:
    """(static positions, static names) declared on a jit call."""
    nums: list = []
    names: list = []
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            items = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for it in items:
                if isinstance(it, ast.Constant) and isinstance(it.value, int):
                    nums.append(it.value)
        elif kw.arg == "static_argnames":
            v = kw.value
            items = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for it in items:
                s = astutil.const_str(it)
                if s:
                    names.append(s)
    return nums, names


class _StaticSlotHooks(dataflow.Hooks):
    """Dataflow hooks recording the abstract value of every static-slot
    argument at every call site of a statically-jitted name. The
    flow-sensitive loop-variance fact replaces the old name-set
    heuristic (see module docstring: XF202 retrofit)."""

    def __init__(self, jitted_specs: dict):
        self.jitted_specs = jitted_specs  # fname -> (nums, names)
        # (id(call), slot) -> [call node, arg node, joined AbsVal]
        self.sites: dict = {}

    def _record(self, call, slot, arg_node, val) -> None:
        key = (id(call), slot)
        cur = self.sites.get(key)
        if cur is None:
            self.sites[key] = [call, arg_node, val]
        else:
            cur[2] = dataflow.join(cur[2], val)

    def at_call(self, node, callee, argvals, kwvals, env, df, fval):
        fname = astutil.dotted(node.func)
        spec = self.jitted_specs.get(fname)
        if spec is None:
            return None
        nums, names = spec
        for idx in nums:
            if idx < len(node.args):
                self._record(node, idx, node.args[idx], argvals[idx])
        for kw in node.keywords:
            if kw.arg in names and kw.arg in kwvals:
                self._record(node, kw.arg, kw.value, kwvals[kw.arg])
        return None


@register_pass("recompile-hazard", RULES)
def run(project: Project) -> list:
    findings = []
    for mod in project.modules:
        if mod.tree is None:
            continue
        parents = astutil.parent_map(mod.tree)
        aliases = astutil.import_aliases(mod.tree)
        # name -> the jit Call that produced it (for static-arg call sites)
        jitted: dict = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if astutil.canonical(astutil.call_name(node.value),
                                     aliases) in JIT_CALLS:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            jitted[tgt.id] = node.value

        in_scope = any(mod.relpath.startswith(p) or mod.relpath == p
                       for p in RECORDER_SCOPED)
        wrapped_names: set = set()
        wrapped_factories: set = set()
        if in_scope:
            # names passed to a `.wrap(...)` call anywhere in the module
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute) and node.func.attr == "wrap":
                    for arg in node.args:
                        nm = astutil.dotted(arg)
                        if nm:
                            wrapped_names.add(nm)
            # factory pattern: `jitted = build(...)` then
            # `recorder.wrap(name, jitted)` — a jit RETURNED from
            # `build` is accounted for at the call site
            for node in ast.walk(mod.tree):
                if (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    cn = astutil.call_name(node.value)
                    if cn is None or "." in cn:
                        continue
                    for tgt in node.targets:
                        nm = astutil.dotted(tgt)
                        if nm and nm in wrapped_names:
                            wrapped_factories.add(cn)

        # decorator-form jit in recorder-scoped modules: `@jax.jit` (or
        # `@partial(jax.jit, ...)`) on a def whose name never reaches a
        # `.wrap(...)` call bypasses compile accounting just as surely
        # as the call form below
        if in_scope:
            from xflow_tpu.analysis.passes.jit_purity import _is_jit_decorator

            for node in ast.walk(mod.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if not any(_is_jit_decorator(d, aliases)
                           for d in node.decorator_list):
                    continue
                if node.name in wrapped_names:
                    continue
                findings.append(Finding(
                    rule="XF204", path=mod.relpath, line=node.lineno,
                    message="decorator-jitted function not routed through "
                            "CompileRecorder.wrap — compile accounting "
                            "cannot see it (exactly-once contract, "
                            "docs/OBSERVABILITY.md)",
                    hint="drop the decorator and wrap explicitly: "
                         "`recorder.wrap(\"<program>\", jax.jit(fn))`",
                ))

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            cn = astutil.canonical(astutil.call_name(node), aliases)
            if cn not in JIT_CALLS:
                continue
            # ---- XF201: jit constructed per loop iteration ------------
            if astutil.in_loop(node, parents):
                findings.append(Finding(
                    rule="XF201", path=mod.relpath, line=node.lineno,
                    message=f"`{cn}(...)` inside a loop builds a fresh "
                            "callable — and recompiles — every iteration",
                    hint="hoist the jit out of the loop (the cache keys on "
                         "the function OBJECT; a new object never hits)",
                ))
            # immediately-invoked jit inside any function that also sits
            # in a loop is covered above; bare immediate invocation at
            # module level compiles once and is left alone.
            # ---- XF204: unrecorded jit in recorder-scoped modules -----
            if in_scope:
                parent = parents.get(node)
                ok = False
                # direct: recorder.wrap("name", jax.jit(f))
                enc = astutil.enclosing(node, parents, (ast.Call,))
                if enc is not None and isinstance(enc.func, ast.Attribute) \
                        and enc.func.attr == "wrap":
                    ok = True
                # assigned then wrapped: fn = jax.jit(f); recorder.wrap(fn)
                if isinstance(parent, ast.Assign):
                    for tgt in parent.targets:
                        nm = astutil.dotted(tgt)
                        if nm and nm in wrapped_names:
                            ok = True
                # returned from a factory whose results get wrapped:
                # `def build(): return jax.jit(f)` + `x = build()` +
                # `recorder.wrap(name, x)`
                if not ok and isinstance(parent, ast.Return):
                    fn = astutil.enclosing(
                        node, parents, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))
                    if fn is not None and fn.name in wrapped_factories:
                        ok = True
                if not ok:
                    findings.append(Finding(
                        rule="XF204", path=mod.relpath, line=node.lineno,
                        message="jit program not routed through "
                                "CompileRecorder.wrap — compile accounting "
                                "cannot see it (exactly-once contract, "
                                "docs/OBSERVABILITY.md)",
                        hint="wrap it: `recorder.wrap(\"<program>\", jitted)`"
                             " when a recorder is configured",
                    ))

        # ---- XF203: unhashable literals in static slots (syntactic) ---
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = astutil.dotted(node.func)
            if fname not in jitted:
                continue
            nums, names = _static_spec(jitted[fname])
            if not nums and not names:
                continue
            for idx in nums:
                if idx < len(node.args):
                    _check_unhashable(findings, mod, node, fname, idx,
                                      node.args[idx])
            for kw in node.keywords:
                if kw.arg in names:
                    _check_unhashable(findings, mod, node, fname, kw.arg,
                                      kw.value)
        # ---- XF202 (loop variance): flow-sensitive dataflow sweep -----
        specs = {}
        for fname, jcall in jitted.items():
            nums, names = _static_spec(jcall)
            if nums or names:
                specs[fname] = (nums, names)
        if specs:
            hooks = _StaticSlotHooks(specs)
            dataflow.Dataflow(mod, hooks).run_all()
            for (_cid, slot), (call, arg_node, val) in sorted(
                    hooks.sites.items(),
                    key=lambda kv: (kv[1][0].lineno, str(kv[0][1]))):
                if not val.tagged("loopvar"):
                    continue
                # the value must still VARY here: some loop that bound
                # it must enclose this call site (a loop variable read
                # after its loop is one value per outer execution)
                if not _inside_binding_loop(call, val.loops, parents):
                    continue
                fname = astutil.dotted(call.func)
                label = arg_node.id if isinstance(arg_node, ast.Name) \
                    else "<derived from a loop variable>"
                findings.append(Finding(
                    rule="XF202", path=mod.relpath, line=call.lineno,
                    message=f"loop variable `{label}` in static slot "
                            f"{slot!r} of jitted `{fname}` — recompiles "
                            "once per loop value",
                    hint="make the argument dynamic (traced) or hoist "
                         "the loop into the program (lax.scan / "
                         "fori_loop)",
                ))
        # cross-site varying literals in static slots
        _varying_literals(findings, mod, jitted)
    return findings


def _inside_binding_loop(call: ast.AST, loop_ids: frozenset,
                         parents: dict) -> bool:
    cur = parents.get(call)
    while cur is not None:
        if isinstance(cur, (ast.For, ast.AsyncFor, ast.While,
                            ast.ListComp, ast.SetComp, ast.GeneratorExp,
                            ast.DictComp)) and id(cur) in loop_ids:
            return True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return False
        cur = parents.get(cur)
    return False


def _check_unhashable(findings, mod, call, fname, slot, arg):
    if isinstance(arg, (ast.List, ast.Dict, ast.Set)):
        findings.append(Finding(
            rule="XF203", path=mod.relpath, line=call.lineno,
            message=f"unhashable {type(arg).__name__.lower()} literal in "
                    f"static slot {slot!r} of jitted `{fname}` — static "
                    "args are cache keys and must hash",
            hint="pass a tuple (or hoist the structure out of the static "
                 "signature)",
        ))


def _varying_literals(findings, mod, jitted) -> None:
    """Two call sites passing DIFFERENT literals in one static slot ->
    one compile per value (XF202)."""
    if not jitted:
        return
    sites: dict = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        fname = astutil.dotted(node.func)
        if fname not in jitted:
            continue
        nums, names = _static_spec(jitted[fname])
        for idx in nums:
            if idx < len(node.args):
                arg = node.args[idx]
                if isinstance(arg, ast.Constant):
                    sites.setdefault((fname, idx), []).append(
                        (node.lineno, arg.value))
        for kw in node.keywords:
            if kw.arg in names and isinstance(kw.value, ast.Constant):
                sites.setdefault((fname, kw.arg), []).append(
                    (kw.value.lineno, kw.value.value))
    for (fname, slot), vals in sites.items():
        distinct = {repr(v) for _ln, v in vals}
        if len(distinct) > 1:
            line = min(ln for ln, _v in vals)
            findings.append(Finding(
                rule="XF202", path=mod.relpath, line=line,
                message=f"jitted `{fname}` called with "
                        f"{len(distinct)} distinct literals in static slot "
                        f"{slot!r} — one compile per value",
                hint="if the values are genuinely few this may be intended;"
                     " otherwise make the argument dynamic",
            ))
