"""XF2xx recompile hazards: patterns that silently thrash the jit cache.

PR 7's CompileRecorder turned "each (program, signature) compiles
exactly once per run" into a runtime `--check` gate; these rules catch
the same class of bug before the code ever runs:

- XF201 jit-in-loop: a `jax.jit(...)` (or immediately-invoked
  `jax.jit(f)(x)`) inside a for/while body builds a FRESH callable —
  and with it a fresh trace + compile — on every iteration. The cache
  keys on the function object; a new object never hits.
- XF202 varying-static-argument: a callable jitted with
  `static_argnums`/`static_argnames` recompiles once per DISTINCT
  value of each static argument. Passing a loop induction variable, or
  different literals across call sites, in a static slot is a
  compile-per-step bug.
- XF203 unhashable-static-argument: a list/dict/set literal in a
  static slot raises (static args are cache keys and must hash) — at
  call time, far from the jit site that declared it static.
- XF204 unrecorded-jit: in the engine/serve modules, every jit must
  route through `telemetry.CompileRecorder.wrap` so the exactly-once
  contract stays observable (docs/OBSERVABILITY.md "Compile
  accounting"). A bare `jax.jit` there compiles invisibly — the
  metrics stream cannot prove it didn't recompile.
"""

from __future__ import annotations

import ast
from xflow_tpu.analysis import astutil
from xflow_tpu.analysis.core import Finding, Project, register_pass

RULES = ("XF201", "XF202", "XF203", "XF204")

JIT_CALLS = {"jax.jit", "jit", "pjit", "jax.pjit"}

# modules where PR 7's recorder contract applies: every jitted program
# must be wrapped so compile accounting sees it
RECORDER_SCOPED = (
    "xflow_tpu/train/step.py",
    "xflow_tpu/parallel/train_step.py",
    "xflow_tpu/parallel/sorted_sharded.py",
    "xflow_tpu/parallel/sorted_fullshard.py",
    "xflow_tpu/models/predict.py",
    "xflow_tpu/serve/",
)


def _static_spec(call: ast.Call) -> tuple:
    """(static positions, static names) declared on a jit call."""
    nums: list = []
    names: list = []
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            items = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for it in items:
                if isinstance(it, ast.Constant) and isinstance(it.value, int):
                    nums.append(it.value)
        elif kw.arg == "static_argnames":
            v = kw.value
            items = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for it in items:
                s = astutil.const_str(it)
                if s:
                    names.append(s)
    return nums, names


def _loop_vars_for(node: ast.AST, parents: dict) -> set:
    """Names bound as for-loop targets in the SAME scope as `node`
    (its enclosing function, or the module top level) — a parameter
    sharing a name with an unrelated loop variable in some other
    function must not read as a loop variable here."""
    owner = astutil.enclosing(
        node, parents, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda))
    if owner is None:
        # module scope: walk up to the root
        owner = node
        while parents.get(owner) is not None:
            owner = parents[owner]
    out: set = set()
    for sub in astutil.walk_scope(owner):
        if isinstance(sub, (ast.For, ast.AsyncFor)):
            for t in ast.walk(sub.target):
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


@register_pass("recompile-hazard", RULES)
def run(project: Project) -> list:
    findings = []
    for mod in project.modules:
        if mod.tree is None:
            continue
        parents = astutil.parent_map(mod.tree)
        aliases = astutil.import_aliases(mod.tree)
        # name -> the jit Call that produced it (for static-arg call sites)
        jitted: dict = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if astutil.canonical(astutil.call_name(node.value),
                                     aliases) in JIT_CALLS:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            jitted[tgt.id] = node.value

        in_scope = any(mod.relpath.startswith(p) or mod.relpath == p
                       for p in RECORDER_SCOPED)
        wrapped_names: set = set()
        wrapped_factories: set = set()
        if in_scope:
            # names passed to a `.wrap(...)` call anywhere in the module
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute) and node.func.attr == "wrap":
                    for arg in node.args:
                        nm = astutil.dotted(arg)
                        if nm:
                            wrapped_names.add(nm)
            # factory pattern: `jitted = build(...)` then
            # `recorder.wrap(name, jitted)` — a jit RETURNED from
            # `build` is accounted for at the call site
            for node in ast.walk(mod.tree):
                if (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    cn = astutil.call_name(node.value)
                    if cn is None or "." in cn:
                        continue
                    for tgt in node.targets:
                        nm = astutil.dotted(tgt)
                        if nm and nm in wrapped_names:
                            wrapped_factories.add(cn)

        # decorator-form jit in recorder-scoped modules: `@jax.jit` (or
        # `@partial(jax.jit, ...)`) on a def whose name never reaches a
        # `.wrap(...)` call bypasses compile accounting just as surely
        # as the call form below
        if in_scope:
            from xflow_tpu.analysis.passes.jit_purity import _is_jit_decorator

            for node in ast.walk(mod.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if not any(_is_jit_decorator(d, aliases)
                           for d in node.decorator_list):
                    continue
                if node.name in wrapped_names:
                    continue
                findings.append(Finding(
                    rule="XF204", path=mod.relpath, line=node.lineno,
                    message="decorator-jitted function not routed through "
                            "CompileRecorder.wrap — compile accounting "
                            "cannot see it (exactly-once contract, "
                            "docs/OBSERVABILITY.md)",
                    hint="drop the decorator and wrap explicitly: "
                         "`recorder.wrap(\"<program>\", jax.jit(fn))`",
                ))

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            cn = astutil.canonical(astutil.call_name(node), aliases)
            if cn not in JIT_CALLS:
                continue
            # ---- XF201: jit constructed per loop iteration ------------
            if astutil.in_loop(node, parents):
                findings.append(Finding(
                    rule="XF201", path=mod.relpath, line=node.lineno,
                    message=f"`{cn}(...)` inside a loop builds a fresh "
                            "callable — and recompiles — every iteration",
                    hint="hoist the jit out of the loop (the cache keys on "
                         "the function OBJECT; a new object never hits)",
                ))
            # immediately-invoked jit inside any function that also sits
            # in a loop is covered above; bare immediate invocation at
            # module level compiles once and is left alone.
            # ---- XF204: unrecorded jit in recorder-scoped modules -----
            if in_scope:
                parent = parents.get(node)
                ok = False
                # direct: recorder.wrap("name", jax.jit(f))
                enc = astutil.enclosing(node, parents, (ast.Call,))
                if enc is not None and isinstance(enc.func, ast.Attribute) \
                        and enc.func.attr == "wrap":
                    ok = True
                # assigned then wrapped: fn = jax.jit(f); recorder.wrap(fn)
                if isinstance(parent, ast.Assign):
                    for tgt in parent.targets:
                        nm = astutil.dotted(tgt)
                        if nm and nm in wrapped_names:
                            ok = True
                # returned from a factory whose results get wrapped:
                # `def build(): return jax.jit(f)` + `x = build()` +
                # `recorder.wrap(name, x)`
                if not ok and isinstance(parent, ast.Return):
                    fn = astutil.enclosing(
                        node, parents, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))
                    if fn is not None and fn.name in wrapped_factories:
                        ok = True
                if not ok:
                    findings.append(Finding(
                        rule="XF204", path=mod.relpath, line=node.lineno,
                        message="jit program not routed through "
                                "CompileRecorder.wrap — compile accounting "
                                "cannot see it (exactly-once contract, "
                                "docs/OBSERVABILITY.md)",
                        hint="wrap it: `recorder.wrap(\"<program>\", jitted)`"
                             " when a recorder is configured",
                    ))

        # ---- XF202/XF203: call sites of statically-jitted names -------
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = astutil.dotted(node.func)
            if fname not in jitted:
                continue
            jcall = jitted[fname]
            nums, names = _static_spec(jcall)
            if not nums and not names:
                continue
            loop_vars = _loop_vars_for(node, parents)
            for idx in nums:
                if idx < len(node.args):
                    arg = node.args[idx]
                    _check_static_arg(findings, mod, node, fname, idx, arg,
                                      loop_vars)
            for kw in node.keywords:
                if kw.arg in names:
                    _check_static_arg(findings, mod, node, fname, kw.arg,
                                      kw.value, loop_vars)
        # cross-site varying literals in static slots
        _varying_literals(findings, mod, jitted)
    return findings


def _check_static_arg(findings, mod, call, fname, slot, arg, loop_vars):
    if isinstance(arg, (ast.List, ast.Dict, ast.Set)):
        findings.append(Finding(
            rule="XF203", path=mod.relpath, line=call.lineno,
            message=f"unhashable {type(arg).__name__.lower()} literal in "
                    f"static slot {slot!r} of jitted `{fname}` — static "
                    "args are cache keys and must hash",
            hint="pass a tuple (or hoist the structure out of the static "
                 "signature)",
        ))
    elif isinstance(arg, ast.Name) and arg.id in loop_vars:
        findings.append(Finding(
            rule="XF202", path=mod.relpath, line=call.lineno,
            message=f"loop variable `{arg.id}` in static slot {slot!r} of "
                    f"jitted `{fname}` — recompiles once per loop value",
            hint="make the argument dynamic (traced) or hoist the loop "
                 "into the program (lax.scan / fori_loop)",
        ))


def _varying_literals(findings, mod, jitted) -> None:
    """Two call sites passing DIFFERENT literals in one static slot ->
    one compile per value (XF202)."""
    if not jitted:
        return
    sites: dict = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        fname = astutil.dotted(node.func)
        if fname not in jitted:
            continue
        nums, names = _static_spec(jitted[fname])
        for idx in nums:
            if idx < len(node.args):
                arg = node.args[idx]
                if isinstance(arg, ast.Constant):
                    sites.setdefault((fname, idx), []).append(
                        (node.lineno, arg.value))
        for kw in node.keywords:
            if kw.arg in names and isinstance(kw.value, ast.Constant):
                sites.setdefault((fname, kw.arg), []).append(
                    (kw.value.lineno, kw.value.value))
    for (fname, slot), vals in sites.items():
        distinct = {repr(v) for _ln, v in vals}
        if len(distinct) > 1:
            line = min(ln for ln, _v in vals)
            findings.append(Finding(
                rule="XF202", path=mod.relpath, line=line,
                message=f"jitted `{fname}` called with "
                        f"{len(distinct)} distinct literals in static slot "
                        f"{slot!r} — one compile per value",
                hint="if the values are genuinely few this may be intended;"
                     " otherwise make the argument dynamic",
            ))
