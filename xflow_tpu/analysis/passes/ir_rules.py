"""XF8xx — the IR tier's rule families (analysis/ir.py).

The AST tier answers "what does the source say"; these rules answer
"what does the LOWERED PROGRAM say", over jaxprs extracted in a pinned
subprocess (``python -m xflow_tpu.analysis.ir``; CPU, trace-only, no
execution). Each rule exists for a ROADMAP contract:

- **XF801 unworklisted-fusion-opportunity**: a gather → elementwise →
  scatter subgraph over a table-sized operand that is NOT recorded in
  the checked-in ``tools/fusion_worklist.json``. The worklist is the
  Pallas kernel arc's machine-checked target list (ROADMAP "[speed]
  fused Pallas sparse-update kernel"): every chain in the live tree is
  recorded there with shapes/dtypes/byte estimates, so the kernel PR
  starts from a gated oracle instead of re-deriving the hot path. A
  new chain (or a chain whose shape/dtype/op-count identity changed)
  must be reviewed into the worklist — regenerate with
  ``xflowlint --write-worklist``.
- **XF802 silent-dtype-promotion**: a ``convert_element_type``
  widening bf16/f16 to f32 over a large operand. FM's measured lever
  is FEWER BYTES (bf16 tables, docs/PERF.md); a hidden upcast silently
  pays the f32 bytes the config opted out of.
- **XF803 scan-carry-waste**: a ``lax.scan`` whose stacked outputs no
  consumer reads (length× memory for nothing) or whose carry leaf the
  body returns unchanged (the leaf rides every iteration for free —
  usually a refactor leftover).
- **XF804 ast-ir-contract-mismatch**: donation or in/out-sharding
  contracts declared at the AST tier (the XF7xx extraction feeding
  ``tools/engine_contracts.json``) that are absent or different in the
  lowered signature — the cross-check that keeps both tiers honest.
  A donation the AST cannot see (built through ``**kwargs``) or an
  in_shardings the lowering dropped would silently rot the contract
  matrix the unified-builder refactor diffs against.

Static-arg hazards stay with the AST tier (XF202/XF203): the captured
jit objects do not expose their static spec, and the lowered program
has already specialized on it.

Availability: the tier needs jax AND an importable tree under the lint
root. When either is missing the pass returns no findings and records
why in ``LAST_STATUS`` — the CLI prints the notice and the AST tier's
verdicts stand alone (scratch-copy AST-only linting keeps working).
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys

from xflow_tpu.analysis.core import Finding, Project, register_pass

RULES = ("XF801", "XF802", "XF803", "XF804")

WORKLIST_REL = "tools/fusion_worklist.json"
SUBPROCESS_TIMEOUT_S = 600

# (state, detail): "ok" | "skipped"; the CLI reads this after run_passes
# to print the graceful-degradation notice
LAST_STATUS: tuple = ("ok", "")

# one extraction per root per process: the lint pass, the worklist gate,
# and the contracts-v2 gate all reuse it
_IR_CACHE: dict = {}


def ir_facts(root: str):
    """-> (facts dict, None) or (None, reason). Cached per root."""
    root = os.path.abspath(root)
    if root in _IR_CACHE:
        return _IR_CACHE[root]
    env = dict(os.environ)
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    try:
        r = subprocess.run(
            [sys.executable, "-m", "xflow_tpu.analysis.ir", "--root", root],
            capture_output=True, text=True, timeout=SUBPROCESS_TIMEOUT_S,
            env=env, cwd=root)
    except Exception as e:
        out = (None, f"IR subprocess failed: {type(e).__name__}")
        _IR_CACHE[root] = out
        return out
    if r.returncode != 0:
        reason = "jax or the tree is unavailable"
        try:
            reason = json.loads(r.stdout.strip().splitlines()[-1])["reason"]
        except Exception:
            if r.returncode != 5:
                reason = (f"IR subprocess exited {r.returncode}: "
                          f"{(r.stderr or '').strip()[-200:]}")
        out = (None, reason)
        _IR_CACHE[root] = out
        return out
    try:
        facts = json.loads(r.stdout.strip().splitlines()[-1])
    except Exception:
        out = (None, "IR subprocess produced unparseable output")
        _IR_CACHE[root] = out
        return out
    _IR_CACHE[root] = (facts, None)
    return facts, None


def reset_cache() -> None:
    _IR_CACHE.clear()


# ------------------------------------------------------------- worklist


def chain_identity(program: str, chain: dict) -> tuple:
    """What makes a chain "the same" across edits: its program, table,
    shape/dtype, and gather/scatter op counts. Source lines are
    excluded (an unrelated edit above the chain must not fire XF801 —
    line drift is --check-worklist's job, exit 4)."""
    return (program, chain["table"], tuple(chain["table_shape"]),
            chain["table_dtype"], chain["gathers"], chain["scatters"])


def build_worklist(facts: dict) -> dict:
    """The fusion worklist artifact from extracted IR facts."""
    entries = []
    for key in sorted(facts.get("programs", {})):
        prog = facts["programs"][key]
        for chain in prog.get("chains", []):
            entries.append({
                "program": key,
                "engine": prog["engine"],
                "table": chain["table"],
                "table_shape": chain["table_shape"],
                "table_dtype": chain["table_dtype"],
                "table_bytes": chain["table_bytes"],
                "occurrences": chain["occurrences"],
                "gathers": chain["gathers"],
                "scatters": chain["scatters"],
                "elementwise_table_ops": chain["elementwise_table_ops"],
                "est_bytes_per_step": chain["est_bytes_per_step"],
                "gather_at": _loc(chain["gather_at"]),
                "scatter_at": _loc(chain["scatter_at"]),
            })
    entries.sort(key=lambda e: (e["program"], e["table"],
                                tuple(e["table_shape"])))
    return {
        "_comment": (
            "Fusion worklist: every gather -> elementwise -> scatter "
            "chain in the lowered engine programs, extracted by "
            "xflowlint's IR tier (analysis/ir.py) — the Pallas "
            "sparse-update kernel arc's machine-checked target list "
            "(ROADMAP '[speed]', docs/PERF.md). Regenerate with "
            "`python tools/xflowlint.py --write-worklist`; CI fails "
            "with exit 4 on drift (--check-worklist) and XF801 fires "
            "on chains missing from this list."
        ),
        "jax_version": facts.get("jax_version"),
        "mesh": facts.get("mesh"),
        "entries": entries,
    }


def render_worklist(worklist: dict) -> str:
    return json.dumps(worklist, indent=2, sort_keys=True) + "\n"


def load_worklist(root: str):
    path = os.path.join(root, *WORKLIST_REL.split("/"))
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except Exception:
        return None


def _loc(src) -> str:
    if not src:
        return ""
    return f"{src[0]}:{src[1]}"


def _split_loc(src, fallback_path: str):
    if src:
        return src[0], int(src[1])
    return fallback_path, 1


# ------------------------------------------------------- contracts v2


def ir_contract_section(facts: dict) -> dict:
    """The per-program jaxpr section of contracts v2: op histogram,
    gather/scatter counts, dtype census, flop/byte estimates."""
    programs = {}
    for key in sorted(facts.get("programs", {})):
        p = facts["programs"][key]
        programs[key] = {
            "engine": p["engine"],
            "recorder_name": p["recorder_name"],
            "op_histogram": p["op_histogram"],
            "gathers": p["gathers"],
            "scatters": p["scatters"],
            "dtype_census": p["dtype_census"],
            "donated_args": p["donated_args"],
            "has_sharding_annotations": p["has_sharding_annotations"],
            "cost": p["cost"],
        }
    return {
        "jax_version": facts.get("jax_version"),
        "device_count": facts.get("device_count"),
        "mesh": facts.get("mesh"),
        "programs": programs,
    }


# ------------------------------------------------------------ the rules


def _xf801(facts: dict, root: str) -> list:
    worklist = load_worklist(root) or {"entries": []}
    # worklist entries carry exactly the keys chain_identity reads, so
    # the suppression set and the identity definition cannot drift
    known = {chain_identity(e["program"], e)
             for e in worklist.get("entries", [])}
    findings = []
    for key in sorted(facts.get("programs", {})):
        prog = facts["programs"][key]
        for chain in prog.get("chains", []):
            if chain_identity(key, chain) in known:
                continue
            path, line = _split_loc(chain["scatter_at"] or
                                    chain["gather_at"], prog["engine"])
            mb = chain["est_bytes_per_step"] / 1e6
            findings.append(Finding(
                rule="XF801", path=path, line=line,
                message=(
                    f"fusion opportunity not in {WORKLIST_REL}: program "
                    f"{key} streams table {chain['table']!r} "
                    f"{chain['table_shape']}/{chain['table_dtype']} "
                    f"through {chain['gathers']} gather(s) + "
                    f"{chain['scatters']} scatter(s) + "
                    f"{chain['elementwise_table_ops']} table-wide "
                    f"elementwise op(s) (~{mb:.0f} MB/step unfused) — "
                    "the Pallas kernel arc's target shape"
                ),
                hint="review the chain into the worklist: `python "
                     "tools/xflowlint.py --write-worklist` and commit "
                     "the diff (it is the kernel arc's acceptance "
                     "oracle)",
            ))
    return findings


def _xf802(facts: dict) -> list:
    findings = []
    for key in sorted(facts.get("programs", {})):
        prog = facts["programs"][key]
        for cv in prog.get("converts", []):
            path, line = _split_loc(cv["src"], prog["engine"])
            findings.append(Finding(
                rule="XF802", path=path, line=line,
                message=(
                    f"silent dtype promotion in program {key}: "
                    f"{cv['from']} -> {cv['to']} over shape "
                    f"{cv['shape']} ({cv['elems']} elements) — pays "
                    f"the {cv['to']} bytes the {cv['from']} config "
                    "opted out of (FM's bytes lever, docs/PERF.md)"
                ),
                hint="keep the compute in the narrow dtype or make "
                     "the upcast explicit at a documented site",
            ))
    return findings


def _xf803(facts: dict) -> list:
    findings = []
    for key in sorted(facts.get("programs", {})):
        prog = facts["programs"][key]
        for sc in prog.get("scans", []):
            path, line = _split_loc(sc["src"], prog["engine"])
            parts = []
            if sc["dead_outputs"]:
                parts.append(
                    f"stacked output(s) {sc['dead_outputs']} have no "
                    f"consumer (length={sc['length']}: the whole stack "
                    "is materialized for nothing)")
            if sc["identity_carries"]:
                parts.append(
                    f"carry leaf/leaves {sc['identity_carries']} are "
                    "returned unchanged by the body (dead weight riding "
                    "every iteration)")
            findings.append(Finding(
                rule="XF803", path=path, line=line,
                message=f"scan-carry waste in program {key}: "
                        + "; ".join(parts),
                hint="drop the dead output (return None from the body) "
                     "or hoist the unchanged leaf out of the carry",
            ))
    return findings


def _ast_jit_records(project: Project) -> list:
    """(engine rel, rec) for every recorder-named jit the AST tier
    extracted from the engine builders (rec carries donate/static/
    shardings/line — sharding_contract's raw per-jit records)."""
    from xflow_tpu.analysis.passes.sharding_contract import _analyze

    _findings, engines = _analyze(project)
    out = []
    for rel, mc in sorted(engines.items()):
        for rec in mc.jits:
            if rec.get("name"):
                out.append((rel, rec))
    return out


def _name_matches(ast_name: str, ir_name: str) -> bool:
    """AST names may carry f-string holes ('train_step.fullshard.'
    '{mode}') — match them as wildcards against the concrete lowered
    name."""
    if ast_name == ir_name:
        return True
    if "{" not in ast_name:
        return False
    pat = re.escape(ast_name)
    pat = re.sub(r"\\\{[^}]*\\\}", r"[^\\s]+", pat)
    return re.fullmatch(pat, ir_name) is not None


def _xf804(facts: dict, project: Project) -> list:
    records = _ast_jit_records(project)
    findings = []
    for key in sorted(facts.get("programs", {})):
        prog = facts["programs"][key]
        matches = [(rel, rec) for rel, rec in records
                   if rel == prog["engine"]
                   and _name_matches(rec["name"], prog["recorder_name"])]
        if not matches:
            continue  # program jitted outside the engine modules
        # several jits may share one recorder name (contract() dedups
        # them with a '#n' suffix): the lowered program came from ONE
        # of them, so fire only when NO matching record agrees — a
        # duplicate that does agree must not false-fire, and a real
        # mismatch shared by all of them must not hide
        ir_donate = set(prog["donated_args"])

        def ast_donate(rec):
            out = {x for x in rec["donate_argnums"]
                   if isinstance(x, int)}
            if "state" in rec["donate_argnums"]:
                out.add(0)
            return out

        rel, rec = matches[0]
        if all(ast_donate(r) != ir_donate for _rel, r in matches):
            findings.append(Finding(
                rule="XF804", path=rel, line=rec["line"],
                message=(
                    f"AST/IR contract mismatch for program "
                    f"{rec['name']!r}: AST-tier donation "
                    f"{sorted(ast_donate(rec))} != lowered donation "
                    f"{sorted(ir_donate)} — the contract matrix "
                    "(tools/engine_contracts.json) no longer reflects "
                    "the program that actually runs"
                ),
                hint="declare donation where the AST tier can see it "
                     "(a literal donate_argnums=(...) on the jit call) "
                     "or fix the lowering",
            ))
        ast_sharded = lambda r: r["in_shardings"] is not None \
            or r["out_shardings"] is not None
        if all(ast_sharded(r) for _rel, r in matches) \
                and not prog["has_sharding_annotations"]:
            findings.append(Finding(
                rule="XF804", path=rel, line=rec["line"],
                message=(
                    f"AST/IR contract mismatch for program "
                    f"{rec['name']!r}: in/out shardings declared at the "
                    "AST tier but the lowered module carries no "
                    "sharding annotations — the program would run "
                    "replicated"
                ),
                hint="check the in_shardings/out_shardings actually "
                     "reach jax.jit",
            ))
    return findings


@register_pass("ir-tier", RULES, scope="ir")
def run(project: Project) -> list:
    """The IR tier. Runs only when the CLI enables the 'ir' tier
    (full-tree runs with jax importable; `--ir` forces, `--no-ir`
    disables)."""
    global LAST_STATUS
    facts, reason = ir_facts(project.root)
    if facts is None:
        LAST_STATUS = ("skipped", reason or "unavailable")
        return []
    detail = ""
    if facts.get("errors"):
        broken = ", ".join(e["program"] for e in facts["errors"])
        detail = f"programs failed to lower: {broken}"
    LAST_STATUS = ("ok", detail)
    findings = []
    findings.extend(_xf801(facts, project.root))
    findings.extend(_xf802(facts))
    findings.extend(_xf803(facts))
    findings.extend(_xf804(facts, project))
    return findings
